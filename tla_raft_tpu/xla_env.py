"""Pre-jax-import environment bootstrap for virtual CPU meshes.

Must run BEFORE jax (or anything that imports it): the ambient TPU-tunnel
sitecustomize pins the platform via jax.config at interpreter start, which
overrides JAX_PLATFORMS alone, and XLA_FLAGS are read at backend init.
This module deliberately does not import jax — callers do, afterwards.

Shared by tests/conftest.py, __graft_entry__.dryrun_multichip and
scripts/mesh_deep_parity.py so the flag set cannot drift between entry
points (round-4 advisor finding).
"""

from __future__ import annotations

import os
import subprocess
import sys

# XLA aborts the whole process (LOG(FATAL) in parse_flags_from_env) on any
# flag the linked jaxlib doesn't know, so the collective-timeout guards
# below must be probed before they are pinned into XLA_FLAGS.  The probe
# result is cached per jaxlib version (file + env var, so child processes
# skip it).
_COLL_FLAGS = (
    " --xla_cpu_collective_call_terminate_timeout_seconds=3600"
    " --xla_cpu_collective_call_warn_stuck_timeout_seconds=600"
)
_PROBE_ENV = "TLA_RAFT_XLA_COLL_FLAGS_OK"


def _collective_flags_supported() -> bool:
    """True iff this jaxlib accepts the CPU collective-timeout flags."""
    cached = os.environ.get(_PROBE_ENV)
    if cached is not None:
        return cached == "1"
    try:
        from importlib.metadata import version

        tag = version("jaxlib")
    except ImportError:  # PackageNotFoundError subclasses ImportError
        tag = "unknown"
    cache_dir = os.path.expanduser("~/.cache/tla_raft_tpu")
    cache = os.path.join(cache_dir, f"xla_coll_flags_{tag}")
    if os.path.exists(cache):
        with open(cache) as f:
            ok = f.read().strip() == "1"
        os.environ[_PROBE_ENV] = "1" if ok else "0"
        return ok
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = _COLL_FLAGS.strip()
    env.pop("PYTHONSTARTUP", None)
    durable = True
    try:
        ok = (
            subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                env=env, capture_output=True, timeout=120,
            ).returncode
            == 0
        )
    except (subprocess.SubprocessError, OSError):
        # a timeout/OSError is TRANSIENT (loaded host), not a verdict on
        # the jaxlib — run without the guards this process, but do not
        # poison the per-version cache (a clean non-zero exit IS the
        # deterministic unknown-flag fatal and is safe to cache)
        ok = False
        durable = False
    if durable:
        try:
            os.makedirs(cache_dir, exist_ok=True)
            with open(cache, "w") as f:
                f.write("1" if ok else "0")
        except OSError:
            pass
    os.environ[_PROBE_ENV] = "1" if ok else "0"
    return ok


def ensure_virtual_cpu_mesh(n_devices: int = 8) -> None:
    """Point JAX at N virtual CPU devices with sane collective timeouts."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    xla = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in xla:
        xla = (
            xla + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    if (
        "collective_call_terminate" not in xla
        and _collective_flags_supported()
    ):
        # virtual devices timeshare the host CPU; XLA aborts the whole
        # process when a collective's participant threads miss a 40 s
        # hard rendezvous window (hit at ~100k-state virtual-mesh levels
        # on a 1-core host).  Wall-clock guards, not correctness knobs —
        # jaxlibs that don't know the flags simply run without them
        # (unknown XLA_FLAGS are themselves a fatal abort, see probe).
        xla += _COLL_FLAGS
    os.environ["XLA_FLAGS"] = xla
