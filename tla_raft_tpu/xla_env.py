"""Pre-jax-import environment bootstrap for virtual CPU meshes.

Must run BEFORE jax (or anything that imports it): the ambient TPU-tunnel
sitecustomize pins the platform via jax.config at interpreter start, which
overrides JAX_PLATFORMS alone, and XLA_FLAGS are read at backend init.
This module deliberately does not import jax — callers do, afterwards.

Shared by tests/conftest.py, __graft_entry__.dryrun_multichip and
scripts/mesh_deep_parity.py so the flag set cannot drift between entry
points (round-4 advisor finding).
"""

from __future__ import annotations

import os


def ensure_virtual_cpu_mesh(n_devices: int = 8) -> None:
    """Point JAX at N virtual CPU devices with sane collective timeouts."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    xla = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in xla:
        xla = (
            xla + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    if "collective_call_terminate" not in xla:
        # virtual devices timeshare the host CPU; XLA aborts the whole
        # process when a collective's participant threads miss a 40 s
        # hard rendezvous window (hit at ~100k-state virtual-mesh levels
        # on a 1-core host).  Wall-clock guards, not correctness knobs.
        xla += (
            " --xla_cpu_collective_call_terminate_timeout_seconds=3600"
            " --xla_cpu_collective_call_warn_stuck_timeout_seconds=600"
        )
    os.environ["XLA_FLAGS"] = xla
