"""Model configuration and derived static bounds.

The reference binds its constants in ``Raft.cfg`` (/root/reference/Raft.cfg:1-21):
``Servers = {s1, s2, s3}``, ``Vals = {v1, v2}``, ``MaxElection = 3``,
``MaxRestart = 3`` (plus a vestigial ``MaxTerm = 3`` that has no matching
``CONSTANT`` in the spec — terms are actually bounded by ``MaxElection``
because ``BecomeCandidate`` is the only action that mints a new term,
/root/reference/Raft.tla:108-111).

Everything the TPU kernels need to be *static* — tensor shapes, radixes of
the message universe, fan-out slot counts — derives from these four numbers.
"""

from __future__ import annotations

import dataclasses
import itertools
import math

# Role encoding (CONSTANT Follower, Candidate, Leader — Raft.tla:14).
FOLLOWER = 0
CANDIDATE = 1
LEADER = 2

# votedFor sentinel (CONSTANT None — Raft.tla:10). Servers are 1..S.
NONE = 0

# Message type tags (CONSTANT VoteReq, VoteResp, AppendReq, AppendResp —
# Raft.tla:8).
VOTE_REQ = 0
VOTE_RESP = 1
APPEND_REQ = 2
APPEND_RESP = 3

MSG_TYPE_NAMES = {
    VOTE_REQ: "VoteReq",
    VOTE_RESP: "VoteResp",
    APPEND_REQ: "AppendReq",
    APPEND_RESP: "AppendResp",
}

ROLE_NAMES = {FOLLOWER: "Follower", CANDIDATE: "Candidate", LEADER: "Leader"}


@dataclasses.dataclass(frozen=True)
class RaftConfig:
    """Static model bounds, the analog of the CONSTANTS block of Raft.cfg.

    Attributes:
      n_servers: |Servers| (Raft.cfg:18).
      n_vals: |Vals| (Raft.cfg:21).
      max_election: MaxElection (Raft.cfg:4) — bound on BecomeCandidate.
      max_restart: MaxRestart (Raft.cfg:3) — bound on Restart.
      symmetry: SYMMETRY symmServers present (Raft.cfg:24).
      use_view: VIEW view present (Raft.cfg:26) — fingerprint on the 8-var
        projection, aux vars excluded (Raft.tla:38).
      invariants: names of INVARIANT predicates to check (Raft.cfg:33-34).
      max_term_cfg: the vestigial ``MaxTerm`` value if present (Raft.cfg:2);
        recorded for cfg fidelity, never used.
      mutations: planted semantic bugs to compile in (SURVEY.md §4.4 —
        the reference keeps buggy variants in comments as checker tests).
        Known: "median-bug" — FindMedian's deliberate off-by-one
        (``pos == Len(mlist) \\div 2`` on the descending-sorted list,
        Raft.tla:65-66): commits at one order statistic above the
        majority median, an over-commit the checker must catch.
        "double-vote" — drops ResponseVote's votedFor guard, making the
        in-path split-brain Assert (Raft.tla:185) reachable.
        "legacy-append" — compiles the dead monolithic
        ``FollowerAppendEntry`` (Raft.tla:323-371) in place of the live
        accept/reject pair: rejects carry ``prevLogIndex - 1`` (:364 vs
        :314) and accepts gain the :347-348 send-guard — detected by
        state-count divergence from the live spec.
        "become-follower" — compiles the dead ``BecomeFollower`` family
        (Raft.tla:191-231) in place of ``UpdateTerm``: a Follower keeps
        its votedFor on term adoption and the split-brain Assert is gone
        — detected by state-count divergence.
    """

    n_servers: int = 3
    n_vals: int = 2
    max_election: int = 3
    max_restart: int = 3
    symmetry: bool = True
    use_view: bool = True
    invariants: tuple[str, ...] = ("Inv",)
    max_term_cfg: int | None = None
    mutations: tuple[str, ...] = ()

    # ---- derived static bounds ------------------------------------------

    @property
    def S(self) -> int:
        return self.n_servers

    @property
    def V(self) -> int:
        return self.n_vals

    @property
    def T(self) -> int:
        """Max reachable currentTerm.

        Only ``BecomeCandidate`` increments a term (Raft.tla:111), gated by
        ``electionCount < MaxElection`` (Raft.tla:108); every term found in a
        message was copied from some server's term at send time, so all terms
        are <= MaxElection.
        """
        return self.max_election

    @property
    def L(self) -> int:
        """Max log length including the sentinel entry.

        Every log starts as ``<<[term |-> 0, val |-> None]>>`` (Raft.tla:97)
        and each value in Vals is appended at most once globally — ClientReq
        requires ``valSent[v] = None`` and is the only writer (Raft.tla:236-237).
        """
        return 1 + self.n_vals

    @property
    def majority(self) -> int:
        """MajoritySize == Cardinality(Servers) \\div 2 + 1 (Raft.tla:41)."""
        return self.n_servers // 2 + 1

    @property
    def median_index(self) -> int:
        """0-based index into the ascending-sorted matchIndex row that
        LeaderCanCommit commits at (Raft.tla:406).

        Correct Median (Raft.tla:70-75): the MajoritySize-th smallest.
        Under the planted "median-bug" mutation (descending-list
        ``pos == Len \\div 2`` instead of ``\\div 2 + 1``, Raft.tla:65-66)
        the picked order statistic shifts one higher — e.g. the *maximum*
        matchIndex for 3 servers, committing entries replicated nowhere.
        """
        if "median-bug" in self.mutations:
            return self.majority
        return self.majority - 1

    @property
    def n_perms(self) -> int:
        return math.factorial(self.n_servers) if self.symmetry else 1

    def server_perms(self) -> list[tuple[int, ...]]:
        """All |Servers|! permutations (or just identity when symmetry off).

        Each perm is a tuple p of length S with p[s-1] = image of server s
        (servers are 1-based). This is ``Permutations(Servers)``
        (Raft.tla:21) activated by ``SYMMETRY symmServers`` (Raft.cfg:24).
        """
        servers = tuple(range(1, self.n_servers + 1))
        if not self.symmetry:
            return [servers]
        return [tuple(p) for p in itertools.permutations(servers)]

    def describe(self) -> str:
        return (
            f"S={self.S} V={self.V} MaxElection={self.max_election} "
            f"MaxRestart={self.max_restart} T={self.T} L={self.L} "
            f"majority={self.majority} symmetry={self.symmetry} "
            f"view={self.use_view} invariants={list(self.invariants)}"
        )


# The reference configuration, Raft.cfg as-is.
REFERENCE_CONFIG = RaftConfig(
    n_servers=3,
    n_vals=2,
    max_election=3,
    max_restart=3,
    symmetry=True,
    use_view=True,
    invariants=("Inv",),
    max_term_cfg=3,
)
