"""graftlint: static analysis + trace sanitation for the TPU checker.

Three layers, each machine-checking a bug class that PR 1 shipped and
code review missed (docs/ANALYSIS.md has the incident-by-incident
rationale):

* **AST lint** (:mod:`.ast_lint`) — repo-specific source rules: no
  device dispatch at import time, no wall-clock/random inside traced
  functions, no blanket excepts, no Python branching on traced values,
  i64 width discipline for row/offset arithmetic, a pinned ledger of
  host-sync call sites in the hot level loops, no jax from thread-pool
  workers, no unused imports.  Waivable inline
  (``# graftlint: waive[RULE]``) and baselined
  (:data:`.ast_lint.BASELINE_PATH`).
* **jaxpr audit** (:mod:`.jaxpr_audit`) — lowers the registered hot
  kernels to closed jaxprs and diffs their primitive ledgers against a
  committed golden ledger; host callbacks, stray collectives and f64
  are hard failures.
* **runtime sanitizer** (:mod:`.sanitize`) — ``GRAFT_SANITIZE=1`` wraps
  a check run with a host-transfer ledger, a per-level compile-count
  ledger, and a worker-thread device-dispatch guard.

Plus **graftsync**, the concurrency layer mirroring the same shape:

* **thread lint** (:mod:`.threadlint`) — GL014 unsynced shared state
  across thread boundaries (with the committed ``sync_registry.json``
  ledger), GL015 static lock-order deadlock detection, GL016
  signal/atexit/``__del__`` handler discipline, and the service
  lease-protocol audit; waivable inline (``# graftsync: waive[RULE]``).
* **happens-before sanitizer** (:mod:`.tsan`) — ``GRAFT_TSAN=1`` wraps
  a check run with a vector-clock race checker over the known thread
  boundaries plus a lock-hold/contention profiler publishing into the
  telemetry hub.

CLI: ``python -m tla_raft_tpu.analysis`` (exit 0 = zero unwaived
findings and no ledger drift — the CI gate; 1 = findings/drift,
2 = usage error).

This module imports nothing heavier than stdlib so the package import
stays device-free (tests/test_import_clean.py).
"""

from __future__ import annotations

RULE_IDS = (
    "GL001", "GL002", "GL003", "GL004",
    "GL005", "GL006", "GL007", "GL008", "GL009",
)
