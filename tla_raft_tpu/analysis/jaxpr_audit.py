"""graftlint layer 2: primitive-level audit of the registered hot kernels.

The AST layer sees source; this layer sees what XLA will actually be
asked to run.  Each registered kernel (dense expand, fingerprint,
successor guards/materialize, exchange pack) is lowered to a closed
jaxpr on a tiny reference config and walked recursively:

* **hard failures** — primitives that must never appear in a
  single-device kernel regardless of ledger state: host callbacks
  (``pure_callback``/``io_callback``/... — a hidden per-dispatch host
  round-trip), cross-device collectives (these kernels are composed
  INSIDE shard_map bodies; a collective baked into one would nest
  axis semantics and deadlock the mesh), and any float64 value (the
  kernels are integer algebra end to end; an f64 appearing means an
  accidental promotion that doubles HBM traffic on the MXU path).
* **ledger diff** — the full per-kernel primitive histogram (plus a
  pseudo-entry counting 64->32-bit integer ``convert_element_type``
  narrowings — the PR 1 overflow class at the jaxpr level) is diffed
  against a committed golden ledger.  Any drift fails: a new gather in
  the fingerprint kernel or an extra convert in dense expand is exactly
  the silent-regression class that erases kernel wins one primitive at
  a time.

* **GL010 — gather/scatter budget** — the hot expand kernels (guards,
  materialize, dense expand, and their retained legacy A/B twins) each
  carry a *budget* of data-indexed ``gather`` and ``scatter*``
  primitives equal to their ledgered count.  Exceeding the budget is a
  HARD failure even across jax versions: the budget is semantic (the
  MXU-native expand exists precisely to kill this primitive class —
  the launch-cost cliff of docs/PERF.md), not a lowering artifact.
  Shrinking below budget only trips the ordinary ledger diff, which
  says "regenerate and bank the win".

The golden ledger records the jax version it was generated under; when
the running version differs, the diff degrades to a warning (jaxpr
lowering legitimately drifts across jax releases) while the hard
failures and the GL010 budget still apply.  Regenerate with
``python -m tla_raft_tpu.analysis --write-ledger`` and review the diff.
"""

from __future__ import annotations

import json
import os

LEDGER_PATH = os.path.join(os.path.dirname(__file__), "golden_ledger.json")

FORBIDDEN_PRIMITIVES = {
    "pure_callback", "io_callback", "debug_callback", "callback",
    "infeed", "outfeed",
}
COLLECTIVE_PRIMITIVES = {
    "psum", "pmin", "pmax", "ppermute", "pbroadcast", "all_gather",
    "all_to_all", "reduce_scatter", "pgather", "axis_index",
}

_NARROW_KEY = "convert_element_type[narrow64]"

# GL010: the kernels under the data-indexed gather/scatter budget —
# the per-level expand hot path (both MXU and legacy A/B variants),
# plus the fused whole-level program (engine/megakernel.py): its MXU
# expand/materialize stages contribute ZERO data-indexed gathers; the
# ledgered budget pins the residue (hashstore probe rounds + the
# materialize parent-row gathers) so fusion can never smuggle the
# gather storm back in
GL010_KERNELS = (
    "successor.expand_guards",
    "successor.materialize",
    "successor.expand_guards_legacy",
    "successor.materialize_legacy",
    "dense.expand",
    "engine.megakernel_level",
    "engine.superstep",
    "store.tiered_compact",
    "ops.sieve_probe",
)


def gather_scatter_count(prims: dict) -> int:
    """Data-indexed gather + scatter-class primitive count of a ledger
    histogram (the GL010 budget metric)."""
    return prims.get("gather", 0) + sum(
        v for k, v in prims.items() if k.startswith("scatter")
    )


def _tiny_cfg():
    from ..config import RaftConfig

    # the smallest config with a non-trivial reachable space (50 states,
    # depth 12 — the CLI smoke config): big enough that every kernel
    # branch lowers, small enough that tracing is milliseconds
    return RaftConfig(
        n_servers=2, n_vals=1, max_election=1, max_restart=1,
    )


def kernel_registry():
    """name -> zero-arg callable returning a ClosedJaxpr.

    Covers the four hot-kernel families the level loop dispatches:
    successor guards + materialize (ops/successor.py), the dense expand
    block algebra (ops/dense_expand.py), state fingerprints
    (ops/fingerprint.py), and the exchange delta packer
    (parallel/exchange.py)."""
    import jax
    import jax.numpy as jnp

    from ..engine import megakernel as megakernel_mod
    from ..engine import superstep as superstep_mod
    from ..store import tiered as tiered_mod
    from ..models.raft import init_batch
    from ..ops import hashstore
    from ..ops import sieve as sieve_mod
    from ..ops.successor import get_kernel
    from ..parallel.exchange import pack_fp_deltas

    cfg = _tiny_cfg()
    # mxu pinned ON so the audited successor.* entries are the shipped
    # default regardless of the caller's TLA_RAFT_MXU; the legacy A/B
    # kernels are registered from the same kernel's *_legacy bindings
    kern = get_kernel(cfg, mxu=True)
    fpr = kern.fpr
    st = init_batch(cfg, 8)
    msum = fpr.msg_hash(st.msgs)
    slots = jnp.zeros((8,), jnp.int64)
    fps = jnp.zeros((256,), jnp.uint64)
    n = jnp.asarray(0, jnp.int64)
    slab = jnp.zeros((hashstore.MIN_CAP,), jnp.uint64)
    pays = jnp.zeros((256,), jnp.int64)

    return {
        # the MXU-native hot path (ops/mxu_expand.py, the default):
        # guards = coefficient matmul + message terms, materialize =
        # select-matrix products — both at a ZERO gather/scatter budget
        "successor.expand_guards":
            lambda: jax.make_jaxpr(kern.expand_guards)(st),
        "successor.materialize":
            lambda: jax.make_jaxpr(kern.materialize)(st, slots),
        # the legacy per-lane kernels, retained for A/B: their ledger
        # entries pin the OLD gather/scatter budget so the comparison
        # baseline cannot silently drift either
        "successor.expand_guards_legacy":
            lambda: jax.make_jaxpr(kern.expand_guards_legacy)(st),
        "successor.materialize_legacy":
            lambda: jax.make_jaxpr(kern.materialize_legacy)(st, slots),
        "dense.expand":
            lambda: jax.make_jaxpr(kern.expand)(st, msum),
        "fingerprint.state_fingerprints":
            lambda: jax.make_jaxpr(fpr.state_fingerprints)(st),
        "exchange.pack_fp_deltas":
            lambda: jax.make_jaxpr(pack_fp_deltas)(fps, n),
        # the open-addressing visited store (ops/hashstore.py): the
        # probe hot path must stay at its pinned ONE gather per probe
        # round (plus the claim scatter-min / compaction scatters of
        # probe_and_insert) — any drift back toward the searchsorted
        # gather storm or a data-indexed sort fails the ledger diff
        "hashstore.probe":
            lambda: jax.make_jaxpr(hashstore.probe_impl)(slab, fps),
        "hashstore.probe_and_insert":
            lambda: jax.make_jaxpr(hashstore.probe_and_insert_impl)(
                slab, fps, fps, pays
            ),
        # the fused whole-level program (engine/megakernel.py): expand
        # while_loop + probe-and-insert + materialize scan + invariant
        # reduce as ONE jaxpr — registered so the fusion's primitive
        # mix is frozen like every other hot kernel's
        "engine.megakernel_level":
            lambda: megakernel_mod.ledger_trace(cfg),
        # the multi-level superstep driver (engine/superstep.py): the
        # while_loop wraps the megakernel's fused_level_core, so the
        # same gather budget pins its residue — plus the ring spool,
        # which must stay drop-mode scatters (no data-indexed gathers)
        "engine.superstep":
            lambda: superstep_mod.ledger_trace(cfg),
        # the tiered store's one device program (store/tiered.py):
        # compacting generation-revisit rows out of a materialized
        # frontier — the budget pins ONE data-indexed gather per
        # frontier field (the stable-argsort row permutation), so the
        # level-tail correction can never grow a gather storm
        "store.tiered_compact":
            lambda: tiered_mod.ledger_trace(cfg),
        # the device spill-sieve probe (ops/sieve.py): the in-kernel
        # filter over spilled generations — the budget pins ONE
        # data-indexed gather per probe (the blocked-bloom word fetch);
        # everything else is lane-local bit algebra
        "ops.sieve_probe":
            lambda: sieve_mod.ledger_trace(cfg),
    }


def _subjaxprs(params: dict):
    import jax.core as jcore

    for v in params.values():
        if isinstance(v, jcore.ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, jcore.Jaxpr):
            yield v
        elif isinstance(v, (tuple, list)):
            for x in v:
                if isinstance(x, jcore.ClosedJaxpr):
                    yield x.jaxpr
                elif isinstance(x, jcore.Jaxpr):
                    yield x


def primitive_ledger(closed) -> dict:
    """Recursive primitive histogram + dtype set of one closed jaxpr."""
    counts: dict[str, int] = {}
    dtypes: set[str] = set()

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            counts[name] = counts.get(name, 0) + 1
            for var in list(eqn.outvars) + list(eqn.invars):
                aval = getattr(var, "aval", None)
                dt = getattr(aval, "dtype", None)
                if dt is not None:
                    dtypes.add(str(dt))
            if name == "convert_element_type":
                new = str(eqn.params.get("new_dtype", ""))
                olds = {
                    str(getattr(getattr(v, "aval", None), "dtype", ""))
                    for v in eqn.invars
                }
                if new in ("int32", "uint32") and (
                    "int64" in olds or "uint64" in olds
                ):
                    counts[_NARROW_KEY] = counts.get(_NARROW_KEY, 0) + 1
            for sub in _subjaxprs(eqn.params):
                walk(sub)

    walk(closed.jaxpr)
    return {
        "primitives": dict(sorted(counts.items())),
        "dtypes": sorted(dtypes),
    }


def build_ledger() -> dict:
    import jax

    ledger = {"_meta": {"jax": jax.__version__, "config": "S2V1E1R1"}}
    for name, trace in kernel_registry().items():
        ledger[name] = primitive_ledger(trace())
    return ledger


def load_golden(path: str = LEDGER_PATH) -> dict | None:
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def write_golden(ledger: dict, path: str = LEDGER_PATH):
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(ledger, fh, indent=1, sort_keys=True)
        fh.write("\n")


_DEFAULT_GOLDEN = object()  # sentinel: "load the committed ledger"


def audit(golden=_DEFAULT_GOLDEN) -> tuple[list[str], list[str]]:
    """Run the audit; returns (failures, warnings).

    Hard rules always apply; the ledger diff is a failure when the
    golden was generated under the running jax version, a warning
    otherwise (lowering drifts across releases).  ``golden=None``
    means "the caller's ledger is missing" and is reported as such —
    it does NOT silently fall back to the committed default."""
    import jax

    failures: list[str] = []
    warnings: list[str] = []
    current = build_ledger()
    for name, entry in current.items():
        if name == "_meta":
            continue
        prims = entry["primitives"]
        bad = sorted(set(prims) & FORBIDDEN_PRIMITIVES)
        if bad:
            failures.append(
                f"{name}: host-callback primitive(s) {bad} — a hidden "
                "host round-trip per dispatch"
            )
        coll = sorted(set(prims) & COLLECTIVE_PRIMITIVES)
        if coll:
            failures.append(
                f"{name}: collective primitive(s) {coll} outside any "
                "declared mesh axis — these kernels compose inside "
                "shard_map bodies; a baked-in collective nests axis "
                "semantics and deadlocks the rendezvous"
            )
        if any(d in ("float64", "complex128") for d in entry["dtypes"]):
            failures.append(
                f"{name}: float64 value in the lowered kernel — the "
                "checker is integer algebra end to end; f64 means an "
                "accidental promotion"
            )

    if golden is _DEFAULT_GOLDEN:
        golden = load_golden()
    if golden is None:
        warnings.append(
            "no golden ledger committed — run `python -m "
            "tla_raft_tpu.analysis --write-ledger` and commit "
            "golden_ledger.json"
        )
        return failures, warnings

    # GL010: gather/scatter budget for the hot expand kernels — a HARD
    # failure regardless of jax version (the budget is semantic; see
    # the module docstring).  Budgets come from the committed ledger.
    for name in GL010_KERNELS:
        entry, gold = current.get(name), golden.get(name)
        if entry is None or gold is None:
            continue  # missing-kernel drift is reported below
        cur_gs = gather_scatter_count(entry["primitives"])
        budget = gather_scatter_count(gold["primitives"])
        if cur_gs > budget:
            failures.append(
                f"[GL010] {name}: data-indexed gather/scatter count "
                f"{cur_gs} exceeds the ledgered budget {budget} — the "
                "expand hot path regressed onto the launch-cost cliff "
                "(docs/PERF.md); keep the kernel on the MXU-factored "
                "formulation or justify a new budget with --write-ledger"
            )

    same_version = golden.get("_meta", {}).get("jax") == jax.__version__
    sink = failures if same_version else warnings
    for name, entry in current.items():
        if name == "_meta":
            continue
        gold = golden.get(name)
        if gold is None:
            sink.append(f"{name}: kernel missing from the golden ledger")
            continue
        drift = _diff_counts(gold["primitives"], entry["primitives"])
        if drift:
            sink.append(
                f"{name}: primitive ledger drift vs golden "
                f"({'; '.join(drift)}) — if intended, regenerate with "
                "--write-ledger and justify in the PR"
            )
        if sorted(gold.get("dtypes", [])) != entry["dtypes"]:
            sink.append(
                f"{name}: dtype set drift vs golden "
                f"(golden {gold.get('dtypes')}, now {entry['dtypes']})"
            )
    for name in golden:
        if name != "_meta" and name not in current:
            sink.append(
                f"{name}: in the golden ledger but no longer registered"
            )
    if not same_version:
        warnings.append(
            f"golden ledger was generated under jax "
            f"{golden.get('_meta', {}).get('jax')}, running "
            f"{jax.__version__} — ledger diff demoted to warnings"
        )
    return failures, warnings


def _diff_counts(gold: dict, cur: dict) -> list[str]:
    out = []
    for k in sorted(set(gold) | set(cur)):
        g, c = gold.get(k, 0), cur.get(k, 0)
        if g != c:
            out.append(f"{k}: {g} -> {c}")
    return out
