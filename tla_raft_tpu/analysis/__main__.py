"""CLI: ``python -m tla_raft_tpu.analysis`` — the graftlint gate.

Default run = AST lint over the package (graftlint GL001-GL012 +
graftsync GL014-GL016, baseline applied) + the service lease-protocol
audit + jaxpr audit against the committed golden ledger.

Exit codes:
  0  clean — no unwaived findings, no audit failures
  1  unwaived findings, lease-protocol failure, or ledger drift
  2  usage error (unknown --select rule, missing --ledger file)

Maintenance flows:
  --write-baseline   regenerate baseline.json from the current findings
                     (review the diff — it is the accepted-debt ledger)
  --write-ledger     regenerate golden_ledger.json from the current
                     kernel jaxprs (justify the drift in the PR)
  --threads / --no-threads
                     force the graftsync layer on/off (default: on;
                     GL014-GL016 + lease audit, pure AST — no jax)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import ast_lint, cost_audit, dispatch_audit, jaxpr_audit, threadlint


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tla_raft_tpu.analysis")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: the package)")
    p.add_argument("--select", action="append", default=None,
                   metavar="RULE", help="run only these rules (repeatable)")
    p.add_argument("--no-jaxpr", action="store_true",
                   help="skip the jaxpr audit (layer 2 needs jax)")
    p.add_argument("--no-dispatch", action="store_true",
                   help="skip the GL011 per-level dispatch-budget audit "
                        "(runs the tiny config through both level-loop "
                        "paths; needs jax)")
    p.add_argument("--no-cost", action="store_true",
                   help="skip the GL013 per-kernel cost/memory budget "
                        "audit (compiles the registered kernels at the "
                        "tiny reference shapes; needs jax)")
    p.add_argument("--threads", action="store_true",
                   help="run ONLY the graftsync thread layer "
                        "(GL014-GL016 + lease audit; pure AST)")
    p.add_argument("--no-threads", action="store_true",
                   help="skip the graftsync thread layer")
    p.add_argument("--no-baseline", action="store_true",
                   help="report baselined findings too")
    p.add_argument("--baseline", default=ast_lint.BASELINE_PATH,
                   help="baseline file (default: the committed one)")
    p.add_argument("--ledger", default=jaxpr_audit.LEDGER_PATH,
                   help="golden ledger file (default: the committed one)")
    p.add_argument("--write-baseline", action="store_true")
    p.add_argument("--write-ledger", action="store_true")
    p.add_argument("--json", action="store_true",
                   help="emit one machine-readable summary line")
    args = p.parse_args(argv)

    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = os.path.dirname(pkg_dir)
    paths = args.paths or [pkg_dir]
    select = set(args.select) if args.select else None
    unknown = (select or set()) - set(ast_lint.RULES) - set(threadlint.RULES)
    if unknown:
        print(f"unknown rule(s): {sorted(unknown)}", file=sys.stderr)
        return 2
    if args.threads and args.no_threads:
        print("--threads and --no-threads are exclusive", file=sys.stderr)
        return 2
    run_lint = not args.threads
    run_threads = not args.no_threads
    if select is not None:
        run_lint = run_lint and bool(select & set(ast_lint.RULES))
        run_threads = run_threads and bool(select & set(threadlint.RULES))

    findings = []
    if run_lint:
        findings += ast_lint.lint_paths(paths, root=root, select=select)
    lease_failures: list[str] = []
    if run_threads:
        findings += threadlint.lint_paths(paths, root=root, select=select)
        if select is None:
            lease_failures = threadlint.audit_lease_protocol(root)

    if args.write_baseline:
        ast_lint.write_baseline(findings, args.baseline)
        print(
            f"wrote {len(findings)} finding(s) to {args.baseline}",
        )
        return 0

    suppressed = 0
    if not args.no_baseline:
        baseline = ast_lint.load_baseline(args.baseline)
        findings, suppressed = ast_lint.apply_baseline(findings, baseline)

    failures: list[str] = []
    warnings: list[str] = []
    if args.write_ledger:
        ledger = jaxpr_audit.build_ledger()
        jaxpr_audit.write_golden(ledger, args.ledger)
        n = len(ledger) - 1
        print(f"wrote {n} kernel ledgers to {args.ledger}")
        dledger = dispatch_audit.build_ledger()
        dispatch_audit.write_golden(dledger)
        print(
            "wrote dispatch budgets "
            f"(fused {dledger['fused']['max_dispatches_per_level']}, "
            f"staged {dledger['staged']['max_dispatches_per_level']} "
            "programs/level; superstep "
            f"{dledger['superstep']['total_dispatches']} programs over "
            f"{dledger['superstep']['levels']} levels at span "
            f"{dledger['superstep']['span']}) to "
            f"{dispatch_audit.DISPATCH_LEDGER_PATH}"
        )
        cledger = cost_audit.build_ledger()
        cost_audit.write_golden(cledger)
        print(
            f"wrote {len(cledger) - 1} kernel cost/memory budgets "
            f"({cledger['_meta']['backend']}/jax "
            f"{cledger['_meta']['jax']}) to "
            f"{cost_audit.COST_LEDGER_PATH}"
        )
        return 0
    run_jaxpr = not args.no_jaxpr and not args.threads
    if run_jaxpr:
        golden = jaxpr_audit.load_golden(args.ledger)
        if golden is None and args.ledger != jaxpr_audit.LEDGER_PATH:
            # an explicit --ledger that doesn't exist is a usage error,
            # not a silent audit against nothing (or the wrong default)
            print(f"--ledger {args.ledger}: no such file", file=sys.stderr)
            return 2
        failures, warnings = jaxpr_audit.audit(golden)
    if run_jaxpr and not args.no_dispatch:
        # GL011: per-level device-dispatch budgets (fused + staged) —
        # measured engine runs, so it rides the same "needs jax" gate
        # as the jaxpr layer plus its own --no-dispatch opt-out
        d_fail, d_warn = dispatch_audit.audit()
        failures += d_fail
        warnings += d_warn
    if run_jaxpr and not args.no_cost:
        # GL013: per-kernel cost/memory budgets — compiled at the same
        # tiny reference shapes the jaxpr audit traces (needs jax)
        c_fail, c_warn = cost_audit.audit()
        failures += c_fail
        warnings += c_warn

    for f in findings:
        print(f.format())
    for w in warnings:
        print(f"warning: jaxpr-audit: {w}")
    for f in lease_failures:
        print(f"FAIL: {f}")
    for f in failures:
        print(f"FAIL: jaxpr-audit: {f}")

    ok = not findings and not failures and not lease_failures
    summary = dict(
        ok=ok,
        findings=len(findings),
        baselined=suppressed,
        lease_failures=len(lease_failures),
        jaxpr_failures=len(failures),
        jaxpr_warnings=len(warnings),
    )
    if args.json:
        print(json.dumps(summary))
    else:
        print(
            f"graftlint: {len(findings)} unwaived finding(s), "
            f"{suppressed} baselined, {len(lease_failures)} lease "
            f"failure(s), {len(failures)} jaxpr failure(s), "
            f"{len(warnings)} warning(s) — "
            + ("OK" if ok else "FAIL")
        )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
