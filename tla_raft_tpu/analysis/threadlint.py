"""graftsync layer 1: thread-boundary static analysis (GL014-GL016).

graftlint (ast_lint.py) pins device hygiene; THIS module pins the host
threads themselves.  Three rules over pure stdlib ``ast`` — no imports
of the linted modules, same contract as graftlint:

* **GL014 unsynced-shared-state** — extract every thread boundary in a
  module (``threading.Thread`` targets, ``*pool*.submit/map``
  callables, ``ThreadPoolExecutor`` initializers), compute the set of
  functions reachable from the thread side, and build the shared-state
  access map: attributes/module globals written on one side of a
  boundary and touched on the other.  An access pair with no COMMON
  lexical lock guard must be covered by a committed
  ``sync_registry.json`` entry (``relpath::Class.attr`` with the
  mechanism + one-line proof) or an inline waiver, else it hard-fails.
* **GL015 lock-order-cycle** — build the global lock-order graph from
  nested ``with lock:`` scopes (including locks taken by callees
  resolved within the module) and hard-fail on cycles: a static
  deadlock detector for the watchdog/hub/prewarmer lock set.
* **GL016 handler-discipline** — ``atexit``/``signal`` handlers and
  ``__del__`` bodies run at interpreter teardown or at arbitrary
  bytecode boundaries; their call closure may set flags and flush
  pre-bound buffers but may not take locks, start threads, or touch
  jax.  Justified exceptions carry a waiver with the proof.

Suppression mirrors graftlint but with its own marker so a waiver is
always attributable to the layer that reviewed it:
``# graftsync: waive[GL016]`` on the finding's line or the comment-only
line above.  Baseline entries ride the same committed
``analysis/baseline.json`` (key = ``rule|path|line-text``).

The same module hosts the **service lease-protocol audit**
(:func:`audit_lease_protocol`): a static state-machine check over
``service/queue.py`` + ``service/daemon.py`` asserting every path out
of a claimed lease releases it, poisons the job, or dies measurably
(stale-lease requeue).  Allowlisted lease-free transitions live in the
same sync registry under ``lease::`` keys.

Known static limits, accepted deliberately: call resolution is
module-local (cross-module attribute sharing is the runtime
sanitizer's job — tsan.py), and guard detection is lexical ``with``
nesting (a callee running entirely under a caller's lock documents
that fact as a registry entry, which is the point: the invariant is
written down where CI can hold it).
"""

from __future__ import annotations

import ast
import json
import os
import re

from .ast_lint import Finding, _dotted, iter_py_files

RULES = {
    "GL014": "unsynced-shared-state: attribute/global crosses a thread "
             "boundary without a common lock, queue hand-off, or "
             "sync_registry entry",
    "GL015": "lock-order-cycle: nested `with lock:` scopes form a "
             "cycle in the global lock-order graph (static deadlock)",
    "GL016": "handler-discipline: signal/atexit/__del__ closure takes "
             "a lock, starts a thread, or touches jax",
}

REGISTRY_PATH = os.path.join(
    os.path.dirname(__file__), "sync_registry.json"
)

_WAIVE_RE = re.compile(r"graftsync:\s*waive\[([A-Za-z0-9*,\s]+)\]")
# attribute/variable names that ARE synchronization objects — excluded
# from shared-state tracking (the lock is the mechanism, not the data)
_LOCK_NAME_RE = re.compile(
    r"lock|mutex|cond|(^|_)cv($|_)|sem($|aphore)", re.IGNORECASE
)
_POOL_OWNER_RE = re.compile(r"pool|executor", re.IGNORECASE)
# threading/queue constructors whose instances are sync objects; an
# attribute bound to one in __init__ is excluded from shared state
_SYNC_CTORS = {
    "Lock", "RLock", "Condition", "Event", "Semaphore",
    "BoundedSemaphore", "Barrier", "Queue", "SimpleQueue", "LifoQueue",
}
# method calls that mutate their receiver — a Load of the receiver
# attribute plus one of these is a WRITE for race purposes
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "remove", "clear", "add", "discard", "update", "setdefault",
    "sort", "reverse", "put", "put_nowait",
}


class _FuncInfo:
    __slots__ = ("node", "name", "cls", "parent")

    def __init__(self, node, name, cls, parent):
        self.node = node
        self.name = name
        self.cls = cls        # enclosing class name or None
        self.parent = parent  # enclosing _FuncInfo or None

    @property
    def qual(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


class _Access:
    __slots__ = ("owner", "name", "write", "held", "node", "fi")

    def __init__(self, owner, name, write, held, node, fi):
        self.owner = owner    # class name for self attrs, None for globals
        self.name = name
        self.write = write
        self.held = held      # frozenset of lock tokens
        self.node = node
        self.fi = fi


class _ModuleThreads:
    """Per-module thread-boundary model: functions, entries, accesses,
    lock scopes.  One instance per linted file."""

    def __init__(self, src: str, path: str, relpath: str):
        self.src = src
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.lines = src.splitlines()
        self.tree = ast.parse(src, filename=path)
        self.findings: list[Finding] = []

        self.funcs: dict[int, _FuncInfo] = {}
        self.methods: dict[tuple[str, str], _FuncInfo] = {}
        self.module_funcs: dict[str, _FuncInfo] = {}
        self.class_names: set[str] = set()
        self._collect_funcs(self.tree, None, None)

        self.module_globals = self._module_globals()
        self.sync_attrs = self._sync_attrs()
        self.pool_bound = self._pool_bound_names()

        # (kind, entry _FuncInfo) — thread-side roots and handler roots
        self.thread_entries: list[tuple[str, _FuncInfo]] = []
        self.handler_entries: list[tuple[str, _FuncInfo]] = []
        self._find_entries()
        self.thread_closure = self._closure(
            [fi for _, fi in self.thread_entries]
        )

        self.accesses: list[_Access] = []
        self._acq_memo: dict[int, set[str]] = {}
        for fi in self.funcs.values():
            self._walk_accesses(fi, fi.node, frozenset())

    # -- structure --------------------------------------------------------

    def _collect_funcs(self, node, cls, parent):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                self.class_names.add(child.name)
                self._collect_funcs(child, child.name, None)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = _FuncInfo(child, child.name, cls, parent)
                self.funcs[id(child)] = fi
                if cls and parent is None:
                    self.methods.setdefault((cls, child.name), fi)
                elif cls is None and parent is None:
                    self.module_funcs.setdefault(child.name, fi)
                self._collect_funcs(child, cls, fi)
            else:
                self._collect_funcs(child, cls, parent)

    def _module_globals(self) -> set[str]:
        out: set[str] = set()
        for node in self.tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        return out

    def _sync_attrs(self) -> dict[str, set[str]]:
        """class -> attribute names bound to a sync-object constructor."""
        out: dict[str, set[str]] = {}
        for fi in self.funcs.values():
            if fi.cls is None:
                continue
            for node in ast.walk(fi.node):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                value = node.value
                if not isinstance(value, ast.Call):
                    continue
                d = _dotted(value.func)
                if not d or d.split(".")[-1] not in _SYNC_CTORS:
                    continue
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        out.setdefault(fi.cls, set()).add(t.attr)
        return out

    def _pool_bound_names(self) -> set[str]:
        """Names bound to an executor constructor (the `as ex:` idiom)."""
        bound: set[str] = set()

        def ctor(call) -> bool:
            if not isinstance(call, ast.Call):
                return False
            d = _dotted(call.func)
            return bool(d) and d.split(".")[-1] in (
                "ThreadPoolExecutor", "ProcessPoolExecutor",
            )

        for node in ast.walk(self.tree):
            if isinstance(node, ast.With):
                for item in node.items:
                    if ctor(item.context_expr) and isinstance(
                        item.optional_vars, ast.Name
                    ):
                        bound.add(item.optional_vars.id)
            elif isinstance(node, ast.Assign) and ctor(node.value):
                for t in node.targets:
                    d = _dotted(t)
                    if d:
                        bound.add(d.split(".")[-1])
        return bound

    def _enclosing(self, node) -> _FuncInfo | None:
        """The innermost _FuncInfo whose body contains ``node``."""
        best = None
        best_span = None
        for fi in self.funcs.values():
            f = fi.node
            if (
                f.lineno <= node.lineno
                and node.lineno <= (f.end_lineno or f.lineno)
            ):
                span = (f.end_lineno or f.lineno) - f.lineno
                if best is None or span < best_span:
                    best, best_span = fi, span
        return best

    def _resolve(self, ref, caller: _FuncInfo | None) -> _FuncInfo | None:
        """Resolve a callable reference to a module-local function."""
        if isinstance(ref, ast.Attribute):
            if (
                isinstance(ref.value, ast.Name)
                and ref.value.id == "self"
                and caller is not None and caller.cls
            ):
                return self.methods.get((caller.cls, ref.attr))
            return None
        if isinstance(ref, ast.Name):
            fi = caller
            while fi is not None:
                for cand in self.funcs.values():
                    if cand.parent is fi and cand.name == ref.id:
                        return cand
                fi = fi.parent
            return self.module_funcs.get(ref.id)
        return None

    # -- boundaries -------------------------------------------------------

    def _find_entries(self):
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func) or ""
            last = d.split(".")[-1]
            caller = self._enclosing(node)
            if last == "Thread" and d in ("Thread", "threading.Thread"):
                for kw in node.keywords:
                    if kw.arg == "target":
                        fi = self._resolve(kw.value, caller)
                        if fi is not None:
                            self.thread_entries.append(("thread", fi))
            elif last in ("ThreadPoolExecutor", "ProcessPoolExecutor"):
                for kw in node.keywords:
                    if kw.arg == "initializer":
                        fi = self._resolve(kw.value, caller)
                        if fi is not None:
                            self.thread_entries.append(("initializer", fi))
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in ("submit", "map")
            ):
                owner = _dotted(node.func.value) or ""
                if (
                    _POOL_OWNER_RE.search(owner)
                    or owner.split(".")[-1] in self.pool_bound
                ) and node.args:
                    fi = self._resolve(node.args[0], caller)
                    if fi is not None:
                        self.thread_entries.append(("pool", fi))
            elif d == "atexit.register" and node.args:
                fi = self._resolve(node.args[0], caller)
                if fi is not None:
                    self.handler_entries.append(("atexit", fi))
            elif d == "signal.signal" and len(node.args) >= 2:
                fi = self._resolve(node.args[1], caller)
                if fi is not None:
                    self.handler_entries.append(("signal", fi))
        for (cls, name), fi in self.methods.items():
            if name == "__del__":
                self.handler_entries.append(("__del__", fi))

    def _closure(self, roots: list[_FuncInfo]) -> set[int]:
        seen: set[int] = set()
        queue = list(roots)
        while queue:
            fi = queue.pop()
            if id(fi.node) in seen:
                continue
            seen.add(id(fi.node))
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Call):
                    callee = self._resolve(node.func, fi)
                    if callee is not None and id(callee.node) not in seen:
                        queue.append(callee)
        return seen

    # -- lock scopes + accesses ------------------------------------------

    def _lock_token(self, expr, fi: _FuncInfo) -> str | None:
        """Normalized lock identity for a `with` context expression."""
        d = _dotted(expr)
        if not d:
            return None
        if d.startswith("self."):
            attr = d[5:]
            cls = fi.cls or "?"
            if _LOCK_NAME_RE.search(attr) or attr in self.sync_attrs.get(
                cls, ()
            ):
                return f"{self.relpath}::{cls}.{attr}"
            return None
        name = d.split(".")[-1]
        if _LOCK_NAME_RE.search(name):
            return f"{self.relpath}::{d}"
        return None

    def _is_lock_name(self, owner_cls: str | None, attr: str) -> bool:
        if _LOCK_NAME_RE.search(attr):
            return True
        if owner_cls is not None:
            return attr in self.sync_attrs.get(owner_cls, ())
        return False

    def _walk_accesses(self, fi: _FuncInfo, node, held: frozenset):
        if isinstance(node, ast.With):
            tokens = set()
            for item in node.items:
                self._walk_accesses(fi, item.context_expr, held)
                tok = self._lock_token(item.context_expr, fi)
                if tok:
                    tokens.add(tok)
            inner = frozenset(held | tokens)
            for b in node.body:
                self._walk_accesses(fi, b, inner)
            return
        self._record(fi, node, held)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue  # separate scope (own _FuncInfo / class body)
            self._walk_accesses(fi, child, held)

    def _record(self, fi: _FuncInfo, node, held: frozenset):
        if isinstance(node, ast.Attribute):
            base = node.value
            owner = None
            if isinstance(base, ast.Name) and base.id == "self":
                owner = fi.cls
            elif (
                isinstance(base, ast.Name)
                and base.id in self.class_names
            ):
                owner = base.id
            if owner is None:
                return
            if self._is_lock_name(owner, node.attr):
                return
            write = isinstance(node.ctx, (ast.Store, ast.Del))
            self.accesses.append(
                _Access(owner, node.attr, write, held, node, fi)
            )
        elif isinstance(node, ast.Call):
            # receiver-mutating method call: self.x.append(...) writes x
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in _MUTATORS
                and isinstance(f.value, ast.Attribute)
            ):
                recv = f.value
                if (
                    isinstance(recv.value, ast.Name)
                    and recv.value.id == "self"
                    and fi.cls
                    and not self._is_lock_name(fi.cls, recv.attr)
                ):
                    self.accesses.append(
                        _Access(fi.cls, recv.attr, True, held, node, fi)
                    )
            elif (
                isinstance(f, ast.Attribute)
                and f.attr in _MUTATORS
                and isinstance(f.value, ast.Name)
                and f.value.id in self.module_globals
            ):
                self.accesses.append(
                    _Access(None, f.value.id, True, held, node, fi)
                )
        elif isinstance(node, ast.Subscript):
            # _FLAGS["x"] = ... mutates the module-global dict
            if (
                isinstance(node.ctx, (ast.Store, ast.Del))
                and isinstance(node.value, ast.Name)
                and node.value.id in self.module_globals
            ):
                self.accesses.append(
                    _Access(None, node.value.id, True, held, node, fi)
                )
        elif isinstance(node, ast.Name):
            if node.id in self.module_globals:
                write = isinstance(node.ctx, (ast.Store, ast.Del))
                if write and not self._declares_global(fi, node.id):
                    return  # local shadowing the module name
                self.accesses.append(
                    _Access(None, node.id, write, held, node, fi)
                )

    def _declares_global(self, fi: _FuncInfo, name: str) -> bool:
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Global) and name in node.names:
                return True
        return False

    # -- GL014 ------------------------------------------------------------

    def gl014(self, registry: dict) -> None:
        if not self.thread_entries:
            return
        entry_names = sorted({fi.qual for _, fi in self.thread_entries})
        by_key: dict[tuple, list[_Access]] = {}
        for a in self.accesses:
            if a.fi.name == "__init__" and a.owner == a.fi.cls:
                continue  # publication before the thread exists
            by_key.setdefault((a.owner, a.name), []).append(a)
        for (owner, name), accs in sorted(
            by_key.items(), key=lambda kv: (str(kv[0][0]), kv[0][1])
        ):
            thr = [a for a in accs if id(a.fi.node) in self.thread_closure]
            main = [
                a for a in accs if id(a.fi.node) not in self.thread_closure
            ]
            if not thr or not main:
                continue
            if not (
                any(a.write for a in thr) or any(a.write for a in main)
            ):
                continue  # read-only after publication
            common = frozenset.intersection(*(a.held for a in accs))
            if common:
                continue  # every access under one shared lock
            what = f"{owner}.{name}" if owner else name
            key = f"{self.relpath}::{what}"
            if key in registry:
                continue
            anchor = next((a for a in accs if not a.held), accs[0])
            self.findings.append(self._finding(
                "GL014", anchor.node,
                f"`{what}` is written across a thread boundary (entries: "
                f"{', '.join(entry_names)}) with no common lock — guard "
                f"every access with one lock, hand it off through a "
                f"queue, or add a sync_registry entry `{key}` with the "
                f"mechanism and proof",
            ))

    # -- GL015 ------------------------------------------------------------

    def _acquires(self, fi: _FuncInfo, stack: set[int]) -> set[str]:
        """Lock tokens fi (or a same-module callee) may take."""
        if id(fi.node) in self._acq_memo:
            return self._acq_memo[id(fi.node)]
        if id(fi.node) in stack:
            return set()
        stack.add(id(fi.node))
        out: set[str] = set()
        for node in ast.walk(fi.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    tok = self._lock_token(item.context_expr, fi)
                    if tok:
                        out.add(tok)
            elif isinstance(node, ast.Call):
                callee = self._resolve(node.func, fi)
                if callee is not None:
                    out |= self._acquires(callee, stack)
        stack.discard(id(fi.node))
        self._acq_memo[id(fi.node)] = out
        return out

    def lock_edges(self) -> dict[tuple[str, str], tuple[str, int, str]]:
        """(held, taken) -> (relpath, line, stripped-line) anchors."""
        edges: dict[tuple[str, str], tuple[str, int, str]] = {}

        def note(a: str, b: str, node):
            if a == b:
                return
            if (a, b) not in edges:
                text = ""
                if 1 <= node.lineno <= len(self.lines):
                    text = self.lines[node.lineno - 1].strip()
                edges[(a, b)] = (self.relpath, node.lineno, text)

        def walk(fi, node, held):
            if isinstance(node, ast.With):
                tokens = set()
                for item in node.items:
                    tok = self._lock_token(item.context_expr, fi)
                    if tok:
                        tokens.add(tok)
                        for h in held:
                            note(h, tok, node)
                for b in node.body:
                    walk(fi, b, held | tokens)
                return
            if isinstance(node, ast.Call) and held:
                callee = self._resolve(node.func, fi)
                if callee is not None:
                    for tok in self._acquires(callee, set()):
                        for h in held:
                            note(h, tok, node)
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr == "acquire":
                    tok = self._lock_token(f.value, fi)
                    if tok:
                        for h in held:
                            note(h, tok, node)
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda, ast.ClassDef)):
                    continue
                walk(fi, child, held)

        for fi in self.funcs.values():
            if fi.parent is None:
                walk(fi, fi.node, frozenset())
        return edges

    # -- GL016 ------------------------------------------------------------

    def gl016(self) -> None:
        for kind, entry in self.handler_entries:
            closure = self._closure([entry])
            for fi in self.funcs.values():
                if id(fi.node) not in closure:
                    continue
                self._gl016_scan(kind, entry, fi)

    def _gl016_scan(self, kind: str, entry: _FuncInfo, fi: _FuncInfo):
        where = (
            f"`{fi.qual}` (reached from {kind} handler `{entry.qual}`)"
            if fi is not entry else f"{kind} handler `{entry.qual}`"
        )
        for node in ast.walk(fi.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    tok = self._lock_token(item.context_expr, fi)
                    if tok:
                        self.findings.append(self._finding(
                            "GL016", item.context_expr,
                            f"{where} takes `{tok.split('::')[-1]}` — a "
                            "handler blocking on a lock the interrupted "
                            "thread holds deadlocks teardown; set a "
                            "flag instead, or waive with the proof the "
                            "holder always releases",
                        ))
            elif isinstance(node, ast.Call):
                f = node.func
                d = _dotted(f) or ""
                if isinstance(f, ast.Attribute) and f.attr == "acquire":
                    tok = self._lock_token(f.value, fi)
                    if tok:
                        self.findings.append(self._finding(
                            "GL016", node,
                            f"{where} calls `.acquire()` on "
                            f"`{tok.split('::')[-1]}` — handlers must "
                            "not block on locks",
                        ))
                elif d in ("Thread", "threading.Thread"):
                    self.findings.append(self._finding(
                        "GL016", node,
                        f"{where} starts a thread — interpreter "
                        "teardown will not wait for it; handlers may "
                        "only flush pre-bound state",
                    ))
                elif d and d.split(".")[0] in ("jax", "jnp"):
                    self.findings.append(self._finding(
                        "GL016", node,
                        f"{where} touches `{d}` — device work from a "
                        "handler re-enters a runtime that may already "
                        "be tearing down",
                    ))

    # -- shared -----------------------------------------------------------

    def _finding(self, rule: str, node, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        text = ""
        if 1 <= line <= len(self.lines):
            text = self.lines[line - 1].strip()
        return Finding(rule, self.relpath, line, col, message, text)

    def apply_waivers(self, findings: list[Finding]) -> list[Finding]:
        waivers: dict[int, set[str]] = {}
        comment_only: set[int] = set()
        for i, line in enumerate(self.lines, start=1):
            m = _WAIVE_RE.search(line)
            if m:
                waivers[i] = {t.strip() for t in m.group(1).split(",")}
                if line.strip().startswith("#"):
                    comment_only.add(i)
        if not waivers:
            return findings

        def waived(f: Finding) -> bool:
            rules = waivers.get(f.line)
            if rules and (f.rule in rules or "*" in rules):
                return True
            if f.line - 1 in comment_only:
                rules = waivers[f.line - 1]
                return f.rule in rules or "*" in rules
            return False

        return [f for f in findings if not waived(f)]


# -- registry -------------------------------------------------------------

def load_registry(path: str = REGISTRY_PATH) -> dict[str, dict]:
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    return dict(data.get("entries", {}))


# -- driver ---------------------------------------------------------------

def lint_source(
    src: str, path: str = "<string>", relpath: str | None = None,
    select: set[str] | None = None, registry: dict | None = None,
) -> list[Finding]:
    """Lint ONE module (GL014 + GL016 + module-local GL015 cycles);
    graftsync waivers applied, baseline NOT applied."""
    mod = _ModuleThreads(src, path, relpath or path)
    reg = load_registry() if registry is None else registry
    if select is None or "GL014" in select:
        mod.gl014(reg)
    if select is None or "GL016" in select:
        mod.gl016()
    findings = list(mod.findings)
    if select is None or "GL015" in select:
        findings += _cycle_findings(mod.lock_edges())
    return mod.apply_waivers(findings)


def _cycle_findings(
    edges: dict[tuple[str, str], tuple[str, int, str]]
) -> list[Finding]:
    graph: dict[str, set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    seen_cycles: set[frozenset] = set()
    findings: list[Finding] = []

    def dfs(node, stack, on_stack, visited):
        visited.add(node)
        on_stack.add(node)
        stack.append(node)
        for nxt in sorted(graph[node]):
            if nxt in on_stack:
                cycle = stack[stack.index(nxt):] + [nxt]
                key = frozenset(cycle)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    findings.append(_cycle_finding(cycle, edges))
            elif nxt not in visited:
                dfs(nxt, stack, on_stack, visited)
        stack.pop()
        on_stack.discard(node)

    visited: set[str] = set()
    for node in sorted(graph):
        if node not in visited:
            dfs(node, [], set(), visited)
    return findings


def _cycle_finding(cycle, edges) -> Finding:
    pairs = list(zip(cycle, cycle[1:]))
    anchors = [edges[p] for p in pairs if p in edges]
    path, line, text = min(anchors) if anchors else ("<unknown>", 1, "")
    pretty = " -> ".join(n.split("::")[-1] for n in cycle)
    sites = ", ".join(f"{p}:{ln}" for p, ln, _ in sorted(anchors))
    return Finding(
        "GL015", path, line, 0,
        f"lock-order cycle {pretty} (take sites: {sites}) — two "
        "threads entering from opposite ends deadlock; impose one "
        "global order or narrow a critical section",
        text,
    )


def lint_paths(
    paths: list[str], root: str | None = None,
    select: set[str] | None = None, registry: dict | None = None,
) -> list[Finding]:
    """Lint files/trees with the full cross-module GL015 graph."""
    if root is None:
        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    reg = load_registry() if registry is None else registry
    findings: list[Finding] = []
    mods: list[_ModuleThreads] = []
    for f in iter_py_files(paths):
        with open(f, encoding="utf-8") as fh:
            src = fh.read()
        rel = os.path.relpath(os.path.abspath(f), root)
        mod = _ModuleThreads(src, f, rel)
        if select is None or "GL014" in select:
            mod.gl014(reg)
        if select is None or "GL016" in select:
            mod.gl016()
        mods.append(mod)
    if select is None or "GL015" in select:
        edges: dict[tuple[str, str], tuple[str, int, str]] = {}
        for mod in mods:
            for k, v in mod.lock_edges().items():
                edges.setdefault(k, v)
        by_path = {m.relpath: m for m in mods}
        for f in _cycle_findings(edges):
            anchor = by_path.get(f.path)
            if anchor is None or anchor.apply_waivers([f]):
                findings.append(f)
    for mod in mods:
        findings.extend(mod.apply_waivers(mod.findings))
    return findings


# -- service lease-protocol audit ----------------------------------------

_TERMINAL_STATES = {"done", "failed", "submitted"}


def audit_lease_protocol(
    root: str | None = None, registry: dict | None = None,
) -> list[str]:
    """Static state-machine audit of the job-queue lease protocol.

    Asserts the structural invariants every fleet worker's liveness
    rests on: claims are exclusive (O_EXCL), every terminal transition
    out of a claimed lease releases the lease file (or is an
    allowlisted lease-free transition under a ``lease::`` registry
    key), stale leases are measurably requeued or poisoned, and every
    daemon-side claim/preemption path releases what it claimed.
    Returns a list of failure strings (empty = protocol holds).
    """
    if root is None:
        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    reg = load_registry() if registry is None else registry
    failures: list[str] = []

    qpath = os.path.join(root, "tla_raft_tpu", "service", "queue.py")
    dpath = os.path.join(root, "tla_raft_tpu", "service", "daemon.py")
    if not os.path.exists(qpath):
        return [f"lease-audit: {qpath} missing"]

    with open(qpath, encoding="utf-8") as fh:
        qtree = ast.parse(fh.read(), filename=qpath)
    methods = _class_methods(qtree)

    def has_call(fn, dotted_suffix: str) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                d = _dotted(node.func) or ""
                if d == dotted_suffix or d.endswith("." + dotted_suffix):
                    return True
        return False

    def mentions(fn, name: str) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Attribute) and node.attr == name:
                return True
            if isinstance(node, ast.Name) and node.id == name:
                return True
            if isinstance(node, ast.Constant) and node.value == name:
                return True
        return False

    claim = methods.get("claim")
    if claim is None:
        failures.append("lease-audit: queue has no claim() method")
    else:
        excl = any(
            isinstance(n, ast.Attribute) and n.attr == "O_EXCL"
            for n in ast.walk(claim)
        )
        if not excl:
            failures.append(
                "lease-audit: claim() does not create the lease with "
                "os.O_EXCL — two workers can claim one job"
            )
    for name in ("complete", "release"):
        fn = methods.get(name)
        if fn is None:
            failures.append(f"lease-audit: queue has no {name}() method")
        elif not (mentions(fn, "_lease_path") and has_call(fn, "unlink")):
            failures.append(
                f"lease-audit: {name}() does not unlink the lease — a "
                "finished job would pin its claim forever"
            )
    rq = methods.get("requeue_stale")
    if rq is None:
        failures.append("lease-audit: queue has no requeue_stale()")
    else:
        if not mentions(rq, "_poison"):
            failures.append(
                "lease-audit: requeue_stale() never poisons — a "
                "crash-looping job would requeue forever"
            )
        if not mentions(rq, "max_attempts"):
            failures.append(
                "lease-audit: requeue_stale() ignores max_attempts"
            )
    poison = methods.get("_poison")
    if poison is not None and not mentions(poison, "failed"):
        failures.append(
            "lease-audit: _poison() does not record the 'failed' state"
        )

    # terminal _set_state transitions must also touch the lease (or be
    # allowlisted as lease-free under a `lease::` registry key)
    for name, fn in methods.items():
        if name == "_set_state":
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func) or ""
            if not d.endswith("_set_state"):
                continue
            states = [
                a.value for a in node.args
                if isinstance(a, ast.Constant)
                and a.value in _TERMINAL_STATES
            ]
            if not states:
                continue
            key = f"lease::queue.{name}"
            if key in reg:
                continue
            if mentions(fn, "_lease_path") or has_call(fn, "unlink"):
                continue
            failures.append(
                f"lease-audit: queue.{name}() moves a job to "
                f"{states[0]!r} without touching its lease — add the "
                f"release/unlink, or allowlist `{key}` in "
                "sync_registry.json with the proof no lease exists"
            )

    if os.path.exists(dpath):
        with open(dpath, encoding="utf-8") as fh:
            dtree = ast.parse(fh.read(), filename=dpath)
        dmethods = _class_methods(dtree)
        for name, fn in dmethods.items():
            if not has_call(fn, "claim"):
                continue
            key = f"lease::daemon.{name}"
            if key in reg:
                continue
            if not (
                has_call(fn, "complete") or has_call(fn, "release")
                or has_call(fn, "_run_one")
            ):
                failures.append(
                    f"lease-audit: daemon.{name}() claims but has no "
                    "complete/release path — a worker crash there "
                    "strands the lease until staleness"
                )
        for name, fn in dmethods.items():
            for node in ast.walk(fn):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                t = node.type
                names = []
                for sub in ast.walk(t) if t is not None else []:
                    d = _dotted(sub)
                    if d:
                        names.append(d.split(".")[-1])
                if "Preempted" not in names:
                    continue
                if not any(
                    isinstance(c, ast.Call)
                    and (_dotted(c.func) or "").endswith("release")
                    for b in node.body for c in ast.walk(b)
                ) and has_call(fn, "claim"):
                    failures.append(
                        f"lease-audit: daemon.{name}() catches "
                        "Preempted after claiming without releasing — "
                        "the preempted worker strands its lease"
                    )
    return failures


def _class_methods(tree: ast.Module) -> dict[str, ast.FunctionDef]:
    out: dict[str, ast.FunctionDef] = {}
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.setdefault(sub.name, sub)
    return out
