"""graftlint GL011: per-level device-dispatch budget audit.

GL010 froze the MXU rewrite's gather win at the jaxpr level; this rule
freezes the megakernel's FUSION win at the runtime level: the number
of device programs a steady-state BFS level dispatches is measured on
the tiny reference config (the same S2V1E1R1 space the jaxpr audit
traces) for BOTH paths — the fused whole-level program and the staged
program chain — and diffed against a committed budget ledger
(``dispatch_ledger.json``).  Exceeding a budget is a hard failure: one
extra program per level is exactly the silent-regression class that
erodes the dispatch-floor win a few milliseconds at a time
(docs/PERF.md "the chunk cost is ~38 ms fixed").

Measurement is choke-point accounting: the engines note every device
program their level loops launch (``analysis.sanitize.note_dispatch``
— the same honest scope as the GL006 host-sync ledger; eager-op
dispatches are out of scope by design), and the per-level counters are
collected through a lightweight :class:`~.sanitize.DispatchLog`
without arming the full runtime sanitizer.  The steady-state metric is
the WORST post-warmup level, so a budget of 1 for the fused path means
literally every steady-state level ran as one device program.

Regenerate with ``python -m tla_raft_tpu.analysis --write-ledger``
(written next to the jaxpr golden ledger) and justify the diff in the
PR; measuring fewer dispatches than budgeted is reported as the
"regenerate and bank the win" warning, mirroring GL010.
"""

from __future__ import annotations

import json
import os

DISPATCH_LEDGER_PATH = os.path.join(
    os.path.dirname(__file__), "dispatch_ledger.json"
)

# post-warmup window: the first levels of the tiny config compile the
# shape ladder and run pre-loop init programs; the budget applies to
# the steady-state tail
WARMUP_LEVELS = 2

# the span the superstep arm measures at (the engine default)
SUPERSTEP_SPAN = 4


def _tiny_cfg():
    from ..config import RaftConfig

    # the jaxpr audit's reference space: 50 states, depth 12 — deep
    # enough that the steady-state tail is real, small enough that both
    # measured runs cost seconds
    return RaftConfig(
        n_servers=2, n_vals=1, max_election=1, max_restart=1,
    )


def measure(megakernel: bool, superstep: int = 1) -> dict:
    """One measured run -> the per-level dispatch profile.

    ``superstep`` pins the multi-level span: the fused/staged arms
    measure the PER-LEVEL paths (span 1) regardless of the ambient
    TLA_RAFT_SUPERSTEP, and the superstep arm measures the resident
    driver at its declared span."""
    from ..engine import JaxChecker
    from .sanitize import DispatchLog, set_dispatch_sink

    log = DispatchLog()
    set_dispatch_sink(log)
    # hashstore pinned ON and orbit pinned OFF: the fused path requires
    # the former and is disabled by the latter, and the budgets must
    # not depend on the caller's ambient env (an ambient
    # TLA_RAFT_ORBIT=1 would silently measure the staged chain as the
    # "fused" arm and fail GL011 with a bogus regression)
    orb = os.environ.pop("TLA_RAFT_ORBIT", None)
    try:
        res = JaxChecker(
            _tiny_cfg(), chunk=64, megakernel=megakernel,
            use_hashstore=True, superstep=superstep,
        ).run()
    finally:
        set_dispatch_sink(None)
        if orb is not None:
            os.environ["TLA_RAFT_ORBIT"] = orb
    log.close()
    out = dict(
        max_dispatches_per_level=log.steady_max(WARMUP_LEVELS),
        levels=len(log.per_level),
        total_dispatches=log.total,
        distinct=res.distinct,
        depth=res.depth,
    )
    if superstep > 1:
        # the superstep budgets: worst dispatches per superstep window
        # (the 1-dispatch claim) and the total-dispatch count for the
        # whole run (the amortized <= 1/N-per-level claim — levels and
        # stops are deterministic on the tiny config, so the total is
        # an exact pin, not a tolerance)
        out["span"] = superstep
        out["supersteps"] = len(log.per_superstep)
        out["superstep_levels"] = int(sum(log.superstep_levels))
        out["max_dispatches_per_superstep"] = log.steady_max_superstep()
    return out


def build_ledger() -> dict:
    import jax

    return {
        "_meta": {
            "jax": jax.__version__,
            "config": "S2V1E1R1",
            "warmup_levels": WARMUP_LEVELS,
            "metric": "worst post-warmup dispatches/level "
                      "(engine-declared program dispatches); the "
                      "superstep arm adds dispatches/superstep and "
                      "the amortized total",
        },
        "fused": measure(True),
        "staged": measure(False),
        "superstep": measure(True, superstep=SUPERSTEP_SPAN),
    }


def load_golden(path: str = DISPATCH_LEDGER_PATH) -> dict | None:
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def write_golden(ledger: dict, path: str = DISPATCH_LEDGER_PATH):
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(ledger, fh, indent=1, sort_keys=True)
        fh.write("\n")


def audit(golden=None) -> tuple[list[str], list[str]]:
    """Run the GL011 audit; returns (failures, warnings)."""
    failures: list[str] = []
    warnings: list[str] = []
    if golden is None:
        golden = load_golden()
    if golden is None:
        warnings.append(
            "[GL011] no dispatch ledger committed — run `python -m "
            "tla_raft_tpu.analysis --write-ledger` and commit "
            "dispatch_ledger.json"
        )
        return failures, warnings
    for arm in ("fused", "staged", "superstep"):
        gold = golden.get(arm)
        if gold is None:
            failures.append(
                f"[GL011] dispatch ledger has no '{arm}' entry — "
                "regenerate with --write-ledger"
            )
            continue
        cur = measure(
            arm != "staged",
            superstep=(
                gold.get("span", SUPERSTEP_SPAN)
                if arm == "superstep" else 1
            ),
        )
        if cur["distinct"] != gold["distinct"]:
            failures.append(
                f"[GL011] {arm}: measured run found {cur['distinct']} "
                f"distinct states, ledger pinned {gold['distinct']} — "
                "the measurement config drifted; fix before trusting "
                "the dispatch budget"
            )
            continue
        budget = gold["max_dispatches_per_level"]
        got = cur["max_dispatches_per_level"]
        if got > budget:
            failures.append(
                f"[GL011] {arm}: worst steady-state level dispatched "
                f"{got} device program(s), over the ledgered budget "
                f"{budget} — the level loop regressed onto the "
                "dispatch floor (docs/PERF.md); fuse the new program "
                "back in or justify a new budget with --write-ledger"
            )
        elif got < budget:
            warnings.append(
                f"[GL011] {arm}: worst steady-state level dispatched "
                f"{got} program(s), under the ledgered budget {budget} "
                "— regenerate with --write-ledger and bank the win"
            )
        if arm != "superstep":
            continue
        # superstep budgets: every window must stay ONE program, and
        # the run's amortized dispatch total (which encodes the
        # <= 1/N-per-level steady state — the tiny run is
        # deterministic, so the total is exact) must not grow
        ss_budget = gold.get("max_dispatches_per_superstep", 1)
        ss_got = cur.get("max_dispatches_per_superstep", 0)
        if ss_got > ss_budget:
            failures.append(
                f"[GL011] superstep: a window dispatched {ss_got} "
                f"device program(s), over the ledgered budget "
                f"{ss_budget} — the multi-level driver regressed to "
                "multiple programs per superstep"
            )
        tot_budget = gold.get("total_dispatches")
        if tot_budget is not None and cur["total_dispatches"] > tot_budget:
            failures.append(
                f"[GL011] superstep: the measured run dispatched "
                f"{cur['total_dispatches']} programs over "
                f"{cur['levels']} levels, above the ledgered "
                f"{tot_budget} — the amortized dispatches/level "
                "regressed from the 1/N steady state"
            )
        elif tot_budget is not None and cur["total_dispatches"] < tot_budget:
            warnings.append(
                "[GL011] superstep: fewer total dispatches than "
                "ledgered — regenerate with --write-ledger and bank "
                "the win"
            )
    return failures, warnings
