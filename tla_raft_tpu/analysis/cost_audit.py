"""graftlint GL013: per-kernel XLA cost/memory budget audit.

GL010 froze the gather win at the jaxpr level and GL011 froze the
fusion win at the dispatch level; this rule freezes the COST level:
every registered hot kernel is lowered + compiled at the audit's tiny
reference shapes (the same S2V1E1R1 space the jaxpr audit traces), its
``cost_analysis()`` + ``memory_analysis()`` harvested
(analysis/devprof.py), and the result diffed against a committed
ledger (``cost_ledger.json``, beside ``golden_ledger.json``).  A hot
kernel whose FLOPs, bytes accessed or temp-HBM exceed the ledgered
budget (plus a small slack) is a HARD failure on the generating
backend+jax version: a regression in any of the three is exactly the
silent-perf-drift class docs/PERF.md fought one incident at a time —
the ~750 GB/chunk coefficient-gather reads (Round 1) were FOUND via
cost_analysis, and the 4.3 GB materialize temp blow-up (Finding 5) via
memory_analysis; this rule turns those one-off profiler sessions into
a committed, CI-diffed gate.

Cross-version/backend runs demote the diff to warnings (XLA's cost
model and lowering legitimately drift across releases and backends)
while keeping the harvest itself exercised.  Shrinking below budget
past the slack trips the "regenerate and bank the win" warning,
mirroring GL010/GL011.  Regenerate with
``python -m tla_raft_tpu.analysis --write-ledger`` and justify the
diff in the PR.
"""

from __future__ import annotations

import json
import os

from . import devprof

COST_LEDGER_PATH = os.path.join(
    os.path.dirname(__file__), "cost_ledger.json"
)

# the budget metrics and their relative slack: flops/bytes are
# deterministic for one backend+version (slack absorbs sub-% cost-model
# jitter); temp allocation depends on buffer-assignment heuristics and
# gets more headroom
BUDGETS = {
    "flops": 0.02,
    "bytes": 0.02,
    "tmp_b": 0.10,
}


def _tiny_cfg():
    from ..config import RaftConfig

    # the jaxpr/dispatch audits' reference space (50 states, depth 12)
    return RaftConfig(
        n_servers=2, n_vals=1, max_election=1, max_restart=1,
    )


def compiled_registry():
    """name -> zero-arg callable returning a COMPILED executable at the
    audit's tiny reference shapes.

    Covers the program-build sites the device-cost observatory
    harvests at runtime: the fused whole-level megakernel, the
    multi-level superstep driver, the hashstore probe kernels, the MXU
    expand pair (guards + materialize) with the dense-expand core, and
    the tiered store's compaction program."""
    import jax
    import jax.numpy as jnp

    from ..engine import megakernel as megakernel_mod
    from ..engine import superstep as superstep_mod
    from ..engine.bfs import JaxChecker
    from ..models.raft import init_batch
    from ..ops import hashstore
    from ..ops import sieve as sieve_mod
    from ..ops.successor import get_kernel
    from ..store import tiered as tiered_mod

    cfg = _tiny_cfg()
    kern = get_kernel(cfg, mxu=True)
    st = init_batch(cfg, 8)
    slots = jnp.zeros((8,), jnp.int64)
    fps = jnp.zeros((256,), jnp.uint64)
    slab = jnp.zeros((hashstore.MIN_CAP,), jnp.uint64)
    pays = jnp.zeros((256,), jnp.int64)
    msum = kern.fpr.msg_hash(st.msgs)

    def _compile(fn, *args, **statics):
        return jax.jit(
            fn, static_argnames=tuple(statics) or None
        ).lower(*args, **statics).compile()

    def _mega():
        eng = JaxChecker(cfg, chunk=64, use_hashstore=True,
                         megakernel=True)
        fr0, _ovf = eng._deflate(init_batch(cfg, 1))
        fr = eng._frontier_struct(fr0, 64)
        prog = megakernel_mod.build_level_program(eng, donate=False)
        return prog.lower(
            fr, jax.ShapeDtypeStruct((hashstore.MIN_CAP,), jnp.uint64),
            jax.ShapeDtypeStruct((), jnp.int64),
            jax.ShapeDtypeStruct((1,), jnp.uint64), cap_out=64,
        ).compile()

    def _sstep():
        eng = JaxChecker(cfg, chunk=64, use_hashstore=True,
                         megakernel=True)
        fr0, _ovf = eng._deflate(init_batch(cfg, 1))
        fr = eng._frontier_struct(fr0, 64)
        prog = superstep_mod.build_superstep_program(
            eng, span=2, donate=False
        )
        s_i64 = jax.ShapeDtypeStruct((), jnp.int64)
        return prog.lower(
            fr, jax.ShapeDtypeStruct((hashstore.MIN_CAP,), jnp.uint64),
            s_i64, s_i64, jax.ShapeDtypeStruct((1,), jnp.uint64),
            cap_f=64, ring=128,
        ).compile()

    def _tiered():
        eng = JaxChecker(cfg, chunk=64, use_hashstore=True)
        fr0, _ovf = eng._deflate(init_batch(cfg, 1))
        fr = eng._frontier_struct(fr0, 64)
        return jax.jit(tiered_mod.drop_rows_impl).lower(
            fr, jax.ShapeDtypeStruct((64,), jnp.bool_),
            jax.ShapeDtypeStruct((), jnp.int64),
        ).compile()

    return {
        "successor.expand_guards":
            lambda: _compile(kern.expand_guards, st),
        "successor.materialize":
            lambda: _compile(kern.materialize, st, slots),
        "dense.expand":
            lambda: _compile(kern.expand, st, msum),
        "hashstore.probe":
            lambda: _compile(hashstore.probe_impl, slab, fps),
        "hashstore.probe_and_insert":
            lambda: _compile(
                hashstore.probe_and_insert_impl, slab, fps, fps, pays
            ),
        "engine.megakernel_level": _mega,
        "engine.superstep": _sstep,
        "store.tiered_compact": _tiered,
        "ops.sieve_probe":
            lambda: _compile(
                sieve_mod.probe_impl, jnp.zeros((64,), jnp.uint64), fps
            ),
    }


def build_ledger() -> dict:
    import jax

    ledger = {
        "_meta": {
            "jax": jax.__version__,
            "backend": jax.default_backend(),
            "config": "S2V1E1R1",
            "metrics": list(devprof.METRIC_KEYS),
            "budgets": {k: f"+{int(v * 100)}%" for k, v in
                        BUDGETS.items()},
        }
    }
    for name, make in compiled_registry().items():
        metrics = devprof.harvest_compiled(make())
        if metrics is None:
            metrics = dict.fromkeys(devprof.METRIC_KEYS, 0)
        metrics["peak_b"] = devprof.peak_bytes(metrics)
        ledger[name] = metrics
    return ledger


def load_golden(path: str = COST_LEDGER_PATH) -> dict | None:
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def write_golden(ledger: dict, path: str = COST_LEDGER_PATH):
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(ledger, fh, indent=1, sort_keys=True)
        fh.write("\n")


def diff_entry(name: str, gold: dict, cur: dict
               ) -> tuple[list[str], list[str]]:
    """(over-budget failures, bank-the-win warnings) for one kernel."""
    failures: list[str] = []
    warnings: list[str] = []
    for metric, slack in BUDGETS.items():
        g = float(gold.get(metric, 0) or 0)
        c = float(cur.get(metric, 0) or 0)
        if g <= 0:
            # a zero budget is exact: any appearance is a regression
            # (e.g. a temp-free kernel growing temps)
            if c > 0:
                failures.append(
                    f"[GL013] {name}: {metric} regressed 0 -> {c:,.0f}"
                    " — the kernel grew a cost class it did not have; "
                    "justify a new budget with --write-ledger"
                )
            continue
        if c > g * (1.0 + slack):
            failures.append(
                f"[GL013] {name}: {metric} {c:,.0f} exceeds the "
                f"ledgered budget {g:,.0f} (+{100 * slack:.0f}% slack)"
                " — the hot kernel's device cost regressed "
                "(docs/PERF.md); fix the kernel or justify a new "
                "budget with --write-ledger"
            )
        elif c < g * (1.0 - slack):
            warnings.append(
                f"[GL013] {name}: {metric} {c:,.0f} is under the "
                f"ledgered {g:,.0f} — regenerate with --write-ledger "
                "and bank the win"
            )
    return failures, warnings


def audit(golden=None, current: dict | None = None
          ) -> tuple[list[str], list[str]]:
    """Run the GL013 audit; returns (failures, warnings).

    Hard on the ledger's own backend + jax version; demoted to
    warnings when either differs (cost models drift across releases
    and backends, and a TPU ledger must not fail a CPU CI box)."""
    import jax

    failures: list[str] = []
    warnings: list[str] = []
    if golden is None:
        golden = load_golden()
    if golden is None:
        warnings.append(
            "[GL013] no cost ledger committed — run `python -m "
            "tla_raft_tpu.analysis --write-ledger` and commit "
            "cost_ledger.json"
        )
        return failures, warnings
    if current is None:
        current = build_ledger()
    meta = golden.get("_meta", {})
    same_env = (
        meta.get("jax") == jax.__version__
        and meta.get("backend") == jax.default_backend()
    )
    sink = failures if same_env else warnings
    for name, cur in current.items():
        if name == "_meta":
            continue
        gold = golden.get(name)
        if gold is None:
            sink.append(
                f"[GL013] {name}: kernel missing from the cost ledger "
                "— regenerate with --write-ledger"
            )
            continue
        f, w = diff_entry(name, gold, cur)
        sink.extend(f)
        warnings.extend(w)
    for name in golden:
        if name != "_meta" and name not in current:
            sink.append(
                f"[GL013] {name}: in the cost ledger but no longer "
                "registered"
            )
    if not same_env:
        warnings.append(
            f"[GL013] cost ledger was generated on "
            f"{meta.get('backend')}/jax {meta.get('jax')}, running "
            f"{jax.default_backend()}/jax {jax.__version__} — budget "
            "diff demoted to warnings"
        )
    return failures, warnings
