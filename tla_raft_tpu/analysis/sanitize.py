"""graftlint layer 3: runtime sanitizer for check runs (GRAFT_SANITIZE=1).

Three runtime ledgers the static layers cannot see:

* **host-transfer ledger** — explicit ``jax.device_get``/``device_put``
  are wrapped to count calls and bytes (the *intended* syncs); implicit
  device->host conversions (``bool()``/``int()``/``float()``/
  ``np.asarray`` on a device array — the *accidental* syncs that stall
  the dispatch pipeline mid-level) raise at the offending site (strict,
  default) or are counted (GRAFT_SANITIZE_STRICT=0).  ``jax``'s own
  ``transfer_guard`` is also armed, but it is a no-op on the CPU
  backend (host arrays are zero-copy), so the dunder interception is
  what makes the guarantee portable to the virtual-mesh CI.
* **compile-count ledger** — every XLA backend compile is counted via
  the jax monitoring events.  The engines tick the sanitizer once per
  BFS level and declare shape events (capacity growth, presize, new
  program shapes); a compile in a post-warmup level with NO declared
  shape event is a violation — that is precisely the "one silent
  retrace per level erases the kernel wins" regression class.
* **dispatch-thread guard** — worker threads marked by
  :func:`forbid_device_dispatch_in_thread` (the sharded checker's
  ``_io_pool``/``_ck_pool`` initializers do this unconditionally) must
  never reach a device dispatch: concurrently dispatched collectives
  interleave differently across devices and deadlock the mesh
  rendezvous (the PR 1 deep-tail incident).  The marking is always on
  and costs one thread-local read; under the sanitizer the wrapped
  ``device_get``/``device_put`` also assert it.

Module import is stdlib-only (device-free import contract); jax is
imported lazily when a :class:`Sanitizer` is entered.
"""

from __future__ import annotations

import os
import threading

# telemetry hub (obs/telemetry.py, stdlib-only): the dispatch /
# superstep / shape hooks below are ALREADY the choke points every
# level loop calls, so the flight recorder publishes from here instead
# of adding a second set of call sites to the engines
from ..obs import telemetry as _obs

_tl = threading.local()

# the active sanitizer (None = every hook below is a cheap no-op)
CURRENT: "Sanitizer | None" = None


# -- always-on dispatch-thread guard --------------------------------------

def forbid_device_dispatch_in_thread() -> None:
    """Mark the CURRENT thread as never-dispatching (pool initializer)."""
    _tl.no_dispatch = True


def device_dispatch_forbidden() -> bool:
    return getattr(_tl, "no_dispatch", False)


def assert_device_dispatch_ok(what: str = "device dispatch") -> None:
    """Raise if called from a thread marked no-dispatch.

    Cheap enough to be always on (one thread-local read): guards the
    program-dispatch helpers of parallel/sharded.py against a worker
    thread ever launching a device program."""
    if getattr(_tl, "no_dispatch", False):
        if CURRENT is not None:
            CURRENT.n_worker_dispatch += 1
        raise RuntimeError(
            f"graftlint: {what} from worker thread "
            f"{threading.current_thread().name!r} — worker threads must "
            "never launch device programs (concurrent collectives "
            "deadlock the mesh rendezvous; do the dispatch on the main "
            "thread and hand workers numpy buffers)"
        )


def mark_thread_compiles_declared() -> None:
    """Mark the CURRENT thread's XLA compiles as declared.

    The AOT prewarm thread (engine/pipeline.Prewarmer) calls this once:
    its compiles are the POINT of the thread, so the compile listener
    books them to the prewarm ledger instead of the per-level
    silent-retrace check (which audits the main dispatch thread)."""
    _tl.declared_compiles = True


def thread_compiles_declared() -> bool:
    return getattr(_tl, "declared_compiles", False)


class DispatchLog:
    """Lightweight per-level engine-dispatch counter.

    The engines note every device PROGRAM dispatch of their level loops
    at the call site (choke-point accounting, like the GL006 host-sync
    ledger — eager op dispatches are out of scope by design), and tick
    the level boundary through :func:`level_tick`.  Consumed by the
    GL011 dispatch-budget audit (analysis/dispatch_audit.py) and the
    bench's dispatches/level report without arming the full Sanitizer.
    """

    def __init__(self):
        self.total = 0
        self._cur = 0
        self.per_level: list[int] = []
        self.tags: dict[str, int] = {}
        # per-superstep accounting (engine/superstep.py): one entry per
        # superstep dispatch window — (programs dispatched, levels
        # covered).  The GL011 superstep budget and the bench's
        # levels_per_dispatch stat read these.
        self.per_superstep: list[int] = []
        self.superstep_levels: list[int] = []
        self._ss_mark: int | None = None

    def note(self, tag: str) -> None:
        self.total += 1
        self._cur += 1
        self.tags[tag] = self.tags.get(tag, 0) + 1

    def tick(self) -> None:
        self.per_level.append(self._cur)
        self._cur = 0

    def superstep_begin(self) -> None:
        self._ss_mark = self.total

    def superstep_tick(self, levels: int) -> None:
        mark = self._ss_mark if self._ss_mark is not None else self.total
        self.per_superstep.append(self.total - mark)
        self.superstep_levels.append(int(levels))
        self._ss_mark = None

    def steady_max_superstep(self) -> int:
        """Worst dispatches/superstep (each window is post-compile by
        construction — the dispatch count is shape-independent)."""
        return max(self.per_superstep) if self.per_superstep else 0

    def close(self) -> None:
        """Fold a trailing partial level (the fixpoint-discovery level
        never reaches the engine's tick) into the ledger."""
        if self._cur:
            self.tick()

    def steady_max(self, warmup: int = 2) -> int:
        """Worst dispatches/level past the compile-warmup prefix."""
        per = self.per_level[warmup:] or self.per_level
        return max(per) if per else 0


_DISPATCH_SINK: DispatchLog | None = None


def set_dispatch_sink(sink: DispatchLog | None) -> None:
    """Attach a :class:`DispatchLog` (bench / GL011 measurement)."""
    global _DISPATCH_SINK
    _DISPATCH_SINK = sink


def dispatch_sink() -> DispatchLog | None:
    return _DISPATCH_SINK


def tracking() -> bool:
    """Is any per-level ledger (sanitizer or dispatch sink) active?"""
    return CURRENT is not None or _DISPATCH_SINK is not None


def note_dispatch(tag: str) -> None:
    """Engines note one device-program dispatch of the level loop."""
    if CURRENT is not None:
        CURRENT.note_dispatch(tag)
    if _DISPATCH_SINK is not None:
        _DISPATCH_SINK.note(tag)
    _obs.dispatch(tag)


def superstep_begin() -> None:
    """The engine is about to dispatch one multi-level superstep."""
    if CURRENT is not None:
        CURRENT.superstep_begin()
    if _DISPATCH_SINK is not None:
        _DISPATCH_SINK.superstep_begin()
    _obs.superstep_begin()


def superstep_tick(levels: int) -> None:
    """One superstep's fetch completed, covering ``levels`` committed
    levels — snapshots the dispatch/fetch counters for the
    per-superstep ledger (the 1-dispatch-+-1-fetch-per-superstep
    acceptance surface)."""
    if CURRENT is not None:
        CURRENT.superstep_tick(levels)
    if _DISPATCH_SINK is not None:
        _DISPATCH_SINK.superstep_tick(levels)
    _obs.superstep_commit(levels)


def note_async_fetch_start() -> None:
    """The async pipeline started one fetch group (copy_to_host_async)."""
    if CURRENT is not None:
        CURRENT.n_async_started += 1


def note_async_fetch_complete() -> None:
    """One async fetch group completed through the ledgered get path."""
    if CURRENT is not None:
        CURRENT.n_async_completed += 1


# -- engine hooks (no-ops unless a Sanitizer is active) -------------------

def level_tick() -> None:
    """Engines call this once per completed BFS level."""
    if CURRENT is not None:
        CURRENT.level_tick()
    if _DISPATCH_SINK is not None:
        _DISPATCH_SINK.tick()


def note_shape_event(reason: str) -> None:
    """Engines declare legitimate recompile causes (capacity growth,
    presize, a new program shape) for the level in flight."""
    if CURRENT is not None:
        CURRENT.note_shape_event(reason)
    _obs.shape(reason)


_OBS_COMPILE_ARMED = False


def obs_watch_compiles() -> None:
    """Publish XLA backend compiles into the telemetry hub.

    Registered ONCE per process (idempotent), independent of the full
    Sanitizer: the listener is a cheap no-op while no hub is
    installed, and the prewarm thread's declared marker tags its
    compiles so the timeline can tell background AOT work from a
    silent in-line retrace.  Lazy jax import — the device-free module
    import contract (GL001) holds, and callers arm this only after
    ``platform.setup_jax``."""
    global _OBS_COMPILE_ARMED
    if _OBS_COMPILE_ARMED:
        return
    from jax._src import monitoring

    def on_event(name, *a, **kw):
        if name == "/jax/core/compile/backend_compile_duration":
            secs = a[0] if a and isinstance(a[0], (int, float)) else (
                kw.get("duration_secs", 0.0)
            )
            _obs.compile_done(
                float(secs or 0.0), thread_compiles_declared()
            )

    monitoring.register_event_duration_secs_listener(on_event)
    _OBS_COMPILE_ARMED = True


_UNSET = object()


class _AllowTransfers:
    """Reentrant thread-local allowance for the wrapped explicit paths."""

    def __enter__(self):
        _tl.allow = getattr(_tl, "allow", 0) + 1

    def __exit__(self, *exc):
        _tl.allow -= 1


def _allowed() -> bool:
    return getattr(_tl, "allow", 0) > 0


class Sanitizer:
    """Context manager wrapping one check run.  See module docstring."""

    def __init__(self, warmup_levels: int | None = None,
                 strict: bool | None = None):
        if warmup_levels is None:
            warmup_levels = int(os.environ.get("GRAFT_SANITIZE_WARMUP", "2"))
        if strict is None:
            strict = os.environ.get("GRAFT_SANITIZE_STRICT", "1") == "1"
        self.warmup_levels = warmup_levels
        self.strict = strict
        self.level = 0
        self.compiles_total = 0
        self._level_compiles = 0
        self._level_events: list[str] = []
        self._grace = 0
        self.n_ledgered_get = 0
        self.n_ledgered_put = 0
        self.ledgered_bytes = 0
        self.n_implicit = 0
        self.n_worker_dispatch = 0
        # per-level engine-program dispatch/fetch ledger: the engines
        # note every level-loop device program at its call site and the
        # level boundary snapshots both counters — the GL011 budget and
        # the megakernel's one-dispatch/one-fetch smoke read these
        self.n_dispatches = 0
        self._level_dispatches = 0
        self._gets_at_tick = 0
        self.per_level_dispatches: list[int] = []
        self.per_level_gets: list[int] = []
        # per-SUPERSTEP dispatch/fetch windows (engine/superstep.py):
        # the engine brackets each multi-level dispatch with
        # superstep_begin/superstep_tick, and the acceptance claim —
        # one device program + one ledgered fetch per superstep — is
        # asserted from these (steady state: every window past the
        # first, which may carry the compile-ladder's extra fetches)
        self.n_supersteps = 0
        self.superstep_levels = 0
        self.per_superstep_dispatches: list[int] = []
        self.per_superstep_gets: list[int] = []
        self._ss_disp_mark: int | None = None
        self._ss_gets_mark: int | None = None
        # async-pipeline fetch groups (engine/pipeline.py): every
        # copy_to_host_async group must complete through the ledgered
        # device_get path — started minus completed is the count of
        # fetches that bypassed the ledger (must be 0 on clean runs)
        self.n_async_started = 0
        self.n_async_completed = 0
        # declared background (prewarm-thread) compiles — counted apart
        # from the per-level retrace check, which audits the main thread
        self.compiles_prewarm = 0
        self.violations: list[str] = []
        self._patches: list[tuple[object, str, object]] = []
        self._listener = None
        self._active = False
        self._tg_prev = _UNSET  # the guard's default is None — a real value
        # GRAFT_SANITIZE_DEBUG=1: capture the NAMES of compiled programs
        # per level (via jax_log_compiles) so a flagged retrace says
        # which program retraced, not just that one did
        self.debug = os.environ.get("GRAFT_SANITIZE_DEBUG") == "1"
        self._level_names: list[str] = []
        self._log_handler = None

    # -- wiring ----------------------------------------------------------

    def __enter__(self):
        global CURRENT
        if CURRENT is not None:
            raise RuntimeError("a Sanitizer is already active")
        try:
            return self._arm()
        except BaseException:  # graftlint: waive[GL003] — unwind + re-raise
            # private jax APIs (monitoring, ArrayImpl dunders) can move
            # across releases: a partially-armed sanitizer must unwind
            # fully or every retry would see stale patches / CURRENT
            self._disarm()
            raise

    def _arm(self):
        global CURRENT
        import jax
        from jax._src import monitoring
        from jax._src.array import ArrayImpl

        def on_event(name, *a, **kw):
            if self._active and name == (
                "/jax/core/compile/backend_compile_duration"
            ):
                # the event fires ON the compiling thread, so the
                # prewarm thread's declared marker routes its compiles
                # race-free to the prewarm ledger
                if thread_compiles_declared():
                    self.compiles_prewarm += 1
                    return
                self.compiles_total += 1
                self._level_compiles += 1

        self._listener = on_event
        monitoring.register_event_duration_secs_listener(on_event)

        if self.debug:
            import logging

            class _H(logging.Handler):
                def emit(h, record):  # noqa: N805
                    msg = record.getMessage()
                    if (self._active and msg.startswith("Compiling ")
                            and not thread_compiles_declared()):
                        self._level_names.append(msg.split()[1])

            self._log_prev = jax.config.jax_log_compiles
            jax.config.update("jax_log_compiles", True)
            self._log_handler = _H()
            logging.getLogger("jax").addHandler(self._log_handler)

        san = self

        def _patch(obj, name, repl):
            self._patches.append((obj, name, getattr(obj, name)))
            setattr(obj, name, repl)

        orig_get, orig_put = jax.device_get, jax.device_put

        def device_get(x, *a, **kw):
            assert_device_dispatch_ok("jax.device_get")
            with _AllowTransfers():
                out = orig_get(x, *a, **kw)
            san.n_ledgered_get += 1
            san.ledgered_bytes += _nbytes(out)
            return out

        def device_put(x, *a, **kw):
            assert_device_dispatch_ok("jax.device_put")
            with _AllowTransfers():
                out = orig_put(x, *a, **kw)
            san.n_ledgered_put += 1
            return out

        _patch(jax, "device_get", device_get)
        _patch(jax, "device_put", device_put)

        def conv_wrapper(name, orig):
            def wrapped(self_arr, *a, **kw):
                if san._active and not _allowed():
                    san.n_implicit += 1
                    if san.strict:
                        raise RuntimeError(
                            f"graftlint: unledgered implicit host "
                            f"transfer ({name} on a device array of "
                            f"shape {getattr(self_arr, 'shape', '?')}) "
                            "— use jax.device_get at an intended sync "
                            "point, or set GRAFT_SANITIZE_STRICT=0 to "
                            "count instead of raise"
                        )
                return orig(self_arr, *a, **kw)
            return wrapped

        for name in ("__array__", "__bool__", "__int__", "__float__",
                     "__index__"):
            orig = getattr(ArrayImpl, name, None)
            if orig is not None:
                _patch(ArrayImpl, name, conv_wrapper(name, orig))

        # arm jax's own guard too: free on CPU (zero-copy, never fires),
        # real coverage of np.asarray paths on accelerator backends
        self._tg_prev = jax.config.jax_transfer_guard_device_to_host
        jax.config.update(
            "jax_transfer_guard_device_to_host",
            "disallow" if self.strict else "log",
        )
        self._active = True
        CURRENT = self  # last: everything fallible is armed by now
        return self

    def _disarm(self):
        global CURRENT
        import jax
        from jax._src import monitoring

        self._active = False
        for obj, name, orig in reversed(self._patches):
            setattr(obj, name, orig)
        self._patches.clear()
        if self._tg_prev is not _UNSET:
            jax.config.update(
                "jax_transfer_guard_device_to_host", self._tg_prev
            )
            self._tg_prev = _UNSET
        if self._log_handler is not None:
            import logging

            logging.getLogger("jax").removeHandler(self._log_handler)
            jax.config.update("jax_log_compiles", self._log_prev)
            self._log_handler = None
        if self._listener is not None:
            try:
                monitoring._unregister_event_duration_listener_by_callback(
                    self._listener
                )
            except (AttributeError, ValueError):
                # listener API drift across jax versions: a stale
                # listener is inert anyway (gated on self._active)
                pass
            self._listener = None
        CURRENT = None

    def __exit__(self, *exc):
        # close the final (partial) level's accounting — the fixpoint-
        # discovery level dispatches and fetches but never reaches the
        # engine's tick (it breaks on n_new == 0)
        if self._level_compiles or self._level_dispatches:
            self.level_tick()
        self._disarm()
        return False

    # -- per-level accounting --------------------------------------------

    def note_shape_event(self, reason: str) -> None:
        self._level_events.append(reason)

    def note_dispatch(self, tag: str) -> None:
        self.n_dispatches += 1
        self._level_dispatches += 1

    def superstep_begin(self) -> None:
        self._ss_disp_mark = self.n_dispatches
        self._ss_gets_mark = self.n_ledgered_get

    def superstep_tick(self, levels: int) -> None:
        dm = (self._ss_disp_mark if self._ss_disp_mark is not None
              else self.n_dispatches)
        gm = (self._ss_gets_mark if self._ss_gets_mark is not None
              else self.n_ledgered_get)
        self.per_superstep_dispatches.append(self.n_dispatches - dm)
        self.per_superstep_gets.append(self.n_ledgered_get - gm)
        self.n_supersteps += 1
        self.superstep_levels += int(levels)
        self._ss_disp_mark = None
        self._ss_gets_mark = None

    def _steady(self, per_level: list[int]) -> list[int]:
        return per_level[self.warmup_levels:] or per_level

    def level_tick(self) -> None:
        self.per_level_dispatches.append(self._level_dispatches)
        self._level_dispatches = 0
        self.per_level_gets.append(
            self.n_ledgered_get - self._gets_at_tick
        )
        self._gets_at_tick = self.n_ledgered_get
        self.level += 1
        excused = bool(self._level_events) or self._grace > 0
        # a shape event declared in level N excuses level N+1 as well:
        # engines observe shape changes at level END (the new frontier/
        # store widths), while the programs built against those widths
        # first compile early in the NEXT level
        if self._level_events:
            self._grace = 1
        elif self._grace:
            self._grace -= 1
        if (
            self.level > self.warmup_levels
            and self._level_compiles > 0
            and not excused
        ):
            names = (
                f" ({', '.join(self._level_names)})"
                if self._level_names else ""
            )
            self.violations.append(
                f"level {self.level}: {self._level_compiles} XLA "
                f"compile(s) with no declared shape event{names} — a "
                "silent retrace in the steady-state level loop"
            )
        self._level_compiles = 0
        self._level_events = []
        self._level_names = []

    # -- reporting -------------------------------------------------------

    @property
    def unledgered_async_fetches(self) -> int:
        """Async fetch groups started but never completed through the
        ledgered get path (a drain/discard hole in the pipeline)."""
        return max(0, self.n_async_started - self.n_async_completed)

    @property
    def ok(self) -> bool:
        return (
            not self.violations
            and self.n_implicit == 0
            and self.n_worker_dispatch == 0
            and self.unledgered_async_fetches == 0
        )

    def report(self) -> dict:
        sd = self._steady(self.per_level_dispatches)
        sg = self._steady(self.per_level_gets)
        return dict(
            ok=self.ok,
            levels=self.level,
            warmup_levels=self.warmup_levels,
            compiles_total=self.compiles_total,
            prewarm_compiles=self.compiles_prewarm,
            unexpected_recompiles=len(self.violations),
            ledgered_device_get=self.n_ledgered_get,
            ledgered_device_put=self.n_ledgered_put,
            ledgered_bytes=self.ledgered_bytes,
            unledgered_transfers=self.n_implicit,
            async_fetches=self.n_async_completed,
            unledgered_async_fetches=self.unledgered_async_fetches,
            worker_thread_dispatches=self.n_worker_dispatch,
            engine_dispatches=self.n_dispatches,
            per_level_dispatches=list(self.per_level_dispatches),
            per_level_fetches=list(self.per_level_gets),
            steady_max_dispatches_per_level=max(sd) if sd else 0,
            steady_max_fetches_per_level=max(sg) if sg else 0,
            supersteps=self.n_supersteps,
            superstep_levels=self.superstep_levels,
            per_superstep_dispatches=list(self.per_superstep_dispatches),
            per_superstep_fetches=list(self.per_superstep_gets),
            steady_max_dispatches_per_superstep=(
                max(self.per_superstep_dispatches)
                if self.per_superstep_dispatches else 0
            ),
            steady_max_fetches_per_superstep=(
                max(self.per_superstep_gets)
                if self.per_superstep_gets else 0
            ),
            violations=list(self.violations),
        )

    def print_report(self, out) -> None:
        r = self.report()
        print(
            f"Sanitizer: {r['compiles_total']} XLA compiles over "
            f"{r['levels']} levels (warmup {r['warmup_levels']}), "
            f"{r['unexpected_recompiles']} post-warmup unexpected "
            "recompiles.",
            file=out,
        )
        print(
            f"Sanitizer: {r['ledgered_device_get']} ledgered fetches / "
            f"{r['ledgered_device_put']} puts "
            f"({r['ledgered_bytes']:,} B), "
            f"{r['unledgered_transfers']} unledgered host transfers, "
            f"{r['worker_thread_dispatches']} worker-thread device "
            "dispatches.",
            file=out,
        )
        print(
            f"Sanitizer: {r['async_fetches']} async pipeline fetches "
            f"({r['unledgered_async_fetches']} unledgered), "
            f"{r['prewarm_compiles']} declared prewarm compiles.",
            file=out,
        )
        print(
            f"Sanitizer: {r['engine_dispatches']} engine program "
            f"dispatches; steady-state max "
            f"{r['steady_max_dispatches_per_level']} dispatch(es) and "
            f"{r['steady_max_fetches_per_level']} ledgered fetch(es) "
            "per level.",
            file=out,
        )
        if r["supersteps"]:
            lvls = r["superstep_levels"]
            avg = lvls / max(r["supersteps"], 1)
            print(
                f"Sanitizer: {r['supersteps']} supersteps covering "
                f"{lvls} levels ({avg:.1f} levels/dispatch); "
                f"steady-state max "
                f"{r['steady_max_dispatches_per_superstep']} "
                f"dispatch(es) and "
                f"{r['steady_max_fetches_per_superstep']} ledgered "
                "fetch(es) per superstep.",
                file=out,
            )
        for v in r["violations"]:
            print(f"Sanitizer: VIOLATION — {v}", file=out)
        print(
            "Sanitizer: OK" if r["ok"] else "Sanitizer: FAIL",
            file=out,
        )


def _nbytes(tree) -> int:
    total = 0
    stack = [tree]
    while stack:
        x = stack.pop()
        if isinstance(x, dict):
            stack.extend(x.values())
        elif isinstance(x, (list, tuple)):
            stack.extend(x)
        elif hasattr(x, "_fields"):  # NamedTuple
            stack.extend(tuple(x))
        else:
            total += int(getattr(x, "nbytes", 0) or 0)
    return total
