"""graftsync layer 2: runtime happens-before sanitizer (GRAFT_TSAN=1).

Where threadlint.py proves thread soundness STATICALLY, this module
checks it on a live run: a lightweight vector-clock checker over the
checker's known boundary objects.  Armed via ``GRAFT_TSAN=1`` in
check.py (composing with ``GRAFT_SANITIZE``), it

* patches the stdlib synchronization primitives the runtime uses —
  ``Thread.start/join``, ``Event.set/wait``, executor
  ``submit``/``Future.result``, ``Queue.put/get`` — so every hand-off
  creates a happens-before edge between the participating threads'
  vector clocks;
* swaps the known boundary locks (Prewarmer ``_lock``, Watchdog
  ``_cv``'s lock, TelemetryHub ``_lock``/``_io_lock``) for
  :class:`InstrumentedLock`, which adds acquire/release edges AND
  measures per-lock wait/hold times (the contention profiler);
* instruments the known cross-thread fields (``AsyncFetchWindow.live``,
  ``Watchdog.fired``) with explicit :meth:`TSan.read`/:meth:`write`
  records: an access not ordered after the previous write by ANY
  happens-before chain is a race, reported with both stacks — the
  writer's (captured at write time) and the racing accessor's.

Lock statistics publish into the telemetry hub at disarm as one
``lock_held`` event per lock (GL012-clean: collection at a choke
point, obs/ renders); an individual acquire that waits longer than
``WAIT_EVENT_S`` publishes a ``lock_wait`` contention event at the
site (hub-internal locks are aggregate-only — a hub lock emitting
about itself would recurse).

The checker is intentionally conservative in the safe direction for a
PROFILER: per-queue (not per-item) queue edges can only create extra
order, never report a false race.  Strictness is the caller's choice:
``strict=True`` (the default, used by tests) raises at the racing
access; check.py arms with ``strict=False`` and fails the run at exit
(exit code 3, the runtime-hygiene class) so a race report never
truncates the counts that prove it.
"""

from __future__ import annotations

import contextlib
import threading
import time
import traceback

# an acquire that blocks longer than this publishes a `lock_wait`
# contention event at the site (aggregates are always collected)
WAIT_EVENT_S = 0.005


class Race:
    """One unordered cross-thread access, with both stacks."""

    def __init__(self, field, w_tid, w_stack, a_tid, a_stack, kind):
        self.field = field
        self.w_tid = w_tid
        self.w_stack = w_stack
        self.a_tid = a_tid
        self.a_stack = a_stack
        self.kind = kind  # "read" | "write" — the racing access

    def format(self) -> str:
        return (
            f"data race on {self.field}: {self.kind} on thread "
            f"{self.a_tid} not ordered after write on thread "
            f"{self.w_tid}\n"
            f"  -- writer stack (thread {self.w_tid}) --\n"
            f"{self.w_stack}"
            f"  -- racing {self.kind} stack (thread {self.a_tid}) --\n"
            f"{self.a_stack}"
        )


class InstrumentedLock:
    """Drop-in ``threading.Lock`` wrapper: happens-before edges through
    the lock token plus wait/hold measurement.  Also serves as the
    inner lock of a ``threading.Condition`` (wait/notify then inherit
    the edges through the release/re-acquire pairs)."""

    def __init__(self, tsan: "TSan", name: str, publish_waits=True):
        self._inner = threading.Lock()
        self._tsan = tsan
        self.name = name
        self._publish_waits = publish_waits
        self._t_acq = 0.0

    def acquire(self, blocking: bool = True, timeout: float = -1):
        t0 = time.monotonic()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            t1 = time.monotonic()
            self._t_acq = t1
            self._tsan._lock_acquired(self, t1 - t0)
        return ok

    def release(self):
        held = time.monotonic() - self._t_acq
        self._tsan._lock_released(self, held)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class TSan:
    """Happens-before sanitizer + lock contention profiler.

    Use as a context manager around the run (check.py) or arm/disarm
    explicitly (tests).  All clock state lives behind one raw internal
    lock; the instrumented program only ever calls into short O(1)
    critical sections.
    """

    def __init__(self, strict: bool = True):
        self.strict = strict
        self.races: list[Race] = []
        self.lock_stats: dict[str, dict] = {}
        self._mu = threading.Lock()
        self._clocks: dict[int, dict[int, int]] = {}
        self._sync: dict[object, dict[int, int]] = {}
        # field -> (writer tid, writer epoch, writer stack)
        self._writes: dict[object, tuple[int, int, str]] = {}
        self._reported: set[object] = set()
        self._task_seq = 0
        self._orig: list[tuple] = []
        self._armed = False

    # -- vector clocks ----------------------------------------------------

    def _clock(self, tid: int) -> dict[int, int]:
        c = self._clocks.get(tid)
        if c is None:
            c = self._clocks[tid] = {tid: 0}
        return c

    def hb_release(self, token) -> None:
        """Publish the calling thread's clock under ``token``."""
        tid = threading.get_ident()
        with self._mu:
            c = self._clock(tid)
            c[tid] = c.get(tid, 0) + 1
            dst = self._sync.setdefault(token, {})
            for k, v in c.items():
                if v > dst.get(k, 0):
                    dst[k] = v

    def hb_acquire(self, token) -> None:
        """Join the clock published under ``token`` into the caller's."""
        tid = threading.get_ident()
        with self._mu:
            src = self._sync.get(token)
            if not src:
                return
            c = self._clock(tid)
            for k, v in src.items():
                if v > c.get(k, 0):
                    c[k] = v

    # -- field access records --------------------------------------------

    def write(self, owner, field: str) -> None:
        self._access(owner, field, write=True)

    def read(self, owner, field: str) -> None:
        self._access(owner, field, write=False)

    def _access(self, owner, field: str, write: bool) -> None:
        tid = threading.get_ident()
        key = (owner, field)
        race = None
        with self._mu:
            c = self._clock(tid)
            prev = self._writes.get(key)
            if (
                prev is not None
                and prev[0] != tid
                and c.get(prev[0], 0) < prev[1]
                and key not in self._reported
            ):
                self._reported.add(key)
                race = Race(
                    f"{owner}.{field}" if not isinstance(owner, str)
                    else f"{owner}.{field}",
                    prev[0], prev[2], tid,
                    "".join(traceback.format_stack(limit=12)),
                    "write" if write else "read",
                )
            if write:
                c[tid] = c.get(tid, 0) + 1
                self._writes[key] = (
                    tid, c[tid],
                    "".join(traceback.format_stack(limit=12)),
                )
        if race is not None:
            self.races.append(race)
            if self.strict:
                raise RuntimeError(f"GRAFT_TSAN: {race.format()}")

    # -- lock profiler hooks ---------------------------------------------

    def _lock_acquired(self, lock: InstrumentedLock, waited: float):
        self.hb_acquire(("lock", id(lock)))
        with self._mu:
            st = self.lock_stats.setdefault(lock.name, {
                "n": 0, "wait_s": 0.0, "held_s": 0.0,
                "max_wait_s": 0.0, "max_held_s": 0.0,
            })
            st["n"] += 1
            st["wait_s"] += waited
            if waited > st["max_wait_s"]:
                st["max_wait_s"] = waited
        if waited >= WAIT_EVENT_S and lock._publish_waits:
            from ..obs import telemetry as obs

            hub = obs.current()
            if hub is not None:
                hub.emit("lock_wait", name=lock.name,
                         wait_s=round(waited, 6))

    def _lock_released(self, lock: InstrumentedLock, held: float):
        self.hb_release(("lock", id(lock)))
        with self._mu:
            st = self.lock_stats.get(lock.name)
            if st is not None:
                st["held_s"] += held
                if held > st["max_held_s"]:
                    st["max_held_s"] = held

    # -- arm/disarm -------------------------------------------------------

    def __enter__(self):
        self._arm()
        return self

    def __exit__(self, *exc):
        self._disarm()
        return False

    def _patch(self, obj, name, repl):
        self._orig.append((obj, name, getattr(obj, name)))
        setattr(obj, name, repl)

    def _arm(self):
        if self._armed:
            return
        self._armed = True
        tsan = self
        import queue as queue_mod
        from concurrent.futures import Future, ThreadPoolExecutor

        # stdlib hand-off edges ------------------------------------------
        orig_start = threading.Thread.start
        orig_join = threading.Thread.join

        def start(t):
            token = ("thread", id(t))
            tsan.hb_release(token)
            orig_run = t.run

            def run():
                tsan.hb_acquire(token)
                try:
                    orig_run()
                finally:
                    tsan.hb_release(("thread_end", id(t)))

            t.run = run
            return orig_start(t)

        def join(t, timeout=None):
            r = orig_join(t, timeout)
            if not t.is_alive():
                tsan.hb_acquire(("thread_end", id(t)))
            return r

        self._patch(threading.Thread, "start", start)
        self._patch(threading.Thread, "join", join)

        orig_set = threading.Event.set
        orig_wait = threading.Event.wait

        def ev_set(ev):
            tsan.hb_release(("event", id(ev)))
            return orig_set(ev)

        def ev_wait(ev, timeout=None):
            r = orig_wait(ev, timeout)
            if r:
                tsan.hb_acquire(("event", id(ev)))
            return r

        self._patch(threading.Event, "set", ev_set)
        self._patch(threading.Event, "wait", ev_wait)

        orig_submit = ThreadPoolExecutor.submit
        orig_result = Future.result

        def submit(exe, fn, *args, **kwargs):
            with tsan._mu:
                tsan._task_seq += 1
                n = tsan._task_seq
            tsan.hb_release(("task", n))

            def wrapped(*a, **k):
                tsan.hb_acquire(("task", n))
                try:
                    return fn(*a, **k)
                finally:
                    tsan.hb_release(("task_done", n))

            fut = orig_submit(exe, wrapped, *args, **kwargs)
            fut._tsan_token = n
            return fut

        def result(fut, timeout=None):
            try:
                return orig_result(fut, timeout)
            finally:
                n = getattr(fut, "_tsan_token", None)
                if n is not None and fut.done():
                    tsan.hb_acquire(("task_done", n))

        self._patch(ThreadPoolExecutor, "submit", submit)
        self._patch(Future, "result", result)

        orig_put = queue_mod.Queue.put
        orig_get = queue_mod.Queue.get

        def put(q, *a, **k):
            tsan.hb_release(("queue", id(q)))
            return orig_put(q, *a, **k)

        def get(q, *a, **k):
            item = orig_get(q, *a, **k)
            tsan.hb_acquire(("queue", id(q)))
            return item

        self._patch(queue_mod.Queue, "put", put)
        self._patch(queue_mod.Queue, "get", get)

        # boundary objects -----------------------------------------------
        from ..engine import pipeline
        from ..obs import telemetry as obs_telemetry
        from ..resilience import elastic

        orig_afw_submit = pipeline.AsyncFetchWindow.submit
        orig_afw_complete = pipeline.AsyncFetchWindow._complete_one

        def afw_submit(win, arrays, consume):
            tsan.write("AsyncFetchWindow", "live")
            return orig_afw_submit(win, arrays, consume)

        def afw_complete(win, run_consume):
            tsan.write("AsyncFetchWindow", "live")
            return orig_afw_complete(win, run_consume)

        self._patch(pipeline.AsyncFetchWindow, "submit", afw_submit)
        self._patch(
            pipeline.AsyncFetchWindow, "_complete_one", afw_complete
        )

        orig_pw_init = pipeline.Prewarmer.__init__

        def pw_init(pw, *a, **k):
            orig_pw_init(pw, *a, **k)
            pw._lock = InstrumentedLock(
                tsan, "pipeline.Prewarmer._lock"
            )

        self._patch(pipeline.Prewarmer, "__init__", pw_init)

        orig_wd_init = elastic.Watchdog.__init__
        orig_wd_fire = elastic.Watchdog._fire

        def wd_init(wd, *a, **k):
            orig_wd_init(wd, *a, **k)
            # Condition binds acquire/release at construction, so the
            # instrumented lock must go in via a NEW Condition (the
            # watchdog thread starts lazily; nothing waits yet)
            wd._cv = threading.Condition(
                InstrumentedLock(tsan, "elastic.Watchdog._cv")
            )

        def wd_fire(wd, ctx):
            tsan.write("Watchdog", "fired")
            return orig_wd_fire(wd, ctx)

        self._patch(elastic.Watchdog, "__init__", wd_init)
        self._patch(elastic.Watchdog, "_fire", wd_fire)
        # a watchdog installed BEFORE arming (check.py builds it before
        # entering the tsan context) — its deadline thread starts
        # lazily at the first arm(), which is always inside the
        # context, so nothing waits on the old condition yet
        wd = getattr(elastic, "_WATCHDOG", None)
        if wd is not None and getattr(wd, "_thread", None) is None:
            wd._cv = threading.Condition(
                InstrumentedLock(tsan, "elastic.Watchdog._cv")
            )

        def hub_locks(hub):
            hub._lock = InstrumentedLock(
                tsan, "telemetry.TelemetryHub._lock",
                publish_waits=False,
            )
            hub._io_lock = InstrumentedLock(
                tsan, "telemetry.TelemetryHub._io_lock",
                publish_waits=False,
            )

        orig_hub_init = obs_telemetry.TelemetryHub.__init__

        def hub_init(hub, *a, **k):
            orig_hub_init(hub, *a, **k)
            hub_locks(hub)

        self._patch(obs_telemetry.TelemetryHub, "__init__", hub_init)
        # a hub installed BEFORE arming (check.py creates it early)
        # gets its locks swapped in place — only the main thread is
        # live at arm time, so nothing can hold them mid-swap
        hub = obs_telemetry.current()
        if hub is not None:
            hub_locks(hub)

    def _disarm(self):
        if not self._armed:
            return
        self._armed = False
        for obj, name, orig in reversed(self._orig):
            setattr(obj, name, orig)
        self._orig.clear()
        self._publish_lock_stats()

    def _publish_lock_stats(self):
        with contextlib.suppress(Exception):
            from ..obs import telemetry as obs

            hub = obs.current()
            if hub is None:
                return
            for name, st in sorted(self.lock_stats.items()):
                hub.emit(
                    "lock_held", name=name, n=st["n"],
                    wait_s=round(st["wait_s"], 6),
                    held_s=round(st["held_s"], 6),
                    max_wait_s=round(st["max_wait_s"], 6),
                    max_held_s=round(st["max_held_s"], 6),
                )

    # -- reporting --------------------------------------------------------

    @property
    def ok(self) -> bool:
        return not self.races

    def report(self) -> dict:
        return dict(
            ok=self.ok,
            races=[r.field for r in self.races],
            locks={k: dict(v) for k, v in self.lock_stats.items()},
        )

    def print_report(self, out) -> None:
        n = sum(st["n"] for st in self.lock_stats.values())
        print(
            f"TSan: {len(self.lock_stats)} instrumented locks, "
            f"{n} acquires profiled, {len(self.races)} race(s).",
            file=out,
        )
        for name, st in sorted(self.lock_stats.items()):
            print(
                f"TSan: lock {name}: n={st['n']} "
                f"wait={st['wait_s']:.4f}s (max {st['max_wait_s']:.4f}s) "
                f"held={st['held_s']:.4f}s (max {st['max_held_s']:.4f}s)",
                file=out,
            )
        for r in self.races:
            print(f"TSan: RACE — {r.format()}", file=out)
        print("TSan: OK" if self.ok else "TSan: FAIL", file=out)
