"""Device-cost observatory: XLA cost/memory harvesting + profiler capture.

PR 11's flight recorder sees every host-side event, but the fused
megakernel/superstep rewrites moved nearly all wall time INSIDE device
programs the hub cannot see.  This module is the jax-side half of the
observability stack (the obs/ package stays host-pure per GL012 and
only renders what this module publishes):

* :func:`harvest_compiled` — normalize one compiled executable's
  ``cost_analysis()`` + ``memory_analysis()`` into the flat metric
  dict the cost ledger (analysis/cost_audit.py, GL013), the
  ``program_profile`` telemetry event and the ``--json`` ``hbm`` block
  all share.
* :func:`profile_program` — the runtime choke-point hook: at a program
  dispatch site, lower+compile the jitted function at the live
  argument shapes ONCE per (tag, shapes) and publish the harvest into
  the telemetry hub.  ``lower().compile()`` populates the same
  executable cache the subsequent call hits (the AOT-prewarm contract,
  engine/pipeline.Prewarmer), so collection is compile-time only — no
  extra device dispatch, no extra XLA compile, and the GL011 dispatch
  budgets are unchanged.  With telemetry off (or
  ``TLA_RAFT_DEVPROF=0``) the hook is one global read + one branch.
* :class:`ProfilerCapture` — the opt-in ``--profile N`` jax-profiler
  session: capture device traces for N dispatch windows (supersteps on
  the fused path — one ledgered fetch per window ticks the counter via
  :func:`profile_tick` from the pipeline's one fetch site) and write a
  Perfetto-format device trace ``obs trace`` merges beside the host
  lanes.

Everything here degrades to a no-op on error: observability must never
take the checker down (the same contract as the telemetry hub).
"""

from __future__ import annotations

import glob
import os

from ..obs import telemetry as _obs

# metrics the cost ledger records per kernel; the *_b entries come from
# memory_analysis (CompiledMemoryStats), flops/bytes from cost_analysis
METRIC_KEYS = (
    "flops",         # model flops of one program execution
    "bytes",         # bytes accessed (operands + outputs, XLA model)
    "arg_b",         # argument buffer bytes
    "out_b",         # output buffer bytes
    "alias_b",       # donated/aliased bytes (in-place reuse)
    "tmp_b",         # temp allocation bytes — the transient HBM cost
    "code_b",        # generated code size
)


def enabled() -> bool:
    """Runtime profiling rides the telemetry hub: a hub must be
    installed, and ``TLA_RAFT_DEVPROF=0`` force-disables."""
    return (
        _obs.current() is not None
        and os.environ.get("TLA_RAFT_DEVPROF", "1") != "0"
    )


def harvest_compiled(compiled) -> dict | None:
    """Compiled executable -> the flat cost/memory metric dict.

    Tolerates backends where either analysis is unimplemented (fields
    default 0); returns None only when NOTHING could be harvested."""
    out = dict.fromkeys(METRIC_KEYS, 0)
    got = False
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if ca:
            out["flops"] = float(ca.get("flops", 0.0) or 0.0)
            out["bytes"] = float(ca.get("bytes accessed", 0.0) or 0.0)
            got = True
    except Exception:  # graftlint: waive[GL003] — cost_analysis is
        # best-effort per backend; a NotImplemented/runtime error just
        # means "no cost model here"
        pass
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            out["arg_b"] = int(
                getattr(ma, "argument_size_in_bytes", 0) or 0
            )
            out["out_b"] = int(
                getattr(ma, "output_size_in_bytes", 0) or 0
            )
            out["alias_b"] = int(
                getattr(ma, "alias_size_in_bytes", 0) or 0
            )
            out["tmp_b"] = int(
                getattr(ma, "temp_size_in_bytes", 0) or 0
            )
            out["code_b"] = int(
                getattr(ma, "generated_code_size_in_bytes", 0) or 0
            )
            got = True
    except Exception:  # graftlint: waive[GL003] — same best-effort
        # contract as cost_analysis above
        pass
    return out if got else None


def peak_bytes(metrics: dict) -> int:
    """The program's peak-HBM approximation: arguments + outputs +
    temps minus the aliased (in-place) overlap — the number the live
    gauge charges for one in-flight program."""
    return max(
        0,
        int(metrics.get("arg_b", 0)) + int(metrics.get("out_b", 0))
        + int(metrics.get("tmp_b", 0))
        - int(metrics.get("alias_b", 0)),
    )


# one profile per (tag, statics, arg avals) per process: the engines
# dispatch the same program shape every level, the harvest runs once
_SEEN: set = set()


def _aval_key(args) -> tuple:
    import jax

    leaves = jax.tree.leaves(args)
    return tuple(
        (tuple(getattr(x, "shape", ())), str(getattr(x, "dtype", "")))
        for x in leaves
    )


def reset_seen() -> None:
    _SEEN.clear()


def profile_program(tag: str, jitfn, *args, statics: dict | None = None,
                    **meta) -> None:
    """Harvest one jitted program's cost/memory ledger at the live
    argument shapes and publish it as a ``program_profile`` event.

    Call at the dispatch site BEFORE invoking ``jitfn`` — the
    lower+compile here lands in the executable cache the call then
    hits, so profiling on/off cannot change dispatch counts, compile
    counts or (a fortiori) any model count.  Never raises."""
    if not enabled():
        return
    try:
        key = (tag, tuple(sorted((statics or {}).items())),
               _aval_key(args))
    except Exception:  # graftlint: waive[GL003] — an unhashable static
        # must not take the dispatch site down
        return
    if key in _SEEN:
        return
    _SEEN.add(key)
    try:
        compiled = jitfn.lower(*args, **(statics or {})).compile()
        metrics = harvest_compiled(compiled)
    except Exception:  # graftlint: waive[GL003] — harvesting is
        # observability, not correctness; the real call still runs
        return
    if metrics is None:
        return
    _obs.program_profile(
        tag, **metrics, peak_b=peak_bytes(metrics), **meta
    )


# -- jax-profiler capture (--profile N) -----------------------------------

PROFILE_DIRNAME = "profile"

_PROFILER: "ProfilerCapture | None" = None


def install_profiler(p: "ProfilerCapture | None") -> None:
    global _PROFILER
    _PROFILER = p


def current_profiler() -> "ProfilerCapture | None":
    return _PROFILER


def profile_tick() -> None:
    """One dispatch window completed (called from the pipeline's ONE
    ledgered fetch site): advance the capture, stopping it after its
    budgeted windows.  No-op unless a capture is live."""
    p = _PROFILER
    if p is not None:
        p.tick()


class ProfilerCapture:
    """One ``--profile N`` device-trace capture session.

    ``start()`` opens a ``jax.profiler`` trace (Perfetto output) under
    ``<run_dir>/profile``; every :func:`profile_tick` counts one
    dispatch window (a superstep on the fused path, a level elsewhere
    — both complete through exactly one ledgered fetch); after
    ``windows`` ticks the trace stops and a ``profile_end`` event
    records where the device lanes landed for ``obs trace`` to merge.
    Stop is idempotent and exception-safe — a profiler failure must
    never take the run down."""

    def __init__(self, run_dir: str, windows: int = 1):
        self.trace_dir = os.path.join(run_dir, PROFILE_DIRNAME)
        self.windows = max(1, int(windows))
        self.done = 0
        self.running = False
        self.failed = False

    def start(self) -> bool:
        import jax.profiler

        try:
            os.makedirs(self.trace_dir, exist_ok=True)
            jax.profiler.start_trace(
                self.trace_dir, create_perfetto_trace=True
            )
        except Exception:  # graftlint: waive[GL003] — a profiler that
            # cannot start (unsupported backend, busy session) degrades
            # to "no device lanes", not a dead run
            self.failed = True
            return False
        self.running = True
        # the begin event's hub timestamp IS the merge anchor: jax
        # trace timestamps are microseconds from start_trace
        _obs.profile_begin(self.trace_dir, self.windows)
        return True

    def tick(self) -> None:
        if not self.running:
            return
        self.done += 1
        if self.done >= self.windows:
            self.stop()

    def stop(self) -> None:
        if not self.running:
            return
        self.running = False
        import jax.profiler

        try:
            jax.profiler.stop_trace()
        except Exception:  # graftlint: waive[GL003] — stop mirrors
            # start's degrade-only contract
            self.failed = True
            return
        _obs.profile_end(self.trace_dir, self.done)

    def perfetto_traces(self) -> list[str]:
        return find_perfetto_traces(self.trace_dir)


def find_perfetto_traces(trace_dir: str) -> list[str]:
    """The gzipped Perfetto traces a capture session wrote (newest
    last — jax nests them under plugins/profile/<timestamp>/)."""
    return sorted(
        glob.glob(
            os.path.join(
                trace_dir, "plugins", "profile", "*",
                "perfetto_trace.json.gz",
            )
        )
    )
