"""graftlint layer 1: repo-specific AST rules over the package source.

Each rule encodes a bug class this project actually shipped (PR 1) or a
discipline the kernels depend on; docs/ANALYSIS.md documents every rule
with the incident that motivated it.  Two suppression mechanisms:

* **waiver** — ``# graftlint: waive[GL003]`` (comma list, or ``[*]``) on
  the finding's line or the line directly above it: the reviewed,
  justified exception, kept next to the code it excuses.
* **baseline** — a committed JSON inventory
  (``tla_raft_tpu/analysis/baseline.json``) keyed by
  ``rule|path|stripped-line-text`` with per-key counts.  Used for rules
  that LEDGER existing sites rather than ban them (GL006 host syncs):
  the inventory pins today's count, so a NEW sync site fails CI until
  it is deliberately baselined or waived.  Line-text keys survive line
  drift; an edited line re-surfaces as a fresh finding, which is the
  point — the sync was touched, re-justify it.

All analysis is pure stdlib ``ast`` — no imports of the linted modules,
so the linter itself can never initialize a backend.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re

RULES = {
    "GL001": "import-time-dispatch: jax/jnp call at module import time",
    "GL002": "impure-in-traced: wall-clock/random call inside a traced "
             "function",
    "GL003": "broad-except: bare `except:` or blanket "
             "`except Exception`",
    "GL004": "traced-branch: Python `if`/`while` on a traced (jnp/lax) "
             "expression inside a traced function",
    "GL005": "narrow-offset: i32 cast on row/offset arithmetic in "
             "native/ or parallel/ call sites",
    "GL006": "host-sync-ledger: host-sync call site in a hot-loop "
             "module (new sites must be baselined or waived)",
    "GL007": "worker-device-dispatch: jax/jnp reference inside a "
             "function handed to a thread pool",
    "GL008": "unused-import: imported name never used",
    "GL009": "raw-checkpoint-write: np.savez/os.replace outside "
             "resilience/ — checkpoint artifacts must commit through "
             "resilience.commit_npz",
    "GL012": "obs-host-purity: telemetry code (tla_raft_tpu/obs/) "
             "must stay host-side — no jax import, no device "
             "sync/dispatch (telemetry observes the run, it never "
             "participates in it)",
}

# GL006 applies only to the hot level-loop modules (the ~140-site sync
# inventory the subsystem exists to pin down).
HOT_LOOP_SUFFIXES = (
    os.path.join("engine", "bfs.py"),
    os.path.join("parallel", "sharded.py"),
)
# GL005 applies to the modules doing row/offset arithmetic against
# >2^32-row stores (the PR 1 i32-overflow incident class).
WIDTH_DIRS = (
    os.path.join("tla_raft_tpu", "native"),
    os.path.join("tla_raft_tpu", "parallel"),
)

BASELINE_PATH = os.path.join(os.path.dirname(__file__), "baseline.json")

_WAIVE_RE = re.compile(r"graftlint:\s*waive\[([A-Za-z0-9*,\s]+)\]")
_OFFSET_NAME_RE = re.compile(
    r"off|offset|row|base|start|rank|cum|idx|pos|gpid|pidx|seek",
    re.IGNORECASE,
)
_I32_NAMES = {"I32", "int32"}
_IMPURE_CALLS = re.compile(
    r"^(time\.(time|monotonic|perf_counter|process_time)"
    r"|random\.\w+"
    r"|np\.random\.\w+|numpy\.random\.\w+"
    r"|datetime\.(datetime\.)?now)$"
)
_SYNC_ATTRS = {"device_get", "device_put", "block_until_ready"}
_TRACE_WRAPPERS = {
    "jit", "shard_map", "_shard_map", "pmap", "vmap", "make_jaxpr",
    "eval_shape", "scan", "while_loop", "cond", "switch", "checkpoint",
}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, posix separators
    line: int
    col: int
    message: str
    text: str  # stripped source line (the baseline key component)

    @property
    def key(self) -> str:
        return f"{self.rule}|{self.path}|{self.text}"

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


def _dotted(node: ast.AST) -> str | None:
    """`a.b.c` -> "a.b.c"; None for anything not a pure name chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _jax_aliases(tree: ast.Module) -> tuple[set[str], set[str]]:
    """(module aliases bound to jax/jax.*, aliases bound to jax.numpy)."""
    jax_mods: set[str] = set()
    jnp_mods: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                name, alias = a.name, a.asname or a.name.split(".")[0]
                if name == "jax.numpy":
                    jnp_mods.add(alias)
                elif name == "jax" or name.startswith("jax."):
                    jax_mods.add(alias)
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                alias = a.asname or a.name
                if node.module == "jax" and a.name == "numpy":
                    jnp_mods.add(alias)
                elif node.module == "jax" and a.name == "lax":
                    jax_mods.add(alias)
    return jax_mods, jnp_mods


# jax.* second components that never dispatch a device program (config,
# tree registration, lazily-compiled wrappers).  jax.jit/shard_map AT
# IMPORT only builds a wrapper; tracing happens at first call.
_GL001_SAFE_SECOND = {
    "config", "tree_util", "util", "typing", "custom_jvp", "custom_vjp",
    "jit", "shard_map", "named_scope", "debug",
}


def _import_time_calls(tree: ast.Module):
    """Calls evaluated at import: module/class bodies plus function
    decorators and default-argument expressions; function BODIES are
    pruned (ast.walk cannot prune, hence the explicit stack)."""
    stack: list[ast.AST] = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(node.decorator_list)
            stack.extend(node.args.defaults)
            stack.extend(d for d in node.args.kw_defaults if d is not None)
            continue
        if isinstance(node, ast.Lambda):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def _calls_in(node: ast.AST):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            yield sub


def _contains_traced_call(node: ast.AST, jax_mods, jnp_mods) -> str | None:
    """A call on a jnp/lax chain inside ``node``, or None."""
    for call in ast.walk(node):
        if not isinstance(call, ast.Call):
            continue
        d = _dotted(call.func)
        if d is None:
            continue
        root = d.split(".")[0]
        if root in jnp_mods:
            return d
        if root in jax_mods and (".lax." in d or d.startswith("lax.")):
            return d
    return None


def _traced_function_names(tree: ast.Module) -> set[str]:
    """Names of functions that get traced: jit/shard_map-decorated, or
    passed (as a name or ``self.attr``) into a trace-wrapper call."""
    traced: set[str] = set()

    def collect_callables(node: ast.AST):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name):
                traced.add(sub.id)
            elif isinstance(sub, ast.Attribute):
                traced.add(sub.attr)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                d = _dotted(dec if not isinstance(dec, ast.Call) else dec.func)
                if d and d.split(".")[-1] in ("jit", "shard_map", "pmap"):
                    traced.add(node.name)
                if isinstance(dec, ast.Call) and _dotted(dec.func) in (
                    "functools.partial", "partial"
                ):
                    for a in dec.args[:1]:
                        da = _dotted(a)
                        if da and da.split(".")[-1] in ("jit", "shard_map"):
                            traced.add(node.name)
        elif isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d and d.split(".")[-1] in _TRACE_WRAPPERS:
                for a in node.args:
                    collect_callables(a)
    return traced


def _function_defs(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


class _Linter:
    def __init__(self, src: str, path: str, relpath: str):
        self.src = src
        self.lines = src.splitlines()
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.tree = ast.parse(src, filename=path)
        self.jax_mods, self.jnp_mods = _jax_aliases(self.tree)
        self.findings: list[Finding] = []

    def add(self, rule: str, node: ast.AST, message: str):
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        text = self.lines[line - 1].strip() if line <= len(self.lines) else ""
        self.findings.append(
            Finding(rule, self.relpath, line, col, message, text)
        )

    # -- rules -----------------------------------------------------------

    def gl001_import_time_dispatch(self):
        for call in _import_time_calls(self.tree):
            d = _dotted(call.func)
            if d is None:
                continue
            parts = d.split(".")
            root = parts[0]
            if root in self.jnp_mods:
                self.add(
                    "GL001", call,
                    f"`{d}(...)` at module import time forces XLA "
                    "client creation (aborts pytest collection on "
                    "backend-less hosts) — use numpy scalars/arrays "
                    "at module scope",
                )
            elif root in self.jax_mods and root == "jax":
                if len(parts) > 1 and parts[1] in _GL001_SAFE_SECOND:
                    continue
                self.add(
                    "GL001", call,
                    f"`{d}(...)` at module import time touches the "
                    "backend — move it inside a function",
                )

    def gl002_impure_in_traced(self, traced: set[str]):
        for fn in _function_defs(self.tree):
            if fn.name not in traced:
                continue
            for call in _calls_in(fn):
                d = _dotted(call.func)
                if d and _IMPURE_CALLS.match(d):
                    self.add(
                        "GL002", call,
                        f"`{d}()` inside traced `{fn.name}` is baked in "
                        "as a compile-time constant (and silently "
                        "frozen across retraces)",
                    )

    def gl003_broad_except(self):
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                self.add("GL003", node, "bare `except:` swallows "
                         "KeyboardInterrupt/SystemExit — name the "
                         "exceptions")
                continue
            types = (
                node.type.elts if isinstance(node.type, ast.Tuple)
                else [node.type]
            )
            for t in types:
                d = _dotted(t)
                if d in ("Exception", "BaseException"):
                    self.add(
                        "GL003", node,
                        f"blanket `except {d}` hides unrelated bugs — "
                        "narrow it or waive with the justification",
                    )
                    break

    def gl004_traced_branch(self, traced: set[str]):
        for fn in _function_defs(self.tree):
            if fn.name not in traced:
                continue
            for node in ast.walk(fn):
                if isinstance(node, (ast.If, ast.While)):
                    d = _contains_traced_call(
                        node.test, self.jax_mods, self.jnp_mods
                    )
                    if d:
                        self.add(
                            "GL004", node,
                            f"Python branch on traced value (`{d}` in "
                            f"the test) inside traced `{fn.name}` — "
                            "this is a TracerBoolConversionError at "
                            "best, a silent trace-time constant at "
                            "worst; use lax.cond/jnp.where",
                        )
                elif isinstance(node, ast.Call):
                    dd = _dotted(node.func)
                    if dd == "bool" and node.args and _contains_traced_call(
                        node.args[0], self.jax_mods, self.jnp_mods
                    ):
                        self.add(
                            "GL004", node,
                            f"`bool(...)` of a traced expression inside "
                            f"traced `{fn.name}`",
                        )

    def gl005_narrow_offset(self):
        if not any(d in os.path.dirname(self.relpath.replace("/", os.sep))
                   or self.relpath.replace("/", os.sep).startswith(d)
                   for d in WIDTH_DIRS):
            return

        def is_i32(node: ast.AST) -> bool:
            d = _dotted(node)
            if d is None:
                return isinstance(node, ast.Constant) and node.value == "int32"
            last = d.split(".")[-1]
            return last in _I32_NAMES

        for node in ast.walk(self.tree):
            # x.astype(I32) / np.int32(expr) where the expression or its
            # assignment target smells like row/offset arithmetic
            expr_src = None
            call = None
            if isinstance(node, ast.Assign):
                targets = "/".join(
                    filter(None, (_dotted(t) for t in node.targets))
                )
                for c in _calls_in(node.value):
                    if (
                        isinstance(c.func, ast.Attribute)
                        and c.func.attr == "astype"
                        and c.args and is_i32(c.args[0])
                    ) or (
                        _dotted(c.func) is not None
                        and _dotted(c.func).split(".")[-1] in _I32_NAMES
                    ):
                        try:
                            expr_src = targets + "=" + ast.unparse(node.value)
                        except Exception:  # graftlint: waive[GL003]
                            expr_src = targets
                        call = c
                        break
            if call is None or expr_src is None:
                continue
            if _OFFSET_NAME_RE.search(expr_src):
                self.add(
                    "GL005", call,
                    "i32 cast on row/offset arithmetic — i32 offsets "
                    "wrap past 2^32 rows (the PR 1 incident class); "
                    "keep row/offset math in i64, or waive with the "
                    "proven bound",
                )

        # cumsum accumulating into i32 wraps at 2 GB packed streams
        # regardless of variable naming (parallel/exchange.py's offsets)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d and d.split(".")[-1] == "cumsum":
                    for kw in node.keywords:
                        if kw.arg == "dtype" and is_i32(kw.value):
                            self.add(
                                "GL005", node,
                                "i32 cumsum — offset accumulators wrap "
                                "once a packed stream passes 2 GB",
                            )

    def gl006_host_sync_ledger(self):
        rel_os = self.relpath.replace("/", os.sep)
        if not any(rel_os.endswith(s) for s in HOT_LOOP_SUFFIXES):
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            attr = d.split(".")[-1] if d else (
                node.func.attr if isinstance(node.func, ast.Attribute)
                else None
            )
            if attr in _SYNC_ATTRS:
                self.add(
                    "GL006", node,
                    f"host-sync call `{attr}` in a hot-loop module — "
                    "every sync stalls the dispatch pipeline; new sites "
                    "must be baselined (python -m tla_raft_tpu.analysis "
                    "--write-baseline) or waived",
                )

    def gl007_worker_device_dispatch(self):
        # local function defs by name (module + class scope)
        defs = {fn.name: fn for fn in _function_defs(self.tree)}
        # names bound to an executor constructor — `with TPE(...) as ex:`
        # and `x = TPE(...)` — so the rule is not fooled by variable
        # naming (the repo's own `as ex:` idiom in native/insert_sharded)
        bound: set[str] = set()

        def ctor(call: ast.AST) -> bool:
            if not isinstance(call, ast.Call):
                return False
            d = _dotted(call.func)
            return bool(d) and d.split(".")[-1] in (
                "ThreadPoolExecutor", "ProcessPoolExecutor",
            )

        for node in ast.walk(self.tree):
            if isinstance(node, ast.With):
                for item in node.items:
                    if ctor(item.context_expr) and isinstance(
                        item.optional_vars, ast.Name
                    ):
                        bound.add(item.optional_vars.id)
            elif isinstance(node, ast.Assign) and ctor(node.value):
                for t in node.targets:
                    d = _dotted(t)
                    if d:
                        bound.add(d.split(".")[-1])
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            if node.func.attr not in ("submit", "map"):
                continue
            owner = _dotted(node.func.value) or ""
            if not (
                re.search(r"pool|executor", owner, re.IGNORECASE)
                or owner.split(".")[-1] in bound
            ):
                continue
            if not node.args:
                continue
            target = node.args[0]
            tname = None
            if isinstance(target, ast.Name):
                tname = target.id
            elif isinstance(target, ast.Attribute):
                tname = target.attr
            fn = defs.get(tname)
            if fn is None:
                continue
            for sub in ast.walk(fn):
                d = None
                if isinstance(sub, ast.Name):
                    d = sub.id
                if d in self.jax_mods or d in self.jnp_mods:
                    self.add(
                        "GL007", node,
                        f"`{tname}` is handed to thread pool "
                        f"`{owner}` but references `{d}` — worker "
                        "threads must never dispatch device programs "
                        "(concurrent collectives deadlock the mesh "
                        "rendezvous; see parallel/sharded.py _io_pool)",
                    )
                    break

    def gl008_unused_import(self):
        if os.path.basename(self.relpath) == "__init__.py":
            return  # re-export surface
        imported: dict[str, ast.AST] = {}
        for node in self.tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name.split(".")[0]
                    imported[name] = node
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    name = a.asname or a.name
                    imported[name] = node
        used: set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Name):
                used.add(node.id)
            elif isinstance(node, ast.Attribute):
                pass  # roots are Names, already collected
        for name, node in imported.items():
            if name.startswith("_"):
                continue
            if name not in used:
                line = self.lines[node.lineno - 1]
                if "noqa" in line:
                    continue
                self.add(
                    "GL008", node,
                    f"imported name `{name}` is never used",
                )

    def gl009_raw_checkpoint_write(self):
        # the whole package except the subsystem that IS the writer:
        # every np.savez / os.replace outside resilience/ is a
        # checkpoint artifact bypassing the atomic-write + digest +
        # manifest contract (the crash matrix only covers committed
        # writers — an unrouted one is silently crash-unsafe)
        rel = self.relpath
        if not rel.startswith("tla_raft_tpu/") or rel.startswith(
            "tla_raft_tpu/resilience/"
        ):
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d is None:
                continue
            last = d.split(".")[-1]
            if last in ("savez", "savez_compressed"):
                self.add(
                    "GL009", node,
                    f"`{d}(...)` writes a checkpoint artifact directly "
                    "— route it through resilience.commit_npz (atomic "
                    "rename + digest + MANIFEST.json), or waive with "
                    "the reason it is not a checkpoint",
                )
            elif d == "os.replace":
                self.add(
                    "GL009", node,
                    "`os.replace(...)` outside resilience/ — atomic "
                    "checkpoint commits must route through "
                    "resilience.commit_npz, or waive with the reason "
                    "this rename is not a checkpoint commit",
                )

    def gl012_obs_host_purity(self):
        # the telemetry subsystem's load-bearing contract: obs/ code
        # runs inside every level loop and from watchdog/writer
        # threads, so a jax import or device sync there would (a) add
        # dispatches the GL011 budgets pin and (b) stall the dispatch
        # pipeline from a hook site.  Banned: importing jax (even
        # lazily — host purity is not a warm-up property), and any
        # device-sync attribute call (device_get/device_put/
        # block_until_ready).
        rel = self.relpath
        if not rel.startswith("tla_raft_tpu/obs/"):
            return
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax" or a.name.startswith("jax."):
                        self.add(
                            "GL012", node,
                            f"`import {a.name}` in obs/ — telemetry "
                            "must stay host-pure (no jax, even "
                            "lazily); publish from the instrumented "
                            "module instead",
                        )
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod == "jax" or mod.startswith("jax."):
                    self.add(
                        "GL012", node,
                        f"`from {mod} import ...` in obs/ — telemetry "
                        "must stay host-pure (no jax, even lazily)",
                    )
            elif isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d and d.split(".")[-1] in _SYNC_ATTRS:
                    self.add(
                        "GL012", node,
                        f"`{d}(...)` in obs/ — telemetry code must "
                        "never sync with or dispatch to a device",
                    )

    # -- driver ----------------------------------------------------------

    def run(self, select: set[str] | None = None) -> list[Finding]:
        traced = _traced_function_names(self.tree)
        rules = {
            "GL001": self.gl001_import_time_dispatch,
            "GL002": lambda: self.gl002_impure_in_traced(traced),
            "GL003": self.gl003_broad_except,
            "GL004": lambda: self.gl004_traced_branch(traced),
            "GL005": self.gl005_narrow_offset,
            "GL006": self.gl006_host_sync_ledger,
            "GL007": self.gl007_worker_device_dispatch,
            "GL008": self.gl008_unused_import,
            "GL009": self.gl009_raw_checkpoint_write,
            "GL012": self.gl012_obs_host_purity,
        }
        for rule, fn in rules.items():
            if select is None or rule in select:
                fn()
        return self._apply_waivers(self.findings)

    def _apply_waivers(self, findings: list[Finding]) -> list[Finding]:
        waivers: dict[int, set[str]] = {}
        comment_only: set[int] = set()
        for i, line in enumerate(self.lines, start=1):
            m = _WAIVE_RE.search(line)
            if m:
                waivers[i] = {t.strip() for t in m.group(1).split(",")}
                if line.strip().startswith("#"):
                    comment_only.add(i)
        if not waivers:
            return findings

        def waived(f: Finding) -> bool:
            # same-line waiver, or a COMMENT-ONLY waiver line directly
            # above (a code line's trailing waiver covers that line only)
            rules = waivers.get(f.line)
            if rules and (f.rule in rules or "*" in rules):
                return True
            if f.line - 1 in comment_only:
                rules = waivers[f.line - 1]
                return f.rule in rules or "*" in rules
            return False

        return [f for f in findings if not waived(f)]


def lint_source(
    src: str, path: str = "<string>", relpath: str | None = None,
    select: set[str] | None = None,
) -> list[Finding]:
    """Lint one module's source; waivers applied, baseline NOT applied."""
    return _Linter(src, path, relpath or path).run(select)


def iter_py_files(paths: list[str]) -> list[str]:
    out = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [
                    d for d in dirnames
                    if d not in ("__pycache__", ".git")
                ]
                out.extend(
                    os.path.join(dirpath, f)
                    for f in filenames if f.endswith(".py")
                )
        elif p.endswith(".py"):
            out.append(p)
    return sorted(out)


def lint_paths(
    paths: list[str], root: str | None = None,
    select: set[str] | None = None,
) -> list[Finding]:
    """Lint files/trees; paths in findings are relative to ``root``
    (default: the repo root inferred as the parent of this package)."""
    if root is None:
        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    findings: list[Finding] = []
    for f in iter_py_files(paths):
        with open(f, encoding="utf-8") as fh:
            src = fh.read()
        rel = os.path.relpath(os.path.abspath(f), root)
        findings.extend(lint_source(src, f, rel, select))
    return findings


# -- baseline -------------------------------------------------------------

def load_baseline(path: str = BASELINE_PATH) -> dict[str, int]:
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    return dict(data.get("entries", {}))


def write_baseline(findings: list[Finding], path: str = BASELINE_PATH):
    entries: dict[str, int] = {}
    for f in findings:
        entries[f.key] = entries.get(f.key, 0) + 1
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(
            {
                "comment": "graftlint baseline: pinned inventory of "
                           "accepted findings (rule|path|line-text -> "
                           "count). Regenerate deliberately with "
                           "`python -m tla_raft_tpu.analysis "
                           "--write-baseline` and review the diff.",
                "version": 1,
                "entries": dict(sorted(entries.items())),
            },
            fh, indent=1,
        )
        fh.write("\n")


def apply_baseline(
    findings: list[Finding], baseline: dict[str, int]
) -> tuple[list[Finding], int]:
    """Subtract baselined findings; returns (unwaived, n_suppressed)."""
    budget = dict(baseline)
    kept: list[Finding] = []
    suppressed = 0
    for f in findings:
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
            suppressed += 1
        else:
            kept.append(f)
    return kept, suppressed
