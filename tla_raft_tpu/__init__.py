"""tla_raft_tpu — a TPU-native model-checking framework.

Re-implements the capability of the reference (kikimo/tla-raft: a TLA+ Raft
specification checked by the Java TLC model checker, see
/root/reference/Raft.tla, /root/reference/Raft.cfg, /root/reference/myrun.sh)
as data-parallel JAX/XLA kernels:

- the Raft state vector is encoded as fixed-width integer tensors
  (models/raft.py),
- the ``Next``-action disjunction (Raft.tla:416-430) compiles to a vmap'd
  masked successor kernel with a statically-bounded fan-out,
- TLC's fingerprint set (FPSet) and worker pool become a sorted on-device
  fingerprint store + per-core frontier shards deduplicated with ICI
  collectives each BFS level (parallel/),
- symmetry reduction (Raft.cfg:24) and the VIEW projection (Raft.cfg:26)
  are permutation-folded coefficient tables + a multilinear 64-bit hash
  run as int8 MXU matmuls (ops/fingerprint.py),
- a pure-Python explicit-state checker (oracle/) reproduces TLC's semantics
  exactly and serves as the differential-testing oracle, since the reference
  publishes no numbers and TLC itself (a Java tool) is not vendored.
"""

# NOTE: importing the bare package stays jax-free (the cfg parser and the
# pure-Python oracle have no accelerator dependency).  The kernel modules
# (ops/fingerprint.py and everything above it) enable jax x64 at *their*
# import, before any u64 fingerprint kernel is traced.

__version__ = "0.1.0"
