"""End-to-end integrity audits: silent-corruption defense.

A flipped bit in a frontier tensor or fingerprint slab propagates into
``distinct``/``depth`` results with no crash to notice — the failure
mode end-to-end-verified ML systems treat as routine (background
integrity sweeps + recomputation cross-checks).  Two tiers:

* **Conservation checks** (always on, host-scalar cheap): per-owner
  count reconciliation across the exchange (states the owner stores
  admitted must equal states the origins materialized — every mesh
  path), and slab-occupancy-vs-distinct invariants (the live slots of
  a visited structure must count exactly the distinct states the run
  believes it has).  A violation raises :class:`IntegrityError`: the
  numbers upstream of the final answer no longer reconcile, so
  continuing would launder corruption into a verdict.

* **Sampled recomputation audit** (opt-in ``--audit N``): every level,
  a deterministic sample of N new-frontier rows is re-expanded through
  the retained ``*_legacy`` kernels (PR 6 keeps them jitted precisely
  as the independent reference) and cross-checked three ways — legacy
  guard admits the recorded slot, legacy child fingerprint matches the
  recorded level fingerprint, and the frontier row as *currently
  materialized on device* re-fingerprints to the same value.  The last
  check is what catches a post-materialize bit flip (the
  ``tensor.flip`` fault site injects exactly that).  On mismatch the
  engine quarantines the level and rewinds to the last committed
  checkpoint (the delta log holds (parent, slot) decisions, not
  tensors, so the replay is clean by construction); after
  ``audit_retries`` reproducible mismatches it fail-stops with
  :class:`AuditFailStop` (CLI exit 4) — at that point the corruption
  is deterministic and no amount of rewinding will outrun it.

Module contract: device-free import (numpy only, no jax).
"""

from __future__ import annotations

import numpy as np

from ..obs import telemetry as _obs


class IntegrityError(RuntimeError):
    """An always-on conservation invariant failed: counts upstream of
    the final answer no longer reconcile."""


class AuditMismatch(IntegrityError):
    """The sampled recomputation audit caught a divergence; the level
    is quarantined and the run rewinds to the last committed
    checkpoint."""


class AuditFailStop(IntegrityError):
    """The audit mismatch reproduced across ``audit_retries`` rewinds:
    deterministic corruption — fail-stop (CLI exit 4)."""


def reconcile(what: str, admitted: int, materialized: int,
              level: int | None = None) -> None:
    """Owner-side admissions must equal origin-side materializations."""
    if int(admitted) != int(materialized):
        at = f" at level {level}" if level is not None else ""
        _obs.integrity(f"reconcile: {what}{at}")
        raise IntegrityError(
            f"conservation check failed{at}: {what} admitted "
            f"{int(admitted)} new state(s) but {int(materialized)} were "
            "materialized — counts no longer reconcile across the "
            "exchange (corrupt exchange buffer, store, or verdict map)"
        )


def occupancy_check(what: str, occupancy: int, distinct: int,
                    level: int | None = None) -> None:
    """A visited structure's live entries must count the distinct set."""
    if int(occupancy) != int(distinct):
        at = f" at level {level}" if level is not None else ""
        _obs.integrity(f"occupancy: {what}{at}")
        raise IntegrityError(
            f"occupancy check failed{at}: {what} holds {int(occupancy)} "
            f"live entrie(s) for {int(distinct)} distinct state(s) — a "
            "fingerprint slab/store diverged from the run's counts"
        )


def audit_indices(n_new: int, n_sample: int) -> np.ndarray:
    """The deterministic per-level audit sample: ``n_sample`` rows
    evenly spread over ``[0, n_new)``, always including row 0 (the
    ``tensor.flip`` site's documented target, so the fault-injection
    suite exercises a guaranteed catch)."""
    n = int(min(max(n_sample, 0), n_new))
    if n <= 0:
        return np.empty(0, np.int64)
    idx = (np.arange(n, dtype=np.int64) * n_new) // n
    return np.unique(np.clip(idx, 0, n_new - 1))


class SkewMeter:
    """Per-owner level-timing/size skew — the straggler metrics.

    Each level notes per-owner work (new rows owned; on the deep path
    also per-owner store-insert seconds).  ``summary()`` feeds the
    ``--json`` ``straggler`` block: cumulative per-owner totals, the
    peak max/mean skew over the run and the owner that caused it — the
    signal a fleet scheduler uses to spot a degraded participant
    *before* it becomes a watchdog event.
    """

    def __init__(self, D: int):
        self.D = int(D)
        self.levels = 0
        self.rows = np.zeros(self.D, np.int64)
        self.seconds = np.zeros(self.D, np.float64)
        self.peak_row_skew = 0.0
        self.peak_time_skew = 0.0
        # tracked PER METRIC: each reported peak must name the owner
        # that caused it (one shared field would pair a row peak with
        # a later time peak's owner and point at the wrong device)
        self.worst_owner = None
        self.worst_owner_time = None
        self._saw_seconds = False

    @staticmethod
    def _skew(vals) -> float:
        vals = np.asarray(vals, np.float64)
        mean = vals.mean() if vals.size else 0.0
        return float(vals.max() / mean) if mean > 0 else 0.0

    def note(self, level: int, rows=None, seconds=None) -> None:
        self.levels += 1
        if rows is not None:
            rows = np.asarray(rows, np.int64).reshape(-1)[: self.D]
            self.rows[: len(rows)] += rows
            s = self._skew(rows)
            # per-level straggler signal into the flight recorder (the
            # hub is the unified sink; summary() keeps the cumulative
            # --json view)
            _obs.skew(level, s)
            if s > self.peak_row_skew:
                self.peak_row_skew = s
                self.worst_owner = int(np.argmax(rows))
        if seconds is not None:
            self._saw_seconds = True
            seconds = np.asarray(seconds, np.float64).reshape(-1)[: self.D]
            self.seconds[: len(seconds)] += seconds
            s = self._skew(seconds)
            if s > self.peak_time_skew:
                self.peak_time_skew = s
                self.worst_owner_time = int(np.argmax(seconds))

    def summary(self) -> dict:
        out = dict(
            levels=self.levels,
            per_owner_rows=[int(x) for x in self.rows],
            peak_row_skew=round(self.peak_row_skew, 3),
            worst_owner=self.worst_owner,
        )
        if self._saw_seconds:
            out["per_owner_seconds"] = [
                round(float(x), 4) for x in self.seconds
            ]
            out["peak_time_skew"] = round(self.peak_time_skew, 3)
            out["worst_owner_time"] = self.worst_owner_time
        return out
