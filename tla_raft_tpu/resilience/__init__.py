"""Fault-tolerance subsystem: crash-safe checkpoints, self-healing
resume, deterministic fault injection, graceful degradation.

Layers (docs/ROBUSTNESS.md):

* ``manifest``  — digests, per-directory ``MANIFEST.json``, and
  ``commit_npz``, the single atomic writer every checkpoint producer
  routes through (pinned by graftlint GL009).
* ``recover``   — tmp sweeping, quarantine, truncate-to-good-prefix
  healing, bounded retry, cooperative preemption.
* ``faults``    — the deterministic ``FaultPlan``
  (``TLA_RAFT_FAULT`` / ``--fault``) that makes all of the above
  testable on CPU in tier-1.
* ``elastic``   — device-loss re-sharding (owner remap onto D' != D
  devices), device-loss classification, and the per-level hang
  watchdog.
* ``integrity`` — always-on conservation checks and the opt-in
  ``--audit`` sampled-recomputation cross-check with rewind/fail-stop.
"""

from . import elastic, integrity  # noqa: F401
from .faults import (  # noqa: F401
    FAULT_SITES,
    DeviceLost,
    FaultError,
    FaultPlan,
)
from .faults import fire as fault_fire  # noqa: F401
from .faults import fire_flag as fault_flag  # noqa: F401
from .faults import install as fault_install  # noqa: F401
from .manifest import (  # noqa: F401
    Manifest,
    RunMismatch,
    adopt_file,
    commit_json,
    commit_npz,
    digest_file,
    load_json_verified,
    run_config_fingerprint,
)
from .recover import (  # noqa: F401
    Preempted,
    clear_preempt,
    discard_artifacts,
    heal_log,
    install_signal_handlers,
    preempt_requested,
    quarantine,
    request_preempt,
    sweep_tmp,
    with_retry,
)
