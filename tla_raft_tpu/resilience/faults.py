"""Deterministic fault injection for the durability layer.

TLC's ``-recover`` earns its keep by surviving hard kills; proving the
same for this checker needs crashes that happen at EXACTLY the right
instruction, repeatably, on CPU, in tier-1.  A :class:`FaultPlan` is a
parsed list of ``site:action@n`` triggers armed from the environment
(``TLA_RAFT_FAULT``) or the CLI (``--fault``); the durability-critical
code paths call :func:`fire` at named sites, and the plan performs the
requested fault when a site's hit counter reaches ``n``:

* ``kill``  — SIGKILL the process (no cleanup, no atexit: the closest
  userspace approximation of a power cut),
* ``torn``  — truncate the artifact at the site to half its bytes and
  continue (a torn write that the kernel half-flushed),
* ``flip``  — flip one byte in the middle of the artifact and continue
  (latent media corruption),
* ``fail``  — raise :class:`FaultError` (a transient error the caller
  is expected to retry or degrade around),
* ``pause`` — SIGSTOP the whole process (every thread, including the
  background lease beater) and keep running once something SIGCONTs
  it: the zombie-worker case — a GC stall, swap storm or operator ^Z
  that ages the worker's lease past the TTL while the process still
  believes it owns its jobs.  The chaos supervisor
  (``service/chaos.py``) is the something that SIGCONTs it.

Sites follow the artifact kinds of the atomic writer
(``resilience.manifest.commit_npz``): ``<kind>.tmp`` fires after the
tmp file is fully written but before digest/rename (a kill here leaves
an orphaned ``.tmp_*`` file and no record), ``<kind>.commit`` fires
after the rename but before the manifest entry lands (a kill here
leaves an unmanifested record; ``flip``/``torn`` here corrupt the
committed file AFTER its digest was recorded — the detectable-latent-
corruption case).  ``manifest.commit`` fires between the manifest's
tmp write and its rename.  Non-writer sites: ``hashstore.grow`` (the
Nth slab grow/rehash), ``exchange.fetch`` (the deep-mode host fetch),
``level.start`` (the top of each BFS level), ``pipeline.window`` (each
fetch-group submit of the async level pipeline — a kill here lands
mid-window, with up to a window's worth of groups dispatched but not
yet consumed/checkpointed).

Determinism: counters are per-site and in-process; the Nth hit is the
Nth call, full stop.  The no-plan fast path is one attribute load and
a truthiness check, so instrumented hot paths cost nothing in
production.
"""

from __future__ import annotations

import os
import signal
import sys

# site registry: name -> what firing there means.  Specs naming a site
# outside this table are rejected at parse time (a typo in a fault spec
# must not silently test nothing).
FAULT_SITES = {
    "delta.tmp": "single-device delta record: tmp written, not renamed",
    "delta.commit": "single-device delta record: renamed, not manifested",
    "partial.tmp": "intra-level partial record: tmp written, not renamed",
    "partial.commit": "intra-level partial record: renamed, not manifested",
    "mdelta.tmp": "mesh delta record: tmp written, not renamed",
    "mdelta.commit": "mesh delta record: renamed, not manifested",
    "hslab.tmp": "hash-slab snapshot: tmp written, not renamed",
    "hslab.commit": "hash-slab snapshot: renamed, not manifested",
    "sieve.tmp": "sieve snapshot / generation bloom side-car: tmp "
                 "written, not renamed",
    "sieve.commit": "sieve snapshot / generation bloom side-car: "
                    "renamed, not manifested (flip/torn here = the "
                    "corrupt-side-car quarantine-and-rebuild case)",
    "monolith.tmp": "monolith snapshot: tmp written, not renamed",
    "monolith.commit": "monolith snapshot: renamed, not manifested",
    "gen.tmp": "tiered-store generation run: tmp written, not renamed "
               "(a kill mid-demotion; resume rebuilds every tier from "
               "the delta log)",
    "gen.commit": "tiered-store generation run: renamed, not manifested",
    "compact.tmp": "tiered-store LSM-merged run: tmp written, not "
                   "renamed (a kill mid-compaction; the input runs are "
                   "still live — resume sweeps and rebuilds, never "
                   "double-counting)",
    "compact.commit": "tiered-store LSM-merged run: renamed, not "
                      "manifested (both the merged run and its inputs "
                      "are on disk until the discard lands)",
    "fseg.tmp": "spilled frontier segment: tmp written, not renamed",
    "fseg.commit": "spilled frontier segment: renamed, not manifested",
    "base.commit": "base monolith copied into a delta dir, not manifested",
    "manifest.commit": "manifest json: tmp written, not renamed",
    "hashstore.grow": "the Nth visited-slab grow/rehash",
    "exchange.fetch": "deep-mode quantized-prefix host fetch",
    "level.start": "top of a BFS level (both engines)",
    "pipeline.window": "async-pipeline fetch-group submit (the Nth "
                       "group entering the in-flight window)",
    # sweep-service artifacts (service/queue.py, service/bucket.py):
    # the same <kind>.tmp / <kind>.commit pair every atomic writer gets
    "job.tmp": "service job spec: tmp written, not renamed",
    "job.commit": "service job spec: renamed, not manifested",
    "jobstate.tmp": "service state record: tmp written, not renamed",
    "jobstate.commit": "service state record: renamed, not manifested",
    "result.tmp": "service result record: tmp written, not renamed",
    "result.commit": "service result record: renamed, not manifested",
    "lease.tmp": "service worker lease: tmp written, not renamed",
    "lease.commit": "service worker lease: renamed (unmanifested kind)",
    "lease.renew": "top of a lease heartbeat, BEFORE the ownership "
                   "re-check (`pause` here is the canonical zombie: "
                   "the beater thread wakes after the TTL aged the "
                   "lease out and must abandon, not double-commit)",
    "bucket.level": "top of each batched-bucket level (service bucket "
                    "loop; `kill` here dies mid-bucket with the bstate "
                    "snapshot behind, `pause` zombifies the worker "
                    "between level commits)",
    "worker.tmp": "pool membership record: tmp written, not renamed",
    "worker.commit": "pool membership record: renamed, not manifested",
    "bstate.tmp": "bucket snapshot: tmp written, not renamed",
    "bstate.commit": "bucket snapshot: renamed, not manifested",
    # elastic-mesh / silent-corruption sites (resilience/elastic.py,
    # resilience/integrity.py): device failures and bit flips are
    # runtime events, not writer events, so their actions are applied
    # by the instrumented code path itself (``lost``/``hang`` raise or
    # block at the site; ``tensor.flip`` is polled with ``fire_flag``
    # and the engine flips the first live frontier row on device)
    "device.lost": "top of a level's device dispatch: a device/XLA "
                   "failure (action `lost` raises DeviceLost; the CLI "
                   "maps it to exit 75 so --supervise relaunches over "
                   "the surviving mesh)",
    "device.hang": "top of a level's device dispatch: a hung XLA "
                   "dispatch (action `hang` blocks forever; the level "
                   "watchdog converts it to a clean exit 75)",
    "tensor.flip": "single-device level end: one bit of the first live "
                   "frontier row flips on device (action `flip`; the "
                   "--audit cross-check catches it and rewinds)",
}

_ACTIONS = ("kill", "torn", "flip", "fail", "lost", "hang", "pause")


class FaultError(RuntimeError):
    """An injected transient failure (``fail`` action)."""


class DeviceLost(RuntimeError):
    """An injected device/XLA failure (``lost`` action): the mesh lost
    a participant mid-run.  ``elastic.is_device_loss`` classifies this
    together with the real backend's runtime errors."""


class FaultPlan:
    """Parsed ``site:action@n`` triggers with per-site hit counters."""

    def __init__(self, spec: str = ""):
        self.triggers: list[tuple[str, str, int]] = []
        self.counts: dict[str, int] = {}
        self.fired: list[str] = []
        for item in spec.replace(";", ",").split(","):
            item = item.strip()
            if not item:
                continue
            try:
                site, action = item.split(":", 1)
            except ValueError:
                raise ValueError(
                    f"fault spec {item!r}: expected site:action[@n]"
                ) from None
            n = 1
            if "@" in action:
                action, ns = action.split("@", 1)
                n = int(ns)
            site, action = site.strip(), action.strip()
            if site not in FAULT_SITES:
                raise ValueError(
                    f"unknown fault site {site!r} (known: "
                    f"{', '.join(sorted(FAULT_SITES))})"
                )
            if action not in _ACTIONS:
                raise ValueError(
                    f"unknown fault action {action!r} (known: "
                    f"{', '.join(_ACTIONS)})"
                )
            if n < 1:
                raise ValueError(f"fault occurrence must be >= 1, got {n}")
            self.triggers.append((site, action, n))

    def fire(self, site: str, path: str | None = None) -> None:
        n = self.counts.get(site, 0) + 1
        self.counts[site] = n
        for tsite, action, tn in self.triggers:
            if tsite != site or tn != n:
                continue
            self.fired.append(f"{site}:{action}@{n}")
            self._perform(site, action, n, path)

    def fire_flag(self, site: str) -> bool:
        """Hit a site whose ``flip`` action is applied BY THE CALLER
        (in-memory tensor flips have no artifact path to mutate here):
        returns True when an armed ``flip`` trigger fires at this hit.
        Other actions armed on the site still perform normally."""
        n = self.counts.get(site, 0) + 1
        self.counts[site] = n
        hit = False
        for tsite, action, tn in self.triggers:
            if tsite != site or tn != n:
                continue
            self.fired.append(f"{site}:{action}@{n}")
            if action == "flip":
                print(f"[fault] {site}:flip@{n} — caller applies the "
                      "in-memory flip", file=sys.stderr)
                hit = True
            else:
                self._perform(site, action, n, None)
        return hit

    def _perform(self, site, action, n, path):
        note = f"[fault] {site}:{action}@{n}"
        if action == "kill":
            print(f"{note} — SIGKILL", file=sys.stderr)
            sys.stderr.flush()
            os.kill(os.getpid(), signal.SIGKILL)
        if action == "pause":
            # SIGSTOP is uncatchable and stops EVERY thread — unlike a
            # sleep here, the background lease beater freezes too, so
            # the lease genuinely ages out.  Execution resumes at the
            # return below when a supervisor SIGCONTs the process: from
            # its own point of view the worker never stopped, which is
            # exactly the confusion lease fencing must survive.
            print(f"{note} — SIGSTOP (waiting for SIGCONT)",
                  file=sys.stderr)
            sys.stderr.flush()
            os.kill(os.getpid(), signal.SIGSTOP)
            print(f"{note} — resumed", file=sys.stderr)
            return
        if action == "fail":
            raise FaultError(f"injected transient failure at {site} (#{n})")
        if action == "lost":
            print(f"{note} — raising DeviceLost", file=sys.stderr)
            raise DeviceLost(
                f"injected device loss at {site} (#{n}): a mesh "
                "participant failed mid-run"
            )
        if action == "hang":
            # the closest userspace approximation of a hung XLA
            # dispatch: the instrumented (main) thread blocks forever;
            # only the watchdog's hard exit or an external kill ends it
            print(f"{note} — hanging this thread forever", file=sys.stderr)
            sys.stderr.flush()
            import time

            while True:
                time.sleep(60)
        if path is None or not os.path.exists(path):
            raise ValueError(
                f"fault {site}:{action} needs an artifact path but the "
                "site fired without one"
            )
        size = os.path.getsize(path)
        if action == "torn":
            print(f"{note} — truncating {path} to {size // 2} B",
                  file=sys.stderr)
            with open(path, "r+b") as fh:
                fh.truncate(size // 2)
        elif action == "flip":
            print(f"{note} — flipping byte {size // 2} of {path}",
                  file=sys.stderr)
            with open(path, "r+b") as fh:
                fh.seek(size // 2)
                b = fh.read(1)
                fh.seek(size // 2)
                fh.write(bytes([b[0] ^ 0xFF]))


# The process-wide plan.  ``None`` means "not yet armed from the env";
# an EMPTY plan (no triggers) is the normal production state.
_PLAN: FaultPlan | None = None


def plan() -> FaultPlan:
    global _PLAN
    if _PLAN is None:
        _PLAN = FaultPlan(os.environ.get("TLA_RAFT_FAULT", ""))
    return _PLAN


def install(spec: str) -> FaultPlan:
    """Arm a plan explicitly (the CLI's ``--fault``; tests)."""
    global _PLAN
    _PLAN = FaultPlan(spec)
    return _PLAN


def reset() -> None:
    """Disarm (tests)."""
    global _PLAN
    _PLAN = FaultPlan("")


def fire(site: str, path: str | None = None) -> None:
    """Hit a fault site (no-op unless a plan targets it)."""
    p = plan()
    if p.triggers:
        p.fire(site, path)


def fire_flag(site: str) -> bool:
    """Hit a caller-applied site; True = perform the flip now."""
    p = plan()
    if p.triggers:
        return p.fire_flag(site)
    return False
