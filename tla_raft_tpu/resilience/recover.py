"""Self-healing resume: tmp sweeping, quarantine, prefix truncation.

The delta/mdelta logs are strictly append-only chains written by a
single ordered writer, so after any crash the directory can only be in
one of a few shapes, each with one right answer:

* **orphaned ``.tmp_*`` files** — a writer died between the payload
  write and the rename.  Swept unconditionally: a tmp file is by
  definition uncommitted (and a leaked one would shadow names and leak
  disk; a ``glob`` that picked one up would poison record ordering).
* **a corrupt/torn record** (digest mismatch, unreadable zip) —
  quarantined into ``<ckdir>/quarantine/`` and the chain truncated to
  the last good contiguous prefix; the resumed run simply re-expands
  the lost levels.
* **an unmanifested record** (renamed but the crash beat the manifest
  commit — or a pre-manifest record in a partially-manifested legacy
  directory): the rename is atomic and the zip CRCs prove the bytes,
  so a structurally-verified record is **adopted** into the ledger;
  only an unreadable one quarantines.
* **an interior hole** — a record depth missing from disk entirely
  while deeper records exist.  The ordered writer cannot produce this;
  it means tampering or mixed directories, so it stays FATAL.

``heal_log`` implements that policy for both engines; the side slabs
(``hslab.npz``, ``sieve_slab.npz``) are pure resume accelerators, so a
bad one is quarantined and the existing rebuild-from-log paths take
over.  Also here: bounded retry-with-backoff for transient failures
and the cooperative SIGTERM/SIGINT preemption flag the level loops
poll (flush-and-exit-resumable instead of dying mid-level).
"""

from __future__ import annotations

import glob
import os
import sys
import time

from . import faults
from .manifest import (
    Manifest,
    TMP_PREFIX,
    npz_readable,
    artifact_depth,
    digest_file,
)

QUARANTINE_DIR = "quarantine"


def _note(msg: str):
    print(f"[resilience] {msg}", file=sys.stderr)


def sweep_tmp(ckdir: str) -> list[str]:
    """Remove orphaned ``.tmp_*`` files (crashed writers' leftovers)."""
    swept = []
    for f in sorted(glob.glob(os.path.join(ckdir, TMP_PREFIX + "*"))):
        if os.path.isfile(f):
            os.unlink(f)
            swept.append(os.path.basename(f))
    if swept:
        _note(f"swept {len(swept)} orphaned tmp file(s) in {ckdir}: "
              + ", ".join(swept))
    return swept


def quarantine(ckdir: str, name: str, reason: str,
               m: Manifest | None = None) -> None:
    """Move a bad artifact aside (never delete: post-mortem evidence)."""
    src = os.path.join(ckdir, name)
    qdir = os.path.join(ckdir, QUARANTINE_DIR)
    os.makedirs(qdir, exist_ok=True)
    dst = os.path.join(qdir, name)
    if os.path.exists(src):
        os.replace(src, dst)
    _note(f"quarantined {name} ({reason}) -> {QUARANTINE_DIR}/")
    if m is not None:
        m.forget(name)


def heal_log(
    ckdir: str,
    prefix: str,
    *,
    run_fp: str | None = None,
    slabs: tuple[str, ...] = (),
    start_depth: int = 1,
    legacy_run_fps: tuple[str, ...] = (),
) -> list[str]:
    """Verify + heal a checkpoint directory; return the usable records.

    ``prefix`` is ``"delta"`` or ``"mdelta"``; ``slabs`` names the
    optional side snapshots to verify alongside (bad ones are
    quarantined — their loaders already fall back to rebuild-from-log).
    ``start_depth`` is where the chain is expected to begin (after a
    ``base.npz`` monolith it is base depth + 1).  ``legacy_run_fps``
    names fingerprint variants of the SAME semantic run from older
    digest schemas (the mesh resume passes its D-pinned pre-elastic
    forms): a manifest bound to one migrates to ``run_fp`` and the
    migration commits with the heal, so later appends bind cleanly.
    Returns the sorted paths of the surviving contiguous records.
    Raises ``ValueError`` on an interior hole and ``RunMismatch`` when
    the manifest belongs to a genuinely different run configuration.
    """
    sweep_tmp(ckdir)
    m = Manifest.load(ckdir)
    migrated = (
        m.exists and run_fp is not None and m.run_fp is not None
        and m.run_fp != run_fp and m.run_fp in legacy_run_fps
    )
    m.bind_run(run_fp, accept=legacy_run_fps)
    if migrated:
        _note(
            f"migrated {ckdir} manifest run fingerprint from a legacy "
            "digest schema (pre-elastic D-pinned form)"
        )
    dirty = migrated

    files = sorted(glob.glob(os.path.join(ckdir, f"{prefix}_*.npz")))
    good: dict[int, str] = {}
    bad_depths: set[int] = set()
    for f in files:
        name = os.path.basename(f)
        d = artifact_depth(name)
        status = m.verify(name)
        if status == "unmanifested" and npz_readable(f):
            # a record that renamed before the manifest commit landed
            # (the crash window between the two), or a pre-manifest
            # record in a directory another commit has since
            # manifested: the rename is atomic and the zip CRCs prove
            # the bytes, so ADOPT it — rebuild the ledger from what
            # verifies instead of destroying a valid log
            algo, dig = digest_file(f)
            m.record(name, kind=prefix, depth=d, algo=algo, digest=dig,
                     nbytes=os.path.getsize(f))
            _note(f"adopted verified unmanifested record {name}")
            status = "ok"
            dirty = True
        elif status == "ok" and not npz_readable(f):
            # a digest can match torn bytes when the tear landed before
            # the digest pass (a write the kernel never flushed): log
            # records are small, so the structural read-back is cheap
            # insurance the replay would otherwise crash on
            status = "corrupt"
        if status == "ok":
            good[d] = f
        else:
            quarantine(ckdir, name, f"{status} record", m)
            bad_depths.add(d)
            dirty = True

    for slab in slabs:
        sf = os.path.join(ckdir, slab)
        if not os.path.exists(sf):
            continue
        status = m.verify(slab)
        if status == "unmanifested" and npz_readable(sf):
            algo, dig = digest_file(sf)
            m.record(slab, kind=slab.split("_")[0].split(".")[0],
                     depth=-1, algo=algo, digest=dig,
                     nbytes=os.path.getsize(sf))
            _note(f"adopted verified unmanifested slab {slab}")
            dirty = True
        elif status != "ok":
            quarantine(ckdir, slab, f"{status} slab snapshot", m)
            dirty = True

    kept: list[str] = []
    expected = start_depth
    for d in sorted(good):
        if d == expected:
            kept.append(good[d])
            expected += 1
            continue
        # a hole before ``d``: records beyond it cannot replay.  If the
        # hole is of our own making (we just quarantined that level, or
        # the level after the last good one) the deeper records are
        # orphans of a healed tail — truncate them.  A hole nobody
        # quarantined means the directory was not produced by the
        # ordered writer: fatal.
        hole = range(expected, d)
        if bad_depths.intersection(hole):
            for dd in sorted(good):
                if dd >= d:
                    quarantine(
                        ckdir, os.path.basename(good[dd]),
                        f"beyond healed level {expected - 1}", m,
                    )
                    dirty = True
            break
        raise ValueError(
            f"{prefix} log interior gap: level {expected} is missing "
            f"from {ckdir} but level {d} exists — the append-only "
            "writer cannot produce this; refusing to guess (clear or "
            "repair the directory)"
        )

    if dirty:
        # also when the directory had no (or a torn) manifest: the
        # adopted entries become the rebuilt ledger
        m.commit()
        lost = len(files) - len(kept)
        _note(
            f"healed {ckdir}: resuming from {len(kept)} verified "
            f"record(s), {lost} truncated/quarantined"
        )
    return kept


def discard_artifacts(ckdir: str, names) -> None:
    """Unlink superseded artifacts (wiped partials) and drop their
    manifest entries in ONE manifest commit."""
    m = Manifest.load(ckdir)
    dirty = False
    for name in names:
        p = os.path.join(ckdir, name)
        if os.path.exists(p):
            os.unlink(p)
        if name in m.artifacts:
            m.forget(name)
            dirty = True
    if dirty and m.exists:
        m.commit()


# -- bounded retry for transient failures ---------------------------------

def with_retry(fn, what: str, attempts: int = 4, base_delay: float = 0.05,
               retry_on: tuple = (faults.FaultError, OSError),
               jitter: bool = True):
    """Call ``fn()`` with exponential backoff + jitter on transient errors.

    Only for IDEMPOTENT operations (re-fetching a device array,
    re-reading a file, rewriting a lease); the last failure propagates.
    ``jitter`` draws each delay uniformly from [0.5, 1.5) of the
    exponential step: when many workers hit one shared filesystem (the
    sweep service's lease renewals), synchronized retries re-collide at
    exactly the backoff boundaries — decorrelating them is what lets a
    transient FS brownout clear instead of resonating.
    """
    import random

    for i in range(attempts):
        try:
            return fn()
        except retry_on as e:
            if i == attempts - 1:
                raise
            delay = base_delay * (2 ** i)
            if jitter:
                delay *= 0.5 + random.random()
            _note(
                f"transient failure in {what} (attempt {i + 1}/"
                f"{attempts}): {e} — retrying in {delay:.2f}s"
            )
            time.sleep(delay)


# -- cooperative preemption (SIGTERM/SIGINT -> flush and exit) ------------

class Preempted(Exception):
    """Raised by the level loops after a preemption request; the run is
    resumable from its checkpoint directory."""

    def __init__(self, checkpoint_dir: str | None, depth: int):
        self.checkpoint_dir = checkpoint_dir
        self.depth = depth
        where = (
            f"state through level {depth} is durable in {checkpoint_dir}"
            if checkpoint_dir else "no checkpoint directory configured"
        )
        super().__init__(f"preempted — {where}")


_PREEMPT = {"requested": False, "signum": None}


def preempt_requested() -> bool:
    return _PREEMPT["requested"]


def request_preempt(signum=None) -> None:
    _PREEMPT["requested"] = True
    _PREEMPT["signum"] = signum


def clear_preempt() -> None:
    _PREEMPT["requested"] = False
    _PREEMPT["signum"] = None


def install_signal_handlers() -> None:
    """SIGTERM/SIGINT set the preemption flag; a second signal of the
    same kind falls through to the default action (a stuck run must
    still be killable).  CLI entry points only — libraries and tests
    poll the flag without touching process-global handler state."""
    import signal

    def handler(signum, frame):
        if _PREEMPT["requested"]:
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)
            return
        request_preempt(signum)
        _note(
            f"signal {signal.Signals(signum).name}: finishing the "
            "current level, flushing checkpoints, then exiting "
            "resumable (send again to kill immediately)"
        )

    signal.signal(signal.SIGTERM, handler)
    signal.signal(signal.SIGINT, handler)
