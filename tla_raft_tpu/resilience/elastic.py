"""Elastic mesh recovery: device-loss re-sharding + hang watchdogs.

Production accelerator fleets treat device loss and stragglers as
routine events to be absorbed, not outages: a sweep that dies because
one mesh participant failed — or that can only resume on exactly the
device count it started with — is not production-scale anything.  This
module holds the device-failure half of the resilience subsystem:

* **Owner remap** (:func:`owner_rebalance`) — the host-side math that
  re-shards a replayed frontier onto a *different* device count.  The
  owner-sharded layout (``fp % D``) already contains everything needed:
  ownership is a pure function of the fingerprint, so a D-device log
  replays into record-layout coordinates and one stable owner sort
  redistributes the live rows across D′ devices.  The mesh resume
  (``parallel/sharded.py``) uses this for the frontier and rebuilds the
  hash slabs / external store shards into the new partition from the
  replayed fingerprints (a rehash, not a copy: slot homes move with
  ``fp % D``).

* **Device-loss classification** (:func:`is_device_loss`) — one place
  that decides whether an exception means "a device/XLA participant
  failed" (resumable over the surviving mesh: exit 75, ``--supervise``
  relaunches, elastic resume absorbs the smaller mesh) versus an
  ordinary bug that must propagate.

* **Watchdog** (:class:`Watchdog`) — a per-level deadline thread that
  converts a hung XLA dispatch into a clean resumable exit instead of
  an infinite stall.  Armed at each level start with a budget of
  ``max(floor, mult * last_level_seconds)`` (generous multipliers: a
  level is only a straggler when it blows far past its predecessor);
  async fetch completions ``touch()`` the deadline so a slow-but-
  progressing level never false-trips.  On expiry it first requests
  cooperative preemption (a merely-slow level then flushes and raises
  ``Preempted`` at the next poll), and only if the run stays wedged
  past the grace window hard-exits 75 — the durable per-level log makes
  that resumable by construction.

Module contract: device-free import (numpy only, no jax) — the import
hygiene gate (tests/test_import_clean.py) covers the whole package.
"""

from __future__ import annotations

import os
import sys
import threading
import time

import numpy as np

from ..obs import telemetry as _obs
from . import faults, recover


def _pow2ceil(n: int) -> int:
    return 1 << max(0, int(n) - 1).bit_length()


# -- owner remap: re-shard a replayed frontier onto D' devices ------------

def owner_rebalance(fp_view: np.ndarray, valid: np.ndarray, D: int,
                    min_cap: int = 1):
    """Permutation that re-shards live rows by owner (``fp % D``).

    ``fp_view``/``valid`` describe a flat replayed frontier in ANY
    source layout (the live rows are wherever the log's layout put
    them).  Returns ``(perm, counts, cap)``: ``cap`` is the pow2
    per-device block width (>= ``min_cap``, sized to the heaviest
    owner), ``perm`` is an i64[D*cap] gather map (target row -> source
    row, -1 for padding) placing owner ``o``'s rows — in stable source
    order — at the prefix of block ``o``, and ``counts`` the per-owner
    live totals.  Works for D == 1 (a plain compaction) and for any
    source-layout device count: ownership is a function of the
    fingerprint alone, which is exactly what makes the mesh elastic.
    """
    fp_view = np.asarray(fp_view, np.uint64)
    valid = np.asarray(valid, bool)
    own = np.where(valid, (fp_view % np.uint64(D)).astype(np.int64), D)
    counts = np.bincount(own, minlength=D + 1)[:D].astype(np.int64)
    # keep the caller's block width when it already fits (a same-D
    # resume then reuses its layout verbatim); grow pow2 otherwise
    need = max(int(counts.max()) if D else 1, 1)
    cap = int(min_cap) if need <= int(min_cap) else _pow2ceil(need)
    order = np.argsort(own, kind="stable")
    starts = np.cumsum(counts) - counts
    perm = np.full(D * cap, -1, np.int64)
    for o in range(D):
        seg = order[starts[o]: starts[o] + counts[o]]
        perm[o * cap: o * cap + counts[o]] = seg
    return perm, counts, cap


# -- device-loss classification -------------------------------------------

# substrings that mark a BACKEND runtime error as "a device went away"
# rather than a program bug.  Deliberately conservative: a misclassified
# bug would relaunch-loop instead of surfacing, so only the XLA/PJRT
# runtime exception types are consulted (never a bare RuntimeError) and
# only health-shaped messages count — "deadline exceeded"/"unavailable"
# are the canonical surviving-peer symptoms of a dead collective
# participant under the pinned XLA collective-timeout flags (xla_env).
_DEVICE_LOSS_MARKERS = (
    "device lost",
    "device is lost",
    "deadline exceeded",
    "failed to enqueue",
    "socket closed",
    "connection reset",
    "unavailable:",
    "halted execution",
    "device failure",
)


def is_device_loss(exc: BaseException) -> bool:
    """True when ``exc`` means a mesh participant/device failed.

    Covers the injected :class:`faults.DeviceLost` and the backend's
    ``XlaRuntimeError`` family when the message carries a device-health
    marker.  Everything else — including plain ``RuntimeError``s whose
    text happens to mention a marker — is an ordinary error and must
    propagate with its traceback.
    """
    if isinstance(exc, faults.DeviceLost):
        return True
    name = type(exc).__name__
    if name not in ("XlaRuntimeError", "JaxRuntimeError"):
        return False
    msg = str(exc).lower()
    return any(m in msg for m in _DEVICE_LOSS_MARKERS)


def effective_mesh(requested: int, out=None) -> int:
    """Clamp a resumed run's mesh width to the surviving device count.

    A relaunch after device loss sees fewer devices than the original
    ``--mesh N``; refusing to start would defeat the elastic resume, so
    recovery runs re-shard onto what is actually there.  Fresh runs
    keep the strict ``make_mesh`` error (a typo'd --mesh must fail)."""
    import jax  # deferred: callers are already past backend init

    avail = len(jax.devices())
    if requested <= avail:
        return requested
    msg = (
        f"[elastic] requested a {requested}-device mesh but only "
        f"{avail} device(s) survive — re-sharding the resumed run "
        f"onto {avail} (owner remap, fp % {avail})"
    )
    print(msg, file=out if out is not None else sys.stderr)
    return avail


# -- the level watchdog ----------------------------------------------------

_WATCHDOG: "Watchdog | None" = None


def install_watchdog(wd: "Watchdog | None") -> None:
    """Publish the run's watchdog so deep layers (the async pipeline's
    fetch completions) can ``touch()`` it without plumbing."""
    global _WATCHDOG
    _WATCHDOG = wd


def watchdog_touch() -> None:
    wd = _WATCHDOG
    if wd is not None:
        wd.touch()


class Watchdog:
    """Per-level deadline thread: hung dispatch -> clean exit 75.

    ``floor`` is the minimum per-level budget in seconds (the CLI's
    ``--watchdog SECS``); the armed budget is
    ``max(floor, mult * last_level_seconds)`` so organic level growth
    never trips it while a wedged collective (one lost participant, a
    deadlocked rendezvous) does.  Expiry ladder: request cooperative
    preemption first (a slow level finishes, flushes checkpoints and
    raises ``Preempted`` — exit 75 with a durable log), then after the
    grace window hard-exit 75 (``os._exit`` — a truly hung dispatch
    never returns to Python, so nothing gentler can run).
    """

    def __init__(self, floor: float, mult: float = 8.0,
                 on_hard_timeout=None):
        self.floor = float(floor)
        self.mult = float(mult)
        self.fired = 0
        self._hist: list[float] = []  # recent level wall times
        self._cv = threading.Condition()
        self._armed: dict | None = None
        self._fired_ctx: dict | None = None  # consumed level, mid-grace
        self._stop = False
        self._last_release = 0.0
        self._thread: threading.Thread | None = None
        self._hard = on_hard_timeout or self._default_hard_timeout

    @staticmethod
    def _default_hard_timeout():
        sys.stderr.flush()
        sys.stdout.flush()
        os._exit(75)

    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="tla-raft-watchdog", daemon=True
            )
            self._thread.start()

    def arm(self, context: str, span: int = 1) -> None:
        """Arm one dispatch window covering ``span`` BFS levels.

        The history holds PER-LEVEL wall times (``disarm`` divides a
        window's elapsed time by its declared span before recording),
        so a multi-level superstep earns ``span`` times the per-level
        adaptive budget instead of tripping the single-level one —
        and the budgets stay comparable when the run switches between
        superstep and per-level windows."""
        span = max(1, int(span))
        last = self._hist[-1] if self._hist else 0.0
        budget = span * max(self.floor, self.mult * last)
        if not self._hist:
            # the first armed level of a (re)launched process pays the
            # cold compile ladder with no history and (pre-pipeline)
            # no touch() heartbeats; at the bare floor a supervised
            # relaunch could hard-kill it mid-compile every time and
            # make zero progress — give the cold level the same
            # multiplier headroom an adaptive level would get
            budget = max(budget, span * self.mult * self.floor)
        with self._cv:
            self._armed = dict(
                context=context, budget=budget, span=span,
                started=time.monotonic(),
                deadline=time.monotonic() + budget,
            )
            self._cv.notify_all()
        self._ensure_thread()
        _obs.watchdog_arm(context, budget)

    def touch(self) -> None:
        """Progress heartbeat (async fetch completions, store inserts):
        a level that keeps moving keeps earning its budget."""
        with self._cv:
            a = self._armed
            if a is not None:
                a["deadline"] = time.monotonic() + a["budget"]

    def disarm(self, levels: int | None = None) -> None:
        with self._cv:
            # _fire consumes _armed before sleeping out the grace; a
            # level that then finishes must still record its wall time
            # (via the parked fired context) or the next arm's adaptive
            # budget would be computed from a level two-plus back and
            # false-trip the following one
            a = self._armed or self._fired_ctx
            self._armed = None
            self._fired_ctx = None
            self._last_release = time.monotonic()
            if a is not None:
                # record PER-LEVEL wall time: a span-N window's elapsed
                # divides by the levels it actually covered so the next
                # adaptive budget is level-normalized regardless of
                # window kind.  ``levels`` lets a stopped superstep
                # report its committed count — dividing a one-level
                # window's elapsed by the full declared span would
                # deflate the history and false-trip the level's own
                # per-level replay (span > mult makes budget < elapsed)
                span = max(1, int(a.get("span", 1)))
                if levels is not None:
                    span = min(span, max(1, int(levels)))
                self._hist.append((time.monotonic() - a["started"]) / span)
                del self._hist[:-3]

    def cancel(self) -> None:
        with self._cv:
            self._armed = None
            self._stop = True
            self._last_release = time.monotonic()
            self._cv.notify_all()

    def _run(self):
        while True:
            with self._cv:
                while not self._stop and self._armed is None:
                    self._cv.wait()
                if self._stop:
                    return
                a = self._armed
                now = time.monotonic()
                if now < a["deadline"]:
                    self._cv.wait(a["deadline"] - now)
                    continue
                self._armed = None
                ctx = dict(a)
                self._fired_ctx = ctx
            self._fire(ctx)

    def _fire(self, a: dict):
        self.fired += 1
        fire_t = time.monotonic()
        print(
            f"[watchdog] {a['context']} exceeded its "
            f"{a['budget']:.1f}s deadline — requesting cooperative "
            "preemption (flush-and-exit-resumable)",
            file=sys.stderr,
        )
        sys.stderr.flush()
        _obs.watchdog_trip(a["context"], "soft")
        recover.request_preempt()
        # the grace scales with the armed budget (a level trusted with
        # a 2-minute budget earns a proportionate wind-down) so a slow-
        # but-finishing level exits COOPERATIVELY with its record
        # committed instead of being hard-killed into a no-progress
        # relaunch loop; capped so a real hang still dies promptly
        grace = min(max(self.floor, 1.0, 0.5 * a["budget"]), 60.0)
        time.sleep(grace)
        with self._cv:
            released = self._last_release >= fire_t or self._stop
        if released:
            return  # the run reacted (finished the level or exited)
        print(
            f"[watchdog] {a['context']} still wedged "
            f"{grace:.1f}s after preemption request — hard exit 75 "
            "(state through the last committed level is durable)",
            file=sys.stderr,
        )
        _obs.watchdog_trip(a["context"], "hard")
        hub = _obs.current()
        if hub is not None:
            # about to os._exit: the trip should reach the flight
            # recorder — but BOUNDED (side thread + timeout): a hung
            # filesystem is exactly the failure class this path
            # converts into exit 75, so it must never block on one
            hub.flush_best_effort()
        self._hard()
