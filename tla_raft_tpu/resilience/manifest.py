"""Checkpoint integrity: digests, per-directory manifests, atomic writes.

Every checkpoint artifact this project writes (``delta_*.npz`` +
``hslab.npz``, ``partial_*.npz``, ``mdelta_*.npz`` + ``sieve_slab.npz``,
monoliths) commits through ONE helper — :func:`commit_npz` — which:

1. writes the payload to ``.tmp_<name>`` in the target directory,
2. digests the tmp bytes (xxh64 when the interpreter carries the
   xxhash wheel, else hashlib's blake2b truncated to 64 bits — the
   algorithm rides in the manifest entry, so mixed-environment dirs
   verify correctly),
3. ``os.replace``-renames tmp -> final (atomic on POSIX),
4. records ``{digest, algo, bytes, kind, depth}`` in the directory's
   ``MANIFEST.json`` and commits THAT atomically too.

The manifest is the durability layer's source of trust, not of truth:
a record that fails its digest is quarantined and the run resumes from
the surviving contiguous prefix (resilience/recover.py); the replay
chain itself remains the only authority on contents.  Besides the
artifact table the manifest pins a **schema version**, the **run
config fingerprint** (a digest of the semantic run configuration —
spec constants, fingerprint definition, mesh width, exchange/canon
mode; NOT tunables like chunk size), so two different runs can never
silently interleave their logs in one directory, and a **contiguous-
depth watermark** — the deepest level whose whole record prefix is
manifested — maintained incrementally and recomputed after healing.

Fault-injection sites (resilience/faults.py) fire between every pair
of steps above, which is what makes the crash matrix in
tests/test_resilience.py exhaustive per artifact kind.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import time

import numpy as np

from ..obs import telemetry as _obs

from . import faults

SCHEMA_VERSION = 1
MANIFEST_NAME = "MANIFEST.json"
TMP_PREFIX = ".tmp_"

try:  # the baked image may or may not carry the xxhash wheel; gate it
    import xxhash as _xxhash
except ImportError:  # pragma: no cover - environment-dependent
    _xxhash = None

_DIGEST_CHUNK = 8 << 20


def _hasher(algo: str | None = None):
    """(algo_name, hasher) — prefer xxh64, fall back to blake2b/64."""
    if algo in (None, "xxh64") and _xxhash is not None:
        return "xxh64", _xxhash.xxh64()
    if algo == "xxh64":  # recorded by an env that had the wheel
        raise LookupError("xxh64 unavailable")
    return "blake2b64", hashlib.blake2b(digest_size=8)


def digest_file(path: str, algo: str | None = None) -> tuple[str, str]:
    """Streamed digest of a file's bytes: (algo, hexdigest)."""
    name, h = _hasher(algo)
    with open(path, "rb") as fh:
        while True:
            chunk = fh.read(_DIGEST_CHUNK)
            if not chunk:
                break
            h.update(chunk)
    return name, h.hexdigest()


def run_config_fingerprint(cfg, **extra) -> str:
    """Digest of the SEMANTIC run configuration.

    Covers the spec constants (every RaftConfig field) plus whatever
    the engine passes in ``extra`` (engine kind, fingerprint
    definition, mesh width, exchange/canon modes).  Deliberately
    excludes tunables (chunk, cap_x, seg_rows): a resume may retune
    them freely without invalidating the log.
    """
    import dataclasses

    doc = dict(dataclasses.asdict(cfg))
    doc.update(extra)
    blob = json.dumps(doc, sort_keys=True, default=str).encode()
    name, h = _hasher()
    h.update(blob)
    return f"{name}:{h.hexdigest()}"


class RunMismatch(ValueError):
    """The directory's manifest belongs to a different run config."""


# parsed-manifest cache keyed by (mtime_ns, size): the per-group
# partial writer and per-level delta/hslab writers each load-commit the
# same file many times per level — without the cache that is a fresh
# JSON parse of every accumulated entry per commit (quadratic over a
# level's groups).  Entry dicts are never mutated in place (record()
# replaces them wholesale), so shallow copies keep cache and instances
# independent.
_DOC_CACHE: dict[str, tuple[tuple[int, int], dict]] = {}


def _stat_key(path: str) -> tuple[int, int]:
    st = os.stat(path)
    return (st.st_mtime_ns, st.st_size)


class Manifest:
    """The per-checkpoint-directory integrity ledger."""

    def __init__(self, ckdir: str):
        self.ckdir = ckdir
        self.path = os.path.join(ckdir, MANIFEST_NAME)
        self.exists = False
        self.schema = SCHEMA_VERSION
        self.run_fp: str | None = None
        self.watermark = 0
        self.artifacts: dict[str, dict] = {}

    @classmethod
    def load(cls, ckdir: str) -> "Manifest":
        m = cls(ckdir)
        try:
            key = _stat_key(m.path)
        except OSError:
            return m
        cached = _DOC_CACHE.get(m.path)
        if cached is not None and cached[0] == key:
            data = cached[1]
        else:
            try:
                with open(m.path, encoding="utf-8") as fh:
                    data = json.load(fh)
            except (OSError, ValueError):
                # a torn manifest is recoverable state, not a fatal
                # error: treat the directory as legacy/unmanifested and
                # let the healer rebuild the ledger from what verifies
                return m
            _DOC_CACHE[m.path] = (key, data)
        m.exists = True
        m.schema = int(data.get("schema", SCHEMA_VERSION))
        m.run_fp = data.get("run_fp")
        m.watermark = int(data.get("watermark", 0))
        m.artifacts = dict(data.get("artifacts", {}))
        return m

    # -- mutation ------------------------------------------------------

    def bind_run(self, run_fp: str | None,
                 accept: tuple[str, ...] = ()):
        """Pin (or check) the directory's run config fingerprint.

        ``accept`` lists LEGACY fingerprint variants of the same
        semantic run (fields since removed from the digest — e.g. the
        mesh device count, dropped when resume went elastic): a
        manifest bound to one of them MIGRATES to ``run_fp`` in place
        instead of refusing a valid log.  The rebinding persists on the
        next commit (every heal/append commits)."""
        if run_fp is None:
            return
        if self.run_fp is None or self.run_fp in accept:
            self.run_fp = run_fp
        elif self.run_fp != run_fp:
            raise RunMismatch(
                f"{self.ckdir} was checkpointed by a different run "
                f"configuration (manifest {self.run_fp}, this run "
                f"{run_fp}) — two runs' logs must not interleave; "
                "clear the directory or resume with the matching "
                "configuration"
            )

    def record(self, name: str, *, kind: str, depth: int, algo: str,
               digest: str, nbytes: int):
        self.artifacts[name] = dict(
            kind=kind, depth=int(depth), algo=algo, digest=digest,
            bytes=int(nbytes),
        )
        if kind in ("delta", "mdelta"):
            self.watermark = self._contiguous_depth()

    def forget(self, name: str):
        if self.artifacts.pop(name, None) is not None:
            self.watermark = self._contiguous_depth()

    def _contiguous_depth(self) -> int:
        depths = sorted(
            e["depth"] for e in self.artifacts.values()
            if e.get("kind") in ("delta", "mdelta")
        )
        if not depths:
            return 0
        hi = depths[0]
        for d in depths[1:]:
            if d != hi + 1:
                break
            hi = d
        return hi

    def commit(self):
        """Atomically persist the ledger."""
        tmp = os.path.join(self.ckdir, TMP_PREFIX + MANIFEST_NAME)
        doc = dict(
            schema=self.schema,
            run_fp=self.run_fp,
            watermark=self.watermark,
            artifacts=dict(sorted(self.artifacts.items())),
        )
        os.makedirs(self.ckdir, exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=1)
            fh.write("\n")
        faults.fire("manifest.commit", tmp)
        os.replace(tmp, self.path)
        self.exists = True
        try:
            _DOC_CACHE[self.path] = (
                _stat_key(self.path),
                dict(doc, artifacts=dict(doc["artifacts"])),
            )
        except OSError:  # racing unlink: just drop the cache entry
            _DOC_CACHE.pop(self.path, None)

    # -- verification --------------------------------------------------

    def verify(self, name: str) -> str:
        """One artifact's integrity status.

        ``ok``           digest matches (or legacy dir: readable file)
        ``missing``      manifested but not on disk
        ``unmanifested`` on disk but unknown to a manifest that exists
        ``corrupt``      digest mismatch or unreadable npz
        """
        path = os.path.join(self.ckdir, name)
        entry = self.artifacts.get(name)
        on_disk = os.path.exists(path)
        if not on_disk:
            return "missing" if entry is not None else "unmanifested"
        if entry is None:
            if not self.exists:
                # legacy (pre-manifest) directory: fall back to a
                # structural read check so torn zips still quarantine
                return "ok" if npz_readable(path) else "corrupt"
            return "unmanifested"
        try:
            algo, dig = digest_file(path, entry.get("algo"))
        except LookupError:
            # recorded with a digest algo this interpreter lacks:
            # keep the record if it is structurally readable
            return "ok" if npz_readable(path) else "corrupt"
        if dig != entry.get("digest"):
            return "corrupt"
        return "ok"


def npz_readable(path: str) -> bool:
    import zipfile

    try:
        with np.load(path) as z:
            for k in z.files:
                z[k]
        return True
    except (OSError, ValueError, KeyError, EOFError, zipfile.BadZipFile):
        return False


def commit_npz(
    ckdir: str,
    name: str,
    arrays: dict,
    *,
    kind: str,
    depth: int = -1,
    run_fp: str | None = None,
    compressed: bool = False,
    manifest: bool = True,
) -> str:
    """The one atomic checkpoint writer (see module docstring).

    Every checkpoint producer in the tree routes through here —
    graftlint rule GL009 pins that no ``np.savez``/``os.replace``
    checkpoint write exists outside this module.
    """
    t0 = time.monotonic()
    os.makedirs(ckdir, exist_ok=True)
    tmp = os.path.join(ckdir, TMP_PREFIX + name)
    save = np.savez_compressed if compressed else np.savez
    save(tmp, **arrays)
    faults.fire(f"{kind}.tmp", tmp)
    algo, dig = digest_file(tmp)
    nbytes = os.path.getsize(tmp)
    final = os.path.join(ckdir, name)
    os.replace(tmp, final)
    faults.fire(f"{kind}.commit", final)
    if manifest:
        m = Manifest.load(ckdir)
        m.bind_run(run_fp)
        m.record(name, kind=kind, depth=depth, algo=algo, digest=dig,
                 nbytes=nbytes)
        m.commit()
    _obs.checkpoint(kind, name, time.monotonic() - t0, nbytes)
    return final


def commit_json(
    ckdir: str,
    name: str,
    doc: dict,
    *,
    kind: str,
    depth: int = -1,
    run_fp: str | None = None,
    manifest: bool = True,
) -> str:
    """The atomic JSON twin of :func:`commit_npz`.

    The sweep service's queue records (job specs, state transitions,
    leases, result summaries) are JSON documents, not arrays — but they
    are checkpoint artifacts all the same: a torn ``state.json`` is a
    stuck job, a torn ``result.json`` is a lost verdict.  Same steps:
    tmp write -> digest -> ``os.replace`` -> manifest entry, with the
    same ``<kind>.tmp`` / ``<kind>.commit`` fault sites so the crash
    matrix covers the queue exactly like the delta log.  Pass
    ``manifest=False`` for high-churn records whose loss is benign
    (worker lease heartbeats): the write stays atomic but skips the
    per-directory ledger commit.
    """
    t0 = time.monotonic()
    os.makedirs(ckdir, exist_ok=True)
    tmp = os.path.join(ckdir, TMP_PREFIX + name)
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True, default=str)
        fh.write("\n")
    faults.fire(f"{kind}.tmp", tmp)
    algo, dig = digest_file(tmp)
    nbytes = os.path.getsize(tmp)
    final = os.path.join(ckdir, name)
    os.replace(tmp, final)
    faults.fire(f"{kind}.commit", final)
    if manifest:
        m = Manifest.load(ckdir)
        m.bind_run(run_fp)
        m.record(name, kind=kind, depth=depth, algo=algo, digest=dig,
                 nbytes=nbytes)
        m.commit()
    if manifest and kind != "metrics":
        # skip the periodic-housekeeping writers: the metrics snapshot
        # is the telemetry system writing about itself, and
        # manifest=False marks high-churn records (lease heartbeats,
        # every ttl/3 per job from the beater thread) — recording
        # either would grow the event stream one non-progress line per
        # tick forever and inflate the checkpoint aggregates
        _obs.checkpoint(kind, name, time.monotonic() - t0, nbytes)
    return final


def load_json_verified(ckdir: str, name: str):
    """Load a JSON artifact, digest-checked against the directory's
    manifest when an entry exists (``commit_json``'s read side).

    Returns the parsed document, or ``None`` when the file is missing
    OR fails verification/parsing — queue readers treat a torn or
    corrupt record exactly like an absent one (the state machine
    re-derives it from the surviving records; nothing here is the
    source of truth, matching the manifest-layer contract).
    """
    path = os.path.join(ckdir, name)
    m = Manifest.load(ckdir)
    status = m.verify(name)
    if status in ("missing", "corrupt"):
        return None
    if status == "unmanifested" and not os.path.exists(path):
        return None
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def adopt_file(ckdir: str, name: str, *, kind: str, depth: int = -1,
               run_fp: str | None = None) -> None:
    """Manifest an artifact that landed by copy rather than through
    :func:`commit_npz` (the ``base.npz`` monolith a delta-appending
    resume anchors into its directory)."""
    path = os.path.join(ckdir, name)
    algo, dig = digest_file(path)
    faults.fire(f"{kind}.commit", path)
    m = Manifest.load(ckdir)
    m.bind_run(run_fp)
    m.record(name, kind=kind, depth=depth, algo=algo, digest=dig,
             nbytes=os.path.getsize(path))
    m.commit()


_DEPTH_RE = re.compile(r"_(\d{4,})\.npz$")


def artifact_depth(name: str) -> int:
    """Level number encoded in a delta/mdelta record name (-1 if none)."""
    m = _DEPTH_RE.search(name)
    return int(m.group(1)) if m else -1
