"""Mesh-parallel BFS: the distributed-communication backend.

The reference's only parallelism is TLC's shared-memory worker pool
(``-workers 4``, /root/reference/myrun.sh:3); its distributed mode is
unused.  The TPU-native replacement shards the **frontier** over a 1-D
device mesh axis ``d`` (each device expands and materializes its own
states — full states never cross the interconnect) and exchanges only
64-bit fingerprints per BFS level:

  v1 (this module): each device locally pre-dedups its candidate
  fingerprints (lexsort + unique), then an ``all_gather`` shares the
  compacted per-device survivors; every device runs the same global
  dedup against the (replicated) visited store and keeps exactly the
  winners it originated.  Deterministic representative choice — min
  (fp_view, fp_full, payload) — is preserved across any device count.

  v2 (planned, BASELINE.json north star): hash-shard the visited store
  by ``fp mod n_dev`` and route candidates to owners with an
  ``all_to_all``, returning verdict bits; drops the replicated store and
  the redundant global dedup.

New states are rebalanced across devices round-robin by global rank so
frontier load stays even regardless of which device discovered them
(states are cheap to ship *as (parent, slot) recipes*: the origin device
holds the parent, so materialization happens on the origin and the
balanced assignment only relabels which device expands the child — we
implement this by keeping children on their origin device; hash
uniformity keeps origination itself balanced).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import RaftConfig
from ..models.raft import RaftState, init_batch
from ..ops.successor import get_kernel

U64 = jnp.uint64
I64 = jnp.int64
SENT = jnp.uint64(0xFFFFFFFFFFFFFFFF)


def make_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), axis_names=("d",))


class LevelOut(NamedTuple):
    """Per-device outputs of one distributed BFS level (shard_map body)."""

    children: RaftState  # [cap_c, ...] local new states (padded)
    child_msum: jnp.ndarray  # u32[cap_c, P, chan]
    n_new_local: jnp.ndarray  # i64[] this device's new states
    n_new_total: jnp.ndarray  # i64[] psum over mesh
    generated: jnp.ndarray  # i64[] psum over mesh
    new_fps_global: jnp.ndarray  # u64[D*cap_x] all new fps (replicated)
    pidx: jnp.ndarray  # i64[cap_c] local parent indices (for traces)
    slots: jnp.ndarray  # i64[cap_c] local slots (for traces)
    abort: jnp.ndarray  # bool[] any split-brain abort (psum'd)
    overflow: jnp.ndarray  # bool[] cap_x exceeded somewhere -> retry bigger


class ShardedChecker:
    """One distributed BFS level step, shard_map'd over a 1-D mesh.

    The host driver (engine/bfs.py's loop generalizes; here we expose the
    level step + a minimal ``run`` used by tests and the multichip
    dry-run) keeps per-device frontier shards as a leading ``[D, cap_f]``
    axis sharded over ``d``.
    """

    def __init__(self, cfg: RaftConfig, mesh: Mesh, cap_x: int = 4096):
        self.cfg = cfg
        self.mesh = mesh
        self.kern = get_kernel(cfg)
        self.fpr = self.kern.fpr
        self.K = self.kern.K
        self.D = mesh.devices.size
        self.cap_x = cap_x  # per-device compacted-candidate capacity

    # -- the per-device level body ----------------------------------------

    def _level_body(self, frontier: RaftState, msum, n_f, visited):
        """Runs per device under shard_map; arrays are local shards.

        frontier leaves: [cap_f_local, ...]; n_f: i64[1] local live count;
        visited: u64[Vcap] replicated sorted store.
        """
        K = self.K
        cap_f = frontier.voted_for.shape[0]
        dev = jax.lax.axis_index("d").astype(I64)

        exp = self.kern.expand(frontier, msum)
        in_range = (jnp.arange(cap_f) < n_f[0])[:, None]
        valid = exp.valid & in_range
        fpv = jnp.where(valid, exp.fp_view, SENT).ravel()
        fpf = jnp.where(valid, exp.fp_full, SENT).ravel()
        # global payload: (device-global parent index) * K + slot
        gparent = dev * cap_f + jnp.arange(cap_f, dtype=I64)
        payload = (gparent[:, None] * K + jnp.arange(K, dtype=I64)[None]).ravel()
        generated = jax.lax.psum(
            jnp.where(valid, exp.mult, 0).astype(I64).sum(), "d"
        )
        abort = jax.lax.psum(
            (exp.abort & in_range[:, 0]).any().astype(jnp.int32), "d"
        ) > 0

        # local pre-dedup: first (min fp_full, min payload) per view fp
        order = jnp.lexsort((payload, fpf, fpv))
        sv, sf, sp = fpv[order], fpf[order], payload[order]
        first = jnp.concatenate([jnp.ones((1,), bool), sv[1:] != sv[:-1]])
        pos = jnp.searchsorted(visited, sv)
        hit = visited[jnp.clip(pos, 0, visited.shape[0] - 1)] == sv
        keep = first & (sv != SENT) & ~hit
        n_keep = keep.sum()
        overflow = n_keep > self.cap_x
        comp = jnp.argsort(~keep, stable=True)
        take = jnp.arange(self.cap_x)
        src = comp[jnp.clip(take, 0, comp.shape[0] - 1)]
        lane = (take < n_keep) & (take < comp.shape[0])
        cv = jnp.where(lane, sv[src], SENT)
        cf = jnp.where(lane, sf[src], SENT)
        cp = jnp.where(lane, sp[src], -1)

        # exchange compacted candidates; global dedup replicated on every
        # device (identical inputs -> identical result, no divergence)
        gv = jax.lax.all_gather(cv, "d").reshape(-1)
        gf = jax.lax.all_gather(cf, "d").reshape(-1)
        gp = jax.lax.all_gather(cp, "d").reshape(-1)
        gorder = jnp.lexsort((gp, gf, gv))
        gsv = gv[gorder]
        gfirst = jnp.concatenate([jnp.ones((1,), bool), gsv[1:] != gsv[:-1]])
        gnew = gfirst & (gsv != SENT)
        n_new_total = gnew.sum().astype(I64)
        # each device keeps the winners whose parent lives on it
        gpay = gp[gorder]
        win = gnew & (gpay // (K * cap_f) == dev)
        n_new_local = win.sum().astype(I64)
        cap_c = self.cap_x  # local children capacity
        wcomp_full = jnp.argsort(~win, stable=True)
        wtake = jnp.arange(cap_c)
        wcomp = wcomp_full[jnp.clip(wtake, 0, wcomp_full.shape[0] - 1)]
        wlane = (wtake < n_new_local) & (wtake < wcomp_full.shape[0])
        wpay = jnp.where(wlane, gpay[wcomp], 0)
        pidx = (wpay // K) % cap_f
        slots = wpay % K
        parents = jax.tree.map(lambda x: x[pidx], frontier)
        children = self.kern.materialize(parents, slots)
        child_msum = self.fpr.msg_hash(children.msgs)
        # mask padding lanes to the (deterministic) init-like zero state so
        # replicated buffers stay bitwise equal across devices
        children = jax.tree.map(
            lambda x: jnp.where(
                wlane.reshape((-1,) + (1,) * (x.ndim - 1)), x, jnp.zeros_like(x)
            ),
            children,
        )
        new_fps = jnp.where(gnew, gsv, SENT)
        gcomp = jnp.argsort(~gnew, stable=True)
        new_fps = new_fps[gcomp]  # compacted, SENT-padded, replicated

        return LevelOut(
            children, child_msum,
            n_new_local[None], n_new_total, generated, new_fps,
            jnp.where(wlane, pidx, -1), jnp.where(wlane, slots, -1),
            abort, jax.lax.psum(overflow.astype(jnp.int32), "d") > 0,
        )

    @functools.cached_property
    def level_step(self):
        spec_state = jax.tree.map(lambda _: P("d"), init_batch(self.cfg, 1))
        return jax.jit(
            jax.shard_map(
                self._level_body,
                mesh=self.mesh,
                in_specs=(spec_state, P("d"), P("d"), P()),
                out_specs=LevelOut(
                    jax.tree.map(lambda _: P("d"), init_batch(self.cfg, 1)),
                    P("d"), P("d"), P(), P(), P(), P("d"), P("d"), P(), P(),
                ),
                # the scatter-in-switch inside materialize trips the vma
                # (varying-axis) type checker; the body is plain SPMD with
                # explicit collectives, so opt out of the check
                check_vma=False,
            )
        )

    # -- minimal distributed run (tests + dry-run) ------------------------

    def run(self, max_depth: int | None = None):
        """Distributed BFS to fixpoint; returns (distinct, generated, depth,
        level_sizes).  Invariants/traces stay on the single-device engine;
        this path is the scaling backend (verdict parity is established by
        comparing distinct counts against it in tests)."""
        cfg, D = self.cfg, self.D
        mesh = self.mesh
        shard = NamedSharding(mesh, P("d"))
        repl = NamedSharding(mesh, P())

        cap_f = 1
        frontier = init_batch(cfg, D)  # one init copy per device lane
        frontier = jax.device_put(frontier, shard)
        fv, _ff, msum = self.fpr.state_fingerprints(frontier)
        msum = jax.device_put(msum, shard)
        # only device 0's lane is live
        n_f = jax.device_put(
            jnp.asarray([1] + [0] * (D - 1), I64), shard
        )
        visited = jnp.sort(
            jnp.concatenate([fv.astype(U64)[:1], jnp.full((63,), SENT, U64)])
        )
        visited = jax.device_put(visited, repl)
        distinct, generated, depth = 1, 0, 0
        level_sizes = [1]

        while True:
            if max_depth is not None and depth >= max_depth:
                break
            out = self.level_step(frontier, msum, n_f, visited)
            if bool(out.overflow):
                raise RuntimeError(
                    f"cap_x={self.cap_x} overflow at level {depth + 1}; "
                    "re-run with a larger capacity"
                )
            n_new = int(out.n_new_total)
            generated += int(out.generated)
            if n_new == 0:
                break
            distinct += n_new
            level_sizes.append(n_new)
            depth += 1
            # merge new fps (replicated) into the replicated store
            visited = jnp.sort(jnp.concatenate([visited, out.new_fps_global]))[
                : 1 << max(6, (distinct + 1).bit_length())
            ]
            visited = jax.device_put(visited, repl)
            frontier = out.children
            msum = out.child_msum
            n_f = jax.device_put(out.n_new_local, shard)
        return distinct, generated, depth, tuple(level_sizes)
