"""Mesh-parallel BFS: the distributed-communication backend.

The reference's only parallelism is TLC's shared-memory worker pool
(``-workers 4``, /root/reference/myrun.sh:3); its distributed mode is
unused.  The TPU-native replacement shards the **frontier** over a 1-D
device mesh axis ``d``: each device expands its own states, candidate
fingerprints are exchanged for dedup, and (all_to_all mode) each NEW
state's full ~700 B crosses the interconnect exactly once — origin to
owner shard (``fp % D``) — so the next frontier is hash-balanced across
devices.  (Rounds 2-4 kept children on their parents' device; since
everything descends from the one init state, the whole frontier stayed
on device 0 and the mesh load-balanced nothing — the round-4 depth-13
chain records n_local = [N, 0, ..., 0] at every level.)  Two exchange
strategies:

* ``all_gather`` (small scale): each device locally pre-dedups its
  candidate fingerprints (lexsort + unique), an ``all_gather`` shares the
  compacted survivors, and every device runs the same global dedup
  against a **replicated** visited store, keeping the winners it
  originated.

* ``all_to_all`` (the scaling design, BASELINE.json north star): the
  visited store is **hash-sharded** — device ``o`` owns fingerprint
  ``fp`` iff ``fp % D == o``.  Each device routes its pre-deduped
  candidates to their owners with one ``lax.all_to_all``, owners dedup
  against their store shard (every copy of a fingerprint reaches the
  same owner, so dedup is exact), update the shard in place, and return
  one verdict bit per candidate with a reverse ``all_to_all``.  Nothing
  is replicated; per-level interconnect traffic is ~16 bytes per
  candidate fingerprint.

Determinism: representative choice is min (fp_view, fp_full, payload)
under a global total order, so results are identical for any device
count and to the single-device engine (engine/bfs.py) and the Python
oracle — the parity tests assert exactly that.

Invariant checking runs on each device over its freshly materialized
children; counterexample traces replay the (slot) chain from Init just
like the single-device engine.
"""

from __future__ import annotations

import functools
import os
import sys
import time
from types import SimpleNamespace
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import resilience
from ..analysis import sanitize as graft_sanitize
from ..obs import telemetry as graft_obs
from ..config import RaftConfig
from ..engine import pipeline as graft_pipeline
from ..engine.bfs import _compact_payloads
from ..engine.invariants import resolve_invariant_kernel
from ..ops import hashstore
from ..models.raft import RaftState, init_batch, to_oracle
from ..ops.successor import get_kernel
from .exchange import (
    ExchangeMeter, pack_fp_deltas, packed_quantum, unpack_fp_deltas,
)

U64 = jnp.uint64
I64 = jnp.int64
I32 = jnp.int32
# numpy scalar, not jnp (device-free import; see engine/bfs.py)
SENT = np.uint64(0xFFFFFFFFFFFFFFFF)


def _shard_map(body, mesh, in_specs, out_specs):
    """shard_map across jax versions: ``jax.shard_map`` (new) with
    ``check_vma=False``, else ``jax.experimental.shard_map.shard_map``
    with its older ``check_rep=False`` spelling.  The opt-out matters
    either way: the scatter-in-switch inside materialize trips the
    varying-axis/replication type checker, while the bodies are plain
    SPMD with explicit collectives."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def make_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"requested a {n_devices}-device mesh but only "
                f"{len(devs)} device(s) are visible "
                f"({[str(d) for d in devs]}); for a virtual CPU mesh set "
                "JAX_PLATFORMS=cpu and "
                "XLA_FLAGS=--xla_force_host_platform_device_count=N"
            )
        devs = devs[:n_devices]
    return Mesh(np.array(devs), axis_names=("d",))


class Phase1Out(NamedTuple):
    """Host-store mode, phase 1: expand + local pre-dedup + owner routing.

    The visited filter moves OFF the device (per-owner external stores,
    native/fpstore.cpp) — phase 1 stops after the routing ``all_to_all``;
    the host filters each owner's level-unique candidates through its
    store shard; phase 2 carries the verdicts back and materializes."""

    cv: jnp.ndarray  # u64[cap_x] compacted local candidates (origin side)
    cf: jnp.ndarray  # u64[cap_x]
    cp: jnp.ndarray  # i64[cap_x]
    rv: jnp.ndarray  # u64[D, cap_r] owner-side recv (fp_view)
    rf: jnp.ndarray  # u64[D, cap_r]
    rp: jnp.ndarray  # i64[D, cap_r]
    mult_slots: jnp.ndarray  # i64[K] psum'd per-slot fired counts
    abort: jnp.ndarray  # bool[] any split-brain abort (psum'd)
    abort_at: jnp.ndarray  # i64[1]
    overflow_x: jnp.ndarray  # bool[] candidate/routing capacity exceeded
    cand_max: jnp.ndarray  # i64[] max per-device candidate count (pmax'd)


class Phase1DeepOut(NamedTuple):
    """Deep-sweep phase 1: expand one frontier segment + sieve + route.

    Like :class:`Phase1Out` but segment-relative (the frontier is a LIST
    of uniform 1/D-sharded segments) and sieved: candidates found in the
    device's sieve cache (fingerprints it routed in a PREVIOUS level —
    provably already in the external store) are dropped before the
    routing ``all_to_all``, which is what shrinks both collective and
    host-link traffic at deep levels where most candidates are
    re-generated duplicates (arXiv:1208.5542's sieve)."""

    cv: jnp.ndarray  # u64[cap_x] sieved compacted candidates (origin side)
    cf: jnp.ndarray  # u64[cap_x]
    cp: jnp.ndarray  # i64[cap_x] payloads — KEPT AT ORIGIN, never routed
    rv: jnp.ndarray  # u64[D, cap_r] owner-side recv (fp_view)
    rf: jnp.ndarray  # u64[D, cap_r] (fp_full — the representative key)
    mult_slots: jnp.ndarray  # i64[K] psum'd per-slot fired counts
    abort: jnp.ndarray  # bool[] any split-brain abort (psum'd)
    abort_at: jnp.ndarray  # i64[1] device-local frontier row or -1
    overflow_x: jnp.ndarray  # bool[] candidate capacity exceeded (psum'd)
    n_pre: jnp.ndarray  # i64[] candidates before the sieve (psum'd)
    n_post: jnp.ndarray  # i64[] candidates actually routed (psum'd)
    cand_max: jnp.ndarray  # i64[] max per-device pre-sieve count (pmax'd)


class DeepFinOut(NamedTuple):
    """Owner-side level finalize: exact dedup + delta-packed fp stream.

    The owner lexsorts EVERY routed candidate of the level (all segment
    rounds), picks the min-(fp_full, payload) representative per view
    fingerprint — the same global choice the host filter used to make,
    now on device — and emits only the sorted unique fingerprints,
    delta-packed (parallel/exchange.py), for the host store verdict."""

    stream: jnp.ndarray  # u8[cap_acc*8] packed delta bytes
    nib: jnp.ndarray  # u8[cap_acc//2] per-entry byte lengths (4-bit)
    n_u: jnp.ndarray  # i64[1] unique candidates this owner
    total: jnp.ndarray  # i64[1] live bytes of ``stream``
    n_recv_sum: jnp.ndarray  # i64[] psum: routed lanes received
    n_u_sum: jnp.ndarray  # i64[] psum: unique candidates mesh-wide


class Phase2Out(NamedTuple):
    children: RaftState
    child_msum: jnp.ndarray
    n_new_local: jnp.ndarray  # i64[1]
    n_new_total: jnp.ndarray  # i64[]
    gpidx: jnp.ndarray
    slots: jnp.ndarray
    inv_bad: jnp.ndarray
    inv_bad_at: jnp.ndarray  # i64[1]
    ovf_w: jnp.ndarray  # bool[] (origin, owner) shipping rows exceeded
    ovf_c: jnp.ndarray  # bool[] an owner's frontier block overflowed


class LevelOut(NamedTuple):
    """Per-device outputs of one distributed BFS level (shard_map body)."""

    children: RaftState  # [cap_c, ...] local new states (padded)
    child_msum: jnp.ndarray  # u32[cap_c, P, chan]
    visited: jnp.ndarray  # u64[vcap] updated store shard (all_to_all mode)
    n_new_local: jnp.ndarray  # i64[1] this device's new states
    n_new_total: jnp.ndarray  # i64[] psum over mesh
    generated: jnp.ndarray  # i64[] psum over mesh
    mult_slots: jnp.ndarray  # i64[K] psum'd per-slot fired counts
    gpidx: jnp.ndarray  # i64[cap_c] global parent index (dev*cap_f+i)
    slots: jnp.ndarray  # i64[cap_c] local slots (for traces)
    inv_bad: jnp.ndarray  # i32[] psum'd violation count this level
    inv_bad_at: jnp.ndarray  # i64[1] local index of first violation or -1
    abort: jnp.ndarray  # bool[] any split-brain abort (psum'd)
    abort_at: jnp.ndarray  # i64[1] local frontier index of first abort or -1
    overflow_x: jnp.ndarray  # bool[] candidate/routing capacity exceeded
    overflow_v: jnp.ndarray  # bool[] visited-shard capacity exceeded
    cand_max: jnp.ndarray  # i64[] max per-device candidate count (pmax'd)
    # cand_max feeds the presize forecast an OBSERVED candidates-per-new
    # ratio, replacing the hand-tuned margin that under-sized cap_x


class CheckResult(NamedTuple):
    ok: bool
    distinct: int
    generated: int
    depth: int
    level_sizes: tuple[int, ...]
    violation: tuple | None
    action_counts: dict | None = None


def _compact(mask, take_n, *arrays, fills):
    """Stable-compact ``arrays`` rows where ``mask`` into ``take_n`` lanes."""
    comp = jnp.argsort(~mask, stable=True)
    take = jnp.arange(take_n)
    src = comp[jnp.clip(take, 0, comp.shape[0] - 1)]
    lane = (take < mask.sum()) & (take < comp.shape[0])
    return tuple(
        jnp.where(lane, a[src], fill) for a, fill in zip(arrays, fills)
    ) + (lane,)


class ShardedChecker:
    """Distributed model checker over a 1-D device mesh.

    Parameters:
      cap_x: per-device compacted-candidate capacity per level.
      vcap:  per-device visited-shard capacity (all_to_all mode; grows on
             demand by the host driver).
      exchange: "all_to_all" (sharded store) or "all_gather" (replicated).
      cap_x_max: ceiling for PREDICTIVE cap_x sizing only (run(presize=
             True)).  The growth forecast can overshoot ~2x early in a
             run, and at pow2 granularity that doubles the one big
             compile; an operator who has measured the real candidate
             peak (e.g. scripts/mesh_deep_parity.py) clamps the forecast
             here.  Reactive overflow growth ignores the ceiling — it is
             a sizing hint, never a correctness bound.
    """

    def __init__(
        self,
        cfg: RaftConfig,
        mesh: Mesh,
        cap_x: int = 4096,
        vcap: int = 1 << 16,
        exchange: str = "all_to_all",
        progress=None,
        canon: str = "late",
        host_store_dir: str | None = None,
        cap_x_max: int | None = None,
        deep: bool = False,
        seg_rows: int = 1 << 15,
        sieve: bool = True,
        compress: bool = True,
        scap: int = 1 << 12,
        scap_max: int = 1 << 22,
        use_hashstore: bool | None = None,
        pipeline: bool | None = None,
        pipeline_window: int | None = None,
        use_mxu: bool | None = None,
        watchdog=None,
        warm_bytes: int | None = None,
    ):
        assert exchange in ("all_to_all", "all_gather")
        # async intra-level pipeline (engine/pipeline.py): the level's
        # big device->host fetches (routed candidates on the hosted
        # path, the repacked trace arrays in deep mode, gpidx/slots on
        # the resident path) go through a bounded AsyncFetchWindow —
        # copies start the moment their producer is dispatched and
        # complete through the LEDGERED get only after the remaining
        # level-tail device work has been dispatched.  Counts are
        # bit-identical either way; TLA_RAFT_PIPELINE=0 reverts to the
        # serial fetch-after-dispatch chain.
        if pipeline is None:
            pipeline = graft_pipeline.enabled_by_env()
        if pipeline_window is None:
            pipeline_window = graft_pipeline.window_from_env()
        self.pipeline_window = int(pipeline_window)
        self.pipeline = bool(pipeline) and self.pipeline_window >= 1
        # deep-sweep tier: the frontier itself is sharded 1/D — each
        # device holds its owner share (fp % D) as a list of uniform
        # ``seg_rows``-row segments, the level loop expands segment by
        # segment, owners dedup the whole level's candidates exactly on
        # device, and only sieved/compressed fingerprint streams cross
        # the host link.  Requires the owner-sharded external stores.
        if deep:
            if host_store_dir is None:
                raise ValueError(
                    "deep=True requires host_store_dir (the sharded "
                    "deep sweep filters through per-owner external "
                    "stores)"
                )
            if canon != "late":
                raise ValueError("deep=True requires canon='late'")
            if exchange != "all_to_all":
                raise ValueError("deep=True requires exchange='all_to_all'")
            if seg_rows % 2:
                raise ValueError("seg_rows must be even")
        self.deep = deep
        self.seg_rows = seg_rows
        # open-addressing fingerprint slabs (ops/hashstore.py) for the
        # two mesh-side membership structures keyed fp % D: the owner
        # visited shards of the plain all_to_all mode (replacing the
        # per-level lexsort + searchsorted + sorted merge) and the deep
        # mode's pre-routing sieve cache (the sieve becomes a plain
        # probe; updates become O(1) inserts instead of a sort-merge).
        # Default ON; TLA_RAFT_HASHSTORE=0 / --no-hashstore reverts.
        # all_gather keeps its replicated sorted store (its dedup IS a
        # global sort — there is no probe structure to replace).
        if use_hashstore is None:
            use_hashstore = hashstore.enabled_by_env()
        self.use_hashstore = bool(use_hashstore) and exchange == "all_to_all"
        self.sieve = sieve
        self.compress = compress
        self.scap = scap
        self.scap_max = scap_max
        self.meter = ExchangeMeter()
        self._dp: dict = {}  # deep-mode compiled programs (keyed by statics)
        self._cap_c_boost = 1  # deep phase-2 owner recv block growth
        # mesh x external store (VERDICT r3 missing #4 / next #6): the
        # visited set leaves the devices entirely — one HostFPStore per
        # owner shard (fp % D keying matches the all_to_all routing), the
        # host filters after the routing exchange.  North-star configs
        # exceed D*HBM on small meshes; this is TLC's states/ spill
        # composed with its worker pool (/root/reference/.gitignore:2).
        if host_store_dir is not None:
            if exchange != "all_to_all":
                raise ValueError(
                    "host_store_dir requires exchange='all_to_all' (the "
                    "store is owner-sharded by fp % D)"
                )
            if canon != "late":
                raise ValueError("host_store_dir requires canon='late'")
        self.host_store_dir = host_store_dir
        self.host_stores = None  # built lazily in run()
        # host-RAM budget for the WARM tier, split across the D
        # per-owner stores: each shard buffers warm_bytes/D in RAM and
        # spills sorted runs (the cold generations of the mesh paths,
        # partition-tagged by their shard directory = fp % D) to disk
        # past it — an elastic D -> D' resume rebuilds them from the
        # mdelta replay under the new owner map (store/tiered.py
        # repartition is the same move applied to raw runs)
        self.warm_bytes = warm_bytes
        # canon="late" (default): guards-only expand, then materialize +
        # full-state-fingerprint only the compacted candidates — no
        # P-sized per-lane intermediates and no per-state msum carried in
        # the frontier (see engine/bfs.py).  canon="expand": the round-2
        # per-lane incremental-hash formulation, kept as a reference.
        assert canon in ("late", "expand")
        self.canon = canon
        self.cfg = cfg
        self.mesh = mesh
        # MXU-native expand (ops/mxu_expand.py): both mesh paths route
        # their guards/materialize through the kernel, so the selection
        # happens here once; TLA_RAFT_MXU=0 / --no-mxu-expand reverts
        self.kern = get_kernel(cfg, mxu=use_mxu)
        self.use_mxu = self.kern.use_mxu
        self.fpr = self.kern.fpr
        self.K = self.kern.K
        self.D = mesh.devices.size
        self.cap_x = cap_x
        self.cap_x_max = cap_x_max
        self.vcap = vcap
        self.exchange = exchange
        # reactive (mid-level) growth events this run: each one is a
        # full level-program recompile the presize forecast should have
        # prevented — scripts surface it (docs/MESH_DEEP.json)
        self.reactive_grows = 0
        self.progress = progress
        self.inv_fns = [(n, resolve_invariant_kernel(n)) for n in cfg.invariants]
        # semantic run fingerprint for the checkpoint manifests: spec
        # constants + the modes the mdelta record meta pins (exchange,
        # canon) — NOT tunables (cap_x, seg_rows) and, since the
        # elastic-resume work, NOT the device count: a D-device log
        # resumes on D' devices by owner remap (resilience/elastic.py),
        # so D is per-record geometry now, never log identity
        self._run_fp = resilience.run_config_fingerprint(
            cfg, log="mdelta", exchange=exchange, canon=canon
        )
        # per-owner level skew metrics (resilience/integrity.py): new
        # rows per owner every level, plus per-owner store-insert
        # seconds on the deep path — the --json "straggler" block
        self.skew = resilience.integrity.SkewMeter(self.D)
        # per-level hang watchdog (resilience/elastic.py); None = off
        self.watchdog = watchdog

    def _store_budget_entries(self) -> int:
        """Per-owner in-RAM entry budget of the external stores (0 =
        the native default): --warm-bytes split across the D shards."""
        if not self.warm_bytes:
            return 0
        return max(int(self.warm_bytes) // 8 // self.D, 1)

    def _legacy_run_fps(self) -> tuple[str, ...]:
        """Pre-elastic run fingerprints of THIS semantic run: the old
        digest schema pinned the writing mesh's device count, which an
        elastic resume cannot know up front — accept the variant for
        every plausible width so an upgraded deployment's in-progress
        checkpoints stay resumable (heal_log migrates the manifest to
        the D-free form on first touch)."""
        return tuple(
            resilience.run_config_fingerprint(
                self.cfg, log="mdelta", D=d, exchange=self.exchange,
                canon=self.canon,
            )
            for d in range(1, 129)
        )

    # -- the per-device level body ----------------------------------------

    def _expand_local(self, frontier, msum, n_f):
        """Expand + local pre-dedup; returns compacted candidates."""
        K = self.K
        cap_f = frontier.voted_for.shape[0]
        dev = jax.lax.axis_index("d").astype(I64)

        if self.canon == "late":
            valid, mult, ab_state = self.kern.expand_guards(frontier)
        else:
            exp = self.kern.expand(frontier, msum)
            valid, mult, ab_state = exp.valid, exp.mult, exp.abort
        in_range = (jnp.arange(cap_f) < n_f[0])[:, None]
        valid = valid & in_range
        gparent = dev * cap_f + jnp.arange(cap_f, dtype=I64)
        payload = (gparent[:, None] * K + jnp.arange(K, dtype=I64)[None]).ravel()
        mult_slots = jax.lax.psum(
            jnp.where(valid, mult, 0).astype(I64).sum(0), "d"
        )
        abort_local = ab_state & in_range[:, 0]
        abort = jax.lax.psum(abort_local.any().astype(I32), "d") > 0
        abort_at = jnp.where(
            abort_local.any(), jnp.argmax(abort_local), -1
        ).astype(I64)

        if self.canon == "late":
            # compact the valid (parent, slot) lanes, materialize them
            # locally, and fingerprint the children from their full
            # states — the symmetry fold runs over cap_x candidates, not
            # cap_f*K fan-out lanes (see engine/bfs.py)
            cp_raw, lane, overflow = _compact_payloads(
                valid.ravel(), payload, self.cap_x
            )
            # graftlint: waive[GL005] — device-local row, < cap_f <= 2^31
            lidx = ((cp_raw // K) % cap_f).astype(I32)
            parents = jax.tree.map(lambda x: x[lidx], frontier)
            children = self.kern.materialize(parents, cp_raw % K)
            fv, ff, _msum = self.fpr.state_fingerprints(children)
            fpv = jnp.where(lane, fv.astype(U64), SENT)
            fpf = jnp.where(lane, ff.astype(U64), SENT)
            payload = jnp.where(lane, cp_raw, -1)
        else:
            fpv = jnp.where(valid, exp.fp_view, SENT).ravel()
            fpf = jnp.where(valid, exp.fp_full, SENT).ravel()

        # local pre-dedup: min (fp_full, payload) representative per view fp
        order = jnp.lexsort((payload, fpf, fpv))
        sv, sf, sp = fpv[order], fpf[order], payload[order]
        first = jnp.concatenate([jnp.ones((1,), bool), sv[1:] != sv[:-1]])
        keep = first & (sv != SENT)
        if self.canon != "late":
            overflow = keep.sum() > self.cap_x
        cv, cf, cp, _lane = _compact(
            keep, self.cap_x, sv, sf, sp, fills=(SENT, SENT, I64(-1))
        )
        return cv, cf, cp, mult_slots, abort, abort_at, overflow, dev, cap_f

    def _ship_winners_to_owners(self, frontier, cap_f, dev, oo, op,
                                win_sorted):
        """Materialize winning children at their ORIGIN (the parent's
        device) and route the full child states to their OWNER shard
        (fp % D) with one all_to_all per field.

        This is the load-balancing half the rounds 2-4 mesh never had:
        children used to stay with their parents, so the entire frontier
        cascaded from the init state's device and D-1 devices idled
        while device 0's candidate caps blew up (measured: the round-4
        depth-13 chain's n_local is [N, 0, ..., 0] at every level).
        Owner-claiming spreads the next frontier ~uniformly (fingerprints
        are pseudorandom), shrinking per-device expand load and cap_x by
        ~D.  Traffic: ~700 B/state origin->owner once per state lifetime
        — well inside ICI budgets, and the fp-only dedup exchanges are
        unchanged.

        Inputs are in owner-grouped candidate order (``oo`` = owner per
        lane, ``op`` = payload per lane, ``win_sorted`` = this origin's
        winners).  Returns (children, child_msum, gpidx, slots, lane,
        n_new_local, inv_bad, first_bad, ovf_w, ovf_c) — ``ovf_w``:
        some (origin, owner) pair exceeded the cap_w shipping rows
        (fix: grow cap_w); ``ovf_c``: an owner received more new states
        than its cap_x frontier block (fix: grow cap_x).
        """
        D, K = self.D, self.K
        cap_w = self.cap_w
        # winners are contiguous per owner group after a stable sort on
        # (not-winner, owner): group o's winners land at rows
        # wstarts[o] .. wstarts[o]+wcounts[o]
        wcounts = jnp.bincount(
            jnp.where(win_sorted, oo, D), length=D + 1
        )
        wstarts = jnp.cumsum(wcounts) - wcounts
        worder = jnp.argsort(jnp.where(win_sorted, oo, D), stable=True)
        idx = jnp.clip(
            wstarts[:D, None] + jnp.arange(cap_w, dtype=wstarts.dtype)[None, :],
            0, oo.shape[0] - 1,
        )
        lane_src = worder[idx]  # [D(owner), cap_w] winner lanes
        in_row = jnp.arange(cap_w)[None, :] < wcounts[:D, None]
        ovf_w = wcounts[:D].max() > cap_w
        spay = jnp.where(in_row, op[lane_src], 0)  # [D, cap_w]
        pidx = (spay // K) % cap_f
        slots = spay % K
        parents = jax.tree.map(
            lambda x: x[pidx.reshape(-1)], frontier
        )
        kids = self.kern.materialize(parents, slots.reshape(-1))
        gp_send = jnp.where(in_row, dev * cap_f + pidx, -1)

        def a2a(x):
            # senders pre-mask dead lanes (jnp.where above); the exchange
            # itself moves rows verbatim
            return jax.lax.all_to_all(
                x.reshape(D, cap_w, *x.shape[1:]), "d", 0, 0, tiled=True
            ).reshape(D * cap_w, *x.shape[1:])

        lane_r = a2a(in_row.astype(jnp.uint8).reshape(-1)).astype(bool)
        gp_r = a2a(gp_send.reshape(-1))
        sl_r = a2a(jnp.where(in_row, slots, 0).reshape(-1))
        kids_r = jax.tree.map(a2a, kids)
        # compact the received rows into this device's frontier block
        cap_c = self.cap_x
        comp = jnp.argsort(~lane_r, stable=True)
        take = jnp.clip(jnp.arange(cap_c), 0, comp.shape[0] - 1)
        src = comp[take]
        lane = (jnp.arange(cap_c) < lane_r.sum()) & (
            jnp.arange(cap_c) < comp.shape[0]
        )
        children = jax.tree.map(
            lambda x: jnp.where(
                lane.reshape((-1,) + (1,) * (x.ndim - 1)),
                x[src], jnp.zeros_like(x[src]),
            ),
            kids_r,
        )
        gpidx = jnp.where(lane, gp_r[src], -1)
        slots_c = jnp.where(lane, sl_r[src], -1)
        n_new_local = lane.sum().astype(I64)
        ovf_c = lane_r.sum() > cap_c
        child_msum = (
            self.fpr.msg_hash(children.msgs)
            if self.canon == "expand"
            else jnp.zeros((cap_c, 1, 1), jnp.uint32)
        )
        bad_local = jnp.zeros(cap_c, bool)
        for _name, fn in self.inv_fns:
            bad_local = bad_local | (
                ~fn(self.cfg, children, self.kern.tables) & lane
            )
        inv_bad = jax.lax.psum(bad_local.sum().astype(I32), "d")
        first_bad = jnp.where(
            bad_local.any(), jnp.argmax(bad_local), -1
        ).astype(I64)
        return (children, child_msum, gpidx, slots_c, lane, n_new_local,
                inv_bad, first_bad, ovf_w, ovf_c)

    @functools.cached_property
    def cap_w(self) -> int:
        # per-(origin, owner) shipping rows.  Steady state puts
        # n_new/D^2 winners on a pair; the healing case (a legacy
        # parent-local frontier concentrated on one device) puts
        # n_new/D on each of that origin's pairs — cap_x/2 covers both
        # with the reactive grow as backstop.  _cap_w_boost grows cap_w
        # alone (phase-2 retries must keep phase-1's cv/cp shapes).
        return max(256, self.cap_x // 2) * getattr(self, "_cap_w_boost", 1)

    def _children_from(self, frontier, cap_f, dev, wpay, wlane):
        """Materialize chosen (payload) slots locally + invariants."""
        K = self.K
        pidx = (wpay // K) % cap_f
        slots = wpay % K
        parents = jax.tree.map(lambda x: x[pidx], frontier)
        children = self.kern.materialize(parents, slots)
        # the per-state message-set hash partial is only carried between
        # levels by the canon="expand" incremental path; it is P-sized
        # per state, so the late path keeps a [cap, 1, 1] dummy instead
        child_msum = (
            self.fpr.msg_hash(children.msgs)
            if self.canon == "expand"
            else jnp.zeros((children.voted_for.shape[0], 1, 1), jnp.uint32)
        )
        children = jax.tree.map(
            lambda x: jnp.where(
                wlane.reshape((-1,) + (1,) * (x.ndim - 1)), x, jnp.zeros_like(x)
            ),
            children,
        )
        # invariants on the fresh level shard
        bad_local = jnp.zeros(children.voted_for.shape[0], bool)
        for _name, fn in self.inv_fns:
            bad_local = bad_local | (~fn(self.cfg, children, self.kern.tables) & wlane)
        inv_bad = jax.lax.psum(bad_local.sum().astype(I32), "d")
        has_bad = bad_local.any()
        first_bad = jnp.where(has_bad, jnp.argmax(bad_local), -1).astype(I64)
        gpidx = jnp.where(wlane, dev * cap_f + pidx, -1)
        return children, child_msum, gpidx, slots, inv_bad, first_bad

    def _body_all_gather(self, frontier, msum, n_f, visited):
        (cv, cf, cp, mult_slots, abort, abort_at, overflow, dev, cap_f) = (
            self._expand_local(frontier, msum, n_f)
        )
        n_cand = (cv != SENT).sum().astype(I64)  # pre-dedup: cap_x load
        pos = jnp.searchsorted(visited, cv)
        hit = visited[jnp.clip(pos, 0, visited.shape[0] - 1)] == cv
        cv = jnp.where(hit, SENT, cv)

        gv = jax.lax.all_gather(cv, "d").reshape(-1)
        gf = jax.lax.all_gather(cf, "d").reshape(-1)
        gp = jax.lax.all_gather(cp, "d").reshape(-1)
        gorder = jnp.lexsort((gp, gf, gv))
        gsv, gpay = gv[gorder], gp[gorder]
        gfirst = jnp.concatenate([jnp.ones((1,), bool), gsv[1:] != gsv[:-1]])
        gnew = gfirst & (gsv != SENT)
        n_new_total = gnew.sum().astype(I64)
        win = gnew & (gpay // (self.K * cap_f) == dev)
        n_new_local = win.sum().astype(I64)
        wpay, wlane = _compact(win, self.cap_x, gpay, fills=(I64(0),))
        children, child_msum, gpidx, slots, inv_bad, first_bad = self._children_from(
            frontier, cap_f, dev, wpay, wlane
        )
        # replicated store update (identical on every device)
        new_fps = jnp.where(gnew, gsv, SENT)
        visited = jnp.sort(jnp.concatenate([visited, new_fps]))[: visited.shape[0] + self.D * self.cap_x]
        return LevelOut(
            children, child_msum, visited,
            n_new_local[None], n_new_total,
            mult_slots.sum(), mult_slots,
            gpidx, jnp.where(wlane, slots, -1),
            inv_bad, first_bad[None], abort, abort_at[None],
            jax.lax.psum(overflow.astype(I32), "d") > 0,
            jnp.zeros((), bool),
            jax.lax.pmax(n_cand, "d"),
        )

    def _body_all_to_all(self, frontier, msum, n_f, visited):
        """Owner-sharded dedup: fp % D owns; candidates route via all_to_all."""
        D, cap_x = self.D, self.cap_x
        cap_r = self.cap_r  # per-(src,dst) routing capacity
        (cv, cf, cp, mult_slots, abort, abort_at, overflow, dev, cap_f) = (
            self._expand_local(frontier, msum, n_f)
        )
        # --- route to owners ---------------------------------------------
        # sentinel lanes sort to a virtual group D past every real owner,
        # so they never land in a send row
        owner = jnp.where(cv == SENT, D, (cv % jnp.uint64(D)).astype(I64))
        oorder = jnp.argsort(owner, stable=True)  # candidates grouped by owner
        ov, of_, op, oo = cv[oorder], cf[oorder], cp[oorder], owner[oorder]
        counts = jnp.bincount(oo, length=D + 1)
        starts = jnp.cumsum(counts) - counts
        rank = jnp.arange(cap_x) - starts[oo]
        overflow_x = overflow | (counts[:D].max() > cap_r)
        rr = jnp.clip(rank, 0, cap_r - 1)
        ok_lane = (ov != SENT) & (rank < cap_r)
        # gather-based send-buffer build (no dynamic scatters on the mesh
        # path — XLA:TPU miscompiled this op class in the materialize pass,
        # docs/PERF.md): row o reads the owner-grouped lanes
        # starts[o] .. starts[o]+cap_r-1, masked to counts[o] entries
        idx = jnp.clip(
            starts[:D, None] + jnp.arange(cap_r, dtype=starts.dtype)[None, :],
            0,
            cap_x - 1,
        )
        in_row = jnp.arange(cap_r)[None, :] < counts[:D, None]
        sendv = jnp.where(in_row, ov[idx], SENT)
        sendf = jnp.where(in_row, of_[idx], SENT)
        sendp = jnp.where(in_row, op[idx], -1)
        rv = jax.lax.all_to_all(sendv, "d", 0, 0, tiled=True).reshape(D, cap_r)
        rf = jax.lax.all_to_all(sendf, "d", 0, 0, tiled=True).reshape(D, cap_r)
        rp = jax.lax.all_to_all(sendp, "d", 0, 0, tiled=True).reshape(D, cap_r)

        # --- owner-side dedup vs the store shard -------------------------
        qv, qf, qp = rv.reshape(-1), rf.reshape(-1), rp.reshape(-1)
        if self.use_hashstore:
            # one fused probe-and-insert: uniqueness, membership AND the
            # shard update — no lexsort over the recv lanes, no binary
            # search against the shard, no whole-shard re-sort.  The
            # min-(fp_full, payload) representative matches the lexsort
            # path's first-occurrence choice exactly (group-min lemma),
            # and verdicts come back already in recv-lane order (the
            # sorted path needs an inverse-permutation gather).  On
            # overflow the driver discards the level and grows the slab.
            upd, verdict, n_own_new, ovf_h = hashstore.probe_and_insert_impl(
                visited, qv, qf, qp
            )
            overflow_v = ovf_h | ((upd != SENT).sum() * 2 > visited.shape[0])
        else:
            qorder = jnp.lexsort((qp, qf, qv))
            qsv = qv[qorder]
            qfirst = jnp.concatenate(
                [jnp.ones((1,), bool), qsv[1:] != qsv[:-1]]
            )
            pos = jnp.searchsorted(visited, qsv)
            qhit = visited[jnp.clip(pos, 0, visited.shape[0] - 1)] == qsv
            qnew = qfirst & (qsv != SENT) & ~qhit
            n_own_new = qnew.sum()
            # update the shard (sorted merge, fixed capacity)
            vcount = (visited != SENT).sum()
            overflow_v = vcount + n_own_new > visited.shape[0]
            upd = jnp.sort(
                jnp.concatenate([visited, jnp.where(qnew, qsv, SENT)])
            )[: visited.shape[0]]
            # verdict bits back to origins, aligned to the recv layout
            # (inverse-permutation gather, not a scatter)
            verdict = qnew[jnp.argsort(qorder)]
        back = jax.lax.all_to_all(
            verdict.reshape(D, cap_r), "d", 0, 0, tiled=True
        ).reshape(D, cap_r)
        # my candidate i (owner-grouped order) sits at (oo[i], rank[i])
        win_sorted = back[jnp.clip(oo, 0, D - 1), rr] & ok_lane
        n_new_total = jax.lax.psum(n_own_new.astype(I64), "d")
        (children, child_msum, gpidx, slots, _lane, n_new_local,
         inv_bad, first_bad, ovf_w, ovf_c) = self._ship_winners_to_owners(
            frontier, cap_f, dev, oo, op, win_sorted
        )
        return LevelOut(
            children, child_msum, upd,
            n_new_local[None], n_new_total,
            mult_slots.sum(), mult_slots,
            gpidx, slots,
            inv_bad, first_bad[None], abort, abort_at[None],
            jax.lax.psum(
                (overflow_x | ovf_w | ovf_c).astype(I32), "d"
            ) > 0,
            jax.lax.psum(overflow_v.astype(I32), "d") > 0,
            jax.lax.pmax(counts[:D].sum().astype(I64), "d"),
        )

    # -- host-store mode: the level split into two collective programs ----

    def _body_a2a_phase1(self, frontier, msum, n_f):
        """Expand + local pre-dedup + route to owners; no visited filter."""
        D, cap_x, cap_r = self.D, self.cap_x, self.cap_r
        (cv, cf, cp, mult_slots, abort, abort_at, overflow, _dev, _cap_f) = (
            self._expand_local(frontier, msum, n_f)
        )
        owner = jnp.where(cv == SENT, D, (cv % jnp.uint64(D)).astype(I64))
        oorder = jnp.argsort(owner, stable=True)
        ov, of_, op, oo = cv[oorder], cf[oorder], cp[oorder], owner[oorder]
        counts = jnp.bincount(oo, length=D + 1)
        starts = jnp.cumsum(counts) - counts
        overflow_x = overflow | (counts[:D].max() > cap_r)
        idx = jnp.clip(
            starts[:D, None] + jnp.arange(cap_r, dtype=starts.dtype)[None, :],
            0,
            cap_x - 1,
        )
        in_row = jnp.arange(cap_r)[None, :] < counts[:D, None]
        sendv = jnp.where(in_row, ov[idx], SENT)
        sendf = jnp.where(in_row, of_[idx], SENT)
        sendp = jnp.where(in_row, op[idx], -1)
        rv = jax.lax.all_to_all(sendv, "d", 0, 0, tiled=True).reshape(D, cap_r)
        rf = jax.lax.all_to_all(sendf, "d", 0, 0, tiled=True).reshape(D, cap_r)
        rp = jax.lax.all_to_all(sendp, "d", 0, 0, tiled=True).reshape(D, cap_r)
        return Phase1Out(
            cv, cf, cp, rv, rf, rp, mult_slots, abort, abort_at[None],
            jax.lax.psum(overflow_x.astype(I32), "d") > 0,
            jax.lax.pmax(counts[:D].sum().astype(I64), "d"),
        )

    def _body_a2a_phase2(self, frontier, cv, cp, verdict_recv, n_f):
        """Verdicts back to origins; compact winners; materialize.

        The owner grouping is recomputed from ``cv`` — ``argsort`` over
        the same input is deterministic, so the lanes line up with the
        phase-1 send layout exactly."""
        D, cap_x, cap_r = self.D, self.cap_x, self.cap_r
        dev = jax.lax.axis_index("d").astype(I64)
        cap_f = frontier.voted_for.shape[0]
        owner = jnp.where(cv == SENT, D, (cv % jnp.uint64(D)).astype(I64))
        oorder = jnp.argsort(owner, stable=True)
        op, oo = cp[oorder], owner[oorder]
        counts = jnp.bincount(oo, length=D + 1)
        starts = jnp.cumsum(counts) - counts
        rank = jnp.arange(cap_x) - starts[oo]
        rr = jnp.clip(rank, 0, cap_r - 1)
        ok_lane = (cv[oorder] != SENT) & (rank < cap_r)
        back = jax.lax.all_to_all(
            verdict_recv, "d", 0, 0, tiled=True
        ).reshape(D, cap_r)
        win_sorted = back[jnp.clip(oo, 0, D - 1), rr] & ok_lane
        n_new_total = jax.lax.psum(win_sorted.sum().astype(I64), "d")
        (children, child_msum, gpidx, slots, _lane, n_new_local,
         inv_bad, first_bad, ovf_w, ovf_c) = self._ship_winners_to_owners(
            frontier, cap_f, dev, oo, op, win_sorted
        )
        return Phase2Out(
            children, child_msum, n_new_local[None], n_new_total,
            gpidx, slots, inv_bad, first_bad[None],
            jax.lax.psum(ovf_w.astype(I32), "d") > 0,
            jax.lax.psum(ovf_c.astype(I32), "d") > 0,
        )

    def _host_filter(self, rv, rf, rp):
        """Filter each owner's recv buffer through its external store.

        Mirrors the device dedup exactly: lexsort (payload, fp_full,
        fp_view), first-occurrence per fp_view is the representative
        (min (fp_full, payload) — the deterministic refinement every
        engine of this project pins), then the store's is-new verdict.
        Inputs arrive HOST-SIDE (the caller fetches them through the
        async window's ledgered path).  Returns (verdict [D, D, cap_r]
        aligned to the recv layout, n_new_total)."""
        D, cap_r = self.D, self.cap_r
        sent = np.uint64(0xFFFFFFFFFFFFFFFF)
        rv = np.asarray(rv).reshape(D, D * cap_r)
        rf = np.asarray(rf).reshape(D, D * cap_r)
        rp = np.asarray(rp).reshape(D, D * cap_r)
        # live-lane byte ledger, same convention as the deep path (so
        # bench can report the sieve+compress reduction against this,
        # the uncompressed exchange): 24 B routing + 1 B verdict per
        # routed candidate lane, host leg fetches all three u64 arrays.
        # Counting live lanes UNDERSTATES this path's true cost (the
        # actual fetch moves the full padded buffers), which keeps any
        # reduction the deep path reports conservative.
        n_routed = int((rv != sent).sum())
        off_diag = (D - 1) / D
        self.meter.begin_level(len(self.meter.levels) + 1)
        self.meter.add(
            n_candidates=n_routed, n_unique=n_routed,
            a2a_bytes=int(n_routed * 25 * off_diag),
            raw_a2a_bytes=int(n_routed * 25 * off_diag),
            host_bytes=n_routed * 25,
            raw_host_bytes=n_routed * 25,
        )
        self.meter.end_level()
        verdict = np.zeros((D, D * cap_r), bool)
        n_new = 0
        n_uniq = 0
        t_probe = time.monotonic()
        for o in range(D):
            order = np.lexsort((rp[o], rf[o], rv[o]))
            sv = rv[o][order]
            first = np.concatenate([[True], sv[1:] != sv[:-1]]) & (sv != sent)
            uniq = sv[first]
            if len(uniq):
                is_new = self.host_stores[o].insert(uniq)
            else:
                is_new = np.zeros(0, bool)
            vs = np.zeros(D * cap_r, bool)
            vs[first] = is_new
            verdict[o][order] = vs
            n_new += int(is_new.sum())
            n_uniq += len(uniq)
        if any(s.num_runs for s in self.host_stores):
            # the per-owner stores hold spilled (disk) runs: publish
            # the warm/cold probe wait of this level's verdicts
            graft_obs.tier_probe(
                len(self.meter.levels), n_uniq, n_uniq - n_new,
                wait_s=time.monotonic() - t_probe,
            )
        return verdict.reshape(D, D, cap_r), n_new

    @functools.cached_property
    def level_phase1(self):
        spec_state = jax.tree.map(lambda _: P("d"), init_batch(self.cfg, 1))
        return jax.jit(
            _shard_map(
                self._body_a2a_phase1,
                self.mesh,
                (spec_state, P("d"), P("d")),
                Phase1Out(
                    P("d"), P("d"), P("d"), P("d"), P("d"), P("d"),
                    P(), P(), P("d"), P(), P(),
                ),
            )
        )

    @functools.cached_property
    def level_phase2(self):
        spec_state = jax.tree.map(lambda _: P("d"), init_batch(self.cfg, 1))
        return jax.jit(
            _shard_map(
                self._body_a2a_phase2,
                self.mesh,
                (spec_state, P("d"), P("d"), P("d"), P("d")),
                Phase2Out(
                    jax.tree.map(lambda _: P("d"), init_batch(self.cfg, 1)),
                    P("d"), P("d"), P(), P("d"), P("d"), P(), P("d"),
                    P(), P(),
                ),
            )
        )

    def _hosted_level(self, frontier, msum, n_f):
        """One BFS level in host-store mode: phase 1 (expand + route),
        host filter through the per-owner external stores, phase 2
        (verdicts back + materialize).  Returns a LevelOut-shaped
        namespace for the shared driver loop."""
        grows = 0
        while True:
            p1 = self.level_phase1(frontier, msum, n_f)
            if not bool(jax.device_get(p1.overflow_x)):
                break
            if grows >= 8:
                raise RuntimeError(
                    f"capacity overflow (cap_x={self.cap_x}, "
                    f"cap_r={self.cap_r})"
                )
            grows += 1
            self.reactive_grows += 1
            self.cap_x *= 2
            for k in ("level_phase1", "level_phase2", "cap_r", "cap_w"):
                self.__dict__.pop(k, None)
        generated = p1.mult_slots.sum()
        common = dict(
            mult_slots=p1.mult_slots, generated=generated, visited=None,
            abort=p1.abort, abort_at=p1.abort_at, cand_max=p1.cand_max,
            overflow_x=jnp.zeros((), bool), overflow_v=jnp.zeros((), bool),
        )
        # the level's big fetch (three D*D*cap_r routed-candidate
        # buffers) enters the async window NOW, so the copies stream
        # over the host link while the abort control sync below waits
        # for the phase-1 programs — and complete through the LEDGERED
        # get path (the implicit np.asarray conversions this fetch used
        # to make would trip the sanitizer's transfer guard)
        routed = graft_pipeline.DeferredFetch(
            self.pipeline, (p1.rv, p1.rf, p1.rp)
        )
        if bool(jax.device_get(p1.abort)):
            routed.discard()  # ledger stays balanced on the abort path
            return SimpleNamespace(
                n_new_total=jnp.asarray(0, I64), children=None,
                child_msum=None, n_new_local=None, gpidx=None, slots=None,
                inv_bad=jnp.asarray(0, I32), inv_bad_at=None, **common,
            )
        verdict, n_new = self._host_filter(*routed.get())
        vr = jax.device_put(
            jnp.asarray(verdict.reshape(self.D * self.D, self.cap_r)),
            NamedSharding(self.mesh, P("d")),
        )
        boosted = False
        while True:
            p2 = self.level_phase2(frontier, p1.cv, p1.cp, vr, n_f)
            ovf_w, ovf_c = jax.device_get((p2.ovf_w, p2.ovf_c))
            if not (bool(ovf_w) or bool(ovf_c)):
                break
            if grows >= 8:
                raise RuntimeError(
                    f"shipping overflow (cap_w={self.cap_w}, "
                    f"cap_x={self.cap_x})"
                )
            grows += 1
            self.reactive_grows += 1
            if bool(ovf_c):
                # an owner received more new states than its cap_x
                # frontier block: growing cap_w cannot help — grow cap_x
                # and redo the WHOLE level (phase-1 shapes change)
                self.cap_x *= 2
                for k in ("level_phase1", "level_phase2", "cap_r",
                          "cap_w"):
                    self.__dict__.pop(k, None)
                return self._hosted_level(frontier, msum, n_f)
            # cap_w rows overflowed (healing a concentrated legacy
            # frontier): grow cap_w ALONE and redo phase 2 — phase-1's
            # cv/cp shapes must stay valid, so cap_x is not touched
            boosted = True
            self._cap_w_boost = getattr(self, "_cap_w_boost", 1) * 2
            for k in ("level_phase2", "cap_w"):
                self.__dict__.pop(k, None)
        if boosted:
            # the boost exists to absorb a one-time concentrated layout;
            # after this level the frontier is owner-balanced, so drop it
            # (one recompile next level beats shipping D x boosted rows
            # of full states every level for the rest of the run)
            self._cap_w_boost = 1
            for k in ("level_phase2", "cap_w"):
                self.__dict__.pop(k, None)
        n2 = int(jax.device_get(p2.n_new_total))
        resilience.integrity.reconcile(
            "host-store verdict map", n_new, n2
        )
        return SimpleNamespace(
            children=p2.children, child_msum=p2.child_msum,
            n_new_local=p2.n_new_local, n_new_total=p2.n_new_total,
            gpidx=p2.gpidx, slots=p2.slots,
            inv_bad=p2.inv_bad, inv_bad_at=p2.inv_bad_at, **common,
        )

    # -- deep-sweep mode: 1/D frontier segments + sieve-and-compress ------
    #
    # The level-29 wall of the single-device external-store sweep is one
    # frontier (~15 GB) resident on one device (docs/PERF.md).  Deep mode
    # shards the frontier itself: device d owns exactly the states whose
    # fingerprint hashes to it (fp % D — same keying as the external
    # store shards and the all_to_all routing), held as a list of uniform
    # ``seg_rows``-row segments, so per-device frontier memory, expand
    # work and dedup sort all drop ~D-fold and the ceiling moves to
    # ~D x 15 GB.  The fingerprint exchange is sieve-then-compress
    # (arXiv:1208.5542): candidates a device routed in ANY previous
    # level are provably already in the store and are dropped before the
    # routing all_to_all (the sieve cache); owners dedup the level
    # exactly ON DEVICE (the host lexsort of the plain host-store mode
    # moves into the finalize program) and ship only sorted fp deltas in
    # a variable-width packed stream over the host link, answered by one
    # is-new bit per fingerprint.  The host-side level tail is double-
    # buffered: per-owner fetch+insert run in a small thread pool (the
    # ctypes store releases the GIL) and checkpoint writes are deferred
    # to a background writer that overlaps the next level's expand.
    #
    # Parity discipline: the owner-side lexsort picks the same global
    # min-(fp_full, payload) representative per view fingerprint the
    # host filter picked, every sieve drop is provably-visited, and the
    # per-level distinct/generated counts are asserted bit-identical to
    # the single-device engine and oracle by the tier-1 parity tests.

    @property
    def cap_c_deep(self) -> int:
        # phase-2 owner recv block (winners shipped to one owner in one
        # segment round); grows alone on ovf_c so phase-1 shapes hold
        return self.cap_x * self._cap_c_boost

    def _expand_local_seg(self, seg, n_f, base, capf):
        """Expand ONE frontier segment + local pre-dedup (canon late).

        ``base``/``capf`` are device i64 scalars: the segment's first row
        within the device's frontier block and the block's total row
        capacity — dynamic so segment count never recompiles this (the
        largest) program.  Global parent index = dev*capf + base + i."""
        K = self.K
        rows = seg.voted_for.shape[0]
        dev = jax.lax.axis_index("d").astype(I64)
        valid, mult, ab_state = self.kern.expand_guards(seg)
        gidx = base + jnp.arange(rows, dtype=I64)
        in_range = (gidx < n_f[0])[:, None]
        valid = valid & in_range
        gparent = dev * capf + gidx
        payload = (gparent[:, None] * K + jnp.arange(K, dtype=I64)[None]).ravel()
        mult_slots = jax.lax.psum(
            jnp.where(valid, mult, 0).astype(I64).sum(0), "d"
        )
        abort_local = ab_state & in_range[:, 0]
        abort = jax.lax.psum(abort_local.any().astype(I32), "d") > 0
        abort_at = jnp.where(
            abort_local.any(), base + jnp.argmax(abort_local), -1
        ).astype(I64)
        cp_raw, lane, overflow = _compact_payloads(
            valid.ravel(), payload, self.cap_x
        )
        # graftlint: waive[GL005] — clipped segment-relative row, < seg_rows
        lidx = jnp.clip(
            (cp_raw // K) - dev * capf - base, 0, rows - 1
        ).astype(I32)
        parents = jax.tree.map(lambda x: x[lidx], seg)
        children = self.kern.materialize(parents, cp_raw % K)
        fv, ff, _msum = self.fpr.state_fingerprints(children)
        fpv = jnp.where(lane, fv.astype(U64), SENT)
        fpf = jnp.where(lane, ff.astype(U64), SENT)
        payload = jnp.where(lane, cp_raw, -1)
        order = jnp.lexsort((payload, fpf, fpv))
        sv, sf, sp = fpv[order], fpf[order], payload[order]
        first = jnp.concatenate([jnp.ones((1,), bool), sv[1:] != sv[:-1]])
        keep = first & (sv != SENT)
        cv, cf, cp, _lane = _compact(
            keep, self.cap_x, sv, sf, sp, fills=(SENT, SENT, I64(-1))
        )
        return cv, cf, cp, mult_slots, abort, abort_at, overflow

    def _deep_phase1_body(self, seg, n_f, base, capf, sieve):
        """Expand segment + sieve + route candidates to owners.

        Only (fp_view, fp_full) cross the mesh — 16 B/lane, not the
        plain exchange's 24.  Payloads stay at their origin: the owner
        needs fp_full to pick the representative (min fp_full per view
        fingerprint — the canonical-state choice the engines share) and
        breaks fp_full TIES by deterministic recv order, which is
        count-exact because equal canonical full-state fingerprints are
        symmetry-images of one state (identical successor fingerprints
        either way)."""
        D, cap_x, cap_r = self.D, self.cap_x, self.cap_r
        (cv, cf, cp, mult_slots, abort, abort_at, overflow) = (
            self._expand_local_seg(seg, n_f, base, capf)
        )
        n_pre = (cv != SENT).sum().astype(I64)
        if self.sieve:
            # drop candidates this device routed in a PREVIOUS level:
            # every routed fingerprint was inserted into the store by
            # that level's filter, so the drop is provably-visited-only.
            # Hash mode: a depth-bounded O(1) probe instead of the
            # ~log2(scap) gather rounds of binary search per candidate.
            if self.use_hashstore:
                hit = hashstore.probe_impl(sieve, cv)
            else:
                pos = jnp.searchsorted(sieve, cv)
                hit = sieve[jnp.clip(pos, 0, sieve.shape[0] - 1)] == cv
            cv = jnp.where(hit, SENT, cv)
            cf = jnp.where(hit, SENT, cf)
            cp = jnp.where(hit, I64(-1), cp)
        n_post = (cv != SENT).sum().astype(I64)
        owner = jnp.where(cv == SENT, D, (cv % jnp.uint64(D)).astype(I64))
        oorder = jnp.argsort(owner, stable=True)
        ov, of_, oo = cv[oorder], cf[oorder], owner[oorder]
        counts = jnp.bincount(oo, length=D + 1)
        starts = jnp.cumsum(counts) - counts
        overflow_x = overflow | (counts[:D].max() > cap_r)
        idx = jnp.clip(
            starts[:D, None] + jnp.arange(cap_r, dtype=starts.dtype)[None, :],
            0,
            cap_x - 1,
        )
        in_row = jnp.arange(cap_r)[None, :] < counts[:D, None]
        sendv = jnp.where(in_row, ov[idx], SENT)
        sendf = jnp.where(in_row, of_[idx], SENT)
        rv = jax.lax.all_to_all(sendv, "d", 0, 0, tiled=True).reshape(D, cap_r)
        rf = jax.lax.all_to_all(sendf, "d", 0, 0, tiled=True).reshape(D, cap_r)
        return Phase1DeepOut(
            cv, cf, cp, rv, rf, mult_slots, abort, abort_at[None],
            jax.lax.psum(overflow_x.astype(I32), "d") > 0,
            jax.lax.psum(n_pre, "d"), jax.lax.psum(n_post, "d"),
            jax.lax.pmax(n_pre, "d"),
        )

    def _deep_finalize_body(self, rv3, rf3):
        """Owner-side exact level dedup + delta-packed unique stream.

        Inputs are the stacked segment rounds' recv buffers [Rq, D,
        cap_r] (padded rounds are all-SENT).  One lexsort over every
        candidate the owner received this level picks the min-fp_full
        representative per view fingerprint (the canonical-state choice
        every engine of this project pins), with fp_full ties broken by
        recv-lane order — deterministic, and count-exact because tied
        canonical fingerprints are symmetry-images of one state.  The
        surviving unique fingerprints leave sorted ascending, which is
        exactly what the delta encoder needs."""
        q = rv3.reshape(-1)
        qf = rf3.reshape(-1)
        qp = jnp.arange(q.shape[0], dtype=I64)  # recv-order tiebreak
        order = jnp.lexsort((qp, qf, q))
        qsv = q[order]
        first = jnp.concatenate([jnp.ones((1,), bool), qsv[1:] != qsv[:-1]])
        keep = first & (qsv != SENT)
        n_u = keep.sum().astype(I64)
        comp = jnp.argsort(~keep, stable=True)
        pref = jnp.arange(qsv.shape[0]) < n_u
        uq = jnp.where(pref, qsv[comp], SENT)
        stream, nib, total = pack_fp_deltas(uq, n_u)
        n_recv = (q != SENT).sum().astype(I64)
        return DeepFinOut(
            stream, nib, n_u[None], total[None],
            jax.lax.psum(n_recv, "d"), jax.lax.psum(n_u, "d"),
        ), uq

    def _deep_verdict_body(self, rv3, rf3, vb):
        """Map per-unique-fp is-new bits back to per-lane win flags.

        Recomputes the finalize ordering (argsort over identical input
        is deterministic) and returns win flags in the recv layout
        [Rq, D, cap_r] so each round's phase 2 can slice its own page
        and route verdicts back with the standard reverse all_to_all."""
        Rq, D, cap_r = rv3.shape
        q = rv3.reshape(-1)
        qf = rf3.reshape(-1)
        qp = jnp.arange(q.shape[0], dtype=I64)
        order = jnp.lexsort((qp, qf, q))
        qsv = q[order]
        first = jnp.concatenate([jnp.ones((1,), bool), qsv[1:] != qsv[:-1]])
        keep = first & (qsv != SENT)
        rank = jnp.cumsum(keep) - 1
        need = q.shape[0] // 8 + 1
        if vb.shape[0] < need:
            vb = jnp.concatenate(
                [vb, jnp.zeros((need - vb.shape[0],), jnp.uint8)]
            )
        rr = jnp.clip(rank, 0, q.shape[0] - 1)
        bit = (vb[rr >> 3] >> (rr & 7).astype(jnp.uint8)) & 1
        win_sorted = keep & (bit == 1)
        win = win_sorted[jnp.argsort(order)]
        return win.reshape(Rq, D, cap_r)

    def _ship_winners_deep(self, seg, base, capf, dev, oo, op, win_sorted):
        """_ship_winners_to_owners with segment-relative parent rows.

        Parents of this round's winners live in ``seg`` (rows base..
        base+rows of this device's frontier block); global parent index
        (dev*capf + row) rides in the payloads, so gpidx stays global
        for the trace walk.  Recv compaction uses cap_c_deep."""
        D, K = self.D, self.K
        cap_w = self.cap_w
        rows = seg.voted_for.shape[0]
        wcounts = jnp.bincount(jnp.where(win_sorted, oo, D), length=D + 1)
        wstarts = jnp.cumsum(wcounts) - wcounts
        worder = jnp.argsort(jnp.where(win_sorted, oo, D), stable=True)
        idx = jnp.clip(
            wstarts[:D, None] + jnp.arange(cap_w, dtype=wstarts.dtype)[None, :],
            0, oo.shape[0] - 1,
        )
        lane_src = worder[idx]
        in_row = jnp.arange(cap_w)[None, :] < wcounts[:D, None]
        ovf_w = wcounts[:D].max() > cap_w
        spay = jnp.where(in_row, op[lane_src], 0)
        pg = spay // K  # global parent index
        pidx = jnp.clip(pg - dev * capf - base, 0, rows - 1)
        slots = spay % K
        parents = jax.tree.map(lambda x: x[pidx.reshape(-1)], seg)
        kids = self.kern.materialize(parents, slots.reshape(-1))
        gp_send = jnp.where(in_row, pg, -1)

        def a2a(x):
            return jax.lax.all_to_all(
                x.reshape(D, cap_w, *x.shape[1:]), "d", 0, 0, tiled=True
            ).reshape(D * cap_w, *x.shape[1:])

        lane_r = a2a(in_row.astype(jnp.uint8).reshape(-1)).astype(bool)
        gp_r = a2a(gp_send.reshape(-1))
        sl_r = a2a(jnp.where(in_row, slots, 0).reshape(-1))
        kids_r = jax.tree.map(a2a, kids)
        cap_c = self.cap_c_deep
        comp = jnp.argsort(~lane_r, stable=True)
        take = jnp.clip(jnp.arange(cap_c), 0, comp.shape[0] - 1)
        src = comp[take]
        lane = (jnp.arange(cap_c) < lane_r.sum()) & (
            jnp.arange(cap_c) < comp.shape[0]
        )
        children = jax.tree.map(
            lambda x: jnp.where(
                lane.reshape((-1,) + (1,) * (x.ndim - 1)),
                x[src], jnp.zeros_like(x[src]),
            ),
            kids_r,
        )
        gpidx = jnp.where(lane, gp_r[src], -1)
        slots_c = jnp.where(lane, sl_r[src], -1)
        n_new_local = lane.sum().astype(I64)
        ovf_c = lane_r.sum() > cap_c
        child_msum = jnp.zeros((cap_c, 1, 1), jnp.uint32)
        bad_local = jnp.zeros(cap_c, bool)
        for _name, fn in self.inv_fns:
            bad_local = bad_local | (
                ~fn(self.cfg, children, self.kern.tables) & lane
            )
        inv_bad = jax.lax.psum(bad_local.sum().astype(I32), "d")
        first_bad = jnp.where(
            bad_local.any(), jnp.argmax(bad_local), -1
        ).astype(I64)
        return (children, child_msum, gpidx, slots_c, lane, n_new_local,
                inv_bad, first_bad, ovf_w, ovf_c)

    def _deep_phase2_body(self, seg, cv, cp, ver, r, base, capf):
        """Verdicts of round ``r`` back to origins; materialize + ship."""
        D, cap_x, cap_r = self.D, self.cap_x, self.cap_r
        dev = jax.lax.axis_index("d").astype(I64)
        verdict_recv = jax.lax.dynamic_index_in_dim(ver, r, 0, keepdims=False)
        owner = jnp.where(cv == SENT, D, (cv % jnp.uint64(D)).astype(I64))
        oorder = jnp.argsort(owner, stable=True)
        op, oo = cp[oorder], owner[oorder]
        counts = jnp.bincount(oo, length=D + 1)
        starts = jnp.cumsum(counts) - counts
        rank = jnp.arange(cap_x) - starts[oo]
        rr = jnp.clip(rank, 0, cap_r - 1)
        ok_lane = (cv[oorder] != SENT) & (rank < cap_r)
        back = jax.lax.all_to_all(
            verdict_recv, "d", 0, 0, tiled=True
        ).reshape(D, cap_r)
        win_sorted = back[jnp.clip(oo, 0, D - 1), rr] & ok_lane
        n_new_total = jax.lax.psum(win_sorted.sum().astype(I64), "d")
        (children, child_msum, gpidx, slots, _lane, n_new_local,
         inv_bad, first_bad, ovf_w, ovf_c) = self._ship_winners_deep(
            seg, base, capf, dev, oo, op, win_sorted
        )
        return Phase2Out(
            children, child_msum, n_new_local[None], n_new_total,
            gpidx, slots, inv_bad, first_bad[None],
            jax.lax.psum(ovf_w.astype(I32), "d") > 0,
            jax.lax.psum(ovf_c.astype(I32), "d") > 0,
        )

    def _deep_repack_body(self, n_out, ch_stack, gp_stack, sl_stack):
        """Merge the rounds' shipped children into uniform segments.

        Per device: compact the valid child lanes of all Rq round blocks
        (stable, round-major — deterministic) into a prefix, then cut it
        into ``n_out`` uniform seg_rows segments.  Also returns the
        repacked gpidx/slots (the trace/mdelta record must describe the
        frontier layout the next level actually expands)."""
        Rq, cap_c = gp_stack.shape
        seg = self.seg_rows
        gp = gp_stack.reshape(-1)
        sl = sl_stack.reshape(-1)
        validl = gp >= 0
        comp = jnp.argsort(~validl, stable=True)
        ntot = n_out * seg
        take = jnp.clip(jnp.arange(ntot), 0, comp.shape[0] - 1)
        src = comp[take]
        lane = (jnp.arange(ntot) < validl.sum()) & (
            jnp.arange(ntot) < comp.shape[0]
        )
        flat = jax.tree.map(
            lambda x: x.reshape(Rq * cap_c, *x.shape[2:]), ch_stack
        )
        out = jax.tree.map(
            lambda x: jnp.where(
                lane.reshape((-1,) + (1,) * (x.ndim - 1)),
                x[src], jnp.zeros_like(x[src]),
            ),
            flat,
        )
        gpo = jnp.where(lane, gp[src], -1)
        slo = jnp.where(lane, sl[src], -1)
        n_loc = validl.sum().astype(I64)
        segs = tuple(
            jax.tree.map(lambda x: x[s * seg:(s + 1) * seg], out)
            for s in range(n_out)
        )
        return segs, gpo, slo, n_loc[None]

    def _deep_sieve_merge_body(self, sieve, cv):
        """Fold one round's routed candidates into the sieve cache.

        Sorted merge + dedup at fixed capacity; on overflow the LARGEST
        fingerprints fall off the end — the cache stays an exact subset
        of the store (a sieve miss is never wrong, only less effective)
        and the driver grows scap for the next level."""
        scap = sieve.shape[0]
        merged = jnp.sort(jnp.concatenate([sieve, cv]))
        first = jnp.concatenate(
            [jnp.ones((1,), bool), merged[1:] != merged[:-1]]
        ) & (merged != SENT)
        n_u = first.sum()
        comp = jnp.argsort(~first, stable=True)
        pref = jnp.arange(merged.shape[0]) < n_u
        out = jnp.where(pref, merged[comp], SENT)[:scap]
        overflow = jax.lax.psum((n_u > scap).astype(I32), "d") > 0
        return out, overflow

    def _deep_sieve_insert_body(self, sieve, cv):
        """Hash-slab sieve update: O(candidates) probe-and-insert
        instead of the sort-merge of ``_deep_sieve_merge_body``.  Lanes
        whose probe window is full are SKIPPED (subset semantics — a
        sieve miss is never wrong) and the psum'd overflow flag makes
        the driver grow/rehash scap for the next level."""
        sieve2, _n, ovf = hashstore.insert_only_impl(sieve, cv)
        overflow = jax.lax.psum(ovf.astype(I32), "d") > 0
        return sieve2, overflow

    # -- deep-mode program cache ------------------------------------------

    def _dprog(self, key, build):
        # all deep-mode program fetch/build goes through here, so this
        # one assert is the always-on choke point keeping device
        # dispatch off the _io_pool/_ck_pool worker threads
        graft_sanitize.assert_device_dispatch_ok(
            f"deep program dispatch ({key!r})"
        )
        prog = self._dp.get(key)
        if prog is None:
            graft_sanitize.note_shape_event(f"deep program build {key!r}")
            prog = self._dp[key] = build()
        return prog

    def _deep_p1(self):
        def build():
            spec_state = jax.tree.map(
                lambda _: P("d"), init_batch(self.cfg, 1)
            )
            return jax.jit(
                _shard_map(
                    self._deep_phase1_body,
                    self.mesh,
                    (spec_state, P("d"), P(), P(), P("d")),
                    Phase1DeepOut(
                        P("d"), P("d"), P("d"), P("d"), P("d"),
                        P(), P(), P("d"), P(), P(), P(), P(),
                    ),
                )
            )

        return self._dprog("p1", build)

    def _deep_fin(self, Rq):
        def build():
            return jax.jit(
                _shard_map(
                    self._deep_finalize_body,
                    self.mesh,
                    (P(None, "d"), P(None, "d")),
                    (
                        DeepFinOut(
                            P("d"), P("d"), P("d"), P("d"), P(), P(),
                        ),
                        P("d"),
                    ),
                )
            )

        return self._dprog(("fin", Rq, self.cap_r), build)

    def _deep_ver(self, Rq, vq):
        def build():
            return jax.jit(
                _shard_map(
                    self._deep_verdict_body,
                    self.mesh,
                    (P(None, "d"), P(None, "d"), P("d")),
                    P(None, "d"),
                )
            )

        return self._dprog(("ver", Rq, vq, self.cap_r), build)

    def _deep_p2(self):
        def build():
            spec_state = jax.tree.map(
                lambda _: P("d"), init_batch(self.cfg, 1)
            )
            return jax.jit(
                _shard_map(
                    self._deep_phase2_body,
                    self.mesh,
                    (spec_state, P("d"), P("d"), P(None, "d"), P(),
                     P(), P()),
                    Phase2Out(
                        jax.tree.map(
                            lambda _: P("d"), init_batch(self.cfg, 1)
                        ),
                        P("d"), P("d"), P(), P("d"), P("d"), P(), P("d"),
                        P(), P(),
                    ),
                )
            )

        return self._dprog("p2", build)

    def _deep_rp(self, Rq, n_out):
        def build():
            spec_state = jax.tree.map(
                lambda _: P(None, "d"), init_batch(self.cfg, 1)
            )
            seg_spec = jax.tree.map(
                lambda _: P("d"), init_batch(self.cfg, 1)
            )
            return jax.jit(
                _shard_map(
                    functools.partial(self._deep_repack_body, n_out),
                    self.mesh,
                    (spec_state, P(None, "d"), P(None, "d")),
                    (
                        tuple(seg_spec for _ in range(n_out)),
                        P("d"), P("d"), P("d"),
                    ),
                )
            )

        return self._dprog(("rp", Rq, n_out, self.cap_c_deep), build)

    def _deep_sv(self):
        body = (
            self._deep_sieve_insert_body
            if self.use_hashstore
            else self._deep_sieve_merge_body
        )

        def build():
            return jax.jit(
                _shard_map(
                    body,
                    self.mesh,
                    (P("d"), P("d")),
                    (P("d"), P()),
                )
            )

        return self._dprog(("sv", self.scap, self.cap_x), build)

    def _deep_prefix(self, width, q):
        """Quantized-prefix fetch program: every device's first ``q``
        elements of its shard, in ONE collective-free dispatch.

        Cached per (width, q) so the program set stays O(log) over a run
        — the fetch is the tunnel cost, and fetching fixed whole buffers
        would forfeit the bytes the compressed stream saved.  The slice
        is shard-LOCAL (shard_map, P('d') in and out): a global
        dynamic_slice over the sharded array would lower to an
        all-gather, and concurrently dispatched collectives from fetch
        worker threads interleave differently across the virtual
        devices and deadlock the CPU rendezvous (measured: two RunIds
        stuck at one AllGather at D=8)."""

        def build():
            return jax.jit(
                _shard_map(
                    lambda x: x[:q], self.mesh, (P("d"),), P("d")
                )
            )

        return self._dprog(("prefix", width, q), build)

    @functools.cached_property
    def _io_pool(self):
        from concurrent.futures import ThreadPoolExecutor

        # per-owner store-insert workers: the ctypes insert releases the
        # GIL for the C++ sort/merge/spill, so the D shard inserts — the
        # single-CPU serial level tail of the resident design — run
        # concurrently on a multi-core host.  Workers never touch jax:
        # concurrently dispatched device programs interleave their
        # collectives differently across devices and deadlock the CPU
        # rendezvous (the reason the prefix fetch is one main-thread
        # dispatch, see _deep_prefix).  The initializer marks each worker
        # no-dispatch so any future code path that DOES reach a device
        # program from a worker fails loudly instead of deadlocking
        # (graftlint GL007's runtime twin; always on, one thread-local
        # write per worker).
        return ThreadPoolExecutor(
            max_workers=max(2, min(self.D, os.cpu_count() or 2)),
            initializer=graft_sanitize.forbid_device_dispatch_in_thread,
        )

    @functools.cached_property
    def _ck_pool(self):
        from concurrent.futures import ThreadPoolExecutor

        return ThreadPoolExecutor(  # deferred tail writes
            max_workers=1,
            initializer=graft_sanitize.forbid_device_dispatch_in_thread,
        )

    def _grow_deep(self, what):
        """Reactive capacity growth for the deep path (recompiles)."""
        self.reactive_grows += 1
        if what == "cap_x":
            self.cap_x *= 2
        elif what == "cap_c":
            self._cap_c_boost *= 2
        elif what == "cap_w":
            self._cap_w_boost = getattr(self, "_cap_w_boost", 1) * 2
        self._dp.clear()
        for k in ("cap_r", "cap_w"):
            self.__dict__.pop(k, None)

    def _grow_sieve(self, new_scap):
        new_scap = min(new_scap, self.scap_max)
        if new_scap <= self.scap:
            return
        arr = np.asarray(
            jax.device_get(self._sieve_cache)
        ).reshape(self.D, self.scap)
        try:
            resilience.fault_fire("hashstore.grow")
            if self.use_hashstore:
                # hash slabs rehash on growth (slot homes move with the
                # capacity mask — padding would orphan every cached
                # entry)
                new = hashstore.rebuild_np(arr, new_scap)
            else:
                pad = np.full((self.D, new_scap - self.scap), SENT)
                new = np.concatenate([arr, pad], axis=1)
        except Exception as e:  # graftlint: waive[GL003]
            # the sieve is a pure optimization cache: a failed growth
            # (host OOM, injected fault) costs effectiveness, never
            # correctness — keep the current capacity and move on
            print(
                f"[resilience] sieve grow to {new_scap} failed ({e}); "
                "keeping the current sieve capacity",
                file=sys.stderr,
            )
            return
        self.scap = new_scap
        self._sieve_cache = jax.device_put(
            jnp.asarray(new).reshape(-1),
            NamedSharding(self.mesh, P("d")),
        )
        self._dp.clear()

    def _load_sieve_slab(self, ckdir, depth, shard):
        """Adopt a checkpointed sieve slab if (version, depth, D, mode)
        all match; silently keep the empty sieve otherwise."""
        path = os.path.join(ckdir, "sieve_slab.npz")
        if not os.path.exists(path):
            return
        try:
            z = np.load(path)
            ver, d, Dz, rows, hs = (int(x) for x in z["meta"])
            slab = np.asarray(z["slab"], np.uint64)
        except (OSError, ValueError, KeyError):
            return
        if (
            ver != hashstore.SLAB_VERSION or d != depth
            or hs != int(self.use_hashstore)
            or slab.shape[0] != Dz * rows
        ):
            return
        if Dz != self.D:
            # elastic resume: the snapshot was cut for a Dz-device mesh.
            # The sieve is origin-keyed (device d holds what d routed),
            # so no fp-based slice reproduces the old locality — instead
            # REPLICATE the union of all shards into every new shard
            # when it fits (every entry is provably in the store, so any
            # superset-per-shard is still an exact sieve), else start
            # empty and re-learn.
            live = slab[slab != SENT]
            union = np.unique(live)
            if len(union) == 0:
                return
            if self.use_hashstore:
                rows_new = hashstore.slab_rows(len(union))
            else:
                rows_new = 1 << (max(1, 2 * len(union)) - 1).bit_length()
            if rows_new > self.scap_max:
                return
            if self.use_hashstore:
                new = hashstore.rebuild_np(
                    [union] * self.D, rows_new
                )
            else:
                new = np.full((self.D, rows_new), SENT)
                new[:, : len(union)] = np.sort(union)[None, :]
            print(
                f"[elastic] sieve slab repartitioned {Dz} -> {self.D} "
                f"shards ({len(union)} entries replicated, "
                f"{rows_new} rows/shard)",
                file=sys.stderr,
            )
            self.scap = rows_new
            # graftlint: waive[GL006] — one-time elastic-resume upload
            self._sieve_cache = jax.device_put(
                jnp.asarray(new).reshape(-1), shard
            )
            self._dp.clear()
            return
        self.scap = rows
        self._sieve_cache = jax.device_put(jnp.asarray(slab), shard)
        self._dp.clear()

    def _deep_level(self, segments, n_f_np, depth):
        """One BFS level of the sharded deep sweep.

        Sequence: per-segment phase 1 (expand + sieve + route; dispatched
        without intermediate host syncs so the device pipelines rounds),
        owner-side finalize (exact level dedup + delta pack), ONE
        quantized-prefix host fetch + concurrent per-owner store inserts
        (the double-buffered level tail), verdict mapping, per-round
        phase 2 (materialize winners at origins + ship to owners),
        repack into uniform segments.  Returns a dict; on abort or
        violation only the locating fields."""
        D, seg = self.D, self.seg_rows
        shard = NamedSharding(self.mesh, P("d"))
        R = len(segments)
        capf = R * seg
        n_f_dev = jax.device_put(jnp.asarray(n_f_np, I64), shard)
        meter = self.meter
        meter.begin_level(depth + 1)

        grows = 0
        while True:
            p1 = self._deep_p1()
            p1s = [
                p1(
                    segments[r], n_f_dev, jnp.asarray(r * seg, I64),
                    jnp.asarray(capf, I64), self._sieve_cache,
                )
                for r in range(R)
            ]
            ovfs = jax.device_get([p.overflow_x for p in p1s])
            if not any(bool(o) for o in ovfs):
                break
            if grows >= 8:
                raise RuntimeError(
                    f"deep candidate overflow (cap_x={self.cap_x})"
                )
            grows += 1
            print(
                f"[mesh-deep] REACTIVE cap_x grow at level {depth + 1} "
                f"({self.cap_x} -> {self.cap_x * 2})", file=sys.stderr,
            )
            self._grow_deep("cap_x")
        aborts = jax.device_get([p.abort for p in p1s])
        mult_np = np.zeros((self.K,), np.int64)
        for m in jax.device_get([p.mult_slots for p in p1s]):
            mult_np += np.asarray(m, np.int64)
        if any(bool(a) for a in aborts):
            for r, p in enumerate(p1s):
                aa = np.asarray(jax.device_get(p.abort_at)).reshape(D)
                devs = np.nonzero(aa >= 0)[0]
                if len(devs):
                    return dict(
                        abort_gidx=int(devs[0]) * capf + int(aa[devs[0]]),
                        mult_slots=mult_np,
                    )

        # --- owner-side finalize + packed host exchange ------------------
        cap_r = self.cap_r
        Rq = 1 << max(0, R - 1).bit_length()
        pads_v = []
        if Rq > R:
            pad_v = self._dprog(
                ("padv", cap_r),
                lambda: jax.device_put(
                    jnp.full((D * D, cap_r), SENT, U64), shard
                ),
            )
            pads_v = [pad_v] * (Rq - R)
        rv3 = jnp.stack([p.rv.reshape(D * D, cap_r) for p in p1s] + pads_v)
        rf3 = jnp.stack([p.rf.reshape(D * D, cap_r) for p in p1s] + pads_v)
        fin, uq = self._deep_fin(Rq)(rv3, rf3)
        (n_us, totals, n_recv, n_uniq, n_pres, n_posts) = jax.device_get((
            fin.n_u, fin.total, fin.n_recv_sum, fin.n_u_sum,
            [p.n_pre for p in p1s], [p.n_post for p in p1s],
        ))
        n_us = np.asarray(n_us).reshape(D)
        totals = np.asarray(totals).reshape(D)
        n_pre = int(sum(int(x) for x in n_pres))
        n_post = int(sum(int(x) for x in n_posts))
        cap_acc = Rq * D * cap_r
        cap8, capnib = cap_acc * 8, cap_acc // 2
        # live-lane byte ledger (capacity padding excluded on both sides;
        # quantized-prefix fetches ARE counted with their padding — that
        # is what actually moves).  Deep routing tiles are 16 B/lane
        # (fp_view + fp_full; payloads never leave their origin) plus
        # the 1 B/lane verdict return; the uncompressed exchange's are
        # 24+1 B/lane.  Off-diagonal share crosses a link.
        off_diag = (D - 1) / D
        meter.add(
            n_candidates=n_pre, n_sieved=n_pre - n_post,
            n_unique=int(n_uniq),
            a2a_bytes=int(n_post * 17 * off_diag),
            raw_a2a_bytes=int(n_pre * 25 * off_diag),
            raw_host_bytes=n_pre * 25,
        )

        max_nu = int(n_us.max()) if len(n_us) else 0
        vq = packed_quantum(max(1, (max_nu + 7) // 8))
        bits_np = np.zeros((D, vq), np.uint8)
        # ONE collective-free prefix fetch for all owners (quantized to
        # the largest owner's live bytes), dispatched from the main
        # thread; then the D store inserts — the serial single-CPU
        # level tail of the resident design — run concurrently in the
        # pool (the ctypes insert releases the GIL).
        #
        # Packing fallback: the delta/varint form wins only once levels
        # carry enough fingerprints to amortize its per-owner quanta —
        # at tiny levels the packed stream + nibble header is BIGGER
        # than the raw u64 prefix (BENCH_r06 per_level reduction
        # 0.21-0.56 on levels 1-2), so compare the two quantized fetch
        # sizes and ship whichever is smaller, recording packed=False
        # in the ledger when the raw form goes out.
        qf = min(packed_quantum(max(max_nu, 1)), cap_acc)
        qb = min(packed_quantum(max(int(totals.max()), 1)), cap8)
        qn = min(packed_quantum(max((max_nu + 1) // 2, 1)), capnib)
        packed_ok = self.compress and (qb + qn) < qf * 8

        def fetch_prefixes():
            """The quantized-prefix host fetch, as one IDEMPOTENT unit:
            re-fetching an already-computed device array has no side
            effects, so transient link failures retry with backoff
            (resilience.with_retry) instead of killing a multi-hour
            sweep.  The fault site makes the retry path testable."""
            resilience.fault_fire("exchange.fetch")
            if packed_ok:
                st_dev = self._deep_prefix(cap8, qb)(fin.stream)
                nb_dev = self._deep_prefix(capnib, qn)(fin.nib)
                if self.pipeline:
                    # both prefix programs are dispatched; start both
                    # copies so the streams overlap instead of fetching
                    # strictly one after the other
                    graft_pipeline.async_start((st_dev, nb_dev))
                st = np.asarray(jax.device_get(st_dev)).reshape(D, qb)
                nb = np.asarray(jax.device_get(nb_dev)).reshape(D, qn)
                return st, nb, None, D * (qb + qn)
            uqh = np.asarray(jax.device_get(
                self._deep_prefix(cap_acc, qf)(uq)
            )).reshape(D, qf)
            return None, None, uqh, D * qf * 8

        st_all, nb_all, uq_all, fetch_bytes = resilience.with_retry(
            fetch_prefixes, "deep exchange prefix fetch"
        )
        inserted = np.zeros(D, np.int64)
        insert_secs = np.zeros(D, np.float64)

        def insert_one(o):
            t_o = time.monotonic()
            n_o = int(n_us[o])
            if n_o == 0:
                return
            if packed_ok:
                # verify=True: the decoded stream must be strictly
                # ascending (integrity check on the host leg)
                fps = unpack_fp_deltas(
                    st_all[o], nb_all[o], n_o, verify=True
                )
            else:
                fps = uq_all[o][:n_o]
            is_new = self.host_stores[o].insert(fps)
            inserted[o] = int(is_new.sum())
            pb = np.packbits(is_new, bitorder="little")
            bits_np[o, : len(pb)] = pb[:vq]
            # per-owner insert wall time: the straggler-skew signal of
            # the double-buffered level tail (one slow store shard =
            # one degraded host/disk path)
            insert_secs[o] = time.monotonic() - t_o

        t_probe = time.monotonic()
        list(self._io_pool.map(insert_one, range(D)))
        if any(s.num_runs for s in self.host_stores):
            # spilled membership: the per-owner stores hold disk runs,
            # so this level's insert verdicts probed the warm/cold
            # tiers — publish the wall elapsed around the concurrent
            # map (NOT the per-owner sum, which overstates a parallel
            # stall by up to D), the spill-overlap acceptance metric
            graft_obs.tier_probe(
                depth + 1, int(n_us.sum()),
                int(n_us.sum()) - int(inserted.sum()),
                wait_s=time.monotonic() - t_probe,
            )
        meter.note_packed(packed_ok)
        meter.add(host_bytes=fetch_bytes + D * vq + 16 * D)
        vb = jax.device_put(jnp.asarray(bits_np.reshape(-1)), shard)
        ver = self._deep_ver(Rq, vq)(rv3, rf3, vb)

        # --- verdicts back; materialize + ship winners per round ---------
        grows = 0
        while True:
            p2 = self._deep_p2()
            p2s = [
                p2(
                    segments[r], p1s[r].cv, p1s[r].cp, ver,
                    jnp.asarray(r, I32),
                    jnp.asarray(r * seg, I64), jnp.asarray(capf, I64),
                )
                for r in range(R)
            ]
            flags = jax.device_get([(p.ovf_w, p.ovf_c) for p in p2s])
            if not any(bool(w) or bool(c) for w, c in flags):
                break
            if grows >= 8:
                raise RuntimeError(
                    f"deep shipping overflow (cap_w={self.cap_w}, "
                    f"cap_c={self.cap_c_deep})"
                )
            grows += 1
            self._grow_deep(
                "cap_c" if any(bool(c) for _w, c in flags) else "cap_w"
            )
        n2s, invs, nls = jax.device_get(
            ([p.n_new_total for p in p2s], [p.inv_bad for p in p2s],
             [p.n_new_local for p in p2s])
        )
        n2 = sum(int(x) for x in n2s)
        n_new = int(inserted.sum())
        # per-owner count reconciliation across the exchange: what the
        # owner stores admitted must equal what the origins materialized
        resilience.integrity.reconcile(
            "deep owner exchange", n_new, n2, level=depth + 1
        )
        self.skew.note(depth + 1, rows=inserted, seconds=insert_secs)
        inv_total = sum(int(x) for x in invs)
        inv = None
        if inv_total > 0:
            for p in p2s:
                ba = np.asarray(jax.device_get(p.inv_bad_at)).reshape(D)
                devs = np.nonzero(ba >= 0)[0]
                if len(devs):
                    cap_c = self.cap_c_deep
                    gidx = int(devs[0]) * cap_c + int(ba[devs[0]])
                    inv = (
                        np.asarray(jax.device_get(p.gpidx), np.int64),
                        np.asarray(jax.device_get(p.slots), np.int64),
                        gidx,
                    )
                    break

        # --- repack shipped children into uniform 1/D segments ----------
        nl = np.zeros(D, np.int64)
        for x in nls:
            nl += np.asarray(x, np.int64).reshape(D)
        n_out = max(1, -(-int(nl.max()) // seg))
        cap_c = self.cap_c_deep
        pads_k, pads_n = [], []
        if Rq > R:
            zero_kids = self._dprog(
                ("padk", cap_c),
                lambda: jax.device_put(
                    jax.tree.map(jnp.zeros_like, p2s[0].children), shard
                ),
            )
            neg = self._dprog(
                ("padn", cap_c),
                lambda: jax.device_put(
                    jnp.full((D * cap_c,), -1, I64), shard
                ),
            )
            pads_k = [zero_kids] * (Rq - R)
            pads_n = [neg] * (Rq - R)
        ch_stack = jax.tree.map(
            lambda *xs: jnp.stack(xs),
            *([p.children for p in p2s] + pads_k),
        )
        gp_stack = jnp.stack([p.gpidx for p in p2s] + pads_n)
        sl_stack = jnp.stack([p.slots for p in p2s] + pads_n)
        segs_new, gpo, slo, _nloc = self._deep_rp(Rq, n_out)(
            ch_stack, gp_stack, sl_stack
        )
        # the level's trace arrays (its two largest host-bound fetches)
        # enter the async window here, then the sieve update and the
        # candidate-peak control fetch below dispatch/run WHILE they
        # stream — the window drains before the arrays are consumed,
        # still inside the level
        tail = graft_pipeline.DeferredFetch(self.pipeline, (gpo, slo))

        # --- sieve cache update (level end: the level's own candidates
        # must never sieve each other — exact representative choice) ----
        if self.sieve and self.scap:
            sv = self._deep_sv()
            ovf_s = False
            for p in p1s:
                self._sieve_cache, ovf = sv(self._sieve_cache, p.cv)
                ovf_s = ovf_s or bool(jax.device_get(ovf))
            if ovf_s and self.scap < self.scap_max:
                print(
                    f"[mesh-deep] sieve cache full at level {depth + 1}: "
                    f"scap {self.scap} -> {self.scap * 4}",
                    file=sys.stderr,
                )
                self._grow_sieve(self.scap * 4)
        stats = meter.end_level()
        self._cand_hist.append(
            max(int(np.asarray(c)) for c in jax.device_get(
                [p.cand_max for p in p1s]
            ))
        )
        gpo_np, slo_np = tail.get()
        gpidx_np = np.asarray(gpo_np, np.int64)
        slots_np = np.asarray(slo_np, np.int64)
        return dict(
            n_new=n_new, segments=list(segs_new), n_f=nl,
            gpidx=gpidx_np, slots=slots_np, mult_slots=mult_np,
            inv=inv, capf=capf, stats=stats,
        )

    def run_deep(
        self,
        max_depth: int | None = None,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 1,
        resume_from: str | None = None,
        presize: bool = True,
    ) -> CheckResult:
        """The sharded deep-sweep driver (frontier 1/D across devices)."""
        cfg, D, seg = self.cfg, self.D, self.seg_rows
        shard = NamedSharding(self.mesh, P("d"))
        repl = NamedSharding(self.mesh, P())
        t0 = time.monotonic()
        if self.host_stores is None:
            from ..native import HostFPStore

            self.host_stores = [
                HostFPStore(
                    os.path.join(self.host_store_dir, f"shard_{o:02d}"),
                    mem_budget_entries=self._store_budget_entries(),
                )
                for o in range(D)
            ]
            if resume_from is None:
                for s in self.host_stores:
                    s.clear()
        if checkpoint_dir and checkpoint_every:
            import glob as _glob

            if resume_from is None and os.path.isdir(checkpoint_dir):
                # a killed earlier writer must not leak .tmp_* files
                # into a fresh run's directory
                resilience.sweep_tmp(checkpoint_dir)
            has_log = _glob.glob(
                os.path.join(checkpoint_dir, "mdelta_*.npz")
            )
            if resume_from is None and has_log:
                raise ValueError(
                    f"{checkpoint_dir} holds checkpoints from a previous "
                    "run; resume with --recover or clear the directory"
                )
        self._sieve_cache = jax.device_put(
            jnp.full((D * self.scap,), SENT, U64), shard
        )
        self._cand_hist = []
        # per-device peak frontier rows (segments are uniform slabs, so
        # rows x per-row state bytes IS the per-device frontier memory —
        # the ~1/D claim the parity tests and bench record measure)
        self.peak_dev_rows = 0
        ck_fut = None

        ck = None
        if resume_from is not None:
            if not os.path.isdir(resume_from):
                raise ValueError(
                    "deep mode resumes from an mdelta directory only"
                )
            ck = self._resume_from_mdeltas(resume_from, shard, repl)
            if ck is None:
                # healing left nothing replayable: restart from Init
                # with clean stores (they may hold pre-crash inserts)
                for s in self.host_stores:
                    s.clear()
        if ck is not None:
            fr = ck["frontier"]
            rows = fr.voted_for.shape[0] // D
            R = max(1, -(-rows // seg))
            fr_np = {}
            for f in RaftState._fields:
                # intended one-time resume sync (ledgered explicit get:
                # the rebuilt frontier re-splits into uniform segments)
                v = np.asarray(jax.device_get(getattr(fr, f)))
                fr_np[f] = v.reshape((D, rows) + v.shape[1:])
            segments = []
            for r in range(R):
                segd = {}
                for f, v in fr_np.items():
                    blk = v[:, r * seg:(r + 1) * seg]
                    if blk.shape[1] < seg:
                        pad = np.zeros(
                            (D, seg - blk.shape[1]) + blk.shape[2:],
                            blk.dtype,
                        )
                        blk = np.concatenate([blk, pad], axis=1)
                    segd[f] = jax.device_put(
                        jnp.asarray(
                            blk.reshape((D * seg,) + blk.shape[2:])
                        ),
                        shard,
                    )
                segments.append(RaftState(**segd))
            n_f_np = np.asarray(
                jax.device_get(ck["n_f"]), np.int64
            ).reshape(D)
            distinct, generated, depth = (
                ck["distinct"], ck["generated"], ck["depth"],
            )
            level_sizes = ck["level_sizes"]
            trace_levels = ck["trace_levels"]
            mult_slots_total = np.asarray(ck["mult_slots"], np.int64)
            # restore the serialized sieve-cache slab when it matches
            # the resume point (pure optimization — an empty sieve is
            # always correct, just less effective for a few levels)
            self._load_sieve_slab(resume_from, depth, shard)
        else:
            segments = [jax.device_put(init_batch(cfg, D * seg), shard)]
            n_f_np = np.array([1] + [0] * (D - 1), np.int64)
            fv0, _ff0, _ms0 = self.fpr.state_fingerprints(
                init_batch(cfg, 1)
            )
            fp0 = np.asarray(jax.device_get(fv0.astype(U64)))[0]
            self.host_stores[int(fp0 % D)].insert(
                np.asarray([fp0], np.uint64)
            )
            distinct, generated, depth = 1, 0, 0
            level_sizes = [1]
            trace_levels = []
            mult_slots_total = np.zeros(self.K, np.int64)
            from ..engine.bfs import JaxChecker

            chk0 = JaxChecker(cfg)
            init1 = jax.device_put(init_batch(cfg, 1), repl)
            bad0 = int(jax.device_get(
                chk0._inv_scan(init1, jnp.asarray(1, I64))
            ))
            if bad0 >= 0:
                name0 = chk0._bad_invariant_name(init1, bad0)
                return CheckResult(
                    False, 1, 0, 0, (1,),
                    (f"Invariant {name0} is violated",
                     self._trace([], 0, 0)), {},
                )

        from ..engine.forecast import (
            MIN_LEVELS, per_device_forecast, pow2ceil,
        )

        def join_ck():
            nonlocal ck_fut
            if ck_fut is not None:
                ck_fut.result()
                ck_fut = None
                self._ck_fut = None

        while True:
            resilience.fault_fire("level.start")
            if resilience.preempt_requested():
                # the deferred tail writer may still hold the last
                # level's record — join it so the log is complete, then
                # exit resumable
                join_ck()
                raise resilience.Preempted(
                    checkpoint_dir if checkpoint_every else None, depth
                )
            if max_depth is not None and depth >= max_depth:
                break
            if self.watchdog is not None:
                self.watchdog.arm(f"mesh-deep level {depth + 1}")
            resilience.fault_fire("device.lost")
            resilience.fault_fire("device.hang")
            if presize and len(level_sizes) > MIN_LEVELS:
                fc = per_device_forecast(
                    level_sizes, distinct, max_depth, D
                )
                if fc is not None:
                    if self._cand_hist:
                        # measured per-round candidate peak, floored by
                        # the forecast: a round's parents are bounded by
                        # min(seg_rows, forecast per-device rows), at
                        # ~4 candidate lanes per parent
                        want_x = pow2ceil(max(
                            int(1.35 * max(self._cand_hist[-3:])),
                            4 * min(fc["peak_rows"], seg),
                        ) + 1)
                        if self.cap_x_max is not None:
                            want_x = min(want_x, self.cap_x_max)
                        want_x = min(
                            want_x, 1 << 22,
                            pow2ceil(fc["budget"] // (48 * D)) // 2,
                        )
                        if want_x > self.cap_x:
                            print(
                                f"[mesh-deep] presize: cap_x {self.cap_x}"
                                f" -> {want_x}", file=sys.stderr,
                            )
                            self.cap_x = want_x
                            self._dp.clear()
                            for k in ("cap_r", "cap_w"):
                                self.__dict__.pop(k, None)
                    want_s = min(
                        pow2ceil(int(2.2 * fc["final_rows"]) + 1),
                        pow2ceil(fc["budget"] // 8),
                        self.scap_max,
                    )
                    if want_s > self.scap:
                        print(
                            f"[mesh-deep] presize: scap {self.scap} -> "
                            f"{want_s}", file=sys.stderr,
                        )
                        self._grow_sieve(want_s)
            out = self._deep_level(segments, n_f_np, depth)
            if "abort_gidx" in out:
                join_ck()
                return CheckResult(
                    False, distinct, generated, depth, tuple(level_sizes),
                    (
                        'Assert "split brain" (Raft.tla:185)',
                        self._trace(trace_levels, depth, out["abort_gidx"]),
                    ),
                )
            mult_slots_total += out["mult_slots"]
            generated += int(out["mult_slots"].sum())
            n_new = out["n_new"]
            if n_new == 0:
                break
            capf_prev = out["capf"]
            segments, n_f_np = out["segments"], out["n_f"]
            self.peak_dev_rows = max(
                self.peak_dev_rows, len(segments) * seg
            )
            distinct += n_new
            # store-occupancy conservation: the per-owner external
            # stores must jointly hold exactly the distinct set (a
            # lost/duplicated insert would silently skew every later
            # sieve drop and verdict)
            resilience.integrity.occupancy_check(
                "deep per-owner stores",
                sum(len(s) for s in self.host_stores), distinct,
                level=depth + 1,
            )
            level_sizes.append(n_new)
            depth += 1
            trace_levels.append((out["gpidx"], out["slots"]))
            graft_obs.level_commit(depth, n_new, distinct, generated)
            if self.progress is not None:
                st = out["stats"]
                self.progress(
                    dict(
                        level=depth, frontier=n_new, distinct=distinct,
                        generated=generated,
                        elapsed=time.monotonic() - t0,
                        exchange_bytes=st["exchanged_bytes"],
                        exchange_raw_bytes=st["raw_bytes"],
                        exchange_reduction=st["reduction"],
                    )
                )
            if graft_sanitize.CURRENT is not None:
                sig = (
                    len(segments), self.seg_rows, self.cap_x,
                    self.scap, self.cap_c_deep, self.cap_w,
                )
                if sig != getattr(self, "_san_sig", None):
                    graft_sanitize.note_shape_event(f"deep level {sig}")
                    self._san_sig = sig
                graft_sanitize.level_tick()
            if out["inv"] is not None:
                gp_r, sl_r, gidx = out["inv"]
                trace = self._trace(
                    trace_levels[:-1] + [(gp_r, sl_r)], depth, gidx
                )
                from ..oracle.explicit import resolve_invariant

                name = next(
                    (
                        n for n in cfg.invariants
                        if not resolve_invariant(n)(cfg, trace[-1][1])
                    ),
                    cfg.invariants[0],
                )
                join_ck()
                return CheckResult(
                    False, distinct, generated, depth, tuple(level_sizes),
                    (f"Invariant {name} is violated", trace),
                )
            if checkpoint_dir and checkpoint_every:
                # deferred tail write: the mdelta record of level L lands
                # on disk while the device expands level L+1 (the chain
                # is still strictly ordered — one writer, joined before
                # the next submit and before any return)
                join_ck()
                ns = SimpleNamespace(
                    gpidx=out["gpidx"], slots=out["slots"],
                    n_new_local=n_f_np.copy(),
                    mult_slots=out["mult_slots"],
                )
                sieve_np = None
                # shared size-aware snapshot cadence (the dump is a
                # resume optimization, not the source of truth)
                dump_every = hashstore.dump_interval(self.D * self.scap * 8)
                if (self.sieve and self.scap and dump_every
                        and depth % dump_every == 0):
                    # intended slab snapshot (the fetch is O(D*scap)):
                    # fetched on the MAIN thread (workers never
                    # dispatch), written by the deferred tail writer
                    # with the mdelta record
                    sieve_np = np.asarray(
                        jax.device_get(self._sieve_cache)
                    )
                ck_fut = self._ck_fut = self._ck_pool.submit(
                    self._save_mdelta, checkpoint_dir, depth, ns,
                    capf_prev, sieve_np,
                )
            if self.watchdog is not None:
                self.watchdog.disarm()
        join_ck()
        return CheckResult(
            True, distinct, generated, depth, tuple(level_sizes), None,
            self._action_counts(mult_slots_total),
        )

    @functools.cached_property
    def cap_r(self) -> int:
        # routing capacity per (src, dst) pair.  Duplicate fan-out lanes
        # CONCENTRATE on their child's owner (same fp -> same shard), so
        # uniform-hashing slack under-provisions skewed levels (measured:
        # reactive cap_x doublings at levels 9-10 of the reference config
        # were routing overflows).  cap_r = cap_x is worst-case exact —
        # per-owner count can never exceed the device's candidate total —
        # and the D*cap_r all_to_all buffers stay MB-scale.
        return self.cap_x

    @functools.cached_property
    def level_step(self):
        body = (
            self._body_all_to_all
            if self.exchange == "all_to_all"
            else self._body_all_gather
        )
        spec_state = jax.tree.map(lambda _: P("d"), init_batch(self.cfg, 1))
        vspec = P("d") if self.exchange == "all_to_all" else P()
        return jax.jit(
            _shard_map(
                body,
                self.mesh,
                (spec_state, P("d"), P("d"), vspec),
                LevelOut(
                    jax.tree.map(lambda _: P("d"), init_batch(self.cfg, 1)),
                    P("d"), vspec, P("d"), P(), P(), P(),
                    P("d"), P("d"), P(), P("d"), P(), P("d"), P(), P(),
                    P(),
                ),
            )
        )

    # -- trace replay (slot chains are device-agnostic) --------------------

    def _trace(self, trace_levels, level, gidx):
        chain = []
        d, j = level, gidx
        while d > 0:
            gpidx, slots = trace_levels[d - 1]
            chain.append(int(slots[j]))
            j = int(gpidx[j])
            d -= 1
        chain.reverse()
        st = init_batch(self.cfg, 1)
        out = [("Init", to_oracle(self.cfg, st)[0])]
        for slot in chain:
            st = self.kern.materialize(st, jnp.asarray([slot], I64))
            fam = int(self.kern.slot_family[slot])
            name = self.kern.families[fam][0]
            server = int(self.kern.slot_coords[slot, 0]) + 1
            out.append((f"{name}({server})", to_oracle(self.cfg, st)[0]))
        return out

    def _action_counts(self, mult_slots: np.ndarray) -> dict:
        out: dict[str, int] = {}
        fam = self.kern.slot_family
        for fi, (name, _fn, _c) in enumerate(self.kern.families):
            out[name] = out.get(name, 0) + int(mult_slots[fam == fi].sum())
        return {k: v for k, v in out.items() if v}

    # -- checkpoint / resume (TLC's states/ + -recover, mesh edition) ------
    #
    # Two formats, mirroring the single-device engine (engine/bfs.py):
    #
    # * **delta log** (``mdelta_####.npz``, the default): each level
    #   appends only its compact (parent-layout-index, slot) pairs plus
    #   per-device winner counts — resume REPLAYS the materialize pass
    #   from Init and recomputes fingerprints, so nothing store-sized is
    #   ever written.  Records are device-layout-relative and pinned to
    #   (D, exchange, canon) in their meta.
    #
    # * **monolith** (``latest.npz``, back-compat): full frontier + store
    #   in one file.

    def _save_mdelta(self, ckdir, depth, out, cap_f, sieve_np=None):
        """Append one level's delta record (compact layout prefixes).

        ``sieve_np`` (deep mode): the level-end sieve-cache slab,
        serialized VERSIONED alongside the segment-quantized frontier
        records so a resumed deep run keeps its sieve effectiveness
        instead of re-learning the visited set from zero.  The slab is
        an optimization cache — resume validates (version, depth, D,
        mode) and silently starts empty on any mismatch."""
        os.makedirs(ckdir, exist_ok=True)
        if sieve_np is not None:
            rows = sieve_np.shape[0] // self.D
            resilience.commit_npz(
                ckdir,
                "sieve_slab.npz",
                dict(
                    slab=sieve_np,
                    meta=np.asarray(
                        [hashstore.SLAB_VERSION, depth, self.D, rows,
                         int(self.use_hashstore)],
                        np.int64,
                    ),
                ),
                kind="sieve",
                depth=depth,
                run_fp=self._run_fp,
            )
        gpidx = np.asarray(out.gpidx).astype(np.int64)
        slots = np.asarray(out.slots).astype(np.int64)
        n_local = np.asarray(out.n_new_local).astype(np.int64).reshape(-1)
        valid = gpidx >= 0
        cap_c = gpidx.shape[0] // self.D
        # winners are compacted to each device block's prefix (_compact),
        # so the valid mask must equal the per-device prefix counts
        assert valid.reshape(self.D, cap_c).sum(1).tolist() == n_local.tolist()
        slot_dt = np.uint16 if self.K <= 0xFFFF else np.uint32
        # deep-sweep global parent indices (dev * capf + row) can pass
        # 2^32 at the frontier scales that tier targets — widen the
        # record rather than silently truncating (the loader reads
        # either width via .astype(int64))
        pidx_dt = (
            np.uint32
            if valid.sum() == 0 or gpidx[valid].max() <= 0xFFFFFFFF
            else np.uint64
        )
        resilience.commit_npz(
            ckdir,
            f"mdelta_{depth:04d}.npz",
            dict(
                pidx=gpidx[valid].astype(pidx_dt),
                slot=slots[valid].astype(slot_dt),
                n_local=n_local,
                mult=np.asarray(out.mult_slots, np.int64),
                meta=np.asarray(
                    [depth, int(valid.sum()), self.D, cap_f, cap_c,
                     1 if self.exchange == "all_to_all" else 0,
                     1 if self.canon == "late" else 0],
                    np.int64,
                ),
            ),
            kind="mdelta",
            depth=depth,
            run_fp=self._run_fp,
        )

    def _resume_from_mdeltas(self, ckdir, shard, repl):
        """Rebuild the mesh run state by replaying the delta log from Init.

        The replay materializes each level's (parent, slot) record with
        the shared successor kernel and recomputes canonical fingerprints
        — minutes of compute instead of a store-sized monolith read, and
        the rebuilt store holds exactly what an uninterrupted run's would
        (fp %% D shards for all_to_all, a sorted replicated array for
        all_gather)."""
        # -- self-healing pass: sweep orphaned tmp files, digest-verify
        # every record, quarantine corrupt/torn/unmanifested ones and
        # truncate to the last good contiguous prefix (a TAIL gap is a
        # healed crash; only an interior hole — which the ordered
        # writer cannot produce — stays fatal).  A bad sieve slab is
        # quarantined here and the resume silently starts with an
        # empty sieve (it is a pure optimization cache).
        files = resilience.heal_log(
            ckdir, "mdelta", run_fp=self._run_fp,
            slabs=("sieve_slab.npz",),
            legacy_run_fps=self._legacy_run_fps(),
        )
        if not files:
            if resilience.Manifest.load(ckdir).exists:
                # everything was quarantined: restart from Init (the
                # worst-case but still hands-free recovery)
                return None
            raise ValueError(f"no mdelta_*.npz checkpoints under {ckdir}")
        cfg, K, D = self.cfg, self.K, self.D
        # -- elastic replay: every record carries its OWN geometry -----
        # A record's pidx index its PARENT level's layout (Dz device
        # blocks of cap_f rows: gpidx = dev*cap_f + row) and its own
        # rows land in a (len(n_local), cap_c) layout.  The replay
        # tracks that per-record geometry instead of assuming the
        # current mesh width, which is what lets a D-device log resume
        # on D' != D devices: after the replay, ONE owner remap
        # (resilience/elastic.py) re-shards the final frontier by
        # fp % D' and the stores/slabs rebuild into the new partition
        # from the replayed fingerprints.
        z0 = np.load(files[0])
        par_D = int(z0["meta"][2])  # the log's initial mesh width
        frontier = init_batch(cfg, par_D)  # layout [par_D, cap_f=1]
        fv0, _ff0, _ms0 = self.fpr.state_fingerprints(
            jax.tree.map(lambda x: x[:1], frontier)
        )
        fps_all = [np.asarray(jax.device_get(fv0.astype(U64)))]
        trace_levels, level_sizes = [], [1]
        mult_slots_total = np.zeros(K, np.int64)
        depth = 0
        n_local = np.array([1] + [0] * (par_D - 1), np.int64)
        for f in files:
            z = np.load(f)
            meta = [int(x) for x in z["meta"]]
            d, n_new, Dz, cap_f, cap_c, a2a, late = meta
            nl = z["n_local"].astype(np.int64)
            D_own = len(nl)  # the record's own device-block count
            if d != depth + 1:
                raise ValueError(
                    f"mdelta log gap: expected level {depth + 1}, found "
                    f"level {d} ({f})"
                )
            if Dz != par_D:
                raise ValueError(
                    f"mdelta geometry break at level {d}: record "
                    f"expects a {Dz}-device parent layout, replay "
                    f"built {par_D} ({f})"
                )
            if a2a != (1 if self.exchange == "all_to_all" else 0):
                raise ValueError(
                    "checkpoint exchange mode differs from this run"
                )
            if late != (1 if self.canon == "late" else 0):
                raise ValueError(
                    "checkpoint canonicalization mode differs from this "
                    "run (pass the matching --canon)"
                )
            built = int(frontier.voted_for.shape[0]) // par_D
            if cap_f < built:
                raise ValueError(
                    f"mdelta level {d} expects a {cap_f}-wide frontier, "
                    f"replay built {built}"
                )
            if cap_f > built:
                # deep-sweep records describe segment-quantized frontier
                # blocks (cap_f = n_segments * seg_rows); pad each
                # DEVICE BLOCK so the record's global parent indices
                # (dev*cap_f + row) land on the replayed rows
                def _padblk(x, _c=cap_f, _b=built, _d=par_D):
                    blk = x.reshape((_d, _b) + x.shape[1:])
                    pad = jnp.zeros(
                        (_d, _c - _b) + x.shape[1:], x.dtype
                    )
                    return jnp.concatenate([blk, pad], axis=1).reshape(
                        (_d * _c,) + x.shape[1:]
                    )

                frontier = jax.tree.map(_padblk, frontier)
            # rebuild the padded device layout from the compact prefixes
            gpidx = np.full(D_own * cap_c, -1, np.int64)
            slots = np.zeros(D_own * cap_c, np.int64)
            off = 0
            for dev in range(D_own):
                c = int(nl[dev])
                gpidx[dev * cap_c : dev * cap_c + c] = z["pidx"][off : off + c]
                slots[dev * cap_c : dev * cap_c + c] = z["slot"][off : off + c]
                off += c
            valid = gpidx >= 0
            parents = jax.tree.map(
                lambda x: x[jnp.asarray(np.clip(gpidx, 0, None))], frontier
            )
            children = self.kern.materialize(parents, jnp.asarray(slots, I64))
            vmask = jnp.asarray(valid)
            children = jax.tree.map(
                lambda x: jnp.where(
                    vmask.reshape((-1,) + (1,) * (x.ndim - 1)),
                    x, jnp.zeros_like(x),
                ),
                children,
            )
            fv, _ff, _ms = self.fpr.state_fingerprints(children)
            fps_all.append(np.asarray(jax.device_get(fv.astype(U64)))[valid])
            trace_levels.append((gpidx, slots))
            level_sizes.append(n_new)
            mult_slots_total = mult_slots_total + z["mult"].astype(np.int64)
            frontier = children
            n_local = nl
            par_D = D_own  # this record's layout is the next's parent
            depth = d
        if par_D != D and trace_levels:
            print(
                f"[elastic] resuming a {par_D}-device log on a "
                f"{D}-device mesh: owner remap re-shards the frontier "
                f"by fp % {D} and the visited structures rehash into "
                "the new partition",
                file=sys.stderr,
            )
        distinct = int(sum(level_sizes))
        fps = np.unique(np.concatenate(fps_all))
        if len(fps) != distinct:
            raise ValueError(
                f"mdelta replay rebuilt {len(fps)} distinct fingerprints "
                f"for {distinct} recorded states — corrupt or mixed log"
            )
        # Rebalance the resumed frontier by OWNER (fp % D) onto the
        # CURRENT mesh.  Three layouts need this: chains written before
        # the owner-shipping exchange (rounds 2-4: the whole frontier on
        # device 0), any same-D resume whose layout drifted, and — the
        # elastic case — a log written on a different device count,
        # whose rows must redistribute by fp % D' before the first
        # resumed level.  The remap permutes rows host-side
        # (resilience/elastic.owner_rebalance), growing the per-device
        # block when the new partition needs it, and permutes the LAST
        # trace record identically so slot-chain replay stays exact
        # (earlier records reference their own levels' layouts, which
        # are untouched).
        if trace_levels and (D > 1 or par_D != D):
            cap_cr = frontier.voted_for.shape[0] // par_D
            fvh = np.asarray(jax.device_get(fv.astype(U64)))
            validh = np.asarray(valid)
            perm, counts_o, cap_new = resilience.elastic.owner_rebalance(
                fvh, validh, D,
                min_cap=cap_cr if par_D == D else 1,
            )
            lane = perm >= 0
            safe = np.clip(perm, 0, None)
            lane_dev = jnp.asarray(lane)
            safe_dev = jnp.asarray(safe)

            def _remap(x):
                g = x[safe_dev]
                return jnp.where(
                    lane_dev.reshape((-1,) + (1,) * (x.ndim - 1)),
                    g, jnp.zeros_like(g),
                )

            frontier = jax.tree.map(_remap, frontier)
            gpidx_l, slots_l = trace_levels[-1]
            gpidx_n = np.where(lane, gpidx_l[safe], -1)
            slots_n = np.where(lane, slots_l[safe], 0)
            trace_levels[-1] = (gpidx_n, slots_n)
            n_local = counts_o.astype(np.int64)
            # Persist the normalized layout: records appended after this
            # resume reference the REBALANCED level-d row positions, so
            # the on-disk level-d record must describe them or the next
            # full replay gathers wrong parents and dies as "corrupt or
            # mixed log".  Row order, n_local and (elastic case) the
            # own-layout geometry (cap_c + device-block count) change;
            # the record's pidx values AND its parent geometry (Dz,
            # cap_f — what the indices point into) are untouched.
            z_last = np.load(files[-1])
            meta_n = [int(x) for x in z_last["meta"]]
            meta_n[4] = int(cap_new)
            validn = gpidx_n >= 0
            slot_dt = z_last["slot"].dtype
            pidx_dt = (
                np.uint32
                if validn.sum() == 0
                or gpidx_n[validn].max() <= 0xFFFFFFFF
                else np.uint64
            )
            resilience.commit_npz(
                ckdir,
                os.path.basename(files[-1]),
                dict(
                    pidx=gpidx_n[validn].astype(pidx_dt),
                    slot=slots_n[validn].astype(slot_dt),
                    n_local=n_local,
                    mult=z_last["mult"],
                    meta=np.asarray(meta_n, np.int64),
                ),
                kind="mdelta",
                depth=depth,
                run_fp=self._run_fp,
            )
        if self.host_stores is not None:
            # the replay rebuilds the EXTERNAL stores: clear first (they
            # may hold pre-crash inserts, including a partially-completed
            # level that never reached the log — those would silently mark
            # reachable states as visited), then insert each owner's fps
            # (concurrently — the ctypes insert releases the GIL)
            from ..native import insert_sharded

            for s in self.host_stores:
                s.clear()
            insert_sharded(self.host_stores, fps)
            visited = None
        elif self.exchange == "all_to_all":
            per_shard = [np.sort(fps[fps % np.uint64(D) == o]) for o in range(D)]
            need = max(len(s) for s in per_shard)
            vcap = max(self.vcap, 1 << (2 * need - 1).bit_length())
            if self.use_hashstore:
                vis = hashstore.rebuild_np(per_shard, vcap)
            else:
                vis = np.full((D, vcap), np.uint64(0xFFFFFFFFFFFFFFFF))
                for o, s in enumerate(per_shard):
                    vis[o, : len(s)] = s
                vis = np.sort(vis, axis=1)
            self.vcap = vcap
            visited = jax.device_put(jnp.asarray(vis).reshape(-1), shard)
        else:
            vcap = max(self.vcap, 1 << (2 * len(fps) - 1).bit_length())
            vis = np.full(vcap, np.uint64(0xFFFFFFFFFFFFFFFF))
            vis[: len(fps)] = fps
            self.vcap = vcap
            visited = jax.device_put(jnp.asarray(np.sort(vis)), repl)
        msum = (
            self.fpr.msg_hash(frontier.msgs)
            if self.canon == "expand"
            else jnp.zeros((frontier.voted_for.shape[0], 1, 1), jnp.uint32)
        )
        return dict(
            frontier=jax.device_put(frontier, shard),
            msum=jax.device_put(msum, shard),
            n_f=jax.device_put(jnp.asarray(n_local, I64), shard),
            visited=visited,
            distinct=distinct,
            generated=int(mult_slots_total.sum()),
            depth=depth,
            level_sizes=level_sizes,
            trace_levels=trace_levels,
            mult_slots=mult_slots_total,
        )

    def _load_checkpoint(self, path, shard, repl):
        """Read a legacy ``latest.npz`` monolith (writer removed — the
        delta log replaced it; kept so old checkpoints stay resumable)."""
        z = np.load(path)
        meta = [int(x) for x in z["meta"]]
        D, distinct, generated, depth, a2a = meta[:5]
        if D != self.D:
            raise ValueError(
                f"checkpoint was taken on a {D}-device mesh, this run has "
                f"{self.D} (fingerprint ownership is D-dependent)"
            )
        if a2a != (1 if self.exchange == "all_to_all" else 0):
            raise ValueError("checkpoint exchange mode differs from this run")
        # the canon="late" frontier carries a dummy msum that the
        # canon="expand" incremental hash would silently consume as zeros
        late = meta[5] if len(meta) > 5 else 0
        if late != (1 if self.canon == "late" else 0):
            raise ValueError(
                "checkpoint canonicalization mode differs from this run "
                "(pass the matching --canon)"
            )
        frontier = RaftState(
            **{
                k[3:]: jax.device_put(jnp.asarray(z[k]), shard)
                for k in z.files
                if k.startswith("st_")
            }
        )
        vis_np = z["visited"]
        if self.use_hashstore and self.exchange == "all_to_all":
            # legacy monoliths hold sorted shards; rebuild the hash
            # slabs host-side at the same per-shard capacity (growing
            # if the sorted shard ran hotter than the 1/2 load line)
            arr = np.asarray(vis_np).reshape(D, -1)
            need = int(max((arr[o] != SENT).sum() for o in range(D)))
            vcap = max(arr.shape[1], hashstore.slab_rows(need))
            vis_np = hashstore.rebuild_np(arr, vcap).reshape(-1)
        visited = jax.device_put(
            jnp.asarray(vis_np),
            shard if self.exchange == "all_to_all" else repl,
        )
        if self.exchange == "all_to_all":
            self.vcap = vis_np.shape[0] // D
        else:
            self.vcap = vis_np.shape[0]
        trace_levels = [
            (z[f"trace_p{i}"], z[f"trace_s{i}"])
            for i in range(int(z["n_trace"][0]))
        ]
        return dict(
            frontier=frontier,
            msum=jax.device_put(jnp.asarray(z["msum"]), shard),
            n_f=jax.device_put(jnp.asarray(z["n_f"]), shard),
            visited=visited,
            distinct=distinct,
            generated=generated,
            depth=depth,
            level_sizes=list(int(x) for x in z["level_sizes"]),
            trace_levels=trace_levels,
            mult_slots=np.asarray(z["mult_slots"]),
        )

    # -- the distributed run ----------------------------------------------

    def run(
        self,
        max_depth: int | None = None,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 1,
        resume_from: str | None = None,
        presize: bool = True,
    ) -> CheckResult:
        try:
            return self._run_impl(
                max_depth=max_depth, checkpoint_dir=checkpoint_dir,
                checkpoint_every=checkpoint_every,
                resume_from=resume_from, presize=presize,
            )
        except BaseException as e:  # graftlint: waive[GL003] —
            # crash-path bookkeeping only: the tail write joins, device
            # loss gets a note, and the exception ALWAYS re-raises
            # a crash (device loss included) must not lose the deep
            # path's deferred tail write: join it so everything the
            # level loop committed stays on disk, then let the CLI map
            # device loss to exit 75 — --supervise relaunches and the
            # elastic resume re-shards onto the surviving mesh
            fut, self._ck_fut = getattr(self, "_ck_fut", None), None
            if fut is not None:
                try:
                    fut.result()
                except Exception:  # graftlint: waive[GL003] — the
                    # original crash must propagate, not the tail
                    # writer's secondary failure
                    pass
            if resilience.elastic.is_device_loss(e):
                print(
                    "[elastic] device failure mid-run — committed "
                    "levels are durable"
                    + (f" in {checkpoint_dir}" if checkpoint_dir else "")
                    + "; a relaunch resumes over the surviving mesh",
                    file=sys.stderr,
                )
            raise
        finally:
            if self.watchdog is not None:
                self.watchdog.disarm()

    def _run_impl(
        self,
        max_depth: int | None = None,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 1,
        resume_from: str | None = None,
        presize: bool = True,
    ) -> CheckResult:
        self._ck_fut = None
        if self.deep:
            return self.run_deep(
                max_depth=max_depth, checkpoint_dir=checkpoint_dir,
                checkpoint_every=checkpoint_every,
                resume_from=resume_from, presize=presize,
            )
        cfg, D = self.cfg, self.D
        mesh = self.mesh
        shard = NamedSharding(mesh, P("d"))
        repl = NamedSharding(mesh, P())
        t0 = time.monotonic()

        if self.host_store_dir is not None and self.host_stores is None:
            from ..native import HostFPStore

            self.host_stores = [
                HostFPStore(
                    os.path.join(self.host_store_dir, f"shard_{o:02d}"),
                    mem_budget_entries=self._store_budget_entries(),
                )
                for o in range(D)
            ]
            if resume_from is None:
                for s in self.host_stores:
                    s.clear()  # orphaned run files from a crashed process

        if checkpoint_dir and checkpoint_every:
            import glob as _glob

            if resume_from is None and os.path.isdir(checkpoint_dir):
                # sweep a killed earlier writer's orphaned tmp files
                resilience.sweep_tmp(checkpoint_dir)
            has_log = _glob.glob(os.path.join(checkpoint_dir, "mdelta_*.npz"))
            if resume_from is None and has_log:
                raise ValueError(
                    f"{checkpoint_dir} holds checkpoints from a previous "
                    "run; a fresh run would interleave two runs' logs — "
                    "resume with --recover or clear the directory"
                )
            if resume_from is not None and not os.path.isdir(resume_from):
                # a monolith resumes at depth d > 0; appending mdelta
                # records from level d+1 would leave a gapped (or, if the
                # directory already holds another run's records,
                # interleaved) chain that replay correctly rejects later —
                # refuse up front
                raise ValueError(
                    "cannot append mdelta checkpoints while resuming from "
                    "a monolith snapshot (the replay chain would start at "
                    f"level {1}+gap); resume from the delta directory, or "
                    "drop --checkpoint-dir for this run"
                )
        ck = None
        if resume_from is not None:
            if os.path.isdir(resume_from):
                ck = self._resume_from_mdeltas(resume_from, shard, repl)
                if ck is None and self.host_stores is not None:
                    # healing left nothing replayable: restart from
                    # Init with clean stores
                    for s in self.host_stores:
                        s.clear()
            else:
                ck = self._load_checkpoint(resume_from, shard, repl)
        if ck is not None:
            frontier, msum, n_f = ck["frontier"], ck["msum"], ck["n_f"]
            visited = ck["visited"]
            distinct, generated, depth = (
                ck["distinct"], ck["generated"], ck["depth"],
            )
            level_sizes, trace_levels = ck["level_sizes"], ck["trace_levels"]
            mult_slots_total = ck["mult_slots"]
        else:
            frontier = jax.device_put(init_batch(cfg, D), shard)
            fv, _ff, msum0 = self.fpr.state_fingerprints(frontier)
            if self.canon == "late":
                msum0 = jnp.zeros((D, 1, 1), jnp.uint32)
            msum = jax.device_put(msum0, shard)
            n_f = jax.device_put(jnp.asarray([1] + [0] * (D - 1), I64), shard)
            fp0 = np.asarray(jax.device_get(fv.astype(U64)))[0]
            if self.host_stores is not None:
                self.host_stores[int(fp0 % D)].insert(
                    np.asarray([fp0], np.uint64)
                )
                visited = None
            elif self.exchange == "all_to_all":
                vis = np.full((D, self.vcap), np.uint64(0xFFFFFFFFFFFFFFFF))
                if self.use_hashstore:
                    hashstore.insert_np(
                        vis[int(fp0 % D)], np.asarray([fp0], np.uint64)
                    )
                else:
                    vis[int(fp0 % D), 0] = fp0
                    vis = np.sort(vis, axis=1)
                visited = jax.device_put(jnp.asarray(vis).reshape(-1), shard)
            else:
                vis = np.full(self.vcap, np.uint64(0xFFFFFFFFFFFFFFFF))
                vis[0] = fp0
                visited = jax.device_put(jnp.asarray(np.sort(vis)), repl)
            distinct, generated, depth = 1, 0, 0
            level_sizes = [1]
            trace_levels = []
            mult_slots_total = np.zeros(self.K, np.int64)

            # init-state invariants (host-side, single state)
            from ..engine.bfs import JaxChecker  # reuse the batched kernels

            chk0 = JaxChecker(cfg)
            init1 = jax.device_put(init_batch(cfg, 1), repl)
            bad0 = int(
                jax.device_get(chk0._inv_scan(init1, jnp.asarray(1, I64)))
            )
            if bad0 >= 0:
                name0 = chk0._bad_invariant_name(init1, bad0)
                return CheckResult(
                    False, 1, 0, 0, (1,),
                    (f"Invariant {name0} is violated", self._trace([], 0, 0)), {},
                )

        def grow_visited(v, new_vcap):
            """Grow every store shard: SENT-pad (sorted mode) or rehash
            into a bigger slab (hash mode — slot homes move with the
            capacity mask, so padding would orphan every entry).  A
            hash rehash failure (host OOM, injected fault) DEGRADES to
            the sorted layout mid-run — the automatic --no-hashstore —
            instead of dying: the slab's live slots hold exactly the
            per-shard visited sets, so the conversion is lossless."""
            arr = np.asarray(v).reshape(D, -1)
            if self.use_hashstore:
                try:
                    resilience.fault_fire("hashstore.grow")
                    out = hashstore.rebuild_np(arr, new_vcap)
                    self.vcap = new_vcap
                    return jax.device_put(
                        jnp.asarray(out).reshape(-1), shard
                    )
                except Exception as e:  # graftlint: waive[GL003]
                    # any rehash failure degrades; never mid-run death
                    print(
                        f"[resilience] mesh hash-store grow failed "
                        f"({e}); degrading to the sorted visited "
                        "layout for the rest of the run",
                        file=sys.stderr,
                    )
                    self.use_hashstore = False
                    sorted_v = np.full(
                        (D, new_vcap), np.uint64(SENT)
                    )
                    for o in range(D):
                        live = np.sort(arr[o][arr[o] != SENT])
                        sorted_v[o, : len(live)] = live
                    self.vcap = new_vcap
                    for k in ("level_step", "level_phase1",
                              "level_phase2", "cap_r", "cap_w"):
                        self.__dict__.pop(k, None)
                    return jax.device_put(
                        jnp.asarray(sorted_v).reshape(-1), shard
                    )
            pad = np.full((D, new_vcap - arr.shape[1]), np.uint64(SENT))
            self.vcap = new_vcap
            return jax.device_put(
                jnp.asarray(np.concatenate([arr, pad], axis=1)).reshape(-1), shard
            )

        # predictive capacity sizing (VERDICT r4 #7): once enough levels
        # are observed to trust the growth model, size cap_x/vcap for the
        # WHOLE remaining run in one step, so reactive growth — a full
        # level-program recompile per doubling, the round-4 depth-14
        # killer (docs/MESH_DEEP.json) — never fires.  Re-checked every
        # level; only grows (a later, better forecast can top it up, but
        # typically this resizes exactly once).  The reactive loops below
        # stay as the backstop for forecast misses.
        from ..engine.forecast import (
            MIN_LEVELS, cap_margin, horizon_forecast, pow2ceil,
        )
        self._gather_keep = 0  # all_gather: forecast floor for store trim
        self._cand_hist = []  # per-level max-device candidates / new states

        def maybe_presize(visited):
            sig = horizon_forecast(level_sizes, distinct, max_depth)
            if sig is None:
                return visited
            peak_new, final_distinct, budget = sig
            # cap_x holds one device's candidates for a level — forecast
            # it from the MEASURED per-device candidates-per-new ratio
            # (duplicate fan-out lanes make the hand-modeled ratio
            # undershoot at shallow depths; cand_max tracks the truth)
            r_cd = max(self._cand_hist[-3:]) if self._cand_hist else 4.0 / D
            want_x = pow2ceil(int(r_cd * peak_new * cap_margin()) + 1)
            if self.cap_x_max is not None:
                want_x = min(want_x, self.cap_x_max)
            # absolute backstops: a forecast gone wrong must degrade to
            # the reactive path, never to an absurd allocation/compile.
            # With cap_r = cap_x, the six all_to_all routing buffers cost
            # 48*D bytes per cap_x lane — keep them inside the budget.
            want_x = min(want_x, 1 << 22, pow2ceil(budget // (48 * D)) // 2)
            if want_x > self.cap_x:
                print(
                    f"[mesh] presize: cap_x {self.cap_x} -> {want_x} "
                    f"(forecast peak {peak_new}/level, measured "
                    f"cand/new ratio {r_cd:.2f})", file=sys.stderr,
                )
                self.cap_x = want_x
                for k in ("level_step", "level_phase1", "level_phase2",
                          "cap_r", "cap_w"):
                    self.__dict__.pop(k, None)
            if self.host_stores is None and self.exchange == "all_to_all":
                # reactive trigger is distinct > D*vcap//2; stay under it
                want_v = pow2ceil(int(2.2 * final_distinct / D) + 1)
                want_v = min(want_v, pow2ceil(budget // (8 * D)))
                if want_v > self.vcap:
                    print(
                        f"[mesh] presize: vcap {self.vcap} -> {want_v} "
                        f"(forecast {final_distinct} final distinct)",
                        file=sys.stderr,
                    )
                    visited = grow_visited(visited, want_v)
            elif self.host_stores is None:  # all_gather
                # ratchet only — a later, lower forecast must not shrink
                # the trim floor (shrinking mints a new store shape)
                self._gather_keep = max(self._gather_keep, min(
                    pow2ceil(int(1.05 * final_distinct)),
                    pow2ceil(budget // 8),
                ))
            return visited

        while True:
            resilience.fault_fire("level.start")
            if resilience.preempt_requested():
                # mdelta records are written synchronously on this
                # path, so the log is already complete — exit resumable
                raise resilience.Preempted(
                    checkpoint_dir if checkpoint_every else None, depth
                )
            if max_depth is not None and depth >= max_depth:
                break
            if self.watchdog is not None:
                self.watchdog.arm(f"mesh level {depth + 1}")
            resilience.fault_fire("device.lost")
            resilience.fault_fire("device.hang")
            if presize and len(level_sizes) > MIN_LEVELS:
                visited = maybe_presize(visited)
            if self.host_stores is not None:
                out = self._hosted_level(frontier, msum, n_f)
            else:
                if self.exchange == "all_to_all" and distinct > D * self.vcap // 2:
                    visited = grow_visited(visited, self.vcap * 4)
                # the level step is pure, so failed (overflowed) outputs
                # drop and the retry recomputes at the grown capacity
                grows = 0
                while True:
                    out = self.level_step(frontier, msum, n_f, visited)
                    ovf_v, ovf_x = jax.device_get(
                        (out.overflow_v, out.overflow_x)
                    )
                    if not (bool(ovf_v) or bool(ovf_x)):
                        break
                    if grows >= 8:
                        raise RuntimeError(
                            f"capacity overflow at level {depth + 1} "
                            f"(cap_x={self.cap_x}, cap_r={self.cap_r}, "
                            f"vcap={self.vcap})"
                        )
                    grows += 1
                    self.reactive_grows += 1
                    print(
                        f"[mesh] REACTIVE grow at level {depth + 1}: "
                        f"{'vcap' if bool(ovf_v) else 'cap_x'} "
                        f"(cap_x={self.cap_x}, cap_r={self.cap_r}, "
                        f"vcap={self.vcap})", file=sys.stderr,
                    )
                    if bool(ovf_v):
                        visited = grow_visited(visited, self.vcap * 4)
                    else:
                        # candidate compaction / routing lanes overflowed:
                        # grow cap_x (recompiles the level step — rare)
                        self.cap_x *= 2
                        for k in ("level_step", "cap_r", "cap_w"):
                            self.__dict__.pop(k, None)
            # one fused fetch of the level's control scalars (the ledger
            # of intended per-level syncs the sanitizer audits against)
            (abort_np, mult_np, gen_np, nnew_np, inv_np, cand_np,
             nloc_np) = jax.device_get((
                out.abort, out.mult_slots, out.generated,
                out.n_new_total, out.inv_bad, out.cand_max,
                out.n_new_local,
            ))
            if bool(abort_np):
                # locate the aborting parent (a current-frontier state) and
                # replay its slot chain, exactly like the single-device path
                bad_at = np.asarray(jax.device_get(out.abort_at))
                devs = np.nonzero(bad_at >= 0)[0]
                cap_f = frontier.voted_for.shape[0] // D
                gidx = int(devs[0]) * cap_f + int(bad_at[devs[0]])
                # action_counts stays None on violations, like the oracle
                return CheckResult(
                    False, distinct, generated, depth, tuple(level_sizes),
                    (
                        'Assert "split brain" (Raft.tla:185)',
                        self._trace(trace_levels, depth, gidx),
                    ),
                )
            mult_slots_total += np.asarray(mult_np)
            generated += int(gen_np)
            n_new = int(nnew_np)
            # per-owner count reconciliation across the exchange: the
            # psum'd owner-store admissions must equal the winners the
            # origins shipped and materialized
            resilience.integrity.reconcile(
                "mesh owner exchange", n_new,
                int(np.asarray(nloc_np, np.int64).sum()),
                level=depth + 1,
            )
            if n_new == 0:
                break
            self.skew.note(
                depth + 1, rows=np.asarray(nloc_np, np.int64).reshape(-1)
            )
            cap_f_prev = frontier.voted_for.shape[0] // D
            distinct += n_new
            level_sizes.append(n_new)
            self._cand_hist.append(int(cand_np) / n_new)
            depth += 1
            # gpidx/slots are the level's two largest host-bound arrays:
            # their copies start now and complete through the ledgered
            # window drain AFTER the store trim / next-frontier device
            # work below has been dispatched (window 0 = serial fetch)
            tail = graft_pipeline.DeferredFetch(
                self.pipeline, (out.gpidx, out.slots)
            )
            if self.host_stores is None:
                visited = out.visited
                if self.exchange == "all_gather":
                    # the replicated store grows by D*cap_x sentinel-padded
                    # slots per level; trim back to the tightest pow2 that
                    # holds every distinct fp (store is sorted, SENT-
                    # padded).  The presize forecast floors the trim so
                    # the store shape stays constant over the run instead
                    # of stepping through every magnitude (one level-step
                    # compile per magnitude otherwise); SENT-pad up to the
                    # floor when the merged store is still shorter (SENT
                    # sorts last, so the pad keeps the array sorted).
                    keep = max(4096, 1 << distinct.bit_length(),
                               self._gather_keep)
                    vis = out.visited[:keep]
                    if vis.shape[0] < keep:
                        vis = jnp.concatenate([
                            vis,
                            jnp.full((keep - vis.shape[0],), SENT, U64),
                        ])
                    visited = jax.device_put(vis, repl)
            frontier, msum = out.children, out.child_msum
            n_f = jax.device_put(out.n_new_local, shard)
            gp_np, sl_np = tail.get()
            trace_levels.append(
                (np.asarray(gp_np, np.int64), np.asarray(sl_np, np.int64))
            )
            graft_obs.level_commit(depth, n_new, distinct, generated)
            if self.progress is not None:
                self.progress(
                    dict(
                        level=depth, frontier=n_new, distinct=distinct,
                        generated=generated, elapsed=time.monotonic() - t0,
                    )
                )
            if graft_sanitize.CURRENT is not None:
                sig = (
                    frontier.voted_for.shape[0],
                    0 if visited is None else visited.shape[0],
                    self.cap_x, self.cap_w, self.vcap,
                )
                if sig != getattr(self, "_san_sig", None):
                    graft_sanitize.note_shape_event(f"mesh level {sig}")
                    self._san_sig = sig
                graft_sanitize.level_tick()
            if int(inv_np) > 0:
                bad_at = np.asarray(jax.device_get(out.inv_bad_at))
                devs = np.nonzero(bad_at >= 0)[0]
                gidx = int(devs[0]) * (out.children.voted_for.shape[0] // D) + int(
                    bad_at[devs[0]]
                )
                trace = self._trace(trace_levels, depth, gidx)
                # identify which configured invariant tripped by re-checking
                # the violating state host-side
                from ..oracle.explicit import resolve_invariant

                name = next(
                    (
                        n
                        for n in cfg.invariants
                        if not resolve_invariant(n)(cfg, trace[-1][1])
                    ),
                    cfg.invariants[0],
                )
                return CheckResult(
                    False, distinct, generated, depth, tuple(level_sizes),
                    (f"Invariant {name} is violated", trace),
                )
            # checkpoint only invariant-clean levels (a resumed run never
            # re-checks the loaded frontier).  Delta-log format: the
            # replay chain needs EVERY level, so checkpoint_every only
            # gates whether checkpointing happens at all.
            if checkpoint_dir and checkpoint_every:
                # pass the HOST copies fetched above — _save_mdelta on
                # the raw LevelOut would re-fetch gpidx/slots (the two
                # largest per-level arrays) a second time per level
                self._save_mdelta(
                    checkpoint_dir, depth,
                    SimpleNamespace(
                        gpidx=gp_np, slots=sl_np,
                        n_new_local=nloc_np, mult_slots=mult_np,
                    ),
                    cap_f_prev,
                )
            if self.watchdog is not None:
                # per-level disarm records this level's wall time so
                # the next arm's budget adapts (max(floor, 8x last))
                self.watchdog.disarm()

        return CheckResult(
            True, distinct, generated, depth, tuple(level_sizes), None,
            self._action_counts(mult_slots_total),
        )
