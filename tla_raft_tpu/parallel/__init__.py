"""Distributed checking: mesh-sharded frontier + fingerprint exchange."""

from .sharded import ShardedChecker, make_mesh  # noqa: F401
