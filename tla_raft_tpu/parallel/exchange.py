"""Sieve-and-compress fingerprint exchange: packing + byte accounting.

The deep-sweep mesh tier (parallel/sharded.py, ``deep=True``) moves only
FINGERPRINTS over the host link: each owner shard's level-unique unknown
candidates, sorted ascending, delta-encoded and packed into a variable-
width byte stream ON DEVICE, fetched as a quantized prefix, and answered
with one is-new bit per fingerprint.  This is the "compress" half of
arXiv:1208.5542's sieve-and-compress BFS exchange; the sieve half (drop
candidates already known visited before any routing) lives in the
phase-1 program of the sharded checker.

Why deltas help at all on 64-bit hashes: a sorted run of n pseudorandom
u64 fingerprints has consecutive gaps ~2^64/n, i.e. ~(64 - log2 n) bits
of real information per entry — at a 10^6-candidate shard that is ~6
bytes instead of 8, and the variable-width encoding additionally never
pays for the routing/padding lanes the fixed-shape u64 exchange ships.
The big multiplier is the sieve and the exact owner-side dedup in front
of this encoder: only never-seen-before candidates reach the stream.

Encoding: entry i stores delta_i = fp_i - fp_{i-1} (fp_{-1} = 0) as
1..8 little-endian bytes; per-entry byte lengths ride in a 4-bit nibble
array (entry 2k in the low nibble of byte k).  Both halves are built on
device with a cumsum + masked scatter-add (no data-dependent shapes);
the host decodes with eight vectorized numpy passes.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

U64 = jnp.uint64
I32 = jnp.int32
I64 = jnp.int64
SENT = np.uint64(0xFFFFFFFFFFFFFFFF)


def pack_fp_deltas(fps_sorted: jnp.ndarray, n: jnp.ndarray):
    """Delta-pack the ascending prefix ``fps_sorted[:n]`` (device-side).

    fps_sorted: u64[cap], strictly ascending real entries in the first
    ``n`` lanes (SENT-padded beyond).  Returns (stream u8[cap*8],
    nibbles u8[cap//2], total_bytes i64) — ``total_bytes`` is the live
    prefix of ``stream``; ``nibbles``' live prefix is ceil(n/2) bytes.
    Traceable under jit/shard_map (fixed shapes; only the host fetch
    slices the prefixes).
    """
    cap = fps_sorted.shape[0]
    assert cap % 2 == 0, "pack capacity must be even (nibble pairing)"
    live = jnp.arange(cap, dtype=I32) < n
    prev = jnp.concatenate([jnp.zeros((1,), U64), fps_sorted[:-1]])
    delta = jnp.where(live, fps_sorted - prev, jnp.uint64(0))
    # byte length of each delta: 1 + (#thresholds passed); exact, no clz.
    # Offsets accumulate in i64: an i32 cumsum would wrap once a shard's
    # packed stream passes 2 GB (~350M fps at ~6 B — inside the deep-
    # sweep target regime) and silently corrupt the stream.
    nb = jnp.ones((cap,), I64)
    for k in range(1, 8):
        nb = nb + (delta >= jnp.uint64(1 << (8 * k))).astype(I64)
    nb = jnp.where(live, nb, 0)
    off = jnp.cumsum(nb) - nb
    total = (off[-1] + nb[-1]).astype(I64)
    # masked scatter-add builds the byte stream; dead lanes all land on
    # one trash slot past the live region with value 0
    j = jnp.arange(8, dtype=I64)[None, :]
    idx = off[:, None] + j
    val = (
        (delta[:, None] >> (8 * j).astype(jnp.uint64)) & jnp.uint64(0xFF)
    ).astype(jnp.uint32)
    mask = (j < nb[:, None]) & live[:, None]
    flat_idx = jnp.where(mask, idx, cap * 8).reshape(-1)
    flat_val = jnp.where(mask, val, 0).reshape(-1)
    stream = (
        jnp.zeros((cap * 8 + 1,), jnp.uint32)
        .at[flat_idx]
        .add(flat_val)[: cap * 8]
        .astype(jnp.uint8)
    )
    nbu = nb.astype(jnp.uint8)
    nibbles = nbu[0::2] | (nbu[1::2] << 4)
    return stream, nibbles, total


def unpack_fp_deltas(stream: np.ndarray, nibbles: np.ndarray,
                     count: int, verify: bool = False) -> np.ndarray:
    """Host-side inverse of :func:`pack_fp_deltas` -> u64[count].

    ``verify=True`` adds the exchange-stream integrity check the deep
    level tail runs before inserting into the owner stores: the packed
    form encodes a STRICTLY ASCENDING unique sequence, so the decoded
    output must be strictly increasing — a flipped bit in the stream,
    the nibble header or the prefix fetch almost surely produces a
    duplicate (zero delta), a wrapped cumsum or a garbage length, all
    of which break monotonicity.  One O(count) compare buys end-to-end
    detection on the host leg that the per-record digests cannot give
    (the fetch crosses the link AFTER any checksumming)."""
    if count == 0:
        return np.empty(0, np.uint64)
    nib = np.asarray(nibbles[: (count + 1) // 2], np.uint8)
    nb = np.empty(2 * len(nib), np.int64)
    nb[0::2] = nib & 0xF
    nb[1::2] = nib >> 4
    nb = nb[:count]
    off = np.cumsum(nb) - nb
    st = np.asarray(stream, np.uint8)
    delta = np.zeros(count, np.uint64)
    for b in range(8):
        m = nb > b
        if not m.any():
            break
        delta[m] |= st[off[m] + b].astype(np.uint64) << np.uint64(8 * b)
    out = np.cumsum(delta, dtype=np.uint64)
    if verify and count > 1 and not (out[1:] > out[:-1]).all():
        from ..resilience.integrity import IntegrityError

        bad = int(np.argmin(out[1:] > out[:-1]))
        raise IntegrityError(
            f"corrupt fingerprint exchange stream: decoded entry "
            f"{bad + 1} of {count} is not strictly greater than its "
            "predecessor (the packed form encodes a sorted unique "
            "sequence) — a bit flipped between the owner's finalize "
            "and the host fetch"
        )
    return out


def packed_quantum(nbytes: int) -> int:
    """Fetch-prefix quantization: smallest c >= nbytes with c in
    {2^k, 3*2^(k-1)} (the repo's half-step ladder), so the prefix-slice
    programs compile O(log) times per run, not once per level."""
    n = max(int(nbytes), 1)
    p = 1 << (n - 1).bit_length()
    half = 3 * (p >> 2)
    return half if half >= n and half > 0 else p


class ExchangeMeter:
    """Per-level byte accounting for the fingerprint exchange.

    Two ledgers: ``a2a`` (device-device collective bytes — the routing
    all_to_all tiles that actually cross a link, i.e. the off-diagonal
    (D-1)/D share — plus verdict return tiles) and ``host`` (host<->
    device bytes: candidate fetches and verdict puts — the 4 MB/s
    tunnel budget at deep levels).  ``raw`` mirrors what the
    uncompressed exchange would have moved for the same level so the
    run summary can report an honest reduction factor.
    """

    def __init__(self):
        self.levels: list[dict] = []
        self._cur: dict | None = None

    def begin_level(self, level: int):
        self._cur = dict(
            level=level, a2a_bytes=0, host_bytes=0,
            raw_a2a_bytes=0, raw_host_bytes=0,
            n_candidates=0, n_sieved=0, n_unique=0,
            # None = no packing decision was made this level (paths
            # that never delta-pack — all_gather, the plain hosted
            # exchange); the deep path records True/False explicitly
            packed=None,
        )

    def add(self, **kw):
        assert self._cur is not None
        for k, v in kw.items():
            self._cur[k] += int(v)

    def note_packed(self, packed: bool):
        """Record whether the level's fp stream went out delta-packed.

        ``packed=False`` means the packing fallback fired: the packed
        form (plus header) was NOT smaller, so the raw u64 stream was
        sent instead.  The level's host leg then has no hypothetical
        uncompressed equivalent — what was sent IS the uncompressed
        form — so ``end_level`` floors the raw-host mirror at the
        actual host bytes and per-level reduction can never read < 1
        (the BENCH_r06 levels 1-2 "reduction 0.21-0.56" artifact was
        exactly quantum padding billed against a live-lane mirror)."""
        assert self._cur is not None
        self._cur["packed"] = bool(packed)

    def end_level(self) -> dict:
        cur, self._cur = self._cur, None
        if cur["packed"] is False:  # None = packing never considered
            cur["raw_host_bytes"] = max(
                cur["raw_host_bytes"], cur["host_bytes"]
            )
        exchanged = cur["a2a_bytes"] + cur["host_bytes"]
        raw = cur["raw_a2a_bytes"] + cur["raw_host_bytes"]
        cur["exchanged_bytes"] = exchanged
        cur["raw_bytes"] = raw
        cur["reduction"] = round(raw / exchanged, 2) if exchanged else None
        self.levels.append(cur)
        # per-level exchange bytes into the telemetry hub (the flight
        # recorder is the unified sink; summary() keeps the CLI view)
        from ..obs import telemetry as _obs

        _obs.exchange(
            cur["level"], exchanged, raw,
            candidates=cur["n_candidates"], sieved=cur["n_sieved"],
        )
        return cur

    def summary(self) -> dict:
        tot = sum(lv["exchanged_bytes"] for lv in self.levels)
        raw = sum(lv["raw_bytes"] for lv in self.levels)
        return dict(
            levels=len(self.levels),
            exchanged_bytes=tot,
            raw_bytes=raw,
            reduction=round(raw / tot, 2) if tot else None,
            sieved=sum(lv["n_sieved"] for lv in self.levels),
            candidates=sum(lv["n_candidates"] for lv in self.levels),
            per_level=[
                {k: lv[k] for k in (
                    "level", "exchanged_bytes", "raw_bytes", "reduction",
                    "n_candidates", "n_sieved", "n_unique", "packed",
                )}
                for lv in self.levels
            ],
        )
