"""Async intra-level pipeline: overlapped expand / fetch / insert windows.

docs/PERF.md's round-5 silicon budget shows the deep-level wall clock
as a strict serial chain — expand spans, the device->host fetch over
the ~4 MB/s tunneled link, the host-side filter/insert tail — with the
device idle during every host stage and vice versa.  This module holds
the two mechanisms that break the chain:

* :class:`AsyncFetchWindow` — a bounded in-flight window of
  device->host fetch groups.  The main thread dispatches group g+1's
  device programs immediately after *starting* group g's copies with
  ``copy_to_host_async()``; group g's host arrays are consumed (through
  the LEDGERED ``jax.device_get`` path, so the GRAFT_SANITIZE transfer
  ledger counts every async fetch) only when the window is full or the
  level ends.  Two invariants from docs/PERF.md carry over by
  construction: all device dispatch stays on the main thread (the
  window never spawns threads — overlap comes from the asynchronous
  copy engine, not from concurrent dispatch), and the window DRAINS at
  the level boundary, so store inserts never see a level's candidates
  early (``AsyncFetchWindow.live`` is the cross-instance assertion
  hook the tests pin this with).

* :class:`Prewarmer` — a forecast-driven AOT compile thread.  The
  engines emit a shape plan (engine/forecast.py predicts the
  power-of-two capacity ladder) and the prewarmer compiles the
  deep-level program set (``jit(...).lower(...).compile()``) in ONE
  background daemon thread while the cheap shallow levels run.
  Lower/compile never dispatches a device program (inputs are
  ``jax.ShapeDtypeStruct`` avals), so the no-worker-dispatch rule is
  not in play; the thread marks itself via
  :func:`analysis.sanitize.mark_thread_compiles_declared` so its
  compiles land in the sanitizer's *declared prewarm* ledger instead
  of tripping the per-level silent-retrace check.  The compiled
  executables are dropped — the payoff routes through JAX's persistent
  compilation cache (platform.setup_jax wires it), which also means a
  supervised relaunch (``--supervise``) never re-pays a compile this
  or any earlier incarnation already did.

Serial fallback: ``TLA_RAFT_PIPELINE=0`` (or a window of 0) makes
every submit complete immediately — bit-identical control flow to the
pre-pipeline engines, which is what the A/B parity gates diff against.

Module import is device-free (jax is imported lazily), matching the
package's import contract (graftlint GL001).
"""

from __future__ import annotations

import atexit
import os
import sys
import threading
from collections import deque

from .. import resilience
from ..analysis import sanitize as graft_sanitize

# bounded in-flight fetch groups: 2 keeps one group streaming over the
# host link while the next group's device programs run, which is the
# whole overlap — deeper windows only add peak memory (each in-flight
# group pins its padded fetch buffers on both sides of the link)
DEFAULT_WINDOW = 2


def enabled_by_env() -> bool:
    """Pipeline default: ON; ``TLA_RAFT_PIPELINE=0`` reverts to serial."""
    return os.environ.get("TLA_RAFT_PIPELINE", "1") != "0"


def window_from_env(default: int = DEFAULT_WINDOW) -> int:
    v = os.environ.get("TLA_RAFT_PIPELINE_WINDOW")
    if v:
        return int(v)
    from ..tune import active

    return int(active.get("pipeline_window", default))


def async_start(tree) -> None:
    """Start device->host copies for every jax array leaf of ``tree``.

    Pure hint: the copy engine begins moving bytes as soon as the
    producing program finishes, so the later (ledgered) ``fetch``
    completes without stalling the dispatch pipeline.  Non-device
    leaves (numpy, None) pass through untouched; a backend without
    ``copy_to_host_async`` degrades to a no-op.
    """
    import jax

    for leaf in jax.tree.leaves(tree):
        start = getattr(leaf, "copy_to_host_async", None)
        if start is not None:
            try:
                start()
            except Exception:  # graftlint: waive[GL003] — the hint must
                # never take the checker down; the ledgered fetch below
                # still works (it just blocks for the full copy)
                return


def fetch(tree):
    """Complete a fetch through the LEDGERED sync path.

    ``jax.device_get`` is looked up at call time so the sanitizer's
    wrapper (the transfer ledger) sees every pipeline fetch; with
    ``async_start`` already issued the call returns as soon as the
    in-flight copy lands instead of round-tripping from scratch.
    Telemetry: every ledgered fetch publishes its measured wait + byte
    count to the hub (the "fetch window" timeline track).
    """
    import time as _time

    import jax

    from ..obs import telemetry as _obs

    t0 = _time.monotonic() if _obs.current() is not None else 0.0
    # graftlint: waive[GL006] — THE intended sync point of the async
    # pipeline: every window fetch funnels through this one site
    out = jax.device_get(tree)
    if _obs.current() is not None:
        _obs.fetch_done(
            _time.monotonic() - t0, graft_sanitize._nbytes(out)
        )
    # --profile N capture: one completed ledgered fetch IS one dispatch
    # window (a superstep on the fused path, a level elsewhere) — tick
    # the jax-profiler session so it stops after its budgeted windows
    from ..analysis import devprof as _devprof

    _devprof.profile_tick()
    return out


class AsyncFetchWindow:
    """Bounded in-flight window of device->host fetch groups.

    ``submit(arrays, consume)`` starts the async copies and queues the
    group; when more than ``window`` groups are in flight the OLDEST
    completes (ledgered fetch + ``consume(host_arrays)`` on the calling
    thread).  ``drain()`` completes everything — call it at the level
    boundary, BEFORE any store insert that level gates on.  ``window=0``
    degenerates to the serial fetch-after-dispatch chain.

    ``AsyncFetchWindow.live`` counts submitted-but-unconsumed groups
    across every instance — the test hook asserting store inserts never
    overlap an open window.
    """

    live = 0  # class-wide in-flight groups (level-boundary assertion)

    def __init__(self, window: int = DEFAULT_WINDOW):
        self.window = max(0, int(window))
        self._q: deque = deque()
        self.submitted = 0
        self.max_inflight = 0

    @property
    def inflight(self) -> int:
        return len(self._q)

    def submit(self, arrays, consume) -> None:
        """Queue one fetch group; completes older groups past the window.

        ``consume(host_arrays)`` runs on the submitting (main) thread —
        handing its host-side work to a pool is the consumer's choice;
        the window itself never spawns threads.
        """
        resilience.fault_fire("pipeline.window")
        graft_sanitize.note_async_fetch_start()
        async_start(arrays)
        self._q.append((arrays, consume))
        AsyncFetchWindow.live += 1
        self.submitted += 1
        self.max_inflight = max(self.max_inflight, len(self._q))
        while len(self._q) > self.window:
            self._complete_one(run_consume=True)

    def _complete_one(self, run_consume: bool) -> None:
        arrays, consume = self._q.popleft()
        AsyncFetchWindow.live -= 1
        host = fetch(arrays)
        graft_sanitize.note_async_fetch_complete()
        # progress heartbeat: a completed fetch group proves the level
        # is still moving, so the hang watchdog re-earns its budget —
        # long multi-window levels never false-trip on total wall time
        resilience.elastic.watchdog_touch()
        if run_consume:
            consume(host)

    def drain(self) -> None:
        """Complete every in-flight group (the level-boundary barrier)."""
        while self._q:
            self._complete_one(run_consume=True)

    def discard(self) -> None:
        """Complete in-flight fetches WITHOUT consuming (abort paths).

        The fetches still finish through the ledgered path so the
        sanitizer's start/complete accounting balances even when a
        level is thrown away (abort, capacity-overflow redo).
        """
        while self._q:
            self._complete_one(run_consume=False)


class DeferredFetch:
    """One-group deferred fetch — the level-tail specialization.

    ``DeferredFetch(enabled, arrays)`` starts the copies immediately
    (ledgered start); ``get()`` completes them through the ledgered
    path — place it AFTER the device work the fetch should overlap and
    BEFORE the level boundary — and returns the host arrays (idempotent
    after the first call).  ``discard()`` balances the ledger on abort
    paths.  ``enabled=False`` fetches at construction: the serial
    chain.  Keeps the submit/drain contract of every single-group tail
    site in one place instead of five hand-rolled window+dict copies.
    """

    def __init__(self, enabled: bool, arrays):
        self._win = AsyncFetchWindow(1 if enabled else 0)
        self._h: dict = {}
        self._win.submit(arrays, lambda h: self._h.update(h=h))

    def get(self):
        self._win.drain()
        return self._h["h"]

    def discard(self) -> None:
        self._win.discard()


class Prewarmer:
    """Background AOT compiler for the forecast shape ladder.

    ``submit(plan)`` takes ``(key, thunk)`` pairs; thunks run
    ``jit(...).lower(shapes...).compile()`` for one program at one
    forecast capacity.  Keys dedupe across submissions (the engines
    re-emit the plan every level as the forecast sharpens; only fresh
    shapes compile).  One daemon thread, never joined by the run loop
    — a prewarm that has not finished by the time the main thread
    needs the shape simply means that compile is paid in line, exactly
    the pre-prewarm behavior.  Thunk failures are logged and counted,
    never raised: prewarm is an optimization, not a correctness gate.
    """

    def __init__(self, name: str = "tla-raft-prewarm"):
        self._name = name
        self._lock = threading.Lock()
        self._seen: set = set()
        self._pending: list = []
        self._thread: threading.Thread | None = None
        self._running = False  # worker loop live (flips under _lock)
        self._stopping = False
        self.n_ok = 0
        self.n_failed = 0
        # a daemon thread still inside an XLA compile when the
        # interpreter tears down segfaults (the compiler calls back into
        # a dying runtime), so interpreter exit drops the queue and
        # joins the one in-flight compile before teardown begins
        atexit.register(self.shutdown)

    def submit(self, plan) -> int:
        """Queue fresh (key, thunk) pairs; returns how many were new."""
        with self._lock:
            fresh = [(k, t) for k, t in plan if k not in self._seen]
            for k, _t in fresh:
                self._seen.add(k)
            self._pending.extend(fresh)
            # _running (not Thread.is_alive) gates the restart: the
            # worker clears it under THIS lock in the same critical
            # section that decides to exit, so a submit landing between
            # that decision and the thread's actual death still starts
            # a fresh worker instead of stranding the queue
            if self._pending and not self._running and not self._stopping:
                self._running = True
                self._thread = threading.Thread(
                    target=self._run, name=self._name, daemon=True
                )
                self._thread.start()
        return len(fresh)

    def _run(self) -> None:
        # compiles from this thread are DECLARED: the sanitizer books
        # them to the prewarm ledger, not the per-level retrace check
        graft_sanitize.mark_thread_compiles_declared()
        while True:
            with self._lock:
                if self._stopping or not self._pending:
                    self._running = False
                    return
                key, thunk = self._pending.pop(0)
            try:
                thunk()
                self.n_ok += 1
            except Exception as e:  # graftlint: waive[GL003] — a failed
                # prewarm costs only the compile it tried to hide; the
                # main loop compiles the shape in line as before
                self.n_failed += 1
                print(
                    f"[pipeline] prewarm {key!r} failed: "
                    f"{type(e).__name__}: {e}",
                    file=sys.stderr,
                )

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._pending)

    @property
    def stopped(self) -> bool:
        """True once shutdown ran — a stopped prewarmer never compiles
        again; owners build a fresh one instead."""
        with self._lock:
            return self._stopping

    def join(self, timeout: float | None = None) -> None:
        """Wait for the compile queue to empty (tests; never the run loop)."""
        t = self._thread
        if t is not None:
            t.join(timeout)

    def shutdown(self, timeout: float = 120.0) -> None:
        """Drop queued thunks and wait out the in-flight compile.

        Remaining queue entries are abandoned (their compiles would now
        be paid in line, the pre-prewarm behavior); only the one compile
        already inside XLA must finish before the interpreter may tear
        down.  Idempotent — the atexit hook and any explicit caller can
        both run it."""
        # the worker holds _lock only for the O(1) queue pop / exit
        # decision, never across a compile, so this atexit-time acquire
        # always completes in microseconds:
        # graftsync: waive[GL016]
        with self._lock:
            self._stopping = True
            self._pending.clear()
        self.join(timeout)
        # a shut-down prewarmer has nothing left for interpreter exit
        # to wait on — unpinning it lets long-lived processes (pytest,
        # sweep drivers) that build many checkers release each one
        atexit.unregister(self.shutdown)
