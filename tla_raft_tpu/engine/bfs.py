"""Level-synchronous BFS on device — TLC's exploration engine, TPU-native.

Replaces the reference checker's core runtime (SURVEY.md §3.1: the BFS
loop, worker pool, FPSet dedup table, invariant evaluation, trace
reconstruction and checkpointing of the external TLC jar driven by
/root/reference/myrun.sh:3) with:

* a **frontier** of full states held as padded struct-of-array tensors,
* the successor kernel's masked fan-out (ops/successor.py) run in chunks,
* **compact-then-dedup**, all on device:
    1. per chunk: a ``top_k`` partial sort compacts the ~0.5%-dense valid
       lanes of the |chunk|*K fan-out into a fixed cap_x lane budget
       (no dedup, no visited access — the expand program stays
       shape-stable for the whole run);
    2. per level: one lexsort over all chunks' compacted candidates
       picks the min-(fp_full, payload) representative per view
       fingerprint (the deterministic refinement of TLC's
       first-writer-wins — see oracle/explicit.py) and drops states
       already in the sorted visited store (``searchsorted``).
  Compaction shrinks the level-wide sort from |frontier|*K dense lanes
  to the ~3.5 valid candidates per frontier state (measured on the
  reference config) padded to the cap_x budget — the difference between
  sorting ~10^8 and ~10^6 keys per level at full scale.
* **materialization** of only the surviving (parent, slot) pairs,
* batched invariant kernels (engine/invariants.py) on each new level,
* per-level (parent, slot) spill to the host for counterexample traces
  (SURVEY.md §3.4: TLC's predecessor-chain walk),
* per-level snapshots for checkpoint/resume (SURVEY.md §3.5: TLC's
  ``states/`` metadir + ``-recover``).

Host/device discipline: the chunk loop runs with **zero host syncs**
(the split-brain abort flag and per-slot multiplicities accumulate on
device); each level fetches one small stats bundle (new-state count,
abort/overflow flags, generated count) and the (parent, slot) trace
spill.  Round 1 synced the abort flag per chunk, serializing host and
device every 256 states (ADVICE.md round 1).

Deadlock states (no action enabled) are not reported, matching the
``-deadlock`` flag in myrun.sh:3 which *disables* deadlock checking.

All device computations run at power-of-two padded shapes so XLA compiles
a logarithmic number of program variants; every array is explicitly
dtyped (u8 state, u64 fingerprints, i64 payloads).
"""

from __future__ import annotations

import functools
import os
import time
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import RaftConfig
from ..models.raft import RaftState, init_batch, to_oracle
from ..ops.successor import SuccessorKernel, get_kernel
from .invariants import resolve_invariant_kernel

U64 = jnp.uint64
I64 = jnp.int64
I32 = jnp.int32
SENT = jnp.uint64(0xFFFFFFFFFFFFFFFF)
BIG = jnp.int64(1 << 62)


class CheckResult(NamedTuple):
    """Same shape as oracle.explicit.CheckResult for differential tests."""

    ok: bool
    distinct: int
    generated: int
    depth: int
    level_sizes: tuple[int, ...]
    violation: tuple | None  # (kind, trace=[(action, OState), ...])
    action_counts: dict | None = None  # TLC -coverage analog (see oracle)


def _pow2(n: int) -> int:
    return 1 << max(0, (n - 1)).bit_length()


def _cap4(n: int) -> int:
    """Next power of 4: capacities quantize coarser so the checker compiles
    ~half as many program shapes (remote TPU compiles are minutes each)."""
    c = 1
    while c < n:
        c <<= 2
    return c


def _pad_axis0(x: jnp.ndarray, cap: int) -> jnp.ndarray:
    pad = cap - x.shape[0]
    if pad <= 0:
        return x[:cap]
    return jnp.concatenate([x, jnp.zeros((pad, *x.shape[1:]), x.dtype)])


def _pad_tree(st: RaftState, cap: int) -> RaftState:
    return jax.tree.map(lambda x: _pad_axis0(x, cap), st)


@functools.partial(jax.jit, static_argnames=("cap_x",))
def _chunk_compact(fps_view, fps_full, payload, cap_x: int):
    """Compact one chunk's valid fan-out lanes into cap_x lanes (no dedup).

    fps_view/full u64[C] (SENT where invalid), payload i64[C] (global
    parent*K+slot).  ``top_k`` on an earliest-lane-first key is a partial
    sort — far cheaper than a full argsort over the ~0.5%-dense C lanes,
    and it keeps the visited store out of this (large, shape-stable)
    program so store growth never recompiles the expand kernel.  Kept
    lanes preserve original lane order (payload-ascending), matching the
    stable compaction the dedup's determinism contract assumes.
    """
    C = fps_view.shape[0]
    live = fps_view != SENT
    n_live = live.sum()
    key = jnp.where(live, C - jnp.arange(C, dtype=I32), 0)
    vals, idx = jax.lax.top_k(key, cap_x)  # descending = earliest lanes first
    lane = vals > 0
    return (
        jnp.where(lane, fps_view[idx], SENT),
        jnp.where(lane, fps_full[idx], SENT),
        jnp.where(lane, payload[idx], -1),
        n_live > cap_x,
    )


@jax.jit
def _level_dedup(cv, cf, cp, visited):
    """Global dedup over the level's compacted candidates, on device.

    One lexsort by (fp_view, fp_full, payload) across every chunk's
    candidates resolves uniqueness and picks the min-(fp_full, payload)
    representative per view fingerprint (the deterministic refinement of
    TLC's first-writer-wins); a searchsorted against the sorted visited
    store drops already-known states.  Doing this once per level instead
    of per chunk halves the sort work of the old two-stage scheme.
    Retraces when the visited capacity grows — acceptable, the program is
    small next to the expand kernel.

    Returns (n_new, new_fps u64[C] view-sorted SENT-padded, payload i64[C]).
    """
    order = jnp.lexsort((cp, cf, cv))
    sv, sp = cv[order], cp[order]
    first = jnp.concatenate([jnp.ones((1,), bool), sv[1:] != sv[:-1]])
    pos = jnp.searchsorted(visited, sv)
    hit = visited[jnp.clip(pos, 0, visited.shape[0] - 1)] == sv
    new = first & (sv != SENT) & ~hit
    n_new = new.sum()
    comp = jnp.argsort(~new, stable=True)
    keep = jnp.arange(sv.shape[0]) < n_new
    return (
        n_new,
        jnp.where(keep, sv[comp], SENT),
        jnp.where(keep, sp[comp], -1),
    )


@jax.jit
def _merge_sorted(visited, new_fps):
    """Insert a level's new fingerprints into the sorted store."""
    return jnp.sort(jnp.concatenate([visited, new_fps]))


class JaxChecker:
    """The TPU model checker for one RaftConfig.

    Parameters:
      chunk: max parents expanded per kernel launch (memory knob; the
        per-launch working set is ~chunk * K * (F + hash) bytes).
      cap_x: per-chunk compacted-survivor lanes (grows on overflow).
      progress: optional callable(level_stats_dict) for per-level logging.
    """

    def __init__(
        self,
        cfg: RaftConfig,
        chunk: int = 1024,
        cap_x: int | None = None,
        progress: Callable[[dict], None] | None = None,
        host_store=None,
    ):
        self.cfg = cfg
        self.kern: SuccessorKernel = get_kernel(cfg)
        self.fpr = self.kern.fpr
        self.K = self.kern.K
        if chunk & (chunk - 1):
            # power-of-two capacities divide evenly into the pow4-padded
            # materialize buffer; arbitrary chunks would mis-slice it
            raise ValueError(f"chunk must be a power of two, got {chunk}")
        self.chunk = chunk
        # a chunk's valid fan-out lanes average ~3.5 per parent on the
        # reference config, so chunk*4 covers the mean and overflow
        # detection grows the budget (with a re-jit) on skewed chunks
        self.cap_x = cap_x or 4 * chunk
        self.progress = progress
        # optional native external-memory visited store (native/fpstore.cpp);
        # when set, the device keeps no visited table at all — the level's
        # deduped candidates are filtered through the host store instead
        self.host_store = host_store
        self.inv_fns = [
            (n, resolve_invariant_kernel(n)) for n in cfg.invariants
        ]
        self._gather_mat = jax.jit(self._gather_materialize)
        self._expand_chunk = jax.jit(self._expand_chunk_impl)
        self._inv_scan = jax.jit(self._inv_scan_impl)

    # -- device helpers ----------------------------------------------------

    def _gather_materialize(self, frontier: RaftState, pidx, slots):
        parents = jax.tree.map(lambda x: x[pidx], frontier)
        children = self.kern.materialize(parents, slots)
        msum = self.fpr.msg_hash(children.msgs)
        return children, msum

    def _expand_chunk_impl(self, part: RaftState, msum_part, start, n_f):
        """One chunk: expand + mask + valid-lane compaction, no host syncs.

        start/n_f are device i64 scalars so chunk position doesn't force
        a recompile; the visited store is deliberately NOT an input (its
        capacity grows over the run and would retrace this — the largest —
        program).  Returns compacted candidates + chunk stats.
        """
        K = self.K
        cap = part.voted_for.shape[0]
        exp = self.kern.expand(part, msum_part)
        in_range = (start + jnp.arange(cap, dtype=I64) < n_f)[:, None]
        valid = exp.valid & in_range
        fpv = jnp.where(valid, exp.fp_view, SENT).ravel()
        fpf = jnp.where(valid, exp.fp_full, SENT).ravel()
        base = ((start + jnp.arange(cap, dtype=I64)) * K)[:, None]
        payload = (base + jnp.arange(K, dtype=I64)[None]).ravel()
        mult_slots = jnp.where(valid, exp.mult, 0).astype(I64).sum(0)
        ab = exp.abort & in_range[:, 0]
        abort_at = jnp.where(
            ab.any(), start + jnp.argmax(ab).astype(I64), BIG
        )
        cv, cf, cp, overflow = _chunk_compact(fpv, fpf, payload, self.cap_x)
        return cv, cf, cp, mult_slots, abort_at, overflow

    def _inv_scan_impl(self, children: RaftState, n_valid):
        """All configured invariants over a level; (first_bad_idx|-1)."""
        N = children.voted_for.shape[0]
        in_range = jnp.arange(N, dtype=I64) < n_valid
        bad = jnp.zeros(N, bool)
        for _name, fn in self.inv_fns:
            bad = bad | (~fn(self.cfg, children, self.kern.tables) & in_range)
        return jnp.where(bad.any(), jnp.argmax(bad).astype(I64), -1)

    def _action_counts(self, mult_per_slot: np.ndarray) -> dict:
        """Fold per-slot fired-transition counts to action names (the TLC
        -coverage analog; UpdateTerm's two slot families sum together)."""
        out: dict[str, int] = {}
        fam = self.kern.slot_family
        for fi, (name, _fn, _c) in enumerate(self.kern.families):
            out[name] = out.get(name, 0) + int(mult_per_slot[fam == fi].sum())
        return {k: v for k, v in out.items() if v}

    def _bad_invariant_name(self, children: RaftState, idx: int) -> str:
        """Identify which invariant a known-bad state violates (cold path)."""
        one = jax.tree.map(lambda x: x[idx : idx + 1], children)
        for name, fn in self.inv_fns:
            if not bool(np.asarray(fn(self.cfg, one, self.kern.tables))[0]):
                return name
        return self.inv_fns[0][0]

    # -- trace reconstruction ---------------------------------------------

    def _trace(self, levels: list[tuple[np.ndarray, np.ndarray]], level: int, idx: int):
        """Walk (parent, slot) spills back to Init, then replay forward.

        levels[d] = (pidx, slot) arrays for the states created at depth d+1;
        ``idx`` indexes into level ``level``'s arrays (level 0 = init).
        """
        chain = []  # slots to apply, init -> violation
        d, j = level, idx
        while d > 0:
            pidx, slots = levels[d - 1]
            chain.append(int(slots[j]))
            j = int(pidx[j])
            d -= 1
        chain.reverse()
        st = init_batch(self.cfg, 1)
        out = [("Init", to_oracle(self.cfg, st)[0])]
        for slot in chain:
            st = self.kern.materialize(st, jnp.asarray([slot], I64))
            fam = int(self.kern.slot_family[slot])
            name = self.kern.families[fam][0]
            server = int(self.kern.slot_coords[slot, 0]) + 1
            out.append((f"{name}({server})", to_oracle(self.cfg, st)[0]))
        return out

    # -- checkpoint / resume (TLC's states/ metadir + -recover) ------------

    def _save_checkpoint(self, path, frontier, msum, visited, n_f, distinct,
                         generated, depth, level_sizes, trace_levels,
                         mult_per_slot):
        arrs = {f"st_{k}": np.asarray(v) for k, v in frontier._asdict().items()}
        for i, (p, s) in enumerate(trace_levels):
            arrs[f"trace_p{i}"] = p
            arrs[f"trace_s{i}"] = s
        tmp = f"{path}.tmp.npz"
        np.savez_compressed(
            tmp,
            msum=np.asarray(msum),
            visited=np.asarray(visited),
            mult_per_slot=mult_per_slot,
            meta=np.asarray([n_f, distinct, generated, depth], np.int64),
            level_sizes=np.asarray(level_sizes, np.int64),
            n_trace=np.asarray([len(trace_levels)], np.int64),
            **arrs,
        )
        os.replace(tmp, path)

    @staticmethod
    def _load_checkpoint(path):
        z = np.load(path)
        frontier = RaftState(
            **{k[3:]: jnp.asarray(z[k]) for k in z.files if k.startswith("st_")}
        )
        n_f, distinct, generated, depth = (int(x) for x in z["meta"])
        trace_levels = [
            (z[f"trace_p{i}"], z[f"trace_s{i}"]) for i in range(int(z["n_trace"][0]))
        ]
        return dict(
            frontier=frontier,
            msum=jnp.asarray(z["msum"]),
            mult_per_slot=np.asarray(z["mult_per_slot"]),
            visited=jnp.asarray(z["visited"]),
            n_f=n_f,
            distinct=distinct,
            generated=generated,
            depth=depth,
            level_sizes=list(int(x) for x in z["level_sizes"]),
            trace_levels=trace_levels,
        )

    # -- the main loop -----------------------------------------------------

    def _expand_level(self, frontier, msum, n_f, visited):
        """Expand all chunks; returns device arrays + one fused host fetch."""
        cap_f = frontier.voted_for.shape[0]
        n_f_dev = jnp.asarray(n_f, I64)
        cvs, cfs, cps = [], [], []
        mult_acc = jnp.zeros((self.K,), I64)
        abort_at = BIG
        overflow = jnp.zeros((), bool)
        for start in range(0, min(cap_f, _pow2(max(n_f, 1))), self.chunk):
            part = jax.tree.map(
                lambda x: jax.lax.dynamic_slice_in_dim(
                    x, start, min(self.chunk, cap_f - start), 0
                ),
                frontier,
            )
            cv, cf, cp, mult_slots, ab_at, ovf = self._expand_chunk(
                part,
                msum[start : start + self.chunk],
                jnp.asarray(start, I64),
                n_f_dev,
            )
            cvs.append(cv)
            cfs.append(cf)
            cps.append(cp)
            mult_acc = mult_acc + mult_slots
            abort_at = jnp.minimum(abort_at, ab_at)
            overflow = overflow | ovf
        # pad the level-dedup input to a power-of-two lane count so its
        # sort program compiles O(log) times per run, not once per level
        n_lanes = len(cvs) * self.cap_x
        pad = _pow2(n_lanes) - n_lanes
        if pad:
            cvs.append(jnp.full((pad,), SENT, U64))
            cfs.append(jnp.full((pad,), SENT, U64))
            cps.append(jnp.full((pad,), -1, I64))
        n_new_dev, new_fps, new_payload = _level_dedup(
            jnp.concatenate(cvs), jnp.concatenate(cfs), jnp.concatenate(cps),
            visited,
        )
        # ONE host sync for the level's control state
        n_new, ab, ovf, mult_np = jax.device_get(
            (n_new_dev, abort_at, overflow, mult_acc)
        )
        return int(n_new), new_fps, new_payload, int(ab), bool(ovf), mult_np

    def run(
        self,
        max_depth: int | None = None,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 1,
        resume_from: str | None = None,
    ) -> CheckResult:
        cfg = self.cfg
        K = self.K
        t0 = time.monotonic()

        if self.host_store is not None and (resume_from or checkpoint_dir):
            raise ValueError(
                "host_store cannot be combined with checkpoint/resume: the "
                ".npz snapshot does not capture the on-disk store, so a "
                "resumed run would see its own pre-crash inserts as "
                "already-visited and report a truncated clean sweep"
            )
        if resume_from is not None:
            ck = self._load_checkpoint(resume_from)
            frontier, msum, visited = ck["frontier"], ck["msum"], ck["visited"]
            n_f, distinct, generated = ck["n_f"], ck["distinct"], ck["generated"]
            depth, level_sizes, trace_levels = (
                ck["depth"], ck["level_sizes"], ck["trace_levels"],
            )
            mult_per_slot = ck["mult_per_slot"]
        else:
            frontier = init_batch(cfg, 1)
            n_f = 1
            fv, _ff, msum = self.fpr.state_fingerprints(frontier)
            if self.host_store is not None:
                self.host_store.insert(np.asarray(fv.astype(U64)))
                visited = jnp.full((64,), SENT, U64)
            else:
                visited = jnp.sort(
                    jnp.concatenate([fv.astype(U64), jnp.full((63,), SENT, U64)])
                )
            distinct = 1
            generated = 0
            level_sizes = [1]
            depth = 0
            trace_levels = []
            mult_per_slot = np.zeros(K, np.int64)

            bad0 = int(np.asarray(self._inv_scan(frontier, jnp.asarray(1, I64))))
            if bad0 >= 0:
                name0 = self._bad_invariant_name(frontier, bad0)
                return CheckResult(
                    False, 1, 0, 0, (1,),
                    (
                        f"Invariant {name0} is violated",
                        self._trace(trace_levels, 0, 0),
                    ),
                )
        # pad the resumed/initial frontier to at least one chunk so the
        # expand kernel compiles at the chunk shape only
        if frontier.voted_for.shape[0] < self.chunk:
            frontier = _pad_tree(frontier, self.chunk)
            msum = _pad_axis0(msum, self.chunk)

        while n_f > 0:
            if max_depth is not None and depth >= max_depth:
                break
            # --- expand + compact-then-dedup (device), fused level fetch -
            while True:
                (n_new, new_fps, new_payload, abort_at, overflow, level_mult
                 ) = self._expand_level(frontier, msum, n_f, visited)
                if not overflow:
                    break
                # a chunk kept more survivors than its lane budget: grow
                # and redo the level (pure computation, rare).  cap_x is
                # baked into the traced program, so re-jit.
                self.cap_x *= 2
                self._expand_chunk = jax.jit(self._expand_chunk_impl)
            if abort_at < n_f:
                # action_counts stays None on violations, like the oracle:
                # coverage of a partially-expanded level is ill-defined
                return CheckResult(
                    False, distinct, generated, depth, tuple(level_sizes),
                    (
                        'Assert "split brain" (Raft.tla:185)',
                        self._trace(trace_levels, depth, abort_at),
                    ),
                )
            mult_per_slot = mult_per_slot + level_mult
            generated += int(level_mult.sum())

            if self.host_store is not None and n_new:
                fps_np = np.asarray(new_fps[:n_new])
                is_new = self.host_store.insert(fps_np)
                pay_np = np.asarray(new_payload[:n_new])[is_new]
                n_new = len(pay_np)
            else:
                pay_np = np.asarray(new_payload[:n_new])
            if n_new == 0:
                break

            # --- materialize the survivors ------------------------------
            # never shrink below one chunk: keeps the expand kernel at one
            # compiled shape instead of one per pow2 frontier size.
            # Materialization runs in chunk-sized slices: msg_hash unpacks
            # a [n, n_words, 32] intermediate that would OOM at millions
            # of survivors in one call.  pow2 (not pow4) capacity: at
            # multi-million frontiers a 4x overshoot is gigabytes.
            cap_c = max(_pow2(n_new), self.chunk)
            pidx_np = pay_np // K
            slot_np = pay_np % K
            pidx = _pad_axis0(jnp.asarray(pidx_np, I64), cap_c)
            slots = _pad_axis0(jnp.asarray(slot_np, I64), cap_c)
            if cap_c <= 4 * self.chunk:
                children, child_msum = self._gather_mat(frontier, pidx, slots)
            else:
                sl = 4 * self.chunk  # divides cap_c (both powers of two)
                parts = [
                    self._gather_mat(
                        frontier, pidx[off : off + sl], slots[off : off + sl]
                    )
                    for off in range(0, cap_c, sl)
                ]
                children = jax.tree.map(
                    lambda *xs: jnp.concatenate(xs), *(p[0] for p in parts)
                )
                child_msum = jnp.concatenate([p[1] for p in parts])

            # --- bookkeeping, invariants, store merge -------------------
            trace_levels.append((pidx_np.astype(np.int64), slot_np.astype(np.int64)))
            distinct += n_new
            level_sizes.append(n_new)
            depth += 1

            bad_idx = int(
                np.asarray(self._inv_scan(children, jnp.asarray(n_new, I64)))
            )

            if self.host_store is None:
                # merge, then trim the store to a pow4 capacity >= distinct;
                # new_fps is survivor-compacted, so slicing to cap_c keeps
                # every real fingerprint and bounds the sort input
                visited = _merge_sorted(visited, new_fps[:cap_c])[
                    : _cap4(distinct + 1)
                ]
            frontier, msum, n_f = children, child_msum, n_new

            if self.progress is not None:
                self.progress(
                    dict(
                        level=depth,
                        frontier=n_new,
                        distinct=distinct,
                        generated=generated,
                        elapsed=time.monotonic() - t0,
                    )
                )
            if bad_idx >= 0:
                name = self._bad_invariant_name(children, bad_idx)
                return CheckResult(
                    False, distinct, generated, depth, tuple(level_sizes),
                    (
                        f"Invariant {name} is violated",
                        self._trace(trace_levels, depth, bad_idx),
                    ),
                )
            # checkpoint only invariant-clean levels: a resumed run never
            # re-checks its loaded frontier, so saving before the check
            # could hide a violation behind a crash+resume
            if checkpoint_dir and checkpoint_every and depth % checkpoint_every == 0:
                os.makedirs(checkpoint_dir, exist_ok=True)
                self._save_checkpoint(
                    os.path.join(checkpoint_dir, "latest.npz"), frontier, msum,
                    visited, n_f, distinct, generated, depth, level_sizes,
                    trace_levels, mult_per_slot,
                )

        return CheckResult(
            True, distinct, generated, depth, tuple(level_sizes), None,
            self._action_counts(mult_per_slot),
        )
