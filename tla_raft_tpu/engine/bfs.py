"""Level-synchronous BFS on device — TLC's exploration engine, TPU-native.

Replaces the reference checker's core runtime (SURVEY.md §3.1: the BFS
loop, worker pool, FPSet dedup table, invariant evaluation, trace
reconstruction and checkpointing of the external TLC jar driven by
/root/reference/myrun.sh:3) with:

* a **device-resident compact frontier**: full states minus the message
  bitmask, which is stored as a sparse id list (``msg_ids``) — a
  reachable state carries at most a few dozen of the universe's
  thousands of message bits, so the sparse form is ~3x smaller
  (~250 B/state), which is what lets multi-million-state frontiers and
  their children coexist in HBM.  Chunks inflate ids -> bitmask on
  device (scatter-free one-hot OR); materialized children deflate via a
  ``top_k`` bit-position extraction.  Nothing state-sized ever crosses
  the host link (measured at only ~2-20 MB/s on the tunneled device —
  streaming states through the host cost ~100 us/state),
* the successor kernel's masked fan-out (ops/successor.py) run in chunks,
* **compact-then-dedup**, all on device:
    1. per chunk: a ``top_k`` partial sort compacts the ~0.5%-dense valid
       lanes of the |chunk|*K fan-out into a fixed cap_x lane budget
       (no dedup, no visited access — the expand program stays
       shape-stable for the whole run);
    2. per level: one lexsort over all chunks' compacted candidates
       picks the min-(fp_full, payload) representative per view
       fingerprint (the deterministic refinement of TLC's
       first-writer-wins — see oracle/explicit.py) and drops states
       already in the sorted visited store (``searchsorted``).
  Compaction shrinks the level-wide sort from |frontier|*K dense lanes
  to the ~3.5 valid candidates per frontier state (measured on the
  reference config) padded to the cap_x budget — the difference between
  sorting ~10^8 and ~10^6 keys per level at full scale.
* **materialization** of only the surviving (parent, slot) pairs,
* batched invariant kernels (engine/invariants.py) on each new level,
* per-level (parent, slot) spill to the host for counterexample traces
  (SURVEY.md §3.4: TLC's predecessor-chain walk),
* per-level snapshots for checkpoint/resume (SURVEY.md §3.5: TLC's
  ``states/`` metadir + ``-recover``).

Host/device discipline: the chunk loop runs with **zero host syncs**
(the split-brain abort flag and per-slot multiplicities accumulate on
device); each level fetches one small stats bundle (new-state count,
abort/overflow flags, generated count) and the (parent, slot) trace
spill.  Round 1 synced the abort flag per chunk, serializing host and
device every 256 states (ADVICE.md round 1).

Deadlock states (no action enabled) are not reported, matching the
``-deadlock`` flag in myrun.sh:3 which *disables* deadlock checking.

All device computations run at power-of-two padded shapes so XLA compiles
a logarithmic number of program variants; every array is explicitly
dtyped (u8 state, u64 fingerprints, i64 payloads).
"""

from __future__ import annotations

import functools
import os
import sys
import time
import weakref
import zipfile
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import resilience
from ..analysis import sanitize as graft_sanitize
from ..obs import telemetry as graft_obs
from ..config import RaftConfig
from ..models.raft import RaftState, init_batch, to_oracle
from ..ops import hashstore
from ..ops import sieve as graft_sieve
from ..ops.successor import SuccessorKernel, get_kernel
from ..store import tiered as graft_tiered
from . import megakernel as graft_megakernel
from . import superstep as graft_superstep
from . import pipeline as graft_pipeline
from ..analysis import devprof as graft_devprof
from . import forecast as graft_forecast
from ..tune import adaptive as graft_adaptive
from .forecast import MIN_LEVELS as PRESIZE_MIN_LEVELS, pow2ceil as _pow2
from .invariants import resolve_invariant_kernel

U64 = jnp.uint64
I64 = jnp.int64
I32 = jnp.int32
U32C = jnp.uint32
# numpy scalars, not jnp: a module-scope jnp.uint64(...) call would force
# XLA client creation at IMPORT time, aborting pytest collection on hosts
# with no usable backend (numpy scalars promote identically inside jit)
SENT = np.uint64(0xFFFFFFFFFFFFFFFF)
BIG = np.int64(1 << 62)


class CheckResult(NamedTuple):
    """Same shape as oracle.explicit.CheckResult for differential tests."""

    ok: bool
    distinct: int
    generated: int
    depth: int
    level_sizes: tuple[int, ...]
    violation: tuple | None  # (kind, trace=[(action, OState), ...])
    action_counts: dict | None = None  # TLC -coverage analog (see oracle)


class Frontier(NamedTuple):
    """Compact device frontier: RaftState minus ``msgs``, plus sparse ids.

    ``msg_ids``: ascending message ids, -1 padded, width ``cap_m``."""

    voted_for: jnp.ndarray
    current_term: jnp.ndarray
    role: jnp.ndarray
    log_term: jnp.ndarray
    log_val: jnp.ndarray
    log_len: jnp.ndarray
    match_index: jnp.ndarray
    next_index: jnp.ndarray
    commit_index: jnp.ndarray
    election_count: jnp.ndarray
    restart_count: jnp.ndarray
    pending: jnp.ndarray
    val_sent: jnp.ndarray
    msg_ids: jnp.ndarray


_CORE_FIELDS = [f for f in RaftState._fields if f != "msgs"]


class _HostSeg:
    """A frontier segment demoted to host RAM (numpy field dict).

    The single-chip deep sweep walls when one level's frontier outgrows
    HBM (level 29 of the reference config: ~15 GB of children at a
    16 GB chip — BASELINE.md).  TLC's answer is disk spill
    (/root/reference/.gitignore:2); ours is this tier: sealed
    destination segments demote to host RAM under a device-byte budget
    (TLA_RAFT_DEV_BYTES) and page back in on demand — the expand and
    materialize walks both consume segments in ascending payload order,
    so residency is a moving window, not a working set.

    Below host RAM sits the WARM tier: a segment past the host budget
    (TLA_RAFT_FSEG_BYTES) spills its field dict to disk through the
    tiered store's FrontierPager (kind="fseg" via the atomic writer)
    and reloads lazily the first time ``fields`` is touched again —
    the same moving-window residency, one tier further down."""

    __slots__ = ("_fields", "_rows", "pager", "token", "__weakref__")

    def __init__(self, fields: dict):
        self._fields = fields
        self._rows = fields["voted_for"].shape[0]
        self.pager = None
        self.token = None

    @property
    def fields(self) -> dict:
        if self._fields is None:
            self._fields = self.pager.load(self.token)
        return self._fields

    @property
    def rows(self) -> int:
        return self._rows

    @property
    def resident_bytes(self) -> int:
        """Host-RAM footprint (0 while spilled to the warm tier)."""
        if self._fields is None:
            return 0
        return sum(
            int(np.prod(v.shape)) * v.dtype.itemsize
            for v in self._fields.values()
        )

    def spill(self, pager, depth: int = -1) -> None:
        """Commit the field dict to the warm tier and drop the RAM copy
        (idempotent re-spill: a reloaded segment already has a token —
        its artifact is still on disk, so dropping the copy is free)."""
        if self.token is None:
            self.token = pager.spill(self._fields, depth=depth)
            self.pager = pager
        self._fields = None


def _seg_rows(seg) -> int:
    return seg.rows if isinstance(seg, _HostSeg) else seg.voted_for.shape[0]


# _pow2 is forecast.pow2ceil (imported above) — one next-power-of-two
# helper shared by the engines and the capacity forecaster.

# Uniform segment size for external-store frontiers (rows).  ONE fixed
# buffer shape per field across every deep level serves two masters:
# the BFC allocator recycles identical slabs instead of fragmenting HBM
# over a replay's worth of odd-sized trees (measured: a fresh process
# can allocate 15.7 GB in one piece, but the replay OOMed at ~12 GB of
# live accounting), and the two-segment materialize gather compiles ONCE
# instead of once per frontier magnitude (remote compiles are minutes).
SEG_ROWS = 1 << 21


def _concat_fields(segs: list) -> Frontier:
    """Collapse a segment list into one frontier, FIELD BY FIELD, consuming
    the list: the naive tree-level concat holds the whole parent twice
    (inputs + outputs across all fields at once); sequencing per field and
    dropping the source column as soon as its concat lands caps the spike
    at ~one parent plus its largest field (the message-id lanes, ~60% of
    state bytes) instead of two parents."""
    if len(segs) == 1:
        return segs[0]
    cols = {f: [getattr(s, f) for s in segs] for f in Frontier._fields}
    segs[:] = []  # drop the tuples so each column is the last reference
    out = {}
    for f in Frontier._fields:
        out[f] = jnp.concatenate(cols[f])
        cols[f] = None
    return Frontier(**out)


def _host_cap(n: int, chunk: int) -> int:
    """Frontier capacity on the external-store path: whole uniform
    segments once past one segment, else the small-level quantizer."""
    if n > SEG_ROWS:
        return -(-n // SEG_ROWS) * SEG_ROWS
    c = _cap_steps(n)
    if c % chunk:
        c = _pow2(n)
    return max(c, chunk)


def _cap_steps(n: int) -> int:
    """Smallest c >= n with c in {2^k, 3*2^(k-1)} — frontier capacities.

    Pure pow2 quantization wastes up to 50% of HBM in padding; at the
    deep-sweep frontiers (tens of GB) that waste IS the memory wall, so
    frontiers quantize on half-steps (~17% max waste) at the cost of at
    most one extra compiled shape per magnitude.  Callers must still
    enforce divisibility by their chunk (a half-step 3*2^(k-1) is only a
    chunk multiple when 2^(k-1) >= chunk — see _frontier_cap)."""
    p = _pow2(n)
    half = 3 * (p >> 2)
    return half if half >= n and half > 0 else p


@functools.lru_cache(maxsize=1)
def _is_tunneled() -> bool:
    """True when the backend is a remote PJRT tunnel (the 'axon' proxy).

    Tunneled workers need the per-chunk dispatch-queue drain (they crash
    under deep async queues); local backends don't."""
    try:
        import jax.extend.backend

        return "axon" in str(
            getattr(jax.extend.backend.get_backend(), "platform_version", "")
        )
    except Exception:  # graftlint: waive[GL003] — any backend-probe
        # failure (missing module, no devices, RPC error) means "not
        # tunneled"; the probe must never take the checker down
        return False


def _cap4(n: int) -> int:
    """Next power of 4: capacities quantize coarser so the checker compiles
    ~half as many program shapes (remote TPU compiles are minutes each)."""
    c = 1
    while c < n:
        c <<= 2
    return c


def _pad_axis0(x: jnp.ndarray, cap: int) -> jnp.ndarray:
    pad = cap - x.shape[0]
    if pad <= 0:
        return x[:cap]
    return jnp.concatenate([x, jnp.zeros((pad, *x.shape[1:]), x.dtype)])




@functools.partial(jax.jit, static_argnames=("cap_x",))
def _compact_payloads(valid_flat, payload, cap_x: int):
    """Compact the valid fan-out lanes' payloads into cap_x lanes.

    The late-canonicalization variant of ``_chunk_compact``: keys on the
    validity mask alone (fingerprints don't exist yet at this point — they
    are computed afterwards from the materialized candidates).  Kept lanes
    preserve original lane order (payload-ascending).  Shared by the
    single-device and mesh engines; outputs are cap_x wide even when the
    fan-out is smaller (tiny mesh frontiers have C = cap_f*K < cap_x).
    Returns (payload[cap_x] with garbage beyond ``lane``, lane bool[cap_x],
    overflow).
    """
    C = valid_flat.shape[0]
    n_live = valid_flat.sum()
    k = min(cap_x, C)
    key = jnp.where(valid_flat, C - jnp.arange(C, dtype=I32), 0)
    vals, idx = jax.lax.top_k(key, k)
    lane = vals > 0
    cp = payload[idx]
    if cap_x > k:
        lane = jnp.concatenate([lane, jnp.zeros((cap_x - k,), bool)])
        cp = jnp.concatenate([cp, jnp.full((cap_x - k,), -1, cp.dtype)])
    return cp, lane, n_live > cap_x


@functools.partial(jax.jit, static_argnames=("cap_x",))
def _chunk_compact(fps_view, fps_full, payload, cap_x: int):
    """Compact one chunk's valid fan-out lanes into cap_x lanes (no dedup).

    fps_view/full u64[C] (SENT where invalid), payload i64[C] (global
    parent*K+slot).  ``top_k`` on an earliest-lane-first key is a partial
    sort — far cheaper than a full argsort over the ~0.5%-dense C lanes,
    and it keeps the visited store out of this (large, shape-stable)
    program so store growth never recompiles the expand kernel.  Kept
    lanes preserve original lane order (payload-ascending), matching the
    stable compaction the dedup's determinism contract assumes.
    """
    C = fps_view.shape[0]
    live = fps_view != SENT
    n_live = live.sum()
    key = jnp.where(live, C - jnp.arange(C, dtype=I32), 0)
    vals, idx = jax.lax.top_k(key, cap_x)  # descending = earliest lanes first
    lane = vals > 0
    return (
        jnp.where(lane, fps_view[idx], SENT),
        jnp.where(lane, fps_full[idx], SENT),
        jnp.where(lane, payload[idx], -1),
        n_live > cap_x,
    )


def _filter_compact(hit, cv, cf, cp, cap_g: int):
    """Shared tail of the two group filters: drop hit lanes, compact
    the survivors into cap_g lanes preserving lane order (stable top_k
    key).  ONE implementation so the hash and sorted membership tests
    can never drift on the compaction contract."""
    C = cv.shape[0]
    keep = (cv != SENT) & ~hit
    n = keep.sum()
    key = jnp.where(keep, C - jnp.arange(C, dtype=I32), 0)
    vals, idx = jax.lax.top_k(key, cap_g)
    lane = vals > 0
    return (
        jnp.where(lane, cv[idx], SENT),
        jnp.where(lane, cf[idx], SENT),
        jnp.where(lane, cp[idx], -1),
        n > cap_g,
    )


@functools.partial(jax.jit, static_argnames=("cap_g",))
def _group_filter(cv, cf, cp, visited, cap_g: int):
    """Drop already-visited candidates from a group of chunks and compact.

    At deep levels ~85-90% of candidate lanes are revisits of the sorted
    store; filtering a fixed-size group before the level-wide sort keeps
    that sort (and its working set) proportional to the NEW states, not
    the whole fan-out.  Dropping a visited view fingerprint removes its
    whole candidate group, so downstream representative choice is
    unaffected; compaction preserves lane order (stable top_k key).
    """
    pos = jnp.searchsorted(visited, cv)
    hit = visited[jnp.clip(pos, 0, visited.shape[0] - 1)] == cv
    return _filter_compact(hit, cv, cf, cp, cap_g)


@functools.partial(jax.jit, static_argnames=("cap_g",))
def _group_filter_hash(cv, cf, cp, slab, cap_g: int):
    """``_group_filter`` with the open-addressing store: the visited
    membership test is a depth-bounded hash probe (O(1) expected, 2-3
    gather rounds at the enforced <=1/2 load) instead of a binary
    search's ~22 rounds of random gathers against the sorted table —
    the membership-side gather cliff (docs/PERF.md "Hashed visited
    store").  Compaction is the SHARED ``_filter_compact`` tail."""
    hit = hashstore.probe_impl(slab, cv)
    return _filter_compact(hit, cv, cf, cp, cap_g)


@jax.jit
def _level_dedup_hash(cv, cf, cp, slab):
    """Hash-store replacement for ``_level_dedup`` + ``_merge_sorted``:
    ONE fused probe-and-insert resolves uniqueness, visited membership
    AND the store update — no 3-key lexsort over the level's lanes, no
    searchsorted, no whole-store re-sort.  The min-(fp_full, payload)
    representative per view fingerprint is chosen by the kernel's
    two-phase min-reduce (the group-min lemma), so counts are
    bit-identical to the sort path; survivors compact in LANE order
    (payload-ascending — the same order the external-store path pins).

    Returns (n_new, new_fps, new_payload, slab', overflow).  On
    overflow the caller grows the store and redoes the level against
    the ORIGINAL slab (the kernel is functional)."""
    slab2, fresh, n_new, ovf = hashstore.probe_and_insert_impl(
        slab, cv, cf, cp
    )
    new_fps, new_pay = hashstore.compact_fresh(fresh, cv, cp, cv.shape[0])
    return n_new, new_fps, new_pay, slab2, ovf


@jax.jit
def _level_dedup(cv, cf, cp, visited):
    """Global dedup over the level's compacted candidates, on device.

    One lexsort by (fp_view, fp_full, payload) across every chunk's
    candidates resolves uniqueness and picks the min-(fp_full, payload)
    representative per view fingerprint (the deterministic refinement of
    TLC's first-writer-wins); a searchsorted against the sorted visited
    store drops already-known states.  Doing this once per level instead
    of per chunk halves the sort work of the old two-stage scheme.
    Retraces when the visited capacity grows — acceptable, the program is
    small next to the expand kernel.

    Returns (n_new, new_fps u64[C] view-sorted SENT-padded, payload i64[C]).
    """
    order = jnp.lexsort((cp, cf, cv))
    sv, sp = cv[order], cp[order]
    first = jnp.concatenate([jnp.ones((1,), bool), sv[1:] != sv[:-1]])
    pos = jnp.searchsorted(visited, sv)
    hit = visited[jnp.clip(pos, 0, visited.shape[0] - 1)] == sv
    new = first & (sv != SENT) & ~hit
    n_new = new.sum()
    comp = jnp.argsort(~new, stable=True)
    keep = jnp.arange(sv.shape[0]) < n_new
    return (
        n_new,
        jnp.where(keep, sv[comp], SENT),
        jnp.where(keep, sp[comp], -1),
    )


def _group_unique_impl(cv, cf, cp):
    """Intra-group dedup for the external-store path.

    Picks the min-(fp_full, payload) representative per view fingerprint
    within one group of chunks and compacts the survivors to a fetchable
    prefix (cv-ascending) — the same ordering contract as
    ``_level_dedup`` but with no visited access: the visited filter
    happens host-side against the external store.  Keeping only the
    group-min per view is lossless for the level-global representative
    choice (the global min over candidates equals the min over
    group-mins), which is what makes the per-group host path bit-
    identical to the level-wide device dedup.
    """
    order = jnp.lexsort((cp, cf, cv))
    sv, sf, sp = cv[order], cf[order], cp[order]
    first = jnp.concatenate([jnp.ones((1,), bool), sv[1:] != sv[:-1]])
    keep = first & (sv != SENT)
    n_u = keep.sum()
    comp = jnp.argsort(~keep, stable=True)
    pref = jnp.arange(sv.shape[0]) < n_u
    return (
        n_u,
        jnp.where(pref, sv[comp], SENT),
        jnp.where(pref, sf[comp], SENT),
        jnp.where(pref, sp[comp], -1),
    )


_group_unique = jax.jit(_group_unique_impl)


@jax.jit
def _merge_sorted(visited, new_fps):
    """Insert a level's new fingerprints into the sorted store."""
    return jnp.sort(jnp.concatenate([visited, new_fps]))


# NOTE: an earlier revision built destination frontiers with donated
# dynamic_update_slice writes; the tunneled backend silently ignores the
# donation (the copy runs at HBM speed, so timing probes can't tell) and
# the two destination copies OOMed the deep-sweep replay.  Destinations
# are now built by SEGMENT-bounded concats — transient is 2 segments,
# never 2 frontiers, with no reliance on donation semantics.


class JaxChecker:
    """The TPU model checker for one RaftConfig.

    Parameters:
      chunk: max parents expanded per kernel launch (memory knob; the
        per-launch working set is ~chunk * K * (F + hash) bytes).
      cap_x: per-chunk compacted-survivor lanes (grows on overflow).
      progress: optional callable(level_stats_dict) for per-level logging.
    """

    def __init__(
        self,
        cfg: RaftConfig,
        chunk: int = 1024,
        cap_x: int | None = None,
        progress: Callable[[dict], None] | None = None,
        host_store=None,
        cap_m: int = 96,
        canon: str = "late",
        use_hashstore: bool | None = None,
        pipeline: bool | None = None,
        pipeline_window: int | None = None,
        prewarm: bool | None = None,
        use_mxu: bool | None = None,
        megakernel: bool | None = None,
        superstep: int | None = None,
        audit: int = 0,
        audit_retries: int = 3,
        watchdog=None,
        store_bytes: int | None = None,
        warm_bytes: int | None = None,
        sieve: bool | None = None,
    ):
        # canon="late": expand computes guards only; the compacted
        # candidates are materialized and fingerprinted with the full-state
        # path — the P-wide symmetry fold runs over ~3.5 candidates/state
        # instead of all K fan-out lanes (the enabler for big symmetry
        # groups, and faster even at S=3).  canon="expand": fold the
        # symmetry hash into every fan-out lane (the round-2 formulation,
        # kept as a differential reference).
        assert canon in ("late", "expand")
        self.canon = canon
        self.cfg = cfg
        # MXU-native expand (ops/mxu_expand.py): guards as the coefficient
        # matmul, materialize as gather-free select-matrix products.
        # Default ON; TLA_RAFT_MXU=0 / --no-mxu-expand / use_mxu=False
        # reverts to the legacy per-lane kernels — counts are
        # bit-identical either way (the MXU parity suite diffs the two).
        self.kern: SuccessorKernel = get_kernel(cfg, mxu=use_mxu)
        self.use_mxu = self.kern.use_mxu
        self.fpr = self.kern.fpr
        self.K = self.kern.K
        self.uni_words = self.kern.uni.n_words
        # sparse-frontier width: max message-set size per reachable state
        # (grows ~1/level, saturating near a structural bound — 96 on the
        # reference family; overflow auto-grows it and re-materializes
        # the level, see _materialize_grow).  TLA_RAFT_CAP_M overrides —
        # deep sweeps start with headroom so growth never fires after
        # parent segments are released.
        env_capm = os.environ.get("TLA_RAFT_CAP_M")
        if env_capm is not None and cap_m == 96:
            # env overrides only the DEFAULT: a caller passing an explicit
            # cap_m (tests bounding HBM, the growth suite) keeps it
            cap_m = int(env_capm)
        self.cap_m = min(cap_m, self.kern.uni.M)
        self.id_dtype = jnp.int16 if self.kern.uni.M < (1 << 15) else jnp.int32
        if chunk & (chunk - 1):
            # power-of-two capacities divide evenly into the pow4-padded
            # materialize buffer; arbitrary chunks would mis-slice it
            raise ValueError(f"chunk must be a power of two, got {chunk}")
        self.chunk = chunk
        # a chunk's valid fan-out lanes average ~3.5 per parent on the
        # reference config, so chunk*4 covers the mean and overflow
        # detection grows the budget (with a re-jit) on skewed chunks
        self.cap_x = cap_x or 4 * chunk
        # chunks per visited-filter group, and the per-group post-filter
        # survivor budget (deep levels see <=50% fresh candidates;
        # overflow grows cap_g like cap_x)
        self.G = 16
        self.cap_g = self.G * self.cap_x // 2
        # chunks dispatched between queue-draining scalar fetches.  The
        # tunneled (remote PJRT) device worker crashes when too many chunk
        # programs queue on multi-million-state levels — even a 32-chunk
        # window died — so the per-chunk drain is the default there
        # (~10 ms against a ~400 ms chunk).  Healthy local hardware
        # doesn't need the serialization; the env knob opens the window.
        env_sync = os.environ.get("TLA_RAFT_SYNC_EVERY")
        if env_sync is not None:
            self.sync_every = max(1, int(env_sync))
        else:
            self.sync_every = 1 if _is_tunneled() else 8
        self.progress = progress
        # optional native external-memory visited store (native/fpstore.cpp);
        # when set, the device keeps no visited table at all — the level's
        # deduped candidates are filtered through the host store instead
        self.host_store = host_store
        # device-resident open-addressing visited store (ops/hashstore.py):
        # replaces the level's 3-key lexsort + searchsorted + sorted store
        # merge with one fused O(1) probe-and-insert on the device-store
        # path.  Default ON; TLA_RAFT_HASHSTORE=0 (or --no-hashstore /
        # use_hashstore=False) reverts to the sort-based path.  Moot when
        # an external host store is attached (membership lives host-side).
        if use_hashstore is None:
            use_hashstore = hashstore.enabled_by_env()
        self.use_hashstore = bool(use_hashstore) and host_store is None
        self.hstore = None  # DeviceHashStore, built in run()/resume
        self._hs_pending = None  # a level's updated slab awaiting adoption
        # tiered visited store (store/tiered.py): a device-byte budget
        # for the hot slab; growth past it DEMOTES a whole generation
        # to host RAM / disk instead of growing (or dying), and the
        # level tail probes the demoted runs host-side, dropping their
        # revisits from the fresh set — |visited| becomes
        # storage-bounded, TLC's disk FPSet move.  0 = off (the
        # hot-only engine, bit-identical counts either way).
        if store_bytes is None:
            store_bytes = graft_tiered.store_bytes_from_env()
        self.store_bytes = int(store_bytes)
        self.warm_bytes = warm_bytes  # None = TLA_RAFT_WARM_BYTES
        self.tiered = None  # TieredVisitedStore, built in run()/resume
        # device-resident spill sieve (ops/sieve.py): a blocked bloom
        # over every demoted fingerprint, probed INSIDE the fused
        # megakernel/superstep body — a level with zero sieve hits
        # provably has no spilled revisits and commits in-window, which
        # restores span-N supersteps under spill (the PR 12 stand-down
        # becomes the sieve-off fallback).  Default ON wherever tiering
        # is; TLA_RAFT_SIEVE=0 / sieve=False reverts to span-1.
        # the arm decision is governed at RUNTIME when TLA_RAFT_SIEVE
        # is unset: recent sieve-dirty windows stand the span down
        # (the replay tax never amortizes — BENCH_SIEVE_AB_r20's ~14%),
        # a probation of per-level progress re-arms it.  =0 / =1 (or an
        # explicit argument) still force either mode unconditionally.
        self.sieve_governor = graft_adaptive.SieveGovernor(
            graft_adaptive.mode_from_env(sieve)
        )
        self.sieve_enabled = self.sieve_governor.mode != "off"
        self._dev_sieve = None      # device u64[M] copy of the filter
        self._dev_sieve_ver = -1    # host filter version it mirrors
        self._dev_sieve_empty = None  # the 1-word all-miss sentinel
        # device-byte budget for frontier segments (0 = paging off): when
        # one level's parent+child segments would exceed it, sealed child
        # segments demote to host RAM and page back in on demand — the
        # tier that breaks the single-frontier-in-HBM wall at level 29 of
        # the reference sweep (BASELINE.md).  The budget prices LIVE
        # buffers only — MULTI-SEGMENT HEADROOM IS REQUIRED: the expand
        # walk's one-entry parent page cache and the paged-parent fetch
        # buffer are transient extras the estimate does not count, and
        # with the async pipeline on, each in-flight window group pins
        # its group-output fetch buffers and keeps its parent segment
        # referenced ~window groups longer (the estimate below adds the
        # window to the live count, the page caches stay uncounted) —
        # so set the budget several segments below physical HBM
        # (run_sweep.sh's 11 GB of 16 GB leaves ~45 segments' worth)
        self.dev_budget = int(float(os.environ.get("TLA_RAFT_DEV_BYTES", "0")))
        # spilled frontiers (the tier BELOW _HostSeg's host RAM): a
        # FrontierPager built in run() when a spill directory exists;
        # host segments past TLA_RAFT_FSEG_BYTES commit to the warm
        # tier (kind="fseg") and reload on demand.  fseg_rows is the
        # uniform segment size the streamed megakernel path cuts
        # oversized parents into (default SEG_ROWS; override for tests)
        self._fpager = None
        self.fseg_host_bytes = graft_tiered.fseg_bytes_from_env()
        fsr = int(os.environ.get("TLA_RAFT_FSEG_ROWS", "0") or 0)
        self.fseg_rows = max(
            -(-fsr // chunk) * chunk if fsr else SEG_ROWS, chunk
        )
        self._fseg_live = []    # weakrefs of admitted host segments
        self._fseg_retire = []  # consumed segments' tokens (retired at
        #                         the next level top — never mid-level,
        #                         so a degrade-redo still has parents)
        # async intra-level pipeline (engine/pipeline.py): overlap the
        # device expand dispatch, the device->host group fetches and the
        # host-side tail under a bounded in-flight window.  Default ON;
        # TLA_RAFT_PIPELINE=0 (or pipeline=False / a window < 1) reverts
        # to the serial fetch-after-dispatch chain — counts are
        # bit-identical either way (the parity tests diff the two).
        if pipeline is None:
            pipeline = graft_pipeline.enabled_by_env()
        if pipeline_window is None:
            pipeline_window = graft_pipeline.window_from_env()
        self.pipeline_window = int(pipeline_window)
        self.pipeline = bool(pipeline) and self.pipeline_window >= 1
        # forecast-driven AOT prewarm (engine/pipeline.Prewarmer): once
        # the growth model has signal, compile the deep-level program
        # set at the forecast capacity ladder in a background thread
        # while the cheap shallow levels run.  Worth it exactly where
        # presize is: on tunneled backends whose remote compiles are
        # minutes each (the payoff routes through the persistent
        # compilation cache, so supervised relaunches also skip them).
        env_pw = os.environ.get("TLA_RAFT_PREWARM")
        if prewarm is None:
            prewarm = bool(int(env_pw)) if env_pw else _is_tunneled()
        self.prewarm = bool(prewarm)
        self._prewarmer = None  # built lazily at first plan submit
        self.paged_out = 0   # sealed child segments demoted to host RAM
        self.paged_disk = 0  # host segments spilled on to the warm tier
        if host_store is not None and chunk > SEG_ROWS:
            # the segment walkers assume chunks never straddle segment
            # boundaries (chunk is pow2 and <= SEG_ROWS => SEG_ROWS % chunk
            # == 0); a larger chunk would make divmod-based slices read
            # past segment bounds (clamped dynamic_slice re-reads wrong
            # parent rows silently)
            raise ValueError(
                f"chunk ({chunk}) must be <= SEG_ROWS ({SEG_ROWS}) "
                "when an external host store is attached"
            )
        self.inv_fns = [
            (n, resolve_invariant_kernel(n)) for n in cfg.invariants
        ]
        self._mat_slice = jax.jit(self._mat_slice_impl)
        self._mat_slice_seg = jax.jit(self._mat_slice_seg_impl)
        self._inv_scan = jax.jit(self._inv_scan_impl)
        # G-chunk span programs replace per-chunk dispatch at real chunk
        # sizes: each per-chunk round costs ~13 host->device dispatches
        # (12 eager field slices + the program) on the tunneled backend,
        # which is most of the warm steady-state cost (docs/PERF.md "chunk
        # cost = 38 ms fixed").  Tests drive tiny chunks through the
        # per-chunk path (some monkeypatch _expand_chunk); lower this to
        # exercise spans at test scale.
        self.span_min_chunk = 2048
        # predictive capacity pre-sizing (VERDICT r4 #7): forecast-floor
        # the frontier/visited pow2 ladders from the measured growth
        # model (engine/forecast.py) so a deep run compiles each program
        # ONCE instead of once per magnitude — on the tunneled backend
        # every extra magnitude is a minutes-class remote compile
        # (docs/PERF.md; the S=5 bench burned most of its 2,075 s wall on
        # 7 magnitude compiles).  Floors only ratchet up (shrinking would
        # mint new shapes).  Default: on for tunneled backends, off
        # locally where compiles are cheap and tests drive tiny shapes.
        env_ps = os.environ.get("TLA_RAFT_PRESIZE")
        self.presize = bool(int(env_ps)) if env_ps else _is_tunneled()
        self._presize_fcap = 0  # frontier-capacity floor (pow2, >= chunk)
        self._presize_vcap = 0  # visited-store trim floor (pow4)
        self._presize_merge = 0  # store merge-input width floor (pow2)
        # orbit pruning (VERDICT r4 #6, ops/fingerprint.py "orbit
        # pruning"): canonical-relabel fingerprints for color-discrete
        # candidates; only the (few) tied states pay the P-fold, on a
        # cap_x/4 compacted budget.  Changes fingerprint VALUES (not
        # counts), so it must stay consistent across a run and its
        # checkpoints — opt-in via TLA_RAFT_ORBIT=1, late canon only.
        env_orb = os.environ.get("TLA_RAFT_ORBIT")
        self.orbit = bool(int(env_orb)) if env_orb else False
        if self.orbit and canon != "late":
            raise ValueError("TLA_RAFT_ORBIT requires canon='late'")
        # whole-level megakernel (engine/megakernel.py): expand ->
        # probe-and-insert -> materialize -> invariant scan fused into
        # ONE jitted program per level, with one ledgered control fetch.
        # Default ON; --megakernel 0 / TLA_RAFT_MEGAKERNEL=0 reverts to
        # the staged program chain (retained as the A/B and audit
        # reference — counts are bit-identical either way).  The fused
        # program needs the functional hash store (its probe-and-insert
        # IS the dedup stage) and the single-program orbit split is
        # structurally incompatible; the host-store path gets the
        # partial fusion (expand span + group dedup in one program —
        # everything up to the host-store probe) under the same flag.
        if megakernel is None:
            megakernel = graft_megakernel.enabled_by_env()
        self._mega_flag = bool(megakernel) and not self.orbit
        self.megakernel = (
            self._mega_flag and self.use_hashstore and host_store is None
        )
        self._mega_donate = (
            self.megakernel and graft_megakernel.donation_supported()
        )
        self._mega_stats = dict(
            levels=0, redo_out=0, redo_x=0, redo_slab=0, redo_m=0,
        )
        # multi-level resident supersteps (engine/superstep.py): run up
        # to N fused levels inside ONE device program + ONE ledgered
        # ring fetch wherever the per-level megakernel is eligible.
        # Default span DEFAULT_SPAN; --superstep 1 / TLA_RAFT_SUPERSTEP
        # reverts to the per-level fused path.  The --audit legacy
        # re-expansion needs every level's parent frontier on device,
        # which the resident loop consumes — audit runs stay per-level.
        if superstep is None:
            superstep = graft_superstep.span_from_env()
        self.superstep_span = (
            max(1, int(superstep)) if self.megakernel and not audit
            else 1
        )
        self._ss_stats = dict(
            supersteps=0, levels=0, stops=0, ring_stops=0,
        )
        self._ss_sig = None  # declared superstep static-shape signature
        self._degraded_visited = None  # sorted store handoff on degrade
        # semantic run fingerprint for the checkpoint manifests: spec
        # constants only — NOT tunables like chunk (a resume may retune
        # those freely), NOT the store tier (the three tiers share one
        # delta-log format), and NOT the fingerprint definition: orbit
        # mixing is guarded one layer down by the per-record fp_def
        # check, whose specific "fingerprint-definition mismatch" error
        # tells the operator which knob to flip.
        self._run_fp = resilience.run_config_fingerprint(cfg, log="delta")
        # sampled recomputation audit (resilience/integrity.py): every
        # level, ``audit`` deterministic new-frontier rows re-expand
        # through the retained *_legacy kernels and cross-check guards/
        # fingerprints against the (MXU) hot path AND the frontier as
        # materialized on device; a mismatch quarantines the level and
        # rewinds to the last committed checkpoint, fail-stopping after
        # ``audit_retries`` reproducible strikes.
        self.audit = max(0, int(audit))
        self.audit_retries = max(1, int(audit_retries))
        self.audit_stats = dict(
            levels=0, sampled=0, mismatches=0, rewinds=0
        )
        self._audit_strikes = 0
        self._audit_strike_depth = None  # level the strikes belong to
        self._audit_keys: set = set()  # declared audit program shapes
        # per-level hang watchdog (resilience/elastic.py), armed by the
        # level loop; None = off
        self.watchdog = watchdog
        self._jit_expand_programs()

    def _jit_expand_programs(self):
        """(Re-)jit the chunk expand programs (cap_x is baked in).

        Orbit runs the chunk as TWO programs — guards/compact/materialize,
        then fingerprints — because the fused variant (canonical-relabel
        machinery + the exact-fold fallback on top of the expand) pushed
        the S=7 compile past the tunnel's remote-compile window (the
        round-5 s7 campaign step died mid-compile).  Split, each program
        is no bigger than the non-orbit fused one, and at S=7 rates the
        extra dispatch is noise.  Spans stay off under orbit for the same
        reason (the scan multiplies program size by G).
        """
        self._expand_span = jax.jit(self._expand_span_impl)
        if self.orbit:
            self._expand_chunk_core = jax.jit(self._expand_chunk_core_impl)
            self._orbit_fps = jax.jit(self._orbit_fps_impl)
            self._expand_chunk = self._expand_chunk_split
        else:
            self._expand_chunk = jax.jit(self._expand_chunk_impl)
        # the fused whole-level program (and the host path's fused
        # span+dedup slice) close over cap_x — rebuild with it
        if getattr(self, "megakernel", False):
            self._mega_prog = graft_megakernel.level_program_for(
                self, self._mega_donate
            )
        if getattr(self, "_mega_flag", False) and not self.orbit:
            self._expand_group_fused = jax.jit(
                self._expand_group_fused_impl
            )
            # grouped ultra-deep regime: span + visited pre-filter in
            # one program per group (cap_g static so its growth
            # retraces like the staged _group_filter_hash)
            self._expand_group_gfused = jax.jit(
                self._expand_group_gfused_impl,
                static_argnames=("cap_g",),
            )

    # -- sparse <-> dense message-set conversion ---------------------------

    def _ids_to_msgs(self, ids: jnp.ndarray) -> jnp.ndarray:
        """msg_ids [n, cap_m] -> packed u32 [n, n_words] (scatter-free).

        Ids are unique per state, so the per-word sum of one-hot bit
        contributions equals the bitwise OR.
        """
        n_words = self.uni_words
        idi = ids.astype(I32)
        live = idi >= 0
        w = jnp.clip(idi, 0, None) >> 5
        bit = jnp.where(live, U32C(1) << (idi & 31).astype(jnp.uint32), U32C(0))
        hit = jnp.arange(n_words, dtype=I32)[None, None, :] == w[:, :, None]
        return (jnp.where(hit, bit[:, :, None], U32C(0))).sum(1, dtype=jnp.uint32)

    def _msgs_to_ids(self, msgs: jnp.ndarray):
        """packed u32 [n, n_words] -> (ids [n, cap_m] ascending -1-padded,
        overflow bool[n]): top_k over bit-position keys.  Overflow is
        per-row so callers can mask out garbage/padding lanes (a padded
        materialize lane holds a clipped parent's garbage child, which
        must not abort a real run)."""
        M = self.kern.uni.M
        bits = self.fpr.unpack_bits(msgs).astype(I32)
        key = bits * (M - jnp.arange(M, dtype=I32))
        vals, _ = jax.lax.top_k(key, self.cap_m)
        ids = jnp.where(vals > 0, M - vals, -1)
        ovf = bits.sum(-1, dtype=I32) > self.cap_m
        return ids.astype(self.id_dtype), ovf

    def _ids_insert(self, ids: jnp.ndarray, added: jnp.ndarray):
        """Child msg-id lists by sorted insertion of the sent ids.

        ids i32ish[n, cap_m]: the PARENTS' ascending -1-padded id lists;
        added i32[n, A]: the ids the materialized action sent (-1 pads).
        Returns (child_ids [n, cap_m], overflow bool[n]) — bit-identical
        to ``_msgs_to_ids(children.msgs)`` (same set, ascending, -1-
        padded) but in A tiny elementwise passes instead of a top_k over
        the M-wide universe per row (182.9 ms vs ~2 ms per 32k-row slice
        on the v5e — the measured dominator of the materialize pass).
        Already-present ids re-sent by guard-free actions (e.g.
        FollowerAcceptEntry, Raft.tla:275-300 — set union semantics,
        Raft.tla:43-45) are skipped like the bitmask OR they mirror.
        """
        M = self.kern.uni.M
        cap_m = ids.shape[1]
        pos_iota = jnp.arange(cap_m, dtype=I32)[None, :]
        cur = jnp.where(ids < 0, I32(M), ids.astype(I32))  # pads sort last
        ovf = jnp.zeros(ids.shape[0], bool)
        for a in range(added.shape[1]):
            aid = added[:, a].astype(I32)[:, None]  # [n, 1]
            live = (aid >= 0) & ~jnp.any(cur == aid, axis=1, keepdims=True)
            pos = jnp.sum(cur < aid, axis=1, dtype=I32)[:, None]
            shifted = jnp.concatenate([cur[:, :1], cur[:, :-1]], axis=1)
            ins = jnp.where(
                pos_iota < pos, cur,
                jnp.where(pos_iota == pos, aid, shifted),
            )
            ovf = ovf | (live[:, 0] & (cur[:, -1] < M))
            cur = jnp.where(live, ins, cur)
        child = jnp.where(cur >= M, I32(-1), cur).astype(self.id_dtype)
        return child, ovf

    def _inflate(self, fr: Frontier) -> RaftState:
        """Frontier chunk -> full RaftState with the packed bitmask."""
        core = {f: getattr(fr, f) for f in _CORE_FIELDS}
        return RaftState(msgs=self._ids_to_msgs(fr.msg_ids), **core)

    def _deflate(self, st: RaftState):
        core = {f: getattr(st, f) for f in _CORE_FIELDS}
        ids, ovf = self._msgs_to_ids(st.msgs)
        return Frontier(msg_ids=ids, **core), ovf

    # -- device helpers ----------------------------------------------------

    def _mat_slice_impl(self, frontier: Frontier, pay, n_valid):
        """Materialize one survivor payload slice, entirely on device.

        Gathers parents from the (compact) frontier, inflates their
        message sets, materializes the children, deflates them back to
        the compact form, and scans invariants — only per-slice scalars
        ever reach the host.
        """
        K = self.K
        pidx = (pay // K).astype(I32)
        slots = pay % K
        parents_c = jax.tree.map(lambda x: x[jnp.clip(pidx, 0, None)], frontier)
        parents = self._inflate(parents_c)
        children, added = self.kern.materialize_added(parents, slots)
        child_ids, ovf_rows = self._ids_insert(parents_c.msg_ids, added)
        child_f = Frontier(
            msg_ids=child_ids,
            **{f: getattr(children, f) for f in _CORE_FIELDS},
        )
        in_range = jnp.arange(ovf_rows.shape[0], dtype=I64) < n_valid
        bad_at = self._inv_scan_impl(children, n_valid)
        return child_f, bad_at, (ovf_rows & in_range).any()

    def _mat_slice_seg_impl(self, seg_a: Frontier, seg_b: Frontier, base,
                            pay, n_valid):
        """_mat_slice over a two-segment parent window (external-store
        path).  Payload-sorted slices touch a narrow parent range, so a
        (segment j, segment j+1) window always covers one slice; parents
        gather from whichever side of the boundary they fall on.  With a
        single-segment frontier the window is (seg, seg) and the where
        collapses to a plain gather."""
        K = self.K
        L = seg_a.voted_for.shape[0]
        pidx = (pay // K).astype(I64) - base
        slots = pay % K
        lo = jnp.clip(pidx, 0, L - 1).astype(I32)
        hi = jnp.clip(pidx - L, 0, L - 1).astype(I32)
        in_a = pidx < L
        parents_c = jax.tree.map(
            lambda a, b: jnp.where(
                in_a.reshape((-1,) + (1,) * (a.ndim - 1)), a[lo], b[hi]
            ),
            seg_a, seg_b,
        )
        parents = self._inflate(parents_c)
        children, added = self.kern.materialize_added(parents, slots)
        child_ids, ovf_rows = self._ids_insert(parents_c.msg_ids, added)
        child_f = Frontier(
            msg_ids=child_ids,
            **{f: getattr(children, f) for f in _CORE_FIELDS},
        )
        in_range = jnp.arange(ovf_rows.shape[0], dtype=I64) < n_valid
        bad_at = self._inv_scan_impl(children, n_valid)
        return child_f, bad_at, (ovf_rows & in_range).any()

    def _expand_chunk_impl(self, part_f: Frontier, start, n_f):
        """One chunk: inflate + expand + mask + valid-lane compaction.

        start/n_f are device i64 scalars so chunk position doesn't force
        a recompile; the visited store is deliberately NOT an input (its
        capacity grows over the run and would retrace this — the largest —
        program).  Returns compacted candidates + chunk stats.

        canon="late": the expand is guards-only; the compacted candidate
        (parent, slot) pairs are materialized in-chunk and fingerprinted
        from their full states (feat matmul + message-set matmul, both
        P-folded) — the symmetry fold touches cap_x lanes, not cap*K.
        """
        K = self.K
        part = self._inflate(part_f)
        cap = part.voted_for.shape[0]
        if self.canon == "late":
            # orbit always goes through the split two-program route
            # (_expand_chunk_split); tracing the fused variant with the
            # orbit machinery inlined is exactly the program that overran
            # the tunnel's remote compile (see _jit_expand_programs)
            assert not self.orbit, "orbit uses _expand_chunk_split"
            (children, lane, cp_raw, mult_slots, abort_at,
             overflow) = self._expand_chunk_core_late(part, start, n_f)
            fv, ff, _msum = self.fpr.state_fingerprints(children)
            cv = jnp.where(lane, fv.astype(U64), SENT)
            cf = jnp.where(lane, ff.astype(U64), SENT)
            cp = jnp.where(lane, cp_raw, -1)
        else:
            msum_part = self.fpr.msg_hash(part.msgs)
            exp = self.kern.expand(part, msum_part)
            valid, payload, mult_slots, abort_at = self._chunk_bookkeeping(
                exp.valid, exp.mult, exp.abort, start, n_f, cap
            )
            fpv = jnp.where(valid, exp.fp_view, SENT).ravel()
            fpf = jnp.where(valid, exp.fp_full, SENT).ravel()
            cv, cf, cp, overflow = _chunk_compact(fpv, fpf, payload, self.cap_x)
        return cv, cf, cp, mult_slots, abort_at, overflow

    def _chunk_bookkeeping(self, valid, mult, ab_state, start, n_f, cap):
        """Shared chunk accounting: in-range mask, global payload ids,
        per-slot multiplicity totals, first-abort position."""
        K = self.K
        in_range = (start + jnp.arange(cap, dtype=I64) < n_f)[:, None]
        valid = valid & in_range
        base = ((start + jnp.arange(cap, dtype=I64)) * K)[:, None]
        payload = (base + jnp.arange(K, dtype=I64)[None]).ravel()
        mult_slots = jnp.where(valid, mult, 0).astype(I64).sum(0)
        ab = ab_state & in_range[:, 0]
        abort_at = jnp.where(
            ab.any(), start + jnp.argmax(ab).astype(I64), BIG
        )
        return valid, payload, mult_slots, abort_at

    def _expand_chunk_core_late(self, part, start, n_f):
        """canon='late' chunk body up to materialize — NO fingerprints.

        ``part`` is the already-inflated chunk.  Shared by the fused
        program and the orbit split path (see ``_jit_expand_programs``).
        """
        K = self.K
        cap = part.voted_for.shape[0]
        valid, mult, ab_state = self.kern.expand_guards(part)
        valid, payload, mult_slots, abort_at = self._chunk_bookkeeping(
            valid, mult, ab_state, start, n_f, cap
        )
        cp_raw, lane, overflow = _compact_payloads(
            valid.ravel(), payload, self.cap_x
        )
        lidx = jnp.clip(cp_raw // K - start, 0, cap - 1).astype(I32)
        slots = cp_raw % K
        parents = jax.tree.map(lambda x: x[lidx], part)
        children = self.kern.materialize(parents, slots)
        return children, lane, cp_raw, mult_slots, abort_at, overflow

    def _expand_chunk_core_impl(self, part_f: Frontier, start, n_f):
        """Jit target for the orbit split's first program."""
        part = self._inflate(part_f)
        return self._expand_chunk_core_late(part, start, n_f)

    def _orbit_fps_impl(self, children, lane, cp_raw):
        """Jit target for the orbit split's second program."""
        fv, ff, nd_ovf = self._orbit_chunk_fps(children, lane)
        cv = jnp.where(lane, fv.astype(U64), SENT)
        cf = jnp.where(lane, ff.astype(U64), SENT)
        cp = jnp.where(lane, cp_raw, -1)
        return cv, cf, cp, nd_ovf

    def _expand_chunk_split(self, part_f: Frontier, start, n_f):
        """Orbit chunk expand as two dispatches (children stay on device)."""
        (children, lane, cp_raw, mult_slots, abort_at,
         overflow) = self._expand_chunk_core(part_f, start, n_f)
        cv, cf, cp, nd_ovf = self._orbit_fps(children, lane, cp_raw)
        return cv, cf, cp, mult_slots, abort_at, overflow | nd_ovf

    def _orbit_chunk_fps(self, children, lane):
        """Orbit-pruned fingerprints for one chunk's compacted candidates.

        Color-discrete rows (the vast majority on non-trivial levels) get
        the canonical-relabel hash; tied rows are compacted into a
        cap_x/4 sub-budget and run the exact min-over-P fold there.  If
        more than cap_x/4 rows are tied (early symmetric levels) the
        chunk reports overflow — the engine's existing redo then grows
        cap_x by half-steps (_cap_steps, ~1.5x), and with it this
        sub-budget, until the level fits.
        Returns (fp_view, fp_full, overflow)."""
        fv, ff, disc = self.fpr.state_fingerprints_orbit(children)
        need = lane & ~disc
        cap_nd = max(256, self.cap_x // 4)
        comp = jnp.argsort(~need, stable=True)[:cap_nd]
        sub = jax.tree.map(lambda x: x[comp], children)
        sv, sf, _ = self.fpr.state_fingerprints(sub)
        take = need[comp]
        fv = fv.at[comp].set(jnp.where(take, sv, fv[comp]))
        ff = ff.at[comp].set(jnp.where(take, sf, ff[comp]))
        return fv, ff, need.sum() > cap_nd

    def _fp_states(self, st):
        """(fp_view, fp_full) for a small batch, honoring the orbit flag.

        Root/trace/aux paths: computes both routes and selects — these
        batches are tiny, and the store must hold ONE consistent
        fingerprint definition per run."""
        if not self.orbit:
            fv, ff, _ = self.fpr.state_fingerprints(st)
            return fv, ff
        ov, of_, disc = self.fpr.state_fingerprints_orbit(st)
        sv, sf, _ = self.fpr.state_fingerprints(st)
        return jnp.where(disc, ov, sv), jnp.where(disc, of_, sf)

    def _expand_span_impl(self, frontier, slice_base, global_base, n_f):
        """G chunks in ONE program via lax.scan.

        The per-chunk host loop costs ~13 dispatches per chunk (12 eager
        per-field slices + the expand program); on the tunneled backend
        that dispatch latency — not compute — dominates warm levels
        (docs/PERF.md).  Scanning G chunks inside one jitted program cuts
        the level's dispatch count by ~G*13.

        ``frontier`` is the whole frontier (or one uniform segment on the
        external-store path); ``slice_base`` is the row offset of the
        span's first chunk within it, ``global_base`` the same position
        in global frontier coordinates (they differ on segment operands —
        payloads and in-range masks are global).  Returns stacked
        [G, cap_x] candidate arrays + span-reduced stats.
        """

        def body(carry, i):
            mult_acc, ab_min, ovf_any = carry
            part_f = jax.tree.map(
                lambda x: jax.lax.dynamic_slice_in_dim(
                    x, slice_base + i * self.chunk, self.chunk
                ),
                frontier,
            )
            cv, cf, cp, mult, ab, ovf = self._expand_chunk_impl(
                part_f, global_base + i * self.chunk, n_f
            )
            return (
                (mult_acc + mult, jnp.minimum(ab_min, ab), ovf_any | ovf),
                (cv, cf, cp),
            )

        init = (jnp.zeros((self.K,), I64), BIG, jnp.zeros((), bool))
        (mult, ab, ovf), (cvs, cfs, cps) = jax.lax.scan(
            body, init, jnp.arange(self.G, dtype=I64)
        )
        return cvs, cfs, cps, mult, ab, ovf

    def _expand_group_fused_impl(self, seg, slice_base, global_base, n_f):
        """Span + intra-group dedup in ONE program — the host-store
        path's megakernel slice (everything up to the host-store probe
        fuses; the probe itself lives host-side by design).  Identical
        outputs to ``_expand_span`` followed by ``_group_unique``: the
        dedup body is the SAME ``_group_unique_impl``."""
        cvs, cfs, cps, mult, ab, ovf = self._expand_span_impl(
            seg, slice_base, global_base, n_f
        )
        n_u, gv, gf, gp = _group_unique_impl(
            cvs.reshape(-1), cfs.reshape(-1), cps.reshape(-1)
        )
        return n_u, gv, gf, gp, mult, ab, ovf

    def _expand_group_gfused_impl(self, seg, slice_base, global_base,
                                  n_f, hslab, cap_g: int):
        """The grouped ultra-deep regime's per-group chain — G-chunk
        span expand + the visited PRE-FILTER (hash probe + compact) —
        in ONE program per group (the staged chain was two).  The
        filter body is the SAME probe + ``_filter_compact`` tail as
        ``_group_filter_hash``, so outputs are bit-identical; the
        pre-filter stays in place because it is what bounds the
        candidate working set to O(groups * cap_g) in this regime
        (the whole-level fusion deliberately does not apply here)."""
        cvs, cfs, cps, mult, ab, ovf = self._expand_span_impl(
            seg, slice_base, global_base, n_f
        )
        hit = hashstore.probe_impl(hslab, cvs.reshape(-1))
        gv, gf, gp, ovf_g = _filter_compact(
            hit, cvs.reshape(-1), cfs.reshape(-1), cps.reshape(-1),
            cap_g,
        )
        return gv, gf, gp, mult, ab, ovf, ovf_g

    # -- whole-level megakernel (engine/megakernel.py) ---------------------

    def _mega_level_ok(self, frontier, n_f) -> bool:
        """Is this level eligible for the fused whole-level program?

        Grouped ultra-deep levels keep the staged path: there the group
        filter's visited pre-probe bounds the candidate working set to
        O(groups * cap_g) before the level-wide buffers exist, which is
        the memory regime the grouping threshold was tuned for."""
        if not self.megakernel or not self.use_hashstore:
            return False
        if isinstance(frontier, list) or self.host_store is not None:
            return False
        n_chunks = -(-max(n_f, 1) // self.chunk)
        return n_chunks <= 16 * self.G

    def _mega_cap_out(self, n_f, level_sizes, max_depth, n_lanes,
                      floor):
        """The fused program's static new-frontier capacity: forecast
        when there is signal (the same 1.25 margin the prewarm ladder
        bakes in, so the AOT-compiled rung is the one requested), the
        early fan-out bound (growth ratios stay under 4 on this family)
        otherwise, quantized through the one frontier-capacity ladder.
        ``floor`` carries an exact redo bound (n_new from the control
        fetch); n_new can never exceed the candidate lane budget, so
        clamping at ``n_lanes`` makes the ladder's top rung overflow-
        free."""
        from .forecast import MIN_LEVELS, forecast_new_states

        est = 0
        if len(level_sizes) > MIN_LEVELS:
            fut = forecast_new_states(level_sizes, max_depth)
            if fut:
                # the 2x floor covers forecast undershoot through the
                # whole sub-2x-growth regime: dead output lanes cost
                # nothing (the materialize scan skips whole-dead
                # slices), a redo costs a full level
                est = max(
                    int(fut[0] * graft_forecast.cap_margin()) + 1,
                    2 * max(n_f, 1),
                )
        if not est:
            est = 4 * max(n_f, 1)
        est = max(est, floor)
        # the quantizer keeps every capacity a chunk multiple >= chunk;
        # clamping the ESTIMATE (not the result) at the lane budget
        # keeps the ladder's top rung overflow-free without ever
        # violating that invariant (the kernel pads when cap_out
        # exceeds the lane count — tiny cap_x configs).  The 4*chunk
        # floor mirrors the staged payload width (max(_pow2(n_new),
        # 4*chunk)): levels below it share ONE program shape instead of
        # stepping through every tiny rung — compile count, not memory,
        # is the cost down there (dead slices are cond-skipped)
        return max(
            self._frontier_cap(min(est, max(n_lanes, 1))),
            4 * self.chunk,
        )

    def _expand_level_mega(self, frontier, n_f, max_depth, level_sizes):
        """One fused device program + ONE ledgered fetch for a whole
        level.  Every overflow class re-enters the engine's existing
        grow-and-redo machinery against the ORIGINAL slab (the pending
        slab is discarded; the kernels are functional).  Returns None
        when the hash store degraded mid-level (the caller adopts the
        rebuilt sorted store from ``_degraded_visited`` and redoes the
        level staged), else the level-result dict; the pending slab
        lands in ``_hs_pending`` for the common adopt path."""
        mk = graft_megakernel
        n_f_dev = jnp.asarray(n_f, I64)
        out_floor = 0
        while True:
            cap_f = frontier.voted_for.shape[0]
            n_lanes = (cap_f // self.chunk) * self.cap_x
            cap_out = self._mega_cap_out(
                n_f, level_sizes, max_depth, n_lanes, out_floor
            )
            # re-resolve through the shared cache EVERY attempt: the
            # staleness guard compares this engine's budgets against
            # the cached creator's, so a creator that grew cap_x/cap_m
            # after we borrowed its program can never hand us a trace
            # against its mutated state (a dict hit costs nothing)
            self._mega_prog = graft_megakernel.level_program_for(
                self, self._mega_donate
            )
            # device-cost observatory: harvest the fused program's XLA
            # cost/memory ledger once per shape (compile-time only —
            # the lower+compile lands in the cache this call then hits)
            sieve_dev = self._sieve_operand()
            graft_devprof.profile_program(
                "megakernel.level", self._mega_prog,
                frontier, self.hstore.slab, n_f_dev, sieve_dev,
                statics=dict(cap_out=cap_out),
            )
            outs = self._mega_prog(
                frontier, self.hstore.slab, n_f_dev, sieve_dev,
                cap_out=cap_out,
            )
            if self._mega_donate:
                (new_frontier, slab2, ctrl_d, mult_d, fps_d, pidx_d,
                 slot_d, frontier) = outs
            else:
                (new_frontier, slab2, ctrl_d, mult_d, fps_d, pidx_d,
                 slot_d) = outs
            graft_sanitize.note_dispatch("megakernel.level")
            self._san_lanes = n_lanes
            # THE level fetch: control vector + trace/delta arrays in
            # one ledgered get, routed through the pipeline's deferred
            # path (transfer ledger, pipeline.window fault site and the
            # watchdog heartbeat all still see it)
            tail = graft_pipeline.DeferredFetch(
                self.pipeline, (ctrl_d, mult_d, fps_d, pidx_d, slot_d)
            )
            ctrl, mult_np, fps_np, pidx_np, slot_np = tail.get()
            ctrl = np.asarray(ctrl, np.int64)
            n_new = int(ctrl[mk.CTRL_N_NEW])
            if ctrl[mk.CTRL_OVF_SLAB]:
                self._hs_pending = None
                try:
                    how = self._slab_grow_or_demote(
                        len(level_sizes), expected=max(n_new, n_f)
                    )
                except Exception as e:  # graftlint: waive[GL003] — any
                    # grow failure (device OOM, injected fault) degrades
                    # to the sort path; the level redoes staged.  The
                    # degrade result MUST carry the pass-through parent:
                    # under donation the caller's frontier buffers were
                    # consumed by the dispatch above, and the staged
                    # redo would otherwise expand a deleted array
                    self._degraded_visited = self._degrade_hashstore(e)
                    return dict(degraded=True, parent=frontier)
                self._mega_stats["redo_slab"] += 1
                if how == "demoted":
                    # the tier form of the slab redo: demote, then redo
                    # against the drained slab (store/tiered.py)
                    self.tiered.stats["tier_redos"] += 1
                    graft_obs.redo("slab_tier")
                else:
                    graft_obs.grow("slab", self.hstore.cap)
                    graft_obs.redo("slab")
                continue
            if ctrl[mk.CTRL_OVF_X]:
                # a chunk overflowed its compaction budget: the same
                # half-step growth + re-jit as the staged redo
                self.cap_x = _cap_steps(self.cap_x + 1)
                self.cap_g = max(self.cap_g, self.G * self.cap_x // 2)
                self._jit_expand_programs()
                self._mega_stats["redo_x"] += 1
                graft_obs.grow("cap_x", self.cap_x)
                graft_obs.redo("cap_x")
                continue
            if n_new > cap_out:
                # exact capacity is now known — one redo lands it
                out_floor = n_new
                self._mega_stats["redo_out"] += 1
                graft_obs.grow("cap_out", n_new)
                graft_obs.redo("cap_out")
                continue
            if int(ctrl[mk.CTRL_ABORT]) < n_f:
                break  # violation: counts are final, nothing is adopted
            if ctrl[mk.CTRL_OVF_M] and n_new:
                if self.cap_m >= self.kern.uni.M:
                    raise RuntimeError(
                        "message-set width exceeds the whole universe — "
                        "corrupt payloads?"
                    )
                self.cap_m = min(self.cap_m + 32, self.kern.uni.M)
                print(
                    f"[engine] cap_m overflow: growing to {self.cap_m} "
                    "and redoing the fused level", file=sys.stderr,
                )
                frontier = self._widen_msg_ids(frontier)
                # re-resolve the fused program under the grown cap_m:
                # the widened shapes drive the retrace, but the shared
                # program cache keys on cap_m, so a stale binding from
                # another engine's key must not be retraced through
                self._jit_expand_programs()
                self._mega_stats["redo_m"] += 1
                graft_obs.grow("cap_m", self.cap_m)
                graft_obs.redo("cap_m")
                continue
            break
        self._hs_pending = slab2
        self._mega_stats["levels"] += 1
        return dict(
            n_new=n_new,
            abort_at=int(ctrl[mk.CTRL_ABORT]),
            bad_idx=int(ctrl[mk.CTRL_BAD]),
            slab_live=int(ctrl[mk.CTRL_SLAB_LIVE]),
            tier_hits=int(ctrl[mk.CTRL_TIER_HITS]),
            level_mult=np.asarray(mult_np, np.int64),
            new_frontier=new_frontier,
            parent=frontier,
            fps=np.asarray(fps_np, np.uint64)[:n_new],
            pidx=np.asarray(pidx_np)[:n_new].astype(np.int64),
            slot=np.asarray(slot_np)[:n_new].astype(np.int64),
            cap_out=cap_out,
        )

    def _mega_segs_ok(self, frontier, n_f) -> bool:
        """Is this level eligible for the SEGMENT-STREAMED fused path?

        The single-frontier fused program needs parent + children
        resident at once; a level past the paging budget streams the
        parent through the same program one uniform segment at a time
        instead (``_expand_level_mega_segs``), so a frontier that
        outgrows HBM still runs fused.  Eligible when the frontier is
        already a segment list, or a single device frontier whose
        level working set (parent + like-sized children) would bust
        TLA_RAFT_DEV_BYTES.  The audit path re-expands sampled rows
        from live parents and keeps the unsegmented routes."""
        if not self.megakernel or not self.use_hashstore:
            return False
        if self.host_store is not None or self.audit:
            return False
        # per-segment dispatch bound: same 16*G grouping threshold the
        # whole-level gate applies, against ONE segment's chunk count
        if -(-self.fseg_rows // self.chunk) > 16 * self.G:
            return False
        if isinstance(frontier, list):
            return True
        if not self.dev_budget or n_f <= self.fseg_rows:
            return False
        return 2 * self._tree_bytes(frontier) > self.dev_budget

    def _cut_frontier(self, frontier, n_f: int, depth: int) -> list:
        """Cut one device frontier into uniform host segments of
        ``fseg_rows`` (the streamed path's input form).  One D2H fetch;
        the device copy is released so the level's HBM peak is one
        segment + its children, not the whole parent."""
        L = self.fseg_rows
        host = {
            f: np.asarray(jax.device_get(getattr(frontier, f)))
            for f in Frontier._fields
        }
        del frontier
        n_seg = -(-max(n_f, 1) // L)
        segs = []
        for j in range(n_seg):
            flds = {}
            for f, v in host.items():
                part = v[j * L:(j + 1) * L]
                if part.shape[0] < L:
                    part = np.concatenate([
                        part,
                        np.zeros((L - part.shape[0],) + part.shape[1:],
                                 part.dtype),
                    ])
                flds[f] = part
            hs = _HostSeg(flds)
            self._fseg_admit(hs, depth)
            segs.append(hs)
        return segs

    def _expand_level_mega_segs(self, segs, n_f, max_depth, level_sizes,
                                depth):
        """Spilled-frontier streaming: one fused level, one PARENT
        SEGMENT at a time through ``_expand_level_mega``, the hash slab
        adopted between segments so later segments dedup against
        earlier segments' children on device.  The generation probe
        (sieve fast path + exact tier filter) runs PER SEGMENT here —
        the combined result reports ``tier_done`` so the level tail
        does not re-probe.  Children seal host-side (trimmed to live
        rows) and collapse back to one device frontier when the next
        level fits the budget, else re-segment through the pager.
        Counts are bit-identical to the unsegmented path: same kernels,
        same slab, same probes — only the dispatch granularity differs.

        Returns an ``_expand_level_mega``-shaped dict, or the degraded
        marker (with this level's committed children rolled back OUT of
        the degraded sorted store, so the staged redo re-finds them)."""
        tier = self._tier_active()
        L = self.fseg_rows
        # hold every parent on host for the degrade-redo (a device seg
        # would be consumed by the donated dispatch below)
        for j, s in enumerate(segs):
            if not isinstance(s, _HostSeg):
                segs[j] = self._seg_to_host(s, depth)
        fps_parts, pidx_parts, slot_parts = [], [], []
        kept_children = []  # per-seg host field dicts, live rows only
        mult_total = None
        total_new = 0
        n_done = 0
        slab_live = 0
        cap_out_last = 0
        abort_global = None
        bad_global = -1
        self._mega_stats["seg_levels"] = (
            self._mega_stats.get("seg_levels", 0) + 1
        )
        for j, seg in enumerate(segs):
            n_seg = min(seg.rows, n_f - n_done)
            if n_seg <= 0:
                break
            mres = self._expand_level_mega(
                self._seg_to_dev(seg), n_seg, max_depth, level_sizes
            )
            if mres.get("degraded"):
                if fps_parts:
                    # un-commit the streamed prefix's children from the
                    # degraded sorted store: the staged redo expands the
                    # WHOLE level and must re-find them as new (the
                    # re-heated generation members stay — they fold in
                    # through the generations and were visited before)
                    done = np.concatenate(fps_parts)
                    vb = np.asarray(jax.device_get(self._degraded_visited))
                    vb = np.setdiff1d(vb[vb != SENT], done)
                    pad = _cap4(len(vb) + 1) - len(vb)
                    self._degraded_visited = jnp.concatenate([
                        jnp.asarray(vb), jnp.full((pad,), SENT, U64),
                    ])
                return dict(degraded=True, parent=segs)
            self._mega_stats["seg_dispatches"] = (
                self._mega_stats.get("seg_dispatches", 0) + 1
            )
            # adopt NOW (kernel-fresh count): the next segment's probe
            # must see this segment's children as visited
            self.hstore.adopt(self._hs_pending, mres["n_new"])
            self._hs_pending = None
            slab_live = mres["slab_live"]
            cap_out_last = mres["cap_out"]
            mult_total = (
                mres["level_mult"] if mult_total is None
                else mult_total + mres["level_mult"]
            )
            n_new_seg = mres["n_new"]
            fps = np.asarray(mres["fps"], np.uint64)
            pidx = mres["pidx"] + n_done
            slot = mres["slot"]
            bad_seg = mres["bad_idx"]
            nf_new = mres["new_frontier"]
            if mres["abort_at"] < n_seg:
                # split-brain abort: counts are final, streaming stops
                # (same early-exit as the unsegmented path's break)
                abort_global = n_done + mres["abort_at"]
                break
            # per-segment tiered tail: sieve fast path, else the exact
            # generation probe + row compaction (store/tiered.py)
            if tier and n_new_seg:
                if (self._sieve_ready()
                        and mres.get("tier_hits", -1) == 0):
                    self.tiered.stats["sieve_skips"] = (
                        self.tiered.stats.get("sieve_skips", 0) + 1
                    )
                else:
                    n_keep, keep, nf_new = self._tier_filter_level(
                        depth, n_new_seg, fps, nf_new,
                        nf_new.voted_for.shape[0],
                    )
                    if keep is not None:
                        fps = fps[:n_new_seg][keep]
                        pidx = pidx[keep]
                        slot = slot[keep]
                        if bad_seg >= 0:
                            assert keep[bad_seg], (
                                "invariant violation attributed to an "
                                "already-visited (generation) row"
                            )
                            bad_seg = int(
                                np.count_nonzero(keep[:bad_seg])
                            )
                    n_new_seg = n_keep
            if bad_seg >= 0 and bad_global < 0:
                bad_global = total_new + bad_seg
            if n_new_seg:
                kept_children.append({
                    f: np.asarray(
                        jax.device_get(getattr(nf_new, f))
                    )[:n_new_seg]
                    for f in Frontier._fields
                })
                fps_parts.append(fps[:n_new_seg])
                pidx_parts.append(pidx[:n_new_seg])
                slot_parts.append(slot[:n_new_seg])
                total_new += n_new_seg
            del nf_new
            n_done += n_seg
        # queue the spilled parents' warm-tier artifacts for retirement
        # at the next level top (never here: a degrade in a LATER call
        # cannot reach back past the committed level, but this one's
        # staged redo still can until the commit lands)
        self._fseg_retire.extend(
            s.token for s in segs
            if isinstance(s, _HostSeg) and s.token is not None
        )
        empty_u64 = np.empty(0, np.uint64)
        empty_i64 = np.empty(0, np.int64)
        out = dict(
            n_new=total_new,
            abort_at=n_f if abort_global is None else abort_global,
            bad_idx=bad_global,
            slab_live=slab_live,
            tier_hits=0,
            tier_done=True,
            level_mult=(
                mult_total if mult_total is not None
                else np.zeros(self.K, np.int64)
            ),
            parent=segs,
            fps=(
                np.concatenate(fps_parts) if fps_parts else empty_u64
            ),
            pidx=(
                np.concatenate(pidx_parts).astype(np.int64)
                if pidx_parts else empty_i64
            ),
            slot=(
                np.concatenate(slot_parts).astype(np.int64)
                if slot_parts else empty_i64
            ),
            cap_out=cap_out_last,
        )
        if abort_global is not None or total_new == 0:
            out["new_frontier"] = None  # never read on abort/fixpoint
            return out
        # seal the combined child frontier: back to ONE device frontier
        # while the next level's working set fits, else stay segmented
        # (uniform L-row host segments, pager-admitted past the budget)
        row_b = sum(
            v.dtype.itemsize * int(np.prod(v.shape[1:], dtype=np.int64))
            for v in kept_children[0].values()
        )
        cap_f = self._frontier_cap(total_new)
        collapse = (
            total_new <= L
            or not self.dev_budget
            or 2 * cap_f * row_b <= self.dev_budget
        )
        cols = {
            f: np.concatenate([c[f] for c in kept_children])
            for f in Frontier._fields
        }
        kept_children = None
        if collapse:
            pad = cap_f - total_new
            out["new_frontier"] = Frontier(**{
                f: jnp.asarray(np.concatenate([
                    v, np.zeros((pad,) + v.shape[1:], v.dtype),
                ]))
                for f, v in cols.items()
            })
            return out
        n_seg_d = -(-total_new // L)
        child_segs = []
        for j in range(n_seg_d):
            flds = {}
            for f, v in cols.items():
                part = v[j * L:(j + 1) * L]
                if part.shape[0] < L:
                    part = np.concatenate([
                        part,
                        np.zeros((L - part.shape[0],) + part.shape[1:],
                                 part.dtype),
                    ])
                flds[f] = part
            hs = _HostSeg(flds)
            self._fseg_admit(hs, depth + 1)
            child_segs.append(hs)
        out["new_frontier"] = child_segs
        return out

    # -- multi-level resident supersteps (engine/superstep.py) -------------

    def _superstep_span_at(self, max_depth, depth) -> int:
        """The span this superstep may cover: the configured span,
        clamped so the resident loop never expands past --max-depth
        (the per-level loop breaks BEFORE expanding at the cap).
        Under spill the full span holds only while the SIEVE covers the
        demoted generations: a level with zero in-program sieve hits
        provably has no generation revisits and commits in-window, and
        a level WITH hits stops on FLAG_TIER for the exact host
        correction (ops/sieve.py).  With the sieve off the PR 12
        stand-down applies — span 1, because a resident window cannot
        host-correct a mid-span level's generation revisits (every
        level after it would have expanded stale rows)."""
        span = self.superstep_span
        if self._tier_active() and not self._sieve_ready():
            return 1
        if max_depth is not None:
            span = min(span, max_depth - depth)
        return span

    def _superstep_shapes(self, fut, span, n_rows, cap_cur):
        """One superstep window's static ``(cap_f, ring)`` — the ONE
        copy of the shape math shared by ``_run_superstep`` and the
        prewarm walk, so the AOT ``("sstep", ...)`` keys always match
        the shapes the runtime requests (a desynchronized margin would
        compile dead programs and pay every window's XLA compile
        synchronously)."""
        if fut:
            # same margins as the per-level _mega_cap_out, applied to
            # the span max: one static seat for every level in flight
            est = max(
                int(max(fut) * graft_forecast.cap_margin()) + 1,
                2 * max(n_rows, 1),
            )
        else:
            est = 4 * max(n_rows, 1)  # early fan-out bound
        cap_f = max(
            self._frontier_cap(est), 4 * self.chunk, cap_cur,
        )
        # resident levels must stay inside the grouping threshold: a
        # frontier the per-level loop would route grouped-staged
        # (n_chunks > 16*G, _mega_level_ok) must never be expanded
        # resident mid-span — cap the seats so such a level overflows
        # FLAG_OVF_OUT (a clean stop) and re-enters the per-level
        # routing, which sends it grouped-staged like the level loop
        cap_f = min(cap_f, max(16 * self.G * self.chunk,
                               4 * self.chunk, cap_cur))
        ring = graft_superstep.ring_capacity(fut, span, cap_f, _pow2)
        return cap_f, ring

    def _run_superstep(self, frontier, n_f, max_depth, depth,
                       level_sizes):
        """ONE device dispatch + ONE ledgered ring fetch for up to N
        consecutive levels.  Returns the committed per-level records
        (the same delta/trace record shape the per-level megakernel
        fetch produces), the carried frontier (the stopped level's
        parent on a STOP), the pending slab and the stop reason; the
        caller adopts the prefix and routes any stopped level through
        the per-level machinery."""
        from .forecast import forecast_new_states

        ss = graft_superstep
        # span: the EFFECTIVE level bound this window may cover (the
        # --max-depth clamp) — a traced operand of the program, so one
        # compiled span-N driver serves every remainder
        span = self._superstep_span_at(max_depth, depth)
        cap_cur = frontier.voted_for.shape[0]
        fut = forecast_new_states(level_sizes, max_depth)[:span]
        # shape statics always use the CONFIGURED span (the clamped
        # span is only the traced lvl_cap operand below), so a
        # --max-depth remainder window reuses the span-N program AND
        # the prewarmed ("sstep", ...) ring/cap_f rungs instead of
        # minting a one-off smaller-ring compile
        cap_f, ring = self._superstep_shapes(
            fut, self.superstep_span, n_f, cap_cur
        )
        # slab headroom for the WHOLE span: a superstep inserts up to
        # the sum of its levels' new states before the host can grow
        # the store, so the between-superstep reserve must budget the
        # span's forecast inserts (margined like the ring rungs), not
        # one level's — otherwise every growing span stops on a probe-
        # window fill and replays per-level, eroding the amortization.
        # reserve() grows to FIT (a single doubling can be short of a
        # 4-level span on a >2x-growth run).
        if fut:
            m = graft_forecast.cap_margin()
            ins_bound = sum(
                min(int(f * m) + 1, cap_f) for f in fut
            )
        else:
            ins_bound = 2 * max(n_f, 1)
        try:
            # budget-clamped under the tiered store: a span whose
            # forecast inserts exceed the device budget stops on
            # FLAG_OVF_SLAB and the stop handler demotes
            self._tier_reserve(
                self.hstore.count + max(ins_bound, 2 * max(n_f, 1))
            )
        except Exception as e:  # graftlint: waive[GL003] — grow
            # failure degrades to the sort path like every other
            # grow site; the caller redoes the level staged
            self._degraded_visited = self._degrade_hashstore(e)
            return dict(degraded=True, frontier=frontier)
        prog = ss.superstep_program_for(
            self, self.superstep_span, self._mega_donate
        )
        # cap_cur (the input frontier's capacity) is part of the traced
        # shape via the in-program padding — a changed input rung is a
        # declared shape event like every other capacity step
        sieve_dev = self._sieve_operand()
        skey = (cap_cur, cap_f, ring, self.hstore.cap,
                self.cap_x, self.cap_m, int(sieve_dev.shape[0]))
        if graft_sanitize.tracking() and skey != self._ss_sig:
            graft_sanitize.note_shape_event(f"superstep shapes {skey}")
            self._ss_sig = skey
        graft_sanitize.superstep_begin()
        # live-HBM gauge: the trace-spool ring (fps u64 + pidx u32 +
        # slot u16/u32 per entry) is the superstep's one extra
        # long-lived buffer
        graft_obs.buffer(
            "ring", ring * (12 + (2 if self.K <= 0xFFFF else 4))
        )
        n_f_dev = jnp.asarray(n_f, I64)
        span_dev = jnp.asarray(span, I64)
        # device-cost observatory (see the megakernel site)
        graft_devprof.profile_program(
            "superstep.levels", prog,
            frontier, self.hstore.slab, n_f_dev, span_dev, sieve_dev,
            statics=dict(cap_f=cap_f, ring=ring),
        )
        outs = prog(
            frontier, self.hstore.slab, n_f_dev, span_dev, sieve_dev,
            cap_f=cap_f, ring=ring,
        )
        (fr_out, slab_out, ctrl_d, mn_d, mm_d, rf_d, rp_d,
         rs_d) = outs
        graft_sanitize.note_dispatch("superstep.levels")
        self._san_lanes = (cap_f // self.chunk) * self.cap_x
        # THE superstep fetch: control vector + per-level meta + the
        # trace/delta ring in one ledgered get through the pipeline's
        # deferred path (transfer ledger, pipeline.window fault site
        # and the watchdog heartbeat all still see it)
        tail = graft_pipeline.DeferredFetch(
            self.pipeline, (ctrl_d, mn_d, mm_d, rf_d, rp_d, rs_d)
        )
        ctrl, mn, mm, rf, rp, rs = tail.get()
        recs, reason, n_f_out, slab_live, flags = ss.unpack_ring(
            ctrl, mn, mm, rf, rp, rs
        )
        graft_sanitize.superstep_tick(len(recs))
        self._ss_stats["supersteps"] += 1
        self._ss_stats["levels"] += len(recs)
        if reason == "stop":
            self._ss_stats["stops"] += 1
        elif reason == "ring":
            self._ss_stats["ring_stops"] += 1
        return dict(
            recs=recs,
            frontier=fr_out,
            slab=slab_out,
            n_total=sum(r["n_new"] for r in recs),
            n_f=n_f_out,
            reason=reason,
            slab_live=slab_live,
            flags=flags,
            cap_f=cap_f,
            span=span,
        )

    def _inv_scan_impl(self, children: RaftState, n_valid):
        """All configured invariants over a level; (first_bad_idx|-1)."""
        N = children.voted_for.shape[0]
        in_range = jnp.arange(N, dtype=I64) < n_valid
        bad = jnp.zeros(N, bool)
        for _name, fn in self.inv_fns:
            bad = bad | (~fn(self.cfg, children, self.kern.tables) & in_range)
        return jnp.where(bad.any(), jnp.argmax(bad).astype(I64), -1)

    def _action_counts(self, mult_per_slot: np.ndarray) -> dict:
        """Fold per-slot fired-transition counts to action names (the TLC
        -coverage analog; UpdateTerm's two slot families sum together)."""
        out: dict[str, int] = {}
        fam = self.kern.slot_family
        for fi, (name, _fn, _c) in enumerate(self.kern.families):
            out[name] = out.get(name, 0) + int(mult_per_slot[fam == fi].sum())
        return {k: v for k, v in out.items() if v}

    def _bad_invariant_name(self, children: RaftState, idx: int) -> str:
        """Identify which invariant a known-bad state violates (cold path)."""
        one = jax.tree.map(lambda x: x[idx : idx + 1], children)
        for name, fn in self.inv_fns:
            ok = jax.device_get(fn(self.cfg, one, self.kern.tables))
            if not bool(np.asarray(ok)[0]):
                return name
        return self.inv_fns[0][0]

    # -- trace reconstruction ---------------------------------------------

    def _trace(self, levels: list[tuple[np.ndarray, np.ndarray]], level: int, idx: int):
        """Walk (parent, slot) spills back to Init, then replay forward.

        levels[d] = (pidx, slot) arrays for the states created at depth d+1;
        ``idx`` indexes into level ``level``'s arrays (level 0 = init).
        """
        chain = []  # slots to apply, init -> violation
        d, j = level, idx
        while d > 0:
            pidx, slots = levels[d - 1]
            chain.append(int(slots[j]))
            j = int(pidx[j])
            d -= 1
        chain.reverse()
        st = init_batch(self.cfg, 1)
        out = [("Init", to_oracle(self.cfg, st)[0])]
        for slot in chain:
            st = self.kern.materialize(st, jnp.asarray([slot], I64))
            fam = int(self.kern.slot_family[slot])
            name = self.kern.families[fam][0]
            server = int(self.kern.slot_coords[slot, 0]) + 1
            out.append((f"{name}({server})", to_oracle(self.cfg, st)[0]))
        return out

    # -- checkpoint / resume (TLC's states/ metadir + -recover) ------------
    #
    # Two formats:
    #
    # * **delta log** (the default; ``checkpoint_dir`` is a directory of
    #   ``delta_####.npz`` files): each BFS level appends only its
    #   (parent, slot) payloads and new canonical fingerprints —
    #   ~14 B/state, all of which the level already fetched to the host
    #   for trace reconstruction (plus the fps).  Resume REPLAYS the
    #   materialize pass level by level from Init — minutes of device
    #   compute instead of a multi-GB frontier fetch.  The monolith
    #   format's full-frontier ``device_get`` (~2.7 GB at a 6M-state
    #   frontier) repeatedly crashed the tunneled device worker.
    #
    # * **monolith** (``latest.npz``, back-compat): full frontier +
    #   visited store in one file; O(1) resume but O(frontier) fetch.

# -- end-to-end integrity audit (resilience/integrity.py) --------------

    def _flip_frontier_row(self, frontier):
        """Apply the ``tensor.flip`` fault: XOR bit 0 of the first live
        frontier row's ``current_term[0]`` on device.  Row 0 is always
        live (frontier rows compact to a prefix) and is always in the
        audit sample (integrity.audit_indices) — the injected flip is
        deterministically catchable."""

        def flip_first(x):
            return x.at[0, 0].set(x[0, 0] ^ jnp.asarray(1, x.dtype))

        if isinstance(frontier, list):
            seg = frontier[0]
            if isinstance(seg, _HostSeg):
                f = dict(seg.fields)
                ct = np.array(f["current_term"], copy=True)
                ct[0, 0] ^= 1
                f["current_term"] = ct
                return [_HostSeg(f)] + frontier[1:]
            return [
                seg._replace(current_term=flip_first(seg.current_term))
            ] + frontier[1:]
        return frontier._replace(
            current_term=flip_first(frontier.current_term)
        )

    def _audit_impl_rows(self, par_rows: Frontier, kid_rows: Frontier,
                         slots):
        """The audit cross-check over pre-gathered rows: (1) the legacy
        guard must admit the recorded slot, (2) legacy materialize +
        fingerprint must equal the recorded fp, (3) the frontier row as
        materialized on device must re-fingerprint to the recorded fp
        (the bit-flip catch).  Returns (guard_ok, fv_legacy, fv_now)."""
        parents = self._inflate(par_rows)
        kids_now = self._inflate(kid_rows)
        valid, _mult, _ab = self.kern.expand_guards_legacy(parents)
        guard_ok = valid[jnp.arange(slots.shape[0]), slots]
        kids_legacy = self.kern.materialize_legacy(parents, slots)
        fv_leg, _ff_leg = self._fp_states(kids_legacy)
        fv_now, _ff_now = self._fp_states(kids_now)
        return guard_ok, fv_leg.astype(U64), fv_now.astype(U64)

    def _audit_impl(self, parents_f, new_frontier, pidx, idx, slots):
        par_rows = jax.tree.map(lambda x: x[pidx], parents_f)
        kid_rows = jax.tree.map(lambda x: x[idx], new_frontier)
        return self._audit_impl_rows(par_rows, kid_rows, slots)

    @functools.cached_property
    def _audit_prog(self):
        return jax.jit(self._audit_impl)

    def _gather_frontier_rows(self, frontier, idx_np) -> Frontier:
        """Sampled rows of a frontier (tree or segment list) as one
        small device-resident Frontier batch."""
        if isinstance(frontier, list):
            L0 = _seg_rows(frontier[0])
            parts = []
            for i in idx_np:
                si, off = divmod(int(i), L0)
                seg = frontier[si]
                if isinstance(seg, _HostSeg):
                    parts.append(Frontier(**{
                        f: jnp.asarray(v[off: off + 1])
                        for f, v in seg.fields.items()
                    }))
                else:
                    parts.append(
                        jax.tree.map(lambda x: x[off: off + 1], seg)
                    )
            return jax.tree.map(lambda *xs: jnp.concatenate(xs), *parts)
        ii = jnp.asarray(np.asarray(idx_np, np.int64))
        return jax.tree.map(lambda x: x[ii], frontier)

    def _audit_level(self, parents_f, new_frontier, pidx_np, slot_np,
                     level_fps, n_new, depth):
        """Re-expand a deterministic sample through the legacy kernels
        and cross-check against the hot path; returns the list of
        problem strings (empty = level verified)."""
        idx = resilience.integrity.audit_indices(n_new, self.audit)
        if idx.size == 0:
            return []
        self.audit_stats["levels"] += 1
        self.audit_stats["sampled"] += int(idx.size)
        # pad the sample to the fixed --audit width (repeating row 0) so
        # the audit programs compile once per frontier shape, not once
        # per distinct sample size; comparisons only read the live lanes
        n_live = int(idx.size)
        if n_live < self.audit:
            idx = np.concatenate([
                idx, np.full(self.audit - n_live, idx[0], np.int64)
            ])
        # recorded level fingerprints at the sampled rows (host numpy on
        # the external-store path; a tiny device gather otherwise)
        if isinstance(level_fps, np.ndarray):
            ref = level_fps[idx].astype(np.uint64)
        else:
            # graftlint: waive[GL006] — audit-mode-only sampled fetch
            ref = np.asarray(jax.device_get(
                level_fps[jnp.asarray(idx)]
            )).astype(np.uint64)
        pidx_s = jnp.asarray(np.asarray(pidx_np)[idx], I64)
        slots = jnp.asarray(np.asarray(slot_np)[idx], I64)
        idx_d = jnp.asarray(idx, I64)
        if not isinstance(parents_f, list) and not isinstance(
            new_frontier, list
        ):
            # device-resident frontiers: the whole cross-check — row
            # gathers, inflate, legacy guards/materialize, fingerprints
            # — runs as ONE jitted program per (parent cap, child cap)
            # shape pair, so audit overhead is two small dispatches +
            # one fetch per level, not ~30 eager ops
            key = (
                parents_f.voted_for.shape[0],
                new_frontier.voted_for.shape[0], self.audit,
            )
            if key not in self._audit_keys:
                self._audit_keys.add(key)
                graft_sanitize.note_shape_event(f"audit program {key}")
            guard_ok, fv_leg, fv_now = self._audit_prog(
                parents_f, new_frontier, pidx_s, idx_d, slots
            )
        else:
            par_rows = self._gather_frontier_rows(
                parents_f, np.asarray(pidx_np)[idx]
            )
            kid_rows = self._gather_frontier_rows(new_frontier, idx)
            guard_ok, fv_leg, fv_now = self._audit_impl_rows(
                par_rows, kid_rows, slots
            )
        # graftlint: waive[GL006] — audit-mode-only verdict fetch
        guard_np, leg_np, now_np = jax.device_get((
            guard_ok, fv_leg.astype(U64), fv_now.astype(U64)
        ))
        guard_np = np.asarray(guard_np, bool)
        leg_np = np.asarray(leg_np, np.uint64)
        now_np = np.asarray(now_np, np.uint64)
        problems = []
        for j, row in enumerate(idx[:n_live]):
            if not guard_np[j]:
                problems.append(
                    f"row {int(row)}: legacy guard refutes recorded "
                    f"slot {int(np.asarray(slot_np)[row])}"
                )
            if leg_np[j] != ref[j]:
                problems.append(
                    f"row {int(row)}: legacy re-expansion fp "
                    f"{leg_np[j]:#x} != recorded {ref[j]:#x}"
                )
            if now_np[j] != ref[j]:
                problems.append(
                    f"row {int(row)}: materialized frontier row "
                    f"re-fingerprints to {now_np[j]:#x} != recorded "
                    f"{ref[j]:#x} (corrupted frontier tensor)"
                )
        if problems:
            self.audit_stats["mismatches"] += len(problems)
            for p in problems[:8]:
                print(f"[integrity] audit level {depth + 1}: {p}",
                      file=sys.stderr)
        return problems

    def _audit_rewind(self, problems, depth, max_depth, checkpoint_dir,
                      checkpoint_every):
        """Quarantine the mismatched level and rewind to the last
        committed checkpoint; fail-stop after ``audit_retries``
        reproducible strikes.

        The mismatched level never reached the delta log (the audit
        runs before the commit), so the rewind is a plain self-resume:
        the replay re-materializes every level from the durable
        (parent, slot) decisions — tensors are recomputed, so the
        corruption cannot survive the rewind unless it is
        deterministic, which is exactly what the strike budget
        detects.  "Reproducible" means AT THE SAME LEVEL: strikes
        count per mismatch depth and reset when a different level
        mismatches, so independent transient flips hours apart never
        sum into a fake fail-stop; a hard cap on TOTAL rewinds
        (4x the budget) still bounds a corruption source that hops
        between levels."""
        if self._audit_strike_depth == depth:
            self._audit_strikes += 1
        else:
            self._audit_strikes = 1
            self._audit_strike_depth = depth
        strikes = self._audit_strikes
        if self.audit_stats["rewinds"] >= 4 * self.audit_retries:
            raise resilience.integrity.AuditFailStop(
                f"audit mismatches forced {self.audit_stats['rewinds']} "
                f"rewinds in one run (cap {4 * self.audit_retries}): "
                "pervasive corruption — fail-stop; latest problem: "
                + problems[0]
            )
        if strikes >= self.audit_retries:
            raise resilience.integrity.AuditFailStop(
                f"audit mismatch at level {depth + 1} reproduced "
                f"{strikes} time(s) (budget {self.audit_retries}): "
                "deterministic corruption — fail-stop; first problem: "
                + problems[0]
            )
        import glob as _glob

        can_resume = bool(checkpoint_dir and checkpoint_every)
        has_records = can_resume and bool(
            _glob.glob(os.path.join(checkpoint_dir, "delta_*.npz"))
            or os.path.exists(os.path.join(checkpoint_dir, "base.npz"))
        )
        if not can_resume:
            raise resilience.integrity.AuditFailStop(
                f"audit mismatch at level {depth + 1} with no "
                "checkpoint directory to rewind to — fail-stop; first "
                "problem: " + problems[0]
            )
        self.audit_stats["rewinds"] += 1
        print(
            f"[integrity] quarantining level {depth + 1} and rewinding "
            f"to the last committed checkpoint (strike {strikes}/"
            f"{self.audit_retries})",
            file=sys.stderr,
        )
        # the in-memory run state (visited slab/store, frontier) is
        # polluted by the quarantined level — drop it all and rebuild
        # from the durable log
        self.hstore = None
        self._hs_pending = None
        if self.host_store is not None and not has_records:
            # a fresh restart re-inserts from Init; pre-crash inserts
            # would silently mark reachable states visited
            self.host_store.clear()
        return self._run(
            max_depth=max_depth,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            resume_from=checkpoint_dir if has_records else None,
        )

    def _save_delta(self, ckdir, depth, pidx_np, slot_np, fps_np,
                    level_mult, n_new):
        # slot ids must round-trip the log exactly; K grows with the
        # S/T/L/V bounds (3,696 at S=7), so widen past the u16 range
        # rather than silently wrapping (the loader reads either width)
        slot_dt = np.uint16 if self.K <= 0xFFFF else np.uint32
        resilience.commit_npz(
            ckdir,
            f"delta_{depth:04d}.npz",
            dict(
                pidx=pidx_np.astype(np.uint32),
                slot=slot_np.astype(slot_dt),
                fps=fps_np.astype(np.uint64),
                mult=level_mult.astype(np.int64),
                # meta[2] (fp definition: 0 = min-over-P fold, 1 = orbit
                # canonical-relabel) guards resume: the two definitions
                # produce different fingerprint VALUES and must never mix
                # in one visited store.  Old two-element logs read as 0.
                meta=np.asarray([depth, n_new, int(self.orbit)], np.int64),
            ),
            kind="delta",
            depth=depth,
            run_fp=self._run_fp,
        )

    def _materialize_payload_slices(self, frontier, new_payload, n_new):
        """Run _mat_slice over every survivor slice; returns the parts.

        (The device-store path's builder: parts + one concat.  The
        external-store path uses the segment-streamed builders instead —
        _materialize_segs / _materialize_fallback_segs — whose transients
        are segment-bounded.)
        """
        # 8x-chunk slices: with the sorted-insert deflate the per-slice
        # compute is light enough that slice count (dispatch + drain
        # round-trips on the tunneled backend) is the next cost; 64k rows
        # x ~240 B keeps the in-flight working set at ~16 MB/slice
        sl = min(8 * self.chunk, new_payload.shape[0])
        n_slices = -(-n_new // sl)
        child_parts, bad_ds, ovf_ds = [], [], []
        for si in range(n_slices):
            take = min(sl, n_new - si * sl)
            pay_slice = jax.lax.dynamic_slice_in_dim(new_payload, si * sl, sl)
            ch_f, bad_d, ovf_d = self._mat_slice(
                frontier, pay_slice, jnp.asarray(take, I64)
            )
            graft_sanitize.note_dispatch("device.mat")
            child_parts.append(ch_f)
            bad_ds.append(bad_d)
            ovf_ds.append(ovf_d)
            # bound the dispatch queue; at deep-sweep slice widths every
            # in-flight slice pins GB-scale working sets, so drain one at
            # a time there
            if sl >= 16384 or si % 4 == 3:
                jax.device_get(bad_d)
        return child_parts, bad_ds, ovf_ds, n_slices, sl

    def _frontier_cap(self, n: int) -> int:
        """Frontier capacity for n states: half-step quantized, but ONLY
        when the step divides evenly into chunks — the chunked expand
        carves the frontier with dynamic slices at chunk strides, and a
        non-multiple capacity would silently clamp the last slice onto
        re-read rows (wrong parents)."""
        c = _cap_steps(n)
        if c % self.chunk:
            c = _pow2(n)
        c = max(c, self.chunk)
        if self._presize_fcap > c:
            # forecast floor: pow2 and >= chunk, so still a chunk multiple
            c = self._presize_fcap
        return c

    def _hbm_note(self, frontier, level_sizes, max_depth,
                  depth) -> None:
        """Live-HBM gauge + predictive pre-OOM forecast (loop-top, one
        per level, telemetry-gated to a single global read when off).

        Registers the frontier's live device bytes beside the slab the
        hash store already registers; under a device budget
        (``--dev-bytes`` hot-tier, or the ``TLA_RAFT_DEV_BYTES`` paging
        budget) it also forecasts the NEXT level's working set — the
        slab after its forecast inserts, its quantized frontier, and
        the expand lane transient — and emits ``pre_oom_forecast``
        when that would bust the budget: the predictive twin of the
        reactive overflow-redo (the tier/pager still handles the real
        crossing; this event is the early warning --progress and the
        service can act on)."""
        if graft_obs.current() is None:
            return
        nbytes = 0
        for x in jax.tree.leaves(frontier):
            it = getattr(getattr(x, "dtype", None), "itemsize", None)
            nbytes += int(getattr(x, "size", 0)) * int(it or 0)
        graft_obs.buffer("frontier", nbytes)
        budget = (
            self.tiered.dev_bytes if self.tiered is not None
            else (self.store_bytes or self.dev_budget)
        )
        if not budget:
            return
        if not getattr(self, "_hbm_budget_noted", False):
            self._hbm_budget_noted = True
            graft_obs.hbm_budget(budget)
        cap_f = getattr(
            getattr(frontier, "voted_for", None), "shape", (0,)
        )[0]
        if not cap_f or getattr(self, "_pre_oom_level", None) == depth:
            return  # segmented external frontier (already paged) / dup
        fut = graft_forecast.forecast_new_states(
            level_sizes, max_depth
        )[:1]
        if not fut:
            return
        nrows = int(fut[0])
        row_b = max(nbytes // max(cap_f, 1), 1)
        cap_next = self._frontier_cap(
            int(nrows * graft_forecast.cap_margin()) + 1
        )
        slab_b = 0
        if self.use_hashstore and self.hstore is not None:
            want = hashstore.slab_rows(self.hstore.count + nrows)
            if self.tiered is not None:
                # the tier demotes rather than grow past the budget:
                # charge the hot slab at its budget-clamped size
                want = min(want, max(
                    hashstore.slab_rows(
                        self.tiered.max_hot_entries or 1
                    ), hashstore.MIN_CAP,
                ))
            slab_b = want * 8
        # expand transient: cv/cf u64 + cp i64 per candidate lane
        lanes_b = (cap_next // self.chunk) * self.cap_x * 24
        # the spill sieve's device image joins the forecast the moment
        # tiering is configured: it allocates at FULL size on the first
        # demotion, so the headroom must exist BEFORE spill starts
        sieve_b = (
            graft_forecast.sieve_bytes(self.tiered.dev_bytes)
            if self.tiered is not None and self.sieve_enabled else 0
        )
        need = slab_b + cap_next * row_b + lanes_b + sieve_b
        if need > budget:
            self._pre_oom_level = depth
            graft_obs.pre_oom(
                depth + 1, need, budget,
                slab=slab_b, frontier=cap_next * row_b,
                lanes=lanes_b, sieve=sieve_b, rows=nrows,
            )

    def _update_presize(self, level_sizes, distinct, max_depth, frontier):
        """Ratchet the forecast capacity floors (see __init__).

        Called once per level; floors only grow.  Frontier bytes are
        clamped (TLA_RAFT_PRESIZE_BYTES, default 4 GB) so a noisy early
        forecast cannot reserve more HBM than the run could use."""
        from .forecast import PRESIZE_HORIZON, forecast_new_states

        fut = forecast_new_states(level_sizes, max_depth)[:PRESIZE_HORIZON]
        if not fut:
            return
        peak = max(fut)
        budget = int(float(
            os.environ.get("TLA_RAFT_PRESIZE_BYTES", "4e9")
        ))
        want_f = max(
            _pow2(int(peak * graft_forecast.cap_margin()) + 1), self.chunk
        )
        if not isinstance(frontier, list):
            row_b = sum(
                int(np.prod(x.shape[1:])) * x.dtype.itemsize
                for x in jax.tree.leaves(frontier)
            )
            while want_f > self.chunk and want_f * row_b > budget:
                want_f //= 2
        self._presize_fcap = max(self._presize_fcap, want_f)
        self._presize_vcap = max(
            self._presize_vcap,
            min(_cap4(distinct + sum(fut) + 1), _cap4(budget // 8)),
        )
        # hash-slab sizing wants the ENTRY forecast, not a pow4 array
        # width (the slab layer applies its own load-factor/pow2 quantum;
        # 8 B/slot at <=1/2 load => entries <= budget/16)
        self._presize_entries = max(
            getattr(self, "_presize_entries", 0),
            min(distinct + sum(fut), budget // 16),
        )
        self._presize_merge = max(
            self._presize_merge,
            min(_pow2(int(peak * 1.05) + 1), _pow2(budget // 16)),
        )

    # -- forecast-driven AOT prewarm (engine/pipeline.Prewarmer) ----------

    def _frontier_struct(self, template, cap: int):
        """ShapeDtypeStruct tree of a ``cap``-row frontier, field shapes
        and dtypes taken from a live frontier/segment (``template``)."""
        if isinstance(template, list):
            template = template[0]
        if isinstance(template, _HostSeg):
            fields = template.fields
            return Frontier(**{
                f: jax.ShapeDtypeStruct((cap,) + v.shape[1:], v.dtype)
                for f, v in fields.items()
            })
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct((cap,) + x.shape[1:], x.dtype),
            template,
        )

    def _prewarm_plan(self, level_sizes, distinct, max_depth, frontier,
                      visited):
        """(key, thunk) pairs compiling the deep-level program set at the
        forecast shape ladder (``jit(...).lower(...).compile()``).

        The thunks never dispatch a device program (inputs are avals),
        so running them on the Prewarmer's background thread does not
        break the all-dispatch-on-the-main-thread rule; the payoff
        routes through the persistent compilation cache
        (platform.setup_jax), which a supervised relaunch also reads.
        Shapes come from the SAME quantizers the level loop uses
        (_frontier_cap/_cap_steps/_cap4/slab_rows), so a sharp forecast
        prewarms exactly the programs the deep levels will request."""
        from .forecast import pow2_ladder, shape_plan

        rows = shape_plan(level_sizes, max_depth)
        if not rows:
            return []
        plan: list = []
        s_i64 = jax.ShapeDtypeStruct((), jnp.int64)
        # the fused programs' sieve operand at its CURRENT shape (the
        # 1-word sentinel pre-spill; the full filter image after — it
        # is allocated at final size on first demotion, so the shape
        # the prewarm keys on is the shape the runtime will request)
        sv_struct = jax.ShapeDtypeStruct(
            (int(self._sieve_operand().shape[0]),), jnp.uint64
        )
        final = distinct + sum(rows)

        def u64(n):
            return jax.ShapeDtypeStruct((n,), jnp.uint64)

        def i64(n):
            return jax.ShapeDtypeStruct((n,), jnp.int64)

        def slab_ladder():
            # the ONE slab-capacity ladder both the fused and staged
            # hashstore plans rung through (drift here would split
            # their compiled shapes)
            return pow2_ladder(
                self.hstore.cap // 2, hashstore.slab_rows(final)
            ) or [self.hstore.cap]

        # forecast rows the FUSED program will serve: the prefix whose
        # levels stay under the grouping threshold (the level loop
        # routes bigger levels to the staged grouped path, so those
        # rows need the staged plan below instead)
        mega_rows = 0
        if self._mega_level_ok(frontier, max(int(rows[0]), 1)):
            prev = max(int(level_sizes[-1]), 1)
            for r in rows:
                if -(-max(prev, 1) // self.chunk) > 16 * self.G:
                    break
                mega_rows += 1
                prev = int(r)
        if mega_rows and self.superstep_span > 1:
            # superstep path: the multi-level driver's shape ladder
            # REPLACES the per-level fused keys (those programs are
            # dead while supersteps are on — compiling them would pay
            # compile time for nothing).  The walk mirrors
            # _run_superstep exactly: span-sized windows over the raw
            # forecast, one static cap_f per window (max rung, same
            # margins), the ring chained from the window's cap_out
            # sequence, the input rung chained from the previous
            # window's cap_f.
            from .forecast import forecast_new_states

            span = self.superstep_span
            scaps = slab_ladder()
            fut_all = forecast_new_states(level_sizes, max_depth)
            prev_cap = frontier.voted_for.shape[0]
            prev_rows = max(int(level_sizes[-1]), 1)
            s_i64_n = jax.ShapeDtypeStruct((), jnp.int64)
            i = 0
            while i < mega_rows:
                fut_w = fut_all[i:i + span]
                if not fut_w:
                    break
                cap_f, ring = self._superstep_shapes(
                    fut_w, span, prev_rows, prev_cap
                )
                prog = graft_superstep.superstep_program_for(
                    self, span, self._mega_donate
                )
                fs = self._frontier_struct(frontier, prev_cap)
                for sc in scaps:
                    plan.append((
                        ("sstep", prev_cap, cap_f, ring, sc, span,
                         self.cap_x, self.cap_m, self.use_mxu,
                         sv_struct.shape[0]),
                        lambda fs=fs, sc=sc, cap_f=cap_f, ring=ring,
                               prog=prog:
                            prog.lower(
                                fs, u64(sc), s_i64_n, s_i64_n,
                                sv_struct, cap_f=cap_f, ring=ring,
                            ).compile(),
                    ))
                prev_cap = cap_f
                prev_rows = max(int(fut_w[-1]), 1)
                i += span
            if mega_rows == len(rows):
                return plan
        elif mega_rows:
            # fused path: the megakernel ladder replaces the staged
            # span/dedup/gfilter program set for these rows — each
            # forecast level's program is keyed by (input cap, output
            # cap, slab cap): the input rung chains from the previous
            # level's output (the fused program's new frontier IS the
            # next level's input), the output rung runs through the
            # SAME capacity function as the runtime _mega_cap_out
            # (shape_plan's rows are already 1.25-margined; the 2x
            # floor, lane clamp and 4*chunk floor match), and the slab
            # ladder follows the store's growth.
            scaps = slab_ladder()
            prev_cap = frontier.voted_for.shape[0]
            prev_rows = max(int(level_sizes[-1]), 1)
            for r in rows[:mega_rows]:
                n_lanes = (prev_cap // self.chunk) * self.cap_x
                est = max(int(r), 2 * prev_rows)
                cout = max(
                    self._frontier_cap(min(est, max(n_lanes, 1))),
                    4 * self.chunk,
                )
                fs = self._frontier_struct(frontier, prev_cap)
                for sc in scaps:
                    plan.append((
                        ("mega", prev_cap, cout, sc, self.cap_x,
                         self.cap_m, self.use_mxu, sv_struct.shape[0]),
                        lambda fs=fs, sc=sc, cout=cout:
                            self._mega_prog.lower(
                                fs, u64(sc), s_i64, sv_struct,
                                cap_out=cout
                            ).compile(),
                    ))
                prev_cap, prev_rows = cout, int(r)
            if mega_rows == len(rows):
                return plan
            # later rows cross into the grouped regime: fall through so
            # the staged span/dedup/gfilter ladder compiles ahead of
            # the regime switch too

        # 1) the expand-span program at the frontier-capacity ladder (the
        # big one: its compile is the round-3 minutes-class cost).  The
        # external-store path walks uniform SEG_ROWS segments once the
        # frontier exceeds one segment, so its ladder collapses there.
        if self.chunk >= self.span_min_chunk and not self.orbit:
            caps = set()
            for r in rows:
                if self.host_store is not None:
                    caps.add(min(_host_cap(r, self.chunk), SEG_ROWS))
                else:
                    caps.add(self._frontier_cap(r))
            for c in sorted(caps):
                fs = self._frontier_struct(frontier, c)
                # the span program traces the kernel's guards/materialize,
                # so its identity includes the MXU-vs-legacy selection
                plan.append((
                    ("span", c, self.use_mxu),
                    lambda fs=fs: self._expand_span.lower(
                        fs, s_i64, s_i64, s_i64
                    ).compile(),
                ))
        if self.host_store is not None:
            # host path: per-group dedup runs at the FIXED G*cap_x lane
            # width (compiled by the first big level); nothing else on
            # the device scales with depth
            return plan
        # 2) the level-dedup program at the lane-count ladder, against
        # the visited structure at its forecast capacity
        lanes = set()
        for r in rows:
            n_chunks = -(-max(int(r), 1) // self.chunk)
            if n_chunks > 16 * self.G:  # the level loop's grouping rule
                lanes.add(_cap_steps((-(-n_chunks // self.G)) * self.cap_g))
            else:
                lanes.add(_cap_steps(n_chunks * self.cap_x))
        if self.use_hashstore:
            scaps = slab_ladder()
            for sc in scaps:
                for L in sorted(lanes):
                    plan.append((
                        ("dedup_hash", L, sc),
                        lambda L=L, sc=sc: _level_dedup_hash.lower(
                            u64(L), u64(L), i64(L), u64(sc)
                        ).compile(),
                    ))
                plan.append((
                    ("gfilter_hash", sc, self.cap_g),
                    lambda sc=sc: _group_filter_hash.lower(
                        u64(self.G * self.cap_x), u64(self.G * self.cap_x),
                        i64(self.G * self.cap_x), u64(sc),
                        cap_g=self.cap_g,
                    ).compile(),
                ))
        else:
            vcap_now = visited.shape[0]
            vcaps = pow2_ladder(
                vcap_now // 2,
                max(_cap4(final + 1), self._presize_vcap),
            ) or [vcap_now]
            vcaps = [v for v in vcaps if v == _cap4(v)]  # store is pow4
            for vc in vcaps:
                for L in sorted(lanes):
                    plan.append((
                        ("dedup", L, vc),
                        lambda L=L, vc=vc: _level_dedup.lower(
                            u64(L), u64(L), i64(L), u64(vc)
                        ).compile(),
                    ))
                plan.append((
                    ("gfilter", vc, self.cap_g),
                    lambda vc=vc: _group_filter.lower(
                        u64(self.G * self.cap_x), u64(self.G * self.cap_x),
                        i64(self.G * self.cap_x), u64(vc),
                        cap_g=self.cap_g,
                    ).compile(),
                ))
                # 3) the store merge at its forecast input widths
                for r in set(rows):
                    w = max(_pow2(int(r)), self.chunk)
                    if self._presize_merge:
                        w = max(w, self._presize_merge)
                    plan.append((
                        ("merge", vc, w),
                        lambda vc=vc, w=w: _merge_sorted.lower(
                            u64(vc), u64(w)
                        ).compile(),
                    ))
        return plan

    def _submit_prewarm(self, level_sizes, distinct, max_depth, frontier,
                        visited):
        """Queue the forecast program set on the background compiler."""
        try:
            plan = self._prewarm_plan(
                level_sizes, distinct, max_depth, frontier, visited
            )
        except Exception as e:  # graftlint: waive[GL003] — plan building
            # is a pure optimization; a forecast edge case must never
            # take the run down (the shapes then compile in line)
            print(f"[pipeline] prewarm plan failed: {e}", file=sys.stderr)
            return
        if not plan:
            return
        if self._prewarmer is None or self._prewarmer.stopped:
            self._prewarmer = graft_pipeline.Prewarmer()
        self._prewarmer.submit(plan)
        # deliberately NO note_shape_event here: the background thread's
        # thread-local marker already diverts every prewarm compile to
        # the declared ledger before the per-level counter sees it, and
        # a submission note would blanket-excuse a genuine MAIN-thread
        # silent retrace at this level — the exact regression class the
        # sanitizer exists to catch

    def _materialize_segs(self, segs, pay_np, new_payload, n_new):
        """Segment-streamed materialize for the external-store path.

        Parents arrive as a list of equal-size segment buffers; payloads
        are sorted ascending (payload = pidx*K + slot), so consecutive
        slices walk the parent segments left to right: each slice
        gathers from a (j, j+1) window and every segment left of the
        window frees as soon as the walk passes it — the INPUT LIST IS
        MUTATED (entries set to None) so every holder drops the buffer.
        Children land in segmented destinations, allocated as the walk
        reaches them.  HBM peak ~ dst + the unconsumed parent tail,
        instead of whole parent + whole dst — the wall the reference
        sweep hit at its level-27 materialize (13+ GB of 14.7 usable).

        Returns (dst_segs, bad_ds, ovf_ds, n_slices, sl), or None when a
        precondition fails (a slice spanning more than two segments —
        practically impossible for payload-sorted deep levels — or a
        legacy record whose payloads aren't ascending, or slice tiling
        that doesn't fit the capacity): the caller then takes the
        window-less whole-parent path.
        """
        K = self.K
        # one-chunk slices at deep-sweep chunk sizes: the materialize
        # program's transient workspace (the scatter-free message-set
        # inflate is ~60 KB/state on this family) scales with slice width
        # — 4*chunk slices cost ~4 GB of HBM headroom for ~20 s/level of
        # dispatch savings, a bad trade close to the ceiling.  Tiny
        # (test-scale) chunks keep the wider slices: their workspace is
        # KBs and 4x the dispatch count quadruples CPU-suite wall time.
        sl_quantum = self.chunk if self.chunk >= 2048 else 4 * self.chunk
        sl = min(sl_quantum, new_payload.shape[0])
        n_slices = -(-n_new // sl)
        cap_f = _host_cap(n_new, self.chunk)
        if n_slices * sl > cap_f:
            return None
        # the window reasoning below is sound only for globally ascending
        # payloads — endpoint checks alone would let a legacy cv-ordered
        # record slip interior payloads outside the window, where the
        # gather clips onto WRONG PARENT ROWS with no error
        if not bool(np.all(np.diff(pay_np[:n_new].astype(np.int64)) > 0)):
            return None
        L = _seg_rows(segs[0])
        n_par = len(segs)
        j_los = []
        for si in range(n_slices):
            a, b = si * sl, min(si * sl + sl, n_new)
            p_lo = int(pay_np[a]) // K
            p_hi = int(pay_np[b - 1]) // K
            j_lo = min(p_lo // L, n_par - 1)
            if p_hi >= min(j_lo + 2, n_par) * L:
                return None  # parent span exceeds the 2-segment window
            j_los.append(j_lo)
        seg_d = SEG_ROWS if cap_f > SEG_ROWS else cap_f
        n_seg_d = cap_f // seg_d
        per_seg = seg_d // sl if n_seg_d > 1 else n_slices
        dst = [None] * n_seg_d
        parts_buf = []
        bad_ds, ovf_ds = [], []
        # host-paged parents transit through this cache (segs keeps the
        # numpy copy as the source of truth); seg_b prices one segment
        # for the demotion decision at seal time
        paged: dict[int, Frontier] = {}
        seg_b = None

        def par(j):
            s = segs[j]
            if isinstance(s, _HostSeg):
                d = paged.get(j)
                if d is None:
                    d = paged[j] = self._seg_to_dev(s)
                return d
            return s

        for si in range(n_slices):
            take = min(sl, n_new - si * sl)
            j = j_los[si]
            pay_slice = jax.lax.dynamic_slice_in_dim(new_payload, si * sl, sl)
            part, bad_d, ovf_d = self._mat_slice_seg(
                par(j), par(min(j + 1, n_par - 1)),
                jnp.asarray(j * L, I64), pay_slice, jnp.asarray(take, I64),
            )
            graft_sanitize.note_dispatch("device.mat_seg")
            parts_buf.append(part)
            if len(parts_buf) == per_seg or si == n_slices - 1:
                # seal one destination segment: a bounded concat (the
                # transient is two segments, never two frontiers — no
                # donation semantics assumed, see note at top)
                dj = (si * sl) // seg_d
                sealed = jax.tree.map(
                    lambda *xs: _pad_axis0(jnp.concatenate(xs), seg_d),
                    *parts_buf,
                )
                parts_buf = []
                if self.dev_budget:
                    if seg_b is None:
                        seg_b = self._tree_bytes(sealed)
                    live = (
                        sum(
                            1 for k, s in enumerate(segs)
                            if s is not None
                            and (not isinstance(s, _HostSeg) or k in paged)
                        )
                        + sum(
                            1 for d in dst
                            if d is not None and not isinstance(d, _HostSeg)
                        )
                        + 2  # the transient concat + one in-flight slice
                        # the async pipeline keeps up to a window's
                        # worth of group fetch buffers (and their parent
                        # segments) alive through the NEXT expand — price
                        # that peak here so demotion leaves room for it
                        + (self.pipeline_window if self.pipeline else 0)
                    )
                    if (live + 1) * seg_b > self.dev_budget:
                        sealed = self._seg_to_host(sealed)
                        self.paged_out += 1
                dst[dj] = sealed
            for k in range(j):  # the walk has passed these parents for good
                segs[k] = None
                paged.pop(k, None)
            bad_ds.append(bad_d)
            ovf_ds.append(ovf_d)
            if sl >= 16384 or si % 4 == 3:
                jax.device_get(bad_d)
        for dj in range(n_seg_d):  # untouched capacity tail
            if dst[dj] is None:
                proto = next(d for d in dst if d is not None)
                if isinstance(proto, _HostSeg):
                    dst[dj] = _HostSeg(
                        {f: np.zeros(v.shape, v.dtype)
                         for f, v in proto.fields.items()}
                    )
                else:
                    dst[dj] = jax.tree.map(jnp.zeros_like, proto)
        return dst, bad_ds, ovf_ds, n_slices, sl

    def _materialize_fallback_segs(self, whole, new_payload, n_new):
        """Whole-parent materialize that still emits a SEGMENTED
        destination with bounded concat transients — the external-store
        path for legacy (non-ascending) records and tiny levels."""
        sl_quantum = self.chunk if self.chunk >= 2048 else 4 * self.chunk
        sl = min(sl_quantum, new_payload.shape[0])  # see _materialize_segs
        n_slices = -(-n_new // sl)
        cap_f = _host_cap(n_new, self.chunk)
        if n_slices * sl > cap_f:
            seg_d, n_seg_d = cap_f, 1
        else:
            seg_d = SEG_ROWS if cap_f > SEG_ROWS else cap_f
            n_seg_d = cap_f // seg_d
        # a single-segment destination seals once, at the end (tiny levels
        # whose slice tiling overshoots the capacity get truncated there)
        per_seg = seg_d // sl if n_seg_d > 1 else n_slices
        dst = [None] * n_seg_d
        parts_buf = []
        bad_ds, ovf_ds = [], []
        for si in range(n_slices):
            take = min(sl, n_new - si * sl)
            pay_slice = jax.lax.dynamic_slice_in_dim(new_payload, si * sl, sl)
            ch_f, bad_d, ovf_d = self._mat_slice(
                whole, pay_slice, jnp.asarray(take, I64)
            )
            graft_sanitize.note_dispatch("device.mat")
            parts_buf.append(ch_f)
            if len(parts_buf) == per_seg or si == n_slices - 1:
                dj = min((si * sl) // seg_d, n_seg_d - 1)
                dst[dj] = jax.tree.map(
                    lambda *xs: _pad_axis0(jnp.concatenate(xs), seg_d),
                    *parts_buf,
                )
                parts_buf = []
            bad_ds.append(bad_d)
            ovf_ds.append(ovf_d)
            if sl >= 16384 or si % 4 == 3:
                jax.device_get(bad_d)
        for dj in range(n_seg_d):
            if dst[dj] is None:
                dst[dj] = jax.tree.map(jnp.zeros_like, dst[0])
        return dst, bad_ds, ovf_ds, n_slices, sl

    def _widen_msg_ids(self, frontier: Frontier) -> Frontier:
        """Pad the frontier's sparse message-id lanes out to self.cap_m."""
        if isinstance(frontier, _HostSeg):
            ids = frontier.fields["msg_ids"]
            pad = self.cap_m - ids.shape[1]
            if pad <= 0:
                return frontier
            f2 = dict(frontier.fields)
            f2["msg_ids"] = np.concatenate(
                [ids, np.full((ids.shape[0], pad), -1, ids.dtype)], axis=1
            )
            return _HostSeg(f2)
        ids = frontier.msg_ids
        pad = self.cap_m - ids.shape[1]
        if pad <= 0:
            return frontier
        return frontier._replace(
            msg_ids=jnp.concatenate(
                [ids, jnp.full((ids.shape[0], pad), -1, ids.dtype)], axis=1
            )
        )

    # -- host-RAM segment paging (the level-29 HBM wall breaker) -----------

    def _seg_to_host(self, seg: Frontier, depth: int = -1) -> _HostSeg:
        hs = _HostSeg(
            {f: np.asarray(jax.device_get(getattr(seg, f)))
             for f in Frontier._fields}
        )
        self._fseg_admit(hs, depth)
        return hs

    def _fseg_admit(self, hs: _HostSeg, depth: int = -1) -> None:
        """Host-budget admission for a paged-out segment: once the
        RAM-resident host segments exceed TLA_RAFT_FSEG_BYTES, the
        incoming segment spills straight to the warm tier (kind="fseg")
        — the walks consume segments in ascending order, so keeping the
        EARLIER segments resident and spilling the later ones is the
        moving-window policy (by the time a spilled segment reloads,
        its predecessors are consumed and freed)."""
        if self._fpager is None or not self.fseg_host_bytes:
            return
        live = [r for r in self._fseg_live if r() is not None]
        resident = sum(r().resident_bytes for r in live)
        if resident + hs.resident_bytes > self.fseg_host_bytes:
            hs.spill(self._fpager, depth)
            self.paged_disk += 1
        live.append(weakref.ref(hs))
        self._fseg_live = live

    def _fseg_retire_consumed(self) -> None:
        """Drop consumed segments' warm-tier artifacts (level top: the
        previous level is committed, its parents can never be replayed
        — a degrade-redo only ever reaches back one level)."""
        if self._fseg_retire and self._fpager is not None:
            self._fpager.retire(self._fseg_retire)
        self._fseg_retire = []

    def _seg_to_dev(self, seg) -> Frontier:
        if not isinstance(seg, _HostSeg):
            return seg
        return Frontier(**{f: jnp.asarray(v) for f, v in seg.fields.items()})

    @staticmethod
    def _tree_bytes(seg) -> int:
        vals = (
            seg.fields.values() if isinstance(seg, _HostSeg)
            else (getattr(seg, f) for f in Frontier._fields)
        )
        return sum(int(np.prod(x.shape)) * x.dtype.itemsize for x in vals)

    def _materialize_grow(self, frontier, new_payload, n_new, pay_np=None):
        """Materialize survivors, auto-growing cap_m on overflow.

        cap_m (the sparse-frontier message-set width) grows ~1 per BFS
        level on the reference family; a fixed budget would make deep
        sweeps die hours in (VERDICT round 2, weak #6).  Overflow is
        detected per slice by ``_ids_insert`` (an action's sent id finds
        the parent's id lanes full); the payloads are already
        known, so growing the width, widening the (parent) frontier's id
        lanes and re-materializing the level is pure re-computation —
        the same recovery shape as the cap_x growth redo.  EXCEPT on the
        segment-streamed path, where consumed parents are already freed:
        there overflow raises, and a restart with TLA_RAFT_CAP_M set
        resumes from the delta log (widths saturate at 96 on the
        reference family, so with the default headroom this is
        unreachable in practice).

        The host-store path passes ``frontier`` as a segment list (and
        ``pay_np``, the host-side sorted payloads); the result is then a
        segment list too.  Returns (new_frontier, bads, n_slices, sl,
        parent) — the new frontier is at its _frontier_cap capacity.
        """
        while True:
            segged = False
            retry_parent = None
            if isinstance(frontier, list):
                res = (
                    self._materialize_segs(frontier, pay_np, new_payload,
                                           n_new)
                    if pay_np is not None
                    else None
                )
                if res is not None:
                    out, bad_ds, ovf_ds, n_slices, sl = res
                    segged = True
                else:
                    # the window-less path concats on device; page any
                    # host-resident segments back in first (in place, so
                    # _concat_fields' list-emptying still frees them)
                    for i, s in enumerate(frontier):
                        frontier[i] = self._seg_to_dev(s)
                    whole = _concat_fields(frontier)
                    out, bad_ds, ovf_ds, n_slices, sl = (
                        self._materialize_fallback_segs(
                            whole, new_payload, n_new
                        )
                    )
                    retry_parent = whole
            else:
                parts, bad_ds, ovf_ds, n_slices, sl = (
                    self._materialize_payload_slices(
                        frontier, new_payload, n_new
                    )
                )
                cap_f = self._frontier_cap(n_new)
                out = jax.tree.map(
                    lambda *xs: _pad_axis0(jnp.concatenate(xs), cap_f),
                    *parts,
                )
                del parts
                retry_parent = frontier
            bads, ovfs = jax.device_get((bad_ds, ovf_ds))
            if not any(bool(np.asarray(o)) for o in ovfs):
                return out, bads, n_slices, sl, frontier
            if self.cap_m >= self.kern.uni.M:
                raise RuntimeError(
                    "message-set width exceeds the whole universe — "
                    "corrupt payloads?"
                )
            if segged and any(s is None for s in frontier):
                # only unrecoverable once the walk actually released
                # parent segments; a restart with TLA_RAFT_CAP_M set
                # resumes from the delta log
                raise RuntimeError(
                    f"cap_m={self.cap_m} overflowed after parent segments "
                    "were released; restart with TLA_RAFT_CAP_M="
                    f"{self.cap_m + 32} — the delta log resumes the run"
                )
            # widths grow ~1/level on this spec family and saturate near
            # the structural bound (measured 96 at depth 22 of the
            # reference sweep), so grow in small steps: doubling 96->192
            # doubles every deep frontier's bytes for ~10 lanes of need
            self.cap_m = min(self.cap_m + 32, self.kern.uni.M)
            print(f"[engine] cap_m overflow: growing to {self.cap_m} and "
                  f"re-materializing the level", file=sys.stderr)
            if isinstance(frontier, list):
                if retry_parent is not None:
                    # the fallback concat consumed the segment list in
                    # place (_concat_fields empties it); retry on the
                    # concatenated whole as a single segment, and drop
                    # pay_np so the retry takes the fallback path again
                    frontier = [self._widen_msg_ids(retry_parent)]
                    pay_np = None
                else:
                    frontier = [self._widen_msg_ids(s) for s in frontier]
            else:
                frontier = self._widen_msg_ids(retry_parent)

    def _resume_from_deltas(self, ckdir):
        """Rebuild the run state by replaying the delta log.

        The replay starts from Init, or from a ``base.npz`` monolith
        snapshot if one sits in the directory (written when a run that
        itself resumed from a monolith starts appending deltas)."""
        import glob

        # -- self-healing pass (resilience/recover.py): sweep orphaned
        # .tmp_* files, verify every record against the directory
        # manifest, quarantine corrupt/torn/unmanifested records and
        # truncate the chain to the last good contiguous prefix.  The
        # replay below then consumes only verified records; its gap
        # check stays as the backstop for the interior-hole case.
        base_path = os.path.join(ckdir, "base.npz")
        man = resilience.Manifest.load(ckdir)
        man.bind_run(self._run_fp)
        base_depth = 0
        if os.path.exists(base_path):
            st_base = man.verify("base.npz") if man.exists else "ok"
            if st_base == "unmanifested":
                # renamed/copied in before the manifest commit landed:
                # the meta read below is the structural probe; adopt so
                # the chain it anchors survives the next heal too
                resilience.adopt_file(
                    ckdir, "base.npz", kind="base", run_fp=self._run_fp
                )
                st_base = "ok"
            ok_base = st_base == "ok"
            if ok_base:
                try:
                    base_depth = int(np.load(base_path)["meta"][3])
                except (OSError, ValueError, KeyError, EOFError,
                        zipfile.BadZipFile):
                    ok_base = False
            if not ok_base:
                # the whole delta chain hangs off the base snapshot:
                # with it gone the deltas are orphans — quarantine
                # everything and restart from Init (the worst-case but
                # still hands-free recovery)
                resilience.quarantine(
                    ckdir, "base.npz", "corrupt base snapshot", man
                )
                for f in sorted(
                    glob.glob(os.path.join(ckdir, "delta_*.npz"))
                ):
                    resilience.quarantine(
                        ckdir, os.path.basename(f),
                        "orphaned by quarantined base", man,
                    )
                if man.exists:
                    man.commit()
        files = resilience.heal_log(
            ckdir, "delta", run_fp=self._run_fp, slabs=("hslab.npz",),
            start_depth=base_depth + 1,
        )
        if (
            not files and not os.path.exists(base_path) and not man.exists
        ):
            # a directory that was never one of ours (no manifest, no
            # records) is a caller error, not a healable crash
            raise ValueError(f"no delta_*.npz checkpoints under {ckdir}")
        if self.host_store is not None:
            # rebuild the external store from the log as the replay walks
            # it (level-at-a-time inserts keep the store's spill budget in
            # force — the whole point of the external tier is a visited
            # set bigger than host RAM).  clear() first: the store may
            # still hold pre-crash inserts, including a partially-
            # completed level that never reached the log, and those would
            # silently mark reachable states as already-visited.
            self.host_store.clear()
        cfg, K = self.cfg, self.K
        if os.path.exists(base_path):
            ck = self._load_checkpoint(
                base_path,
                device_visited=(
                    self.host_store is None and not self.use_hashstore
                ),
            )
            self._check_fp_def(ck["fp_def"], base_path)
            frontier, n_f = ck["frontier"], ck["n_f"]
            visited_base = ck["visited"]
            if self.host_store is not None:
                # a device-store monolith seeds the external store: its
                # visited array IS the fingerprint set (sorted, SENT-
                # padded).  The base may be a checkpoint of a device-store
                # run — the two tiers' contents are interchangeable; only
                # their location differs.
                self._seed_host_store(visited_base)
                visited_base = None
                # host-path frontiers are segment lists; split a monolith
                # frontier into uniform segments when it tiles evenly so
                # the replay's first materialize gathers through windows
                # (a whole-frontier gather materializes operand-sized
                # temps on this backend — the gather cliff, docs/PERF.md)
                rows = frontier.voted_for.shape[0]
                if rows % SEG_ROWS == 0 and rows > SEG_ROWS:
                    cut = jax.jit(
                        lambda t, s: jax.tree.map(
                            lambda x: jax.lax.dynamic_slice_in_dim(
                                x, s, SEG_ROWS
                            ),
                            t,
                        )
                    )
                    frontier = [
                        cut(frontier, jnp.asarray(i * SEG_ROWS, I32))
                        for i in range(rows // SEG_ROWS)
                    ]
                else:
                    frontier = [frontier]
            fps_parts = []
            trace_levels = ck["trace_levels"]
            level_sizes = list(ck["level_sizes"])
            mult_per_slot = np.asarray(ck["mult_per_slot"])
            depth = ck["depth"]
        else:
            st0 = init_batch(cfg, 1)
            fv0, _ff0 = self._fp_states(st0)
            frontier, _ovf = jax.jit(self._deflate)(st0)
            frontier = jax.tree.map(
                lambda x: _pad_axis0(x, self.chunk), frontier
            )
            if self.host_store is not None:
                frontier = [frontier]
            n_f = 1
            visited_base = None
            init_fps = np.asarray(fv0.astype(U64))
            if self.host_store is not None:
                self.host_store.insert(init_fps)
                fps_parts = []
            else:
                fps_parts = [init_fps]
            trace_levels, level_sizes = [], [1]
            mult_per_slot = np.zeros(K, np.int64)
            depth = 0
        for f in files:
            z = np.load(f)
            meta = [int(x) for x in z["meta"]]
            d, n_new = meta[0], meta[1]
            fp_def = meta[2] if len(meta) > 2 else 0
            if fp_def != int(self.orbit):
                raise ValueError(
                    f"fingerprint-definition mismatch: delta log {f} was "
                    f"written with {'orbit' if fp_def else 'min-over-P'} "
                    f"fingerprints but this run uses "
                    f"{'orbit' if self.orbit else 'min-over-P'} "
                    "(TLA_RAFT_ORBIT) — the two cannot share a visited "
                    "store; resume with the matching setting"
                )
            if d != depth + 1:
                raise ValueError(
                    f"delta log gap: expected level {depth + 1}, found "
                    f"level {d} ({f})"
                )
            pidx = z["pidx"].astype(np.int64)
            slot = z["slot"].astype(np.int64)
            payload_np = pidx * K + slot
            cap = max(_pow2(n_new), 4 * self.chunk)
            new_payload = _pad_axis0(jnp.asarray(payload_np, I64), cap)
            frontier, _bads, _ns, _sl, _parent = self._materialize_grow(
                frontier, new_payload, n_new,
                pay_np=payload_np if self.host_store is not None else None,
            )
            del _parent  # the replay keeps only the new frontier alive
            n_f = n_new
            if self.host_store is not None:
                self.host_store.insert(z["fps"])
            else:
                fps_parts.append(z["fps"])
            trace_levels.append((pidx, slot))
            level_sizes.append(n_new)
            mult_per_slot = mult_per_slot + z["mult"]
            depth = d
        distinct = int(sum(level_sizes))
        if self.host_store is not None:
            visited = jnp.full((64,), SENT, U64)
        elif self.use_hashstore and self._tier_on():
            # tiered resume: the dumped slab holds only the HOT tier
            # (its count deliberately mismatches distinct), so the
            # replayed per-level fps re-tier from scratch — whole
            # levels demote together, making the rebuilt generations
            # DISJOINT and the tier total exactly the distinct count
            parts = [np.asarray(p, np.uint64) for p in fps_parts]
            if visited_base is not None:
                vb = np.asarray(visited_base, np.uint64)
                parts.insert(0, vb[vb != SENT])
            hot = self.tiered.rebuild(
                list(enumerate(parts)),
                hot_slots=self.tiered.hot_slot_budget(),
            )
            self.hstore = hashstore.DeviceHashStore.from_fps(hot)
            total = self.hstore.count + self.tiered.spilled_distinct()
            if total != distinct:
                raise ValueError(
                    f"tiered resume rebuilt {total} distinct "
                    f"fingerprints across {1 + len(self.tiered.gens)} "
                    f"tier(s) for {distinct} recorded states — corrupt "
                    "or mixed log"
                )
            visited = jnp.full((64,), SENT, U64)
        elif self.use_hashstore:
            # slab checkpoint fast path: the dumped slab IS the visited
            # set at the resume depth — one device_put instead of a
            # host-side rebuild.  Any mismatch (depth, count, fp def,
            # version) falls back to rebuilding from the replayed fps.
            self.hstore = hashstore.DeviceHashStore.load(
                os.path.join(ckdir, "hslab.npz"), depth, distinct,
                int(self.orbit),
            )
            if self.hstore is None:
                parts = [np.asarray(p, np.uint64) for p in fps_parts]
                if visited_base is not None:
                    parts.insert(0, np.asarray(visited_base, np.uint64))
                allf = (
                    np.concatenate(parts) if parts
                    else np.empty(0, np.uint64)
                )
                self.hstore = hashstore.DeviceHashStore.from_fps(allf)
            if self.hstore.count != distinct:
                raise ValueError(
                    f"hash-store resume rebuilt {self.hstore.count} "
                    f"distinct fingerprints for {distinct} recorded "
                    "states — corrupt or mixed log"
                )
            visited = jnp.full((64,), SENT, U64)
        else:
            new_fp_count = int(sum(len(p) for p in fps_parts))
            parts_dev = (
                [jnp.asarray(np.concatenate(fps_parts), U64)] if fps_parts else []
            )
            if visited_base is not None:
                parts_dev.insert(0, visited_base)
                pad_to = _cap4(distinct + 1) - new_fp_count - visited_base.shape[0]
            else:
                pad_to = _cap4(distinct + 1) - new_fp_count
            if pad_to > 0:
                parts_dev.append(jnp.full((pad_to,), SENT, U64))
            visited = jnp.sort(jnp.concatenate(parts_dev))[: _cap4(distinct + 1)]
        return dict(
            frontier=frontier,
            visited=visited,
            n_f=n_f,
            distinct=distinct,
            generated=int(mult_per_slot.sum()),
            depth=depth,
            level_sizes=level_sizes,
            trace_levels=trace_levels,
            mult_per_slot=mult_per_slot,
        )

    def _save_checkpoint(self, path, frontier, visited, n_f, distinct,
                         generated, depth, level_sizes, trace_levels,
                         mult_per_slot):
        if self.use_hashstore and self.hstore is not None:
            # the run's visited set lives in the hash slab; the monolith
            # format pins a SORTED array (it seeds host stores and
            # sorted-mode resumes), so derive it from the live slots
            # graftlint: waive[GL006] — one slab fetch per monolith save
            vb = np.asarray(jax.device_get(self.hstore.slab))
            vb = vb[vb != SENT]
            if self._tier_active():
                # the monolith's visited array IS the fingerprint set:
                # fold the demoted generations back in (the hot slab
                # alone is only the top tier)
                vb = np.union1d(vb, self.tiered.all_fps())
            vb = np.sort(np.unique(vb)) if len(vb) else vb
            pad = _cap4(len(vb) + 1) - len(vb)
            visited = np.concatenate([vb, np.full(pad, SENT)])
        arrs = {f"st_{k}": np.asarray(v) for k, v in frontier._asdict().items()}
        for i, (p, s) in enumerate(trace_levels):
            arrs[f"trace_p{i}"] = p
            arrs[f"trace_s{i}"] = s
        payload = dict(
            visited=np.asarray(visited),
            mult_per_slot=mult_per_slot,
            meta=np.asarray([n_f, distinct, generated, depth], np.int64),
            fp_def=np.asarray([int(self.orbit)], np.int64),
            level_sizes=np.asarray(level_sizes, np.int64),
            n_trace=np.asarray([len(trace_levels)], np.int64),
            **arrs,
        )
        # zlib on multi-GB frontiers costs ~a minute of host time per
        # level; past 256 MB the disk is cheaper than the CPU
        total = sum(a.nbytes for a in payload.values())
        resilience.commit_npz(
            os.path.dirname(os.path.abspath(path)),
            os.path.basename(path),
            payload,
            kind="monolith",
            depth=depth,
            run_fp=self._run_fp,
            compressed=total < (256 << 20),
        )

    # -- tiered visited store (store/tiered.py) ---------------------------

    def _tier_on(self) -> bool:
        """Tiering configured: a device budget bounds the hot slab."""
        return (
            self.tiered is not None and self.use_hashstore
            and self.host_store is None
        )

    def _tier_active(self) -> bool:
        """At least one generation demoted: level tails must probe."""
        return self._tier_on() and self.tiered.active

    def _sieve_ready(self) -> bool:
        """The spill sieve covers every demoted fingerprint: fused
        levels may rely on zero-hit = provably-clean."""
        return (
            self.sieve_enabled and self.sieve_governor.armed
            and self._tier_active()
            and self.tiered.spill_sieve is not None
        )

    def _sieve_operand(self):
        """The fused programs' sieve operand: the spill sieve's device
        word image, refreshed exactly when the host filter changed (a
        demotion), else the cached 1-word all-miss sentinel — ONE
        traced operand serves both regimes (ops/sieve.py), and jit
        retraces only when the filter SHAPE changes (it never does:
        the filter is allocated at full size on first demotion)."""
        if not self._sieve_ready():
            if self._dev_sieve_empty is None:
                self._dev_sieve_empty = graft_sieve.empty_device_sieve()
            return self._dev_sieve_empty
        sv = self.tiered.spill_sieve
        if self._dev_sieve is None or self._dev_sieve_ver != sv.version:
            self._dev_sieve = jnp.asarray(sv.words)
            self._dev_sieve_ver = sv.version
            graft_obs.sieve_refresh(
                len(self.tiered.gens), len(sv.words), sv.n_added,
                sv.fp_rate(),
            )
            # live-HBM gauge: the filter image is a long-lived buffer
            graft_obs.buffer("sieve", sv.nbytes)
        return self._dev_sieve

    def _demote_generation(self, depth: int, expected: int = 0) -> None:
        """Flush the hot slab into one warm generation and restart hot.

        The restart slab is sized to SEAT the in-flight level's
        expected fresh set — even past the budget: the device budget
        bounds the store RESIDENT between levels (that is what makes
        |visited| storage-bounded), while one level's insert set is a
        transient working set exactly like the frontier is, and the
        between-level demote drains any soft overshoot right after the
        level commits."""
        # one slab fetch per demotion: the rare budget-crossing event,
        # same deliberate-sync class as the slab dump / degrade fetches
        # graftlint: waive[GL006] — demotion's one deliberate slab fetch
        vb = np.asarray(jax.device_get(self.hstore.slab))
        self.tiered.demote(vb, depth=depth)
        want = hashstore.slab_rows(max(2 * max(expected, 1),
                                       hashstore.MIN_CAP // 2))
        if not self.tiered.slab_fits(want):
            soft = hashstore.slab_rows(max(expected, 1))
            want = max(min(want, soft), hashstore.MIN_CAP)
        self.hstore = hashstore.DeviceHashStore(cap=want)
        self._hs_pending = None
        print(
            f"[tiered] hot slab demoted to generation "
            f"{self.tiered.gens[-1].gid if self.tiered.gens else '-'} "
            f"at level {depth} ({len(vb[vb != SENT])} fps spilled, "
            f"{self.tiered.spilled_distinct()} total across "
            f"{len(self.tiered.gens)} gen(s)); hot restarts at "
            f"{self.hstore.cap} slots"
            + ("" if self.tiered.slab_fits(self.hstore.cap) else
               " (soft over-budget: one level's fresh set exceeds the "
               "hot budget; drained again at the next level boundary)"),
            file=sys.stderr,
        )

    def _slab_grow_or_demote(self, depth: int, expected: int = 0,
                             min_cap: int | None = None) -> str:
        """The tier-aware form of ``hstore.grow()``: grow while the
        grown slab still fits the device budget, DEMOTE a generation
        otherwise ("demote, then redo" where the untiered path would
        grow or die).  A demotion only helps while the slab has content
        to flush — an (almost) empty slab that still overflows means
        ONE level's fresh set exceeds the budget, and the level must be
        seated transiently (soft overshoot, drained at the next level
        boundary) or it would redo forever.  Returns "grew" or
        "demoted"; grow failures propagate so callers keep their
        degrade-to-sorted ladder."""
        want = max(self.hstore.cap * 2, min_cap or 0)
        want = 1 << (want - 1).bit_length()
        if self._tier_on() and not self.tiered.slab_fits(want):
            if self.hstore.count > 0:
                self._demote_generation(depth, expected=expected)
                return "demoted"
            print(
                f"[tiered] level {depth}: fresh set exceeds the hot "
                f"budget even after demotion — seating it transiently "
                f"at {want} slots (drained at the level boundary)",
                file=sys.stderr,
            )
        self.hstore.grow(min_cap=min_cap)
        return "grew"

    def _tier_drain(self, depth: int, n_next: int) -> None:
        """Between-level demotion check, run at the loop top: drains a
        slab that sits over the budget (a transient soft-seat, or the
        MIN_CAP floor under a sub-minimum budget) or whose next growth
        would bust it.  The only drain site the superstep windows have
        — their commit path adopts without the staged path's
        between-level grow — and a no-op while the hot slab can keep
        growing inside the budget."""
        if not self._tier_on() or self.hstore.count == 0:
            return
        over = not self.tiered.slab_fits(self.hstore.cap)
        grow_needed = self.hstore.need_grow(extra=2 * max(n_next, 1))
        grow_busts = not self.tiered.slab_fits(self.hstore.cap * 2)
        if over or (grow_needed and grow_busts):
            self._demote_generation(depth, expected=2 * max(n_next, 1))

    def _tier_reserve(self, entries: int) -> None:
        """Budget-clamped ``hstore.reserve``: never presize the hot
        slab past the device budget (the overflow path demotes when
        the level actually needs the room)."""
        if self._tier_on():
            cap_e = self.tiered.max_hot_entries
            if cap_e:
                entries = min(entries, cap_e)
        self.hstore.reserve(int(entries))

    def _tier_filter_level(self, depth: int, n_new: int, fps_np,
                           new_frontier, cap_out: int):
        """The level-tail generation probe + row compaction.

        ``fps_np`` are the level's kernel-fresh fingerprints (hot-slab
        verdicts); revisits of demoted generations among them are
        dropped from the already-materialized frontier with ONE small
        device program (store.tiered.drop_rows), keeping counts
        bit-identical to the uncapped run.  The hit fingerprints stay
        in the hot slab (the fused probe re-inserted them) — that is
        the re-heat, so the next revisit resolves on device.  Returns
        ``(n_keep, keep_mask | None, new_frontier)``."""
        hits = self.tiered.probe(fps_np[:n_new], level=depth + 1)
        n_hit = int(hits.sum())
        if not n_hit:
            return n_new, None, new_frontier
        self.tiered.stats["reheats"] += n_hit
        keep = ~hits
        n_keep = n_new - n_hit
        if n_keep:
            keep_dev = jnp.asarray(
                np.concatenate([
                    keep, np.zeros(cap_out - n_new, bool),
                ])
            )
            new_frontier = graft_tiered.drop_rows(
                new_frontier, keep_dev, jnp.asarray(n_keep, I64)
            )
            graft_sanitize.note_dispatch("tiered.compact")
        return n_keep, keep, new_frontier

    def _degrade_hashstore(self, why) -> jnp.ndarray:
        """Hash-store grow failed (device OOM or an injected
        ``hashstore.grow`` fault): fall back to the sort-based visited
        path MID-RUN — the automatic form of the ``--no-hashstore``
        lever — instead of dying.  The slab's live slots hold exactly
        the visited set, so one fetch + sort rebuilds the sorted store
        losslessly and the run continues with identical counts."""
        print(
            f"[resilience] hash-store grow failed ({why}); degrading to "
            "the sort-based visited path (--no-hashstore equivalent) "
            "for the rest of the run",
            file=sys.stderr,
        )
        # graftlint: waive[GL006] — one-time degradation fetch
        vb = np.asarray(jax.device_get(self.hstore.slab))
        vb = vb[vb != SENT]
        if self._tier_active():
            # the sorted fallback must hold the WHOLE union: fold every
            # demoted generation back in (host-side; the degraded run
            # is already off the fast path, correctness first)
            vb = np.union1d(vb, self.tiered.all_fps())
            self.tiered = None
        vb = np.sort(np.unique(vb)) if len(vb) else vb
        pad = _cap4(len(vb) + 1) - len(vb)
        visited = jnp.concatenate(
            [jnp.asarray(vb), jnp.full((pad,), SENT, U64)]
        )
        self.use_hashstore = False
        self.hstore = None
        self._hs_pending = None
        # the fused level program IS a hash-store consumer — the sorted
        # path runs staged for the rest of the run (and with it the
        # multi-level superstep driver, which wraps the fused body)
        self.megakernel = False
        self.superstep_span = 1
        return visited

    def _check_fp_def(self, fp_def: int, path: str) -> None:
        """Refuse to mix fingerprint definitions in one visited store."""
        if fp_def != int(self.orbit):
            raise ValueError(
                f"fingerprint-definition mismatch: {path} was written "
                f"with {'orbit' if fp_def else 'min-over-P'} fingerprints "
                f"but this run uses "
                f"{'orbit' if self.orbit else 'min-over-P'} "
                "(TLA_RAFT_ORBIT) — resume with the matching setting"
            )

    def _seed_host_store(self, visited):
        """Insert a visited array's real (non-SENT) fps into the store.

        Sliced inserts keep the store's spill budget in force; `visited`
        should be host-side (numpy) — pass ``device_visited=False`` to
        ``_load_checkpoint`` so multi-GB snapshots never round-trip
        through the device on a host-store resume.
        """
        vb = np.asarray(visited)
        vb = vb[vb != np.uint64(0xFFFFFFFFFFFFFFFF)]
        for i in range(0, len(vb), 1 << 22):
            self.host_store.insert(vb[i : i + (1 << 22)])

    @staticmethod
    def _load_checkpoint(path, device_visited=True):
        z = np.load(path)
        fields = {k[3:] for k in z.files if k.startswith("st_")}
        if fields != set(Frontier._fields):
            raise ValueError(
                f"incompatible checkpoint format at {path}: has fields "
                f"{sorted(fields)}, this build expects "
                f"{sorted(Frontier._fields)} (written by an older engine?)"
            )
        frontier = Frontier(
            **{k[3:]: jnp.asarray(z[k]) for k in z.files if k.startswith("st_")}
        )
        n_f, distinct, generated, depth = (int(x) for x in z["meta"])
        trace_levels = [
            (z[f"trace_p{i}"], z[f"trace_s{i}"]) for i in range(int(z["n_trace"][0]))
        ]
        return dict(
            fp_def=int(z["fp_def"][0]) if "fp_def" in z.files else 0,
            frontier=frontier,
            mult_per_slot=np.asarray(z["mult_per_slot"]),
            # host-store resumes read the (potentially multi-GB) visited
            # snapshot host-side only — it seeds the external store and
            # must not ride along on the device through the replay
            visited=jnp.asarray(z["visited"]) if device_visited else z["visited"],
            n_f=n_f,
            distinct=distinct,
            generated=generated,
            depth=depth,
            level_sizes=list(int(x) for x in z["level_sizes"]),
            trace_levels=trace_levels,
        )

    # -- the main loop -----------------------------------------------------

    def _expand_level(self, frontier: Frontier, n_f, visited, ckdir=None,
                      depth=None):
        """Expand all chunks of one level.

        Dispatches between the two dedup tiers: with an external host
        store the level runs per-group host filtering (device memory
        O(group) — the fix for the round-2 level-25 HBM ceiling, where
        the ungrouped level-wide candidate concat OOMed at an 11.1M-state
        frontier); with a device-resident visited table the level-wide
        on-device dedup is cheaper.  ``ckdir``/``depth`` enable
        intra-level (per-group) partial checkpoints on the host path.
        """
        if self.host_store is not None:
            return self._expand_level_host(frontier, n_f, ckdir, depth)
        return self._expand_level_device(frontier, n_f, visited)

    def _expand_level_device(self, frontier: Frontier, n_f, visited):
        """Expand all chunks; returns device arrays + one fused host fetch.

        The frontier is device-resident in compact form; chunks are
        carved out with dynamic slices (the frontier capacity is always a
        multiple of the chunk size).
        """
        n_f_dev = jnp.asarray(n_f, I64)
        use_hs = self.use_hashstore
        hslab = self.hstore.slab if use_hs else None

        def gfilter(av, af, ap):
            """Visited filter for one group: hash probe or searchsorted."""
            graft_sanitize.note_dispatch("device.gfilter")
            if use_hs:
                return _group_filter_hash(av, af, ap, hslab, self.cap_g)
            return _group_filter(av, af, ap, visited, self.cap_g)

        cvs, cfs, cps = [], [], []  # pending (unfiltered) chunk outputs
        gvs, gfs, gps = [], [], []  # filtered+compacted group outputs
        svs, sfs, sps = [], [], []  # ungrouped span outputs ([G*cap_x] each)
        mult_acc = jnp.zeros((self.K,), I64)
        abort_at = BIG
        overflow = jnp.zeros((), bool)
        overflow_g = jnp.zeros((), bool)
        G = self.G  # chunks per visited-filter group
        n_chunks = -(-max(n_f, 1) // self.chunk)
        synced = 0  # chunks dispatched since the last queue drain
        # Group-wise compaction bounds the level-wide candidate concat to
        # n_groups*cap_g lanes instead of n_chunks*cap_x, but ONLY
        # because the group filter drops candidates already in the
        # device visited table (deep levels are <=50% fresh; it does NO
        # intra-group dedup).  It stays off at small frontiers (the
        # level-wide sort is tiny and new/parent ratios up to ~2.5 would
        # overflow cap_g) — and the threshold matters for throughput: the
        # filter's searchsorted against the visited store costs ~0.7 s
        # per 1M-lane group on the v5e (binary search = 22 rounds of
        # random gathers; measured round 5), so levels small enough for
        # the level-wide sort to fit run ~25% faster without grouping.
        # 256 chunks * cap_x 64k * 24 B = ~1.2 GB of sort operands —
        # comfortably inside one chip's HBM next to frontier + visited.
        grouping = n_chunks > 16 * G

        def flush_group():
            while len(cvs) < G:  # pad the group to its fixed width
                cvs.append(jnp.full((self.cap_x,), SENT, U64))
                cfs.append(jnp.full((self.cap_x,), SENT, U64))
                cps.append(jnp.full((self.cap_x,), -1, I64))
            gv, gf, gp, ovf = gfilter(
                jnp.concatenate(cvs), jnp.concatenate(cfs),
                jnp.concatenate(cps),
            )
            gvs.append(gv)
            gfs.append(gf)
            gps.append(gp)
            cvs.clear()
            cfs.clear()
            cps.clear()
            return ovf

        # full G-chunk groups go through the scanned span program (one
        # dispatch per G chunks instead of ~13 per chunk); the tail — and
        # every test-scale chunk size — keeps the per-chunk path.  On
        # grouped (deep) levels the span output feeds the group filter
        # directly; on mid-size levels it joins the level-wide concat as
        # G per-chunk-shaped entries.
        start0 = 0
        if (self.chunk >= self.span_min_chunk and n_chunks >= G
                and not self.orbit):
            span_rows = G * self.chunk
            # grouped ultra-deep levels with the hash store: the whole
            # per-group staged chain (span expand + visited pre-filter
            # + compact) fuses into ONE program per group under the
            # megakernel flag — the regime the whole-level fusion
            # deliberately leaves staged (the pre-filter bounds the
            # candidate working set there)
            gfused = (
                grouping and use_hs
                and getattr(self, "_expand_group_gfused", None)
                is not None
            )
            for g in range(n_chunks // G):
                b = jnp.asarray(g * span_rows, I64)
                if gfused:
                    (gv, gf, gp, mult_s, ab_s, ovf_s,
                     ovf_g) = self._expand_group_gfused(
                        frontier, b, b, n_f_dev, hslab,
                        cap_g=self.cap_g,
                    )
                    graft_sanitize.note_dispatch("device.span_gfused")
                    mult_acc = mult_acc + mult_s
                    abort_at = jnp.minimum(abort_at, ab_s)
                    overflow = overflow | ovf_s
                    overflow_g = overflow_g | ovf_g
                    gvs.append(gv)
                    gfs.append(gf)
                    gps.append(gp)
                    synced += 1
                    if synced >= self.sync_every:
                        jax.device_get(abort_at)
                        synced = 0
                    continue
                cvs_s, cfs_s, cps_s, mult_s, ab_s, ovf_s = self._expand_span(
                    frontier, b, b, n_f_dev
                )
                graft_sanitize.note_dispatch("device.span")
                mult_acc = mult_acc + mult_s
                abort_at = jnp.minimum(abort_at, ab_s)
                overflow = overflow | ovf_s
                if grouping:
                    gv, gf, gp, ovf_g = gfilter(
                        cvs_s.reshape(-1), cfs_s.reshape(-1),
                        cps_s.reshape(-1),
                    )
                    overflow_g = overflow_g | ovf_g
                    gvs.append(gv)
                    gfs.append(gf)
                    gps.append(gp)
                else:
                    svs.append(cvs_s.reshape(-1))
                    sfs.append(cfs_s.reshape(-1))
                    sps.append(cps_s.reshape(-1))
                synced += 1
                if synced >= self.sync_every:
                    jax.device_get(abort_at)
                    synced = 0
            start0 = (n_chunks // G) * span_rows

        for start in range(start0, max(n_f, 1), self.chunk):
            part_f = jax.tree.map(
                lambda x: jax.lax.dynamic_slice_in_dim(x, start, self.chunk),
                frontier,
            )
            cv, cf, cp, mult_slots, ab_at, ovf = self._expand_chunk(
                part_f,
                jnp.asarray(start, I64),
                n_f_dev,
            )
            graft_sanitize.note_dispatch("device.chunk")
            cvs.append(cv)
            cfs.append(cf)
            cps.append(cp)
            mult_acc = mult_acc + mult_slots
            abort_at = jnp.minimum(abort_at, ab_at)
            overflow = overflow | ovf
            if grouping and len(cvs) == G:
                overflow_g = overflow_g | flush_group()
            # bound the async dispatch queue: queued chunk programs (each
            # holding its input slices and outputs) crash the tunneled
            # device worker on multi-million-state levels — even a
            # 32-chunk window died; the per-chunk scalar drain is the
            # profiler-proven configuration and costs ~10 ms against a
            # ~400 ms chunk (the round-1 regression was per-chunk
            # fetches of whole result arrays at 256-state chunks, not
            # the drain itself)
            synced += 1
            if synced >= self.sync_every:
                jax.device_get(abort_at)
                synced = 0
        if grouping and cvs:
            overflow_g = overflow_g | flush_group()
        if grouping:
            lvs, lfs, lps = gvs, gfs, gps
            n_lanes = len(gvs) * self.cap_g
        else:
            # span outputs are [G*cap_x]-wide entries, chunk outputs
            # [cap_x]; lane order is irrelevant to the level dedup
            # (payloads are unique per lane, the sort is global)
            lvs = svs + cvs
            lfs = sfs + cfs
            lps = sps + cps
            n_lanes = (len(svs) * G + len(cvs)) * self.cap_x
        # pad the level-dedup input to a half-step-quantized lane count
        # ({2^k, 3*2^(k-1)}) so its sort program compiles O(log) times per
        # run, not once per level — and a just-over-pow2 level (the common
        # case after a 1.5x cap_x growth) pays a 12% pad, not 95%
        pad = _cap_steps(max(n_lanes, 1)) - n_lanes
        if pad:
            lvs.append(jnp.full((pad,), SENT, U64))
            lfs.append(jnp.full((pad,), SENT, U64))
            lps.append(jnp.full((pad,), -1, I64))
        # the level-dedup sort shape: part of the sanitizer's per-level
        # shape signature (a new lane count legitimately recompiles it)
        self._san_lanes = n_lanes + pad
        if use_hs:
            # fused probe-and-insert: uniqueness + visited filter + store
            # update in one O(lanes) program — the slab comes back as a
            # PENDING update so the overflow-redo loop can discard it
            (n_new_dev, new_fps, new_payload, slab2,
             ovf_h) = _level_dedup_hash(
                jnp.concatenate(lvs), jnp.concatenate(lfs),
                jnp.concatenate(lps), hslab,
            )
            graft_sanitize.note_dispatch("device.dedup_hash")
            self._hs_pending = slab2
        else:
            ovf_h = jnp.zeros((), bool)
            n_new_dev, new_fps, new_payload = _level_dedup(
                jnp.concatenate(lvs), jnp.concatenate(lfs),
                jnp.concatenate(lps), visited,
            )
            graft_sanitize.note_dispatch("device.dedup")
        # ONE host sync for the level's control state
        n_new, ab, ovf, ovf_g, ovf_hs, mult_np = jax.device_get(
            (n_new_dev, abort_at, overflow, overflow_g, ovf_h, mult_acc)
        )
        return (
            int(n_new), new_fps, new_payload, int(ab), bool(ovf), bool(ovf_g),
            bool(ovf_hs), mult_np,
        )

    # -- external-store path: per-group host filtering ---------------------
    #
    # The device never holds more than one group (G chunks) of candidates:
    # each group is deduped on device (min-(fp_full, payload) representative
    # per view fp, ``_group_unique``), its unique candidates are fetched,
    # and the level-global choice + visited filter run host-side — a numpy
    # lexsort with exactly ``_level_dedup``'s ordering, then one batched
    # ``host_store.insert``.  Device memory is O(G * cap_x) regardless of
    # frontier size, which removes the round-2 ceiling (11.1M-state level
    # 25 OOMed on the level-wide concat).  Per-group fetches double as the
    # dispatch-queue drains the tunneled device needs anyway.
    #
    # Groups are also the unit of intra-level durability: each completed
    # group's unique candidates land in ``partial_####_#####.npz`` before
    # the next group starts, so a mid-level crash costs one group, not the
    # level (TLC's mid-level ``states/`` queue spill analog; the level-23
    # corruption saga in BASELINE.md is the motivation).  Partials are
    # self-validating (level, chunk, G, K, n_f in the meta; cap_x is
    # recorded but deliberately not matched — see _load_partials) — BFS
    # determinism makes a matching partial's contents exact.

    def _expand_level_host(self, frontier, n_f, ckdir=None, depth=None):
        # the host path's frontier is a LIST of segment buffers (len >= 1;
        # see _materialize_segs); chunks never straddle segments (segment
        # sizes are chunk multiples by construction)
        seg_len = _seg_rows(frontier[0])
        n_f_dev = jnp.asarray(n_f, I64)
        G = self.G
        n_chunks = -(-max(n_f, 1) // self.chunk)
        n_groups = -(-n_chunks // G)
        level = (depth + 1) if depth is not None else None
        hv, hf, hp = [], [], []  # per-group unique candidates, host-side
        mult_np = np.zeros((self.K,), np.int64)
        saved = self._load_partials(ckdir, level, n_f) if ckdir else {}
        # host-paged segments transit through a one-entry cache: the chunk
        # walk is ascending, so when it enters segment sj+1 the device
        # copy of sj drops (the numpy copy in ``frontier`` stays)
        page = {"j": -1, "dev": None}

        def seg_dev(sj):
            s = frontier[sj]
            if not isinstance(s, _HostSeg):
                return s
            if page["j"] != sj:
                page["j"], page["dev"] = sj, self._seg_to_dev(s)
            return page["dev"]

        # async group window (engine/pipeline.py): group gi's padded
        # fetch starts with copy_to_host_async and completes — through
        # the LEDGERED device_get — only after group gi+1..gi+W have
        # been dispatched, so the device expands the next groups while
        # the previous ones stream over the (4 MB/s tunneled) host link
        # and the host tail (slice/append + partial save) runs.  All
        # dispatch stays on this (the main) thread; window 0 == the
        # serial fetch-after-dispatch chain, bit-identically.
        win = graft_pipeline.AsyncFetchWindow(
            self.pipeline_window if self.pipeline else 0
        )
        stop: dict = {}

        def consume(gi_, host):
            nonlocal mult_np
            n_u, ab, ovf_h, mult_g, gv_np, gf_np, gp_np = host
            if stop:
                # a prior group already aborted/overflowed: drop this
                # group's mult too — the serial chain never expands past
                # the aborting group, and the discard() path likewise
                # contributes nothing
                return
            mult_np += np.asarray(mult_g, np.int64)
            if int(ab) < n_f or bool(ovf_h):
                # abort (split-brain) or cap_x overflow: nothing reached
                # the store yet, so run() can report the trace / grow the
                # budget and redo the level cleanly.  Completed groups'
                # partials survive the redo — their candidate sets are
                # budget-independent (see _load_partials)
                stop["ab"], stop["ovf"] = int(ab), bool(ovf_h)
                return
            n_u = int(n_u)
            gv_c = np.asarray(gv_np[:n_u])
            gf_c = np.asarray(gf_np[:n_u])
            gp_c = np.asarray(gp_np[:n_u])
            hv.append(gv_c)
            hf.append(gf_c)
            hp.append(gp_c)
            if ckdir:
                self._save_partial(
                    ckdir, level, gi_, gv_c, gf_c, gp_c,
                    np.asarray(mult_g, np.int64), n_f,
                )

        for gi in range(n_groups):
            if stop:
                break
            if gi in saved:
                z = saved[gi]
                hv.append(z["hv"])
                hf.append(z["hf"])
                hp.append(z["hp"])
                mult_np += z["mult"]
                continue
            mult_acc = jnp.zeros((self.K,), I64)
            abort_at = BIG
            overflow = jnp.zeros((), bool)
            # a FULL group whose G chunks sit inside one segment runs as
            # one scanned span program (one dispatch instead of ~13*G);
            # the tail group and small chunks keep the per-chunk path
            g_lo, g_hi = gi * G * self.chunk, (gi + 1) * G * self.chunk
            span_ok = (
                self.chunk >= self.span_min_chunk
                and not self.orbit
                and (gi + 1) * G <= n_chunks
                and g_lo // seg_len == (g_hi - 1) // seg_len
            )
            fused = None
            if span_ok and self._mega_flag:
                # megakernel slice of the host-store path: span expand +
                # intra-group dedup in ONE program per group — the level
                # is then one dispatch + one fetch per group up to the
                # host-store probe
                sj, off = divmod(g_lo, seg_len)
                (n_u_dev, gv, gf, gp, mult_acc, abort_at,
                 overflow) = self._expand_group_fused(
                    seg_dev(sj), jnp.asarray(off, I64),
                    jnp.asarray(g_lo, I64), n_f_dev,
                )
                graft_sanitize.note_dispatch("host.group_fused")
                fused = True
            elif span_ok:
                sj, off = divmod(g_lo, seg_len)
                cvs_s, cfs_s, cps_s, mult_acc, abort_at, overflow = (
                    self._expand_span(
                        seg_dev(sj), jnp.asarray(off, I64),
                        jnp.asarray(g_lo, I64), n_f_dev,
                    )
                )
                graft_sanitize.note_dispatch("host.span")
                cat_v, cat_f, cat_p = (
                    cvs_s.reshape(-1), cfs_s.reshape(-1), cps_s.reshape(-1)
                )
            else:
                cvs, cfs, cps = [], [], []
                synced = 0
                for ci in range(gi * G, min((gi + 1) * G, n_chunks)):
                    sj, off = divmod(ci * self.chunk, seg_len)
                    part_f = jax.tree.map(
                        lambda x: jax.lax.dynamic_slice_in_dim(
                            x, off, self.chunk
                        ),
                        seg_dev(sj),
                    )
                    cv, cf, cp, mult_slots, ab_at, ovf = self._expand_chunk(
                        part_f, jnp.asarray(ci * self.chunk, I64), n_f_dev
                    )
                    graft_sanitize.note_dispatch("host.chunk")
                    cvs.append(cv)
                    cfs.append(cf)
                    cps.append(cp)
                    mult_acc = mult_acc + mult_slots
                    abort_at = jnp.minimum(abort_at, ab_at)
                    overflow = overflow | ovf
                    synced += 1
                    if synced >= self.sync_every:
                        jax.device_get(abort_at)
                        synced = 0
                while len(cvs) < G:  # pad the group to its fixed width
                    cvs.append(jnp.full((self.cap_x,), SENT, U64))
                    cfs.append(jnp.full((self.cap_x,), SENT, U64))
                    cps.append(jnp.full((self.cap_x,), -1, I64))
                cat_v = jnp.concatenate(cvs)
                cat_f = jnp.concatenate(cfs)
                cat_p = jnp.concatenate(cps)
            if fused is None:
                n_u_dev, gv, gf, gp = _group_unique(cat_v, cat_f, cat_p)
                graft_sanitize.note_dispatch("host.unique")
            # submit the FIXED-shape padded buffers to the fetch window
            # (host-side slicing: a device-side gv[:n_u] slice would
            # compile a fresh tiny program per distinct n_u — one remote
            # compile per group on a tunneled backend, each a hang/crash
            # opportunity — for a bandwidth saving of ~6% of the fetch)
            win.submit(
                (n_u_dev, abort_at, overflow, mult_acc, gv, gf, gp),
                functools.partial(consume, gi),
            )
        # ---- window drain: the LEVEL BOUNDARY -------------------------
        # every group's candidates must be on the host before the level-
        # global representative choice and the store insert below — a
        # store insert with the window still open would let half a
        # level's candidates filter against the other half's inserts
        if not stop:
            win.drain()
        if stop:
            win.discard()  # complete in-flight fetches, ledger balanced
            return (0, None, None, stop["ab"], stop["ovf"], False, False,
                    mult_np)
        # ---- level-global representative choice + visited filter --------
        av = np.concatenate(hv) if hv else np.empty(0, np.uint64)
        af = np.concatenate(hf) if hf else np.empty(0, np.uint64)
        ap = np.concatenate(hp) if hp else np.empty(0, np.int64)
        order = np.lexsort((ap, af, av))
        sv, sp = av[order], ap[order]
        first = np.ones(len(sv), bool)
        first[1:] = sv[1:] != sv[:-1]
        uniq_v, uniq_p = sv[first], sp[first]
        t_probe = time.monotonic()
        is_new = self.host_store.insert(uniq_v)
        if getattr(self.host_store, "num_runs", 0):
            # the external store holds spilled (disk) runs: this
            # level's membership verdicts probed the warm/cold tiers —
            # publish the non-overlapped wait (the group candidates
            # themselves streamed through the async fetch window, so
            # the device expanded ahead of this probe)
            graft_obs.tier_probe(
                (depth + 1) if depth is not None else 0, len(uniq_v),
                int(len(uniq_v) - is_new.sum()),
                wait_s=time.monotonic() - t_probe,
            )
        new_fps = uniq_v[is_new]
        new_pay = uniq_p[is_new]
        # emit survivors in ASCENDING PAYLOAD order (payload = pidx*K+slot,
        # unique, so a plain argsort is deterministic): the delta record,
        # the trace spill and the frontier all share this order, and it is
        # what lets the segment-streamed materialize walk the parent
        # segments monotonically (the fps are no longer cv-sorted; nothing
        # downstream relied on that)
        o = np.argsort(new_pay)
        return (len(new_fps), np.ascontiguousarray(new_fps[o]),
                np.ascontiguousarray(new_pay[o]), int(BIG), False, False,
                False, mult_np)

    def _save_partial(self, ckdir, level, gi, hv, hf, hp, mult, n_f):
        resilience.commit_npz(
            ckdir,
            f"partial_{level:04d}_{gi:05d}.npz",
            dict(
                hv=hv, hf=hf, hp=hp, mult=mult,
                # meta[7]: fingerprint definition (0 = min-over-P,
                # 1 = orbit) — a partial's hv/hf are raw fingerprints and
                # must never be replayed into a run using the other
                # definition.  meta[8]: the async pipeline's in-flight
                # window at save time — INFORMATIONAL, never matched on
                # resume: partials commit in submission order, so a
                # crash mid-level loses at most this many trailing
                # groups (the recovery re-expands at most one window)
                meta=np.asarray(
                    [level, gi, self.chunk, self.cap_x, self.G, self.K,
                     n_f, int(self.orbit),
                     self.pipeline_window if self.pipeline else 0],
                    np.int64,
                ),
            ),
            kind="partial",
            depth=level,
            run_fp=self._run_fp,
        )

    def _load_partials(self, ckdir, level, n_f):
        """Completed-group partials for this level; stale ones are wiped.

        A partial is valid only if its meta matches the in-flight level
        exactly (a cap_x growth redo or a chunk-size change moves every
        group boundary).  Partials from other levels are leftovers of a
        crash between the delta save and the wipe — delete them.
        meta[8] (the async pipeline window, when present) is
        deliberately NOT matched: the window changes only how many
        trailing groups a crash can lose (consume order == submission
        order, so saved partials are always a clean prefix-with-holes
        of completed groups), never a completed group's contents —
        a resume may freely retune the window like chunk/cap_x."""
        import glob

        out = {}
        stale = []
        for f in sorted(glob.glob(os.path.join(ckdir, "partial_*.npz"))):
            try:
                z = np.load(f)
                meta = tuple(int(x) for x in z["meta"])
                # cap_x (meta[3]) deliberately does NOT participate in the
                # match: a saved group's candidate set is budget-
                # independent (its chunks passed the overflow check before
                # the save), so a cap_x-growth redo of the level keeps
                # every completed group instead of re-expanding it
                fp_def = meta[7] if len(meta) > 7 else 0
                want = (level, meta[1], self.chunk, self.G, self.K, n_f,
                        int(self.orbit))
                got = (meta[0], meta[1], meta[2], meta[4], meta[5], meta[6],
                       fp_def)
                if level is None or got != want:
                    stale.append(os.path.basename(f))
                    continue
                rec = dict(
                    hv=z["hv"], hf=z["hf"], hp=z["hp"],
                    mult=z["mult"].astype(np.int64),
                )
            except (OSError, ValueError, KeyError, EOFError,
                    zipfile.BadZipFile):
                # crash-truncated partial: the zip layer raises any of
                # these depending on where the write stopped
                stale.append(os.path.basename(f))
                continue
            out[meta[1]] = rec
        if stale:
            resilience.discard_artifacts(ckdir, stale)
        return out

    def _wipe_partials(self, ckdir):
        import glob

        resilience.discard_artifacts(
            ckdir,
            [os.path.basename(f)
             for f in glob.glob(os.path.join(ckdir, "partial_*.npz"))],
        )

    def run(
        self,
        max_depth: int | None = None,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 1,
        resume_from: str | None = None,
    ) -> CheckResult:
        self._audit_strikes = 0
        try:
            return self._run(
                max_depth=max_depth, checkpoint_dir=checkpoint_dir,
                checkpoint_every=checkpoint_every, resume_from=resume_from,
            )
        finally:
            if self._fpager is not None:
                # frontier segments are per-level transients: a finished
                # (or raised) run leaves none worth keeping — resume
                # rebuilds frontiers from the delta log
                try:
                    self._fpager.retire_all()
                except OSError:
                    pass  # a torn teardown is sweep_fsegs' problem
            if self.watchdog is not None:
                self.watchdog.disarm()
            if self._prewarmer is not None:
                # run over (done, raised, or preempted): give the almost-
                # finished tail a bounded grace to land in the persistent
                # compile cache, then drop the queued rest — nothing in
                # THIS process will use it, and a supervised relaunch
                # re-forecasts the same plan (a later run() on this
                # checker builds a fresh prewarmer via _submit_prewarm)
                self._prewarmer.join(10.0)
                self._prewarmer.shutdown()

    def _run(
        self,
        max_depth: int | None = None,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 1,
        resume_from: str | None = None,
    ) -> CheckResult:
        cfg = self.cfg
        K = self.K
        t0 = time.monotonic()

        if checkpoint_dir and checkpoint_every:
            import glob as _glob

            if resume_from is None and os.path.isdir(checkpoint_dir):
                # a killed earlier writer must not leak .tmp_* files
                # into a fresh run's directory (they waste disk and
                # shadow names; resume paths sweep via heal_log)
                resilience.sweep_tmp(checkpoint_dir)
            stale = _glob.glob(os.path.join(checkpoint_dir, "delta_*.npz"))
            has_base = os.path.exists(os.path.join(checkpoint_dir, "base.npz"))
            if resume_from is None and (stale or has_base):
                raise ValueError(
                    f"{checkpoint_dir} holds checkpoints from a previous "
                    "run; a fresh run would interleave two runs' logs into "
                    "one (silently wrong) replay chain — resume with "
                    "--recover or clear the directory"
                )
            if (
                resume_from is not None
                and os.path.isdir(resume_from)
                and os.path.abspath(resume_from) != os.path.abspath(checkpoint_dir)
                and (stale or has_base)
            ):
                raise ValueError(
                    f"resuming from {resume_from} but {checkpoint_dir} "
                    "already holds another run's checkpoints — the two "
                    "logs would interleave; clear it or checkpoint into "
                    "the resumed directory itself"
                )
            if (
                resume_from is not None
                and not os.path.isdir(resume_from)
                and os.path.abspath(resume_from)
                == os.path.abspath(os.path.join(checkpoint_dir, "base.npz"))
                and stale
            ):
                # resuming from the directory's own base monolith while it
                # already holds deltas would re-append a second chain on
                # top of the existing one (stale deeper deltas would then
                # replay with no gap error) — the directory itself is the
                # correct resume point
                raise ValueError(
                    f"{checkpoint_dir} holds delta checkpoints beyond its "
                    "base.npz; resume from the directory itself (delta "
                    "replay) instead of the base monolith, or clear the "
                    "deltas first"
                )
            if (
                resume_from is not None
                and not os.path.isdir(resume_from)
                and os.path.abspath(resume_from)
                != os.path.abspath(os.path.join(checkpoint_dir, "base.npz"))
            ):
                # resuming from a monolith while appending deltas: anchor
                # the delta chain by copying the monolith in as the base
                if stale:
                    raise ValueError(
                        f"{checkpoint_dir} already holds delta checkpoints; "
                        "resume from the directory itself instead of a "
                        "monolith file"
                    )
                import shutil

                os.makedirs(checkpoint_dir, exist_ok=True)
                shutil.copyfile(
                    resume_from, os.path.join(checkpoint_dir, "base.npz")
                )
                resilience.adopt_file(
                    checkpoint_dir, "base.npz", kind="base",
                    run_fp=self._run_fp,
                )
        # tiered visited store: the hot slab lives under a device-byte
        # budget; demotions spill whole generations to the checkpoint
        # directory (warm in host RAM, cold on disk — store/tiered.py)
        spill = (
            checkpoint_dir if (checkpoint_dir and checkpoint_every)
            else (resume_from if (
                resume_from and os.path.isdir(resume_from)
            ) else None)
        )
        if self.store_bytes and self.use_hashstore and (
            self.host_store is None
        ):
            self.tiered = graft_tiered.TieredVisitedStore(
                self.store_bytes, warm_bytes=self.warm_bytes,
                spill_dir=spill, run_fp=self._run_fp,
            )
            if spill:
                # stale generation files (a previous incarnation's, or
                # a crash mid-demotion) are noise: the delta log is the
                # source of truth and the resume rebuild re-commits a
                # fresh, disjoint set
                graft_tiered.sweep_gens(spill)
        if spill is not None:
            # spilled frontiers: host segments past TLA_RAFT_FSEG_BYTES
            # page through the warm tier (kind="fseg").  Orphans from a
            # crashed incarnation are per-level transients the delta
            # log supersedes — swept, never replayed
            graft_tiered.sweep_fsegs(spill)
            if self.fseg_host_bytes:
                self._fpager = graft_tiered.FrontierPager(
                    spill, run_fp=self._run_fp,
                )
        self._fseg_live = []
        self._fseg_retire = []
        if resume_from is not None:
            if os.path.isdir(resume_from):
                ck = self._resume_from_deltas(resume_from)
            else:
                ck = self._load_checkpoint(
                    resume_from,
                    device_visited=(
                        self.host_store is None and not self.use_hashstore
                    ),
                )
                self._check_fp_def(ck["fp_def"], resume_from)
                if self.host_store is not None:
                    # a monolith of a device-store run resumes onto the
                    # external tier: its visited array IS the fingerprint
                    # set, so it seeds the cleared store (same move as the
                    # base.npz path in _resume_from_deltas)
                    self.host_store.clear()
                    self._seed_host_store(ck.pop("visited"))
                    ck["visited"] = jnp.full((64,), SENT, U64)
                    ck["frontier"] = [ck["frontier"]]
                elif self.use_hashstore:
                    # a sorted-store monolith resumes onto the hash slab:
                    # its visited array is the fingerprint set — rebuild
                    # host-side (insert_np), one device_put of the slab.
                    # Under a tiered budget the monolith's set re-tiers:
                    # whatever exceeds the hot budget demotes up front.
                    vall = np.asarray(ck.pop("visited"))
                    vall = vall[vall != SENT]
                    if self._tier_on():
                        hot = self.tiered.rebuild(
                            [(ck["depth"], vall)],
                            hot_slots=self.tiered.hot_slot_budget(),
                        )
                        self.hstore = hashstore.DeviceHashStore.from_fps(
                            hot
                        )
                    else:
                        self.hstore = hashstore.DeviceHashStore.from_fps(
                            vall
                        )
                    ck["visited"] = jnp.full((64,), SENT, U64)
            frontier, visited = ck["frontier"], ck["visited"]
            n_f, distinct, generated = ck["n_f"], ck["distinct"], ck["generated"]
            depth, level_sizes, trace_levels = (
                ck["depth"], ck["level_sizes"], ck["trace_levels"],
            )
            mult_per_slot = ck["mult_per_slot"]
        else:
            st0 = init_batch(cfg, 1)
            n_f = 1
            fv, _ff = self._fp_states(st0)
            if self.host_store is not None:
                self.host_store.insert(np.asarray(fv.astype(U64)))
                visited = jnp.full((64,), SENT, U64)
            elif self.use_hashstore:
                self.hstore = hashstore.DeviceHashStore.from_fps(
                    np.asarray(jax.device_get(fv.astype(U64)))
                )
                visited = jnp.full((64,), SENT, U64)
            else:
                visited = jnp.sort(
                    jnp.concatenate([fv.astype(U64), jnp.full((63,), SENT, U64)])
                )
            distinct = 1
            generated = 0
            level_sizes = [1]
            depth = 0
            trace_levels = []
            mult_per_slot = np.zeros(K, np.int64)

            bad0 = int(
                jax.device_get(self._inv_scan(st0, jnp.asarray(1, I64)))
            )
            if bad0 >= 0:
                name0 = self._bad_invariant_name(st0, bad0)
                return CheckResult(
                    False, 1, 0, 0, (1,),
                    (
                        f"Invariant {name0} is violated",
                        self._trace(trace_levels, 0, 0),
                    ),
                )
            frontier, ovf0 = jax.jit(self._deflate)(st0)
            if bool(jax.device_get(ovf0.any())):
                raise RuntimeError(
                    f"initial state's message set exceeds cap_m={self.cap_m}"
                )
            if self.host_store is not None:
                frontier = [frontier]
        # frontier capacity must be a chunk multiple for dynamic slicing
        # (segment lists are chunk-aligned by construction)
        if (
            not isinstance(frontier, list)
            and frontier.voted_for.shape[0] % self.chunk
        ):
            cap0 = -(-frontier.voted_for.shape[0] // self.chunk) * self.chunk
            frontier = jax.tree.map(
                lambda x: _pad_axis0(x, cap0), frontier
            )
        elif isinstance(frontier, list) and (
            _seg_rows(frontier[0]) % self.chunk
        ):
            cap0 = -(-_seg_rows(frontier[0]) // self.chunk) * self.chunk
            frontier = [
                jax.tree.map(lambda x: _pad_axis0(x, cap0),
                             self._seg_to_dev(s))
                for s in frontier
            ]

        # a STOPPED superstep (uncommitted abort/violation/overflow
        # level) must route its level through the per-level machinery
        # exactly once before supersteps re-engage
        skip_superstep = False
        while n_f > 0:
            resilience.fault_fire("level.start")
            if resilience.preempt_requested():
                # every completed level's delta record is already
                # durable (written synchronously at level end), so
                # there is nothing left to flush — exit resumable
                raise resilience.Preempted(
                    checkpoint_dir if checkpoint_every else None, depth
                )
            if max_depth is not None and depth >= max_depth:
                break
            graft_obs.level_begin(depth + 1, n_f)
            self._hbm_note(frontier, level_sizes, max_depth, depth)
            if self.watchdog is not None:
                # armed BEFORE the device fault sites: an injected hang
                # at the dispatch site is exactly what it must convert
                # into a clean exit 75
                self.watchdog.arm(f"level {depth + 1} (single-device)")
            resilience.fault_fire("device.lost")
            resilience.fault_fire("device.hang")
            if self.presize and len(level_sizes) > PRESIZE_MIN_LEVELS:
                self._update_presize(level_sizes, distinct, max_depth,
                                     frontier)
                if self.host_store is None and self.use_hashstore:
                    # slab presize: one rehash up to the forecast entry
                    # count, so deep runs compile one probe shape per
                    # pow2 magnitude instead of overflow-redoing levels
                    ent = getattr(self, "_presize_entries", 0)
                    if ent:
                        try:
                            self._tier_reserve(int(ent * 1.1))
                        except Exception as e:  # graftlint: waive[GL003]
                            # a failed presize reserve degrades like any
                            # other grow failure (reserve() only grows)
                            visited = self._degrade_hashstore(e)
                elif (self.host_store is None
                        and self._presize_vcap > visited.shape[0]):
                    # SENT-pad the sorted store up front so its shape is
                    # pinned for the rest of the run (SENT sorts last, so
                    # appending keeps the array sorted)
                    visited = jnp.concatenate([
                        visited,
                        jnp.full(
                            (self._presize_vcap - visited.shape[0],),
                            SENT, U64,
                        ),
                    ])
            if self.prewarm and len(level_sizes) > PRESIZE_MIN_LEVELS:
                # forecast-driven AOT prewarm: the shape ladder the deep
                # levels will hit compiles in the background while the
                # cheap shallow levels run (re-submitted every level —
                # the Prewarmer dedupes keys, so only a SHARPER forecast
                # queues fresh programs)
                self._submit_prewarm(
                    level_sizes, distinct, max_depth, frontier, visited
                )
            # --- tiered drain: a slab left over-budget (transient
            # soft-seat, MIN_CAP floor) or whose next growth would bust
            # the budget demotes HERE, between levels, where no redo is
            # ever needed — and this is the superstep windows' only
            # drain site (their commit path adopts without the staged
            # between-level grow) ----------------------------------------
            self._tier_drain(depth, n_f)
            # consumed frontier segments' warm-tier artifacts retire at
            # the level top: the previous level is committed, nothing
            # can replay its parents
            self._fseg_retire_consumed()
            # adaptive sieve: per-level tick drives the stood-down
            # governor's re-arm probation (tune/adaptive.py)
            self.sieve_governor.note_level(depth)
            # --- multi-level resident superstep: up to N fused levels
            # in ONE device program + ONE ledgered ring fetch
            # (engine/superstep.py).  A stopped level (abort /
            # violation / any overflow / ring high-water) falls
            # through to the per-level paths below, which re-enter the
            # existing grow-and-redo machinery against the slab as of
            # the committed prefix --------------------------------------
            if (not skip_superstep
                    and self._superstep_span_at(max_depth, depth) > 1
                    and self._mega_level_ok(frontier, n_f)):
                if self.watchdog is not None:
                    # the armed deadline scales with the declared level
                    # span (satellite: an N-level superstep must not
                    # trip the per-level hang budget)
                    self.watchdog.arm(
                        f"levels {depth + 1}..{depth + self._superstep_span_at(max_depth, depth)}"
                        " (superstep)",
                        span=self._superstep_span_at(max_depth, depth),
                    )
                sres = self._run_superstep(
                    frontier, n_f, max_depth, depth, level_sizes
                )
            else:
                sres = None
            skip_superstep = False
            if sres is not None and sres.get("degraded"):
                # hash store degraded while presizing for the span:
                # adopt the rebuilt sorted store and run staged
                frontier = sres["frontier"]
                visited = self._degraded_visited
                self._degraded_visited = None
                sres = None
                if self.watchdog is not None:
                    # the span-N window must not cover the staged
                    # single level below: its end-of-level disarm
                    # would divide one level's wall by N and deflate
                    # the adaptive budget right when the degraded
                    # (sorted-store) levels run slowest
                    self.watchdog.arm(f"level {depth + 1} (degraded)")
            if sres is not None:
                frontier = sres["frontier"]
                hit_fixpoint = False
                depth0 = depth  # window entry, for the dump cadence
                for li, rec in enumerate(sres["recs"]):
                    if li:
                        # the per-level crash sites keep their once-
                        # per-level cadence (the while-loop top fired
                        # for the superstep's first level)
                        resilience.fault_fire("level.start")
                    level_mult = rec["mult"]
                    mult_per_slot = mult_per_slot + level_mult
                    generated += int(level_mult.sum())
                    if rec["n_new"] == 0:
                        # the terminal fixpoint level: generated counts
                        # (the staged loop breaks AFTER the mult add),
                        # distinct/depth do not
                        hit_fixpoint = True
                        break
                    n_new = rec["n_new"]
                    distinct += n_new
                    level_sizes.append(n_new)
                    depth += 1
                    trace_levels.append((rec["pidx"], rec["slot"]))
                    n_f = n_new
                    graft_obs.level_commit(
                        depth, n_new, distinct, generated,
                        slab_cap=(
                            self.hstore.cap
                            if self.use_hashstore and self.hstore
                            is not None else 0
                        ),
                    )
                    if self.progress is not None:
                        self.progress(
                            dict(
                                level=depth,
                                frontier=n_new,
                                distinct=distinct,
                                generated=generated,
                                elapsed=time.monotonic() - t0,
                            )
                        )
                    if graft_sanitize.tracking():
                        sig = (
                            sres["cap_f"], self.hstore.cap,
                            sres["cap_f"], self.cap_x, self.cap_g,
                            self.cap_m, self._san_lanes,
                        )
                        if sig != getattr(self, "_san_sig", None):
                            graft_sanitize.note_shape_event(
                                f"level shapes {sig}"
                            )
                            self._san_sig = sig
                        graft_sanitize.level_tick()
                    if checkpoint_dir and checkpoint_every:
                        self._save_delta(
                            checkpoint_dir, depth, rec["pidx"],
                            rec["slot"], rec["fps"], level_mult, n_new,
                        )
                if sres["n_total"] or hit_fixpoint:
                    # adopt the committed prefix's slab in one step
                    self.hstore.adopt(sres["slab"], sres["n_total"])
                    # free conservation check: the driver counted the
                    # returned slab's live slots — they must equal the
                    # distinct set after the committed prefix (or, once
                    # generations exist, the HOT-tier count: the slab
                    # holds only the post-demotion residue, and every
                    # committed level was sieve-clean so its fresh
                    # count is insert-exact)
                    resilience.integrity.occupancy_check(
                        "device hash slab", sres["slab_live"],
                        self.hstore.count if self._tier_active()
                        else distinct,
                        level=depth,
                    )
                if checkpoint_dir and checkpoint_every and sres["recs"]:
                    dump_every = hashstore.dump_interval(
                        self.hstore.cap * 8
                    ) if self.use_hashstore else 0
                    # floor-crossing, not ==: the window advanced depth
                    # by up to span levels, and any cadence point it
                    # crossed earns the (one, end-of-window) dump —
                    # keeping the per-level path's snapshot cadence
                    if (self.use_hashstore and dump_every
                            and (depth // dump_every)
                            > (depth0 // dump_every)):
                        self.hstore.dump(
                            os.path.join(checkpoint_dir, "hslab.npz"),
                            depth, int(self.orbit),
                            run_fp=self._run_fp,
                        )
                if hit_fixpoint:
                    if self.watchdog is not None:
                        self.watchdog.disarm(levels=len(sres["recs"]))
                    break
                # adaptive sieve: feed the window's outcome — whether
                # it stopped on in-kernel sieve hits — to the governor
                # (only FLAG_TIER stops count as sieve-dirty; overflow
                # and ring stops say nothing about revisit density)
                if self._sieve_ready():
                    self.sieve_governor.note_window(
                        sieve_stop=bool(
                            sres["reason"] == "stop"
                            and sres["flags"]
                            & graft_superstep.FLAG_TIER
                        ),
                        level=depth,
                    )
                if sres["reason"] == "stop" or (
                    sres["reason"] == "ring" and not sres["recs"]
                ):
                    # a zero-commit window (uncommitted stop level, or
                    # a ring too small for even one level) must make
                    # progress through the per-level path before the
                    # next superstep engages
                    skip_superstep = True
                if sres["reason"] == "stop":
                    # the control vector names the stopped level's
                    # overflow class — grow the budget NOW so the
                    # per-level replay lands on its first redo instead
                    # of re-discovering the overflow (a stopped level
                    # then costs one attempt + one redo, exactly the
                    # per-level path's price)
                    flags = sres["flags"]
                    if flags & graft_superstep.FLAG_OVF_X:
                        self.cap_x = _cap_steps(self.cap_x + 1)
                        self.cap_g = max(
                            self.cap_g, self.G * self.cap_x // 2
                        )
                        self._jit_expand_programs()
                        self._mega_stats["redo_x"] += 1
                        graft_obs.grow("cap_x", self.cap_x)
                    if flags & graft_superstep.FLAG_OVF_SLAB:
                        self._hs_pending = None
                        try:
                            how = self._slab_grow_or_demote(
                                depth + 1, expected=max(n_f, 1)
                            )
                        except Exception as e:  # graftlint: waive[GL003]
                            # grow failure degrades to the sort path
                            # like every other grow site
                            visited = self._degrade_hashstore(e)
                        else:
                            self._mega_stats["redo_slab"] += 1
                            if how == "demoted":
                                # FLAG_OVF_SLAB_TIER: the host
                                # reclassified the stop — the grow the
                                # device asked for would bust the tier
                                # budget, so it demoted instead and the
                                # stopped level replays per-level (the
                                # span stands down to 1 from here on)
                                flags |= (
                                    graft_superstep.FLAG_OVF_SLAB_TIER
                                )
                                self._ss_stats["tier_stops"] = (
                                    self._ss_stats.get("tier_stops", 0)
                                    + 1
                                )
                                self.tiered.stats["tier_redos"] += 1
                                graft_obs.redo("slab_tier")
                            else:
                                graft_obs.grow("slab", self.hstore.cap)
                    if (flags & graft_superstep.FLAG_OVF_M
                            and self.cap_m < self.kern.uni.M):
                        # mirror the per-level cap_m redo (widen + re-
                        # jit) so the replay's first attempt lands
                        # under the grown width; at the universe cap
                        # the replay raises through its own error path
                        self.cap_m = min(
                            self.cap_m + 32, self.kern.uni.M
                        )
                        print(
                            f"[engine] cap_m overflow: growing to "
                            f"{self.cap_m} and replaying the stopped "
                            "level per-level", file=sys.stderr,
                        )
                        frontier = self._widen_msg_ids(frontier)
                        self._jit_expand_programs()
                        self._mega_stats["redo_m"] += 1
                        graft_obs.grow("cap_m", self.cap_m)
                    if flags & graft_superstep.FLAG_TIER:
                        # in-kernel sieve hits: POSSIBLE spilled
                        # revisits in the stopped level.  Nothing to
                        # grow — the per-level replay's tier tail
                        # performs the exact generation probe (a false
                        # positive costs exactly this one replay; its
                        # tier_probe event reports zero revisits)
                        self._ss_stats["sieve_stops"] = (
                            self._ss_stats.get("sieve_stops", 0) + 1
                        )
                        graft_obs.sieve_stop(depth + 1, -1)
                if self.watchdog is not None:
                    # a stopped window's elapsed covered only the
                    # committed levels (+ the aborted attempt): keep
                    # the per-level history honest or the stopped
                    # level's own replay budget deflates
                    self.watchdog.disarm(levels=len(sres["recs"]))
                continue
            # --- whole-level megakernel: ONE fused program + ONE
            # ledgered fetch per level (engine/megakernel.py); every
            # overflow redoes inside, a mid-level hash-store
            # degradation falls through to the staged path below -----
            mres = None
            if self._mega_segs_ok(frontier, n_f):
                # spilled frontier: stream the parent through the fused
                # program one segment at a time (cutting an over-budget
                # device frontier first) — the level runs fused even
                # when its working set exceeds HBM
                if not isinstance(frontier, list):
                    frontier = self._cut_frontier(frontier, n_f, depth)
                mres = self._expand_level_mega_segs(
                    frontier, n_f, max_depth, level_sizes, depth
                )
                if mres is not None and mres.get("degraded"):
                    frontier = mres["parent"]
                    visited = self._degraded_visited
                    self._degraded_visited = None
                    mres = None
            elif self._mega_level_ok(frontier, n_f):
                mres = self._expand_level_mega(
                    frontier, n_f, max_depth, level_sizes
                )
                if mres is not None and mres.get("degraded"):
                    # hash store degraded mid-level: adopt the rebuilt
                    # sorted store, rebind the (donation pass-through)
                    # parent and redo the level staged
                    frontier = mres["parent"]
                    visited = self._degraded_visited
                    self._degraded_visited = None
                    mres = None
            if mres is not None:
                n_new = mres["n_new"]
                abort_at = mres["abort_at"]
                level_mult = mres["level_mult"]
                # under donation the parent came back as the aliased
                # pass-through output; rebind so redo/audit/trace all
                # see a live buffer
                frontier = mres["parent"]
                new_fps = new_payload = None
            # --- staged fallback: expand + compact-then-dedup (device),
            # fused level fetch ------------------------------------------
            while mres is None:
                if isinstance(frontier, list) and self.host_store is None:
                    # staged redo of a segment-streamed level (degrade,
                    # or megakernel turned off mid-run): page the parent
                    # segments back in and concat — the staged device
                    # path wants one frontier, and the degraded route is
                    # already off the fast path (correctness first)
                    for i, s in enumerate(frontier):
                        frontier[i] = self._seg_to_dev(s)
                    frontier = _concat_fields(frontier)
                (n_new, new_fps, new_payload, abort_at, overflow, overflow_g,
                 overflow_h, level_mult) = self._expand_level(
                    frontier, n_f, visited,
                    ckdir=checkpoint_dir if checkpoint_every else None,
                    depth=depth,
                )
                if not (overflow or overflow_g or overflow_h):
                    break
                # a lane budget overflowed: grow it and redo the level
                # (pure computation, rare).  cap_x is baked into the traced
                # chunk program, so re-jit; cap_g is a static jit arg and
                # retraces on its own.
                graft_obs.redo(
                    "cap_x" if overflow else
                    ("cap_g" if overflow_g else "slab")
                )
                if overflow_h:
                    # a probe window filled: rehash into a bigger slab and
                    # redo against the ORIGINAL slab (the pending update
                    # is discarded — the kernels are functional); under
                    # the tiered budget the grow becomes a generation
                    # demotion ("demote, then redo") instead
                    self._hs_pending = None
                    try:
                        how = self._slab_grow_or_demote(
                            depth + 1, expected=max(n_f, n_new)
                        )
                    except Exception as e:  # graftlint: waive[GL003]
                        # any grow failure (device OOM, injected fault)
                        # degrades to the sort path — never mid-run death
                        visited = self._degrade_hashstore(e)
                    else:
                        if how == "demoted":
                            self.tiered.stats["tier_redos"] += 1
                        else:
                            graft_obs.grow("slab", self.hstore.cap)
                if overflow:
                    # half-step growth ({2^k, 3*2^(k-1)}): a doubled cap_x
                    # inflates every downstream lane count (group filter,
                    # level sort) for the rest of the run, and the common
                    # overflow is a mid-depth level firing ~5 lanes/parent
                    # against a 4x-chunk budget — 1.5x absorbs it
                    self.cap_x = _cap_steps(self.cap_x + 1)
                    self.cap_g = max(self.cap_g, self.G * self.cap_x // 2)
                    self._jit_expand_programs()
                    graft_obs.grow("cap_x", self.cap_x)
                if overflow_g:
                    self.cap_g *= 2
                    graft_obs.grow("cap_g", self.cap_g)
            if abort_at < n_f:
                # action_counts stays None on violations, like the oracle:
                # coverage of a partially-expanded level is ill-defined
                return CheckResult(
                    False, distinct, generated, depth, tuple(level_sizes),
                    (
                        'Assert "split brain" (Raft.tla:185)',
                        self._trace(trace_levels, depth, abort_at),
                    ),
                )
            mult_per_slot = mult_per_slot + level_mult
            generated += int(level_mult.sum())

            fps_host = None  # host-filtered level fps (delta-log record)
            pay_host = None  # host-side payloads (external-store path)
            if self.host_store is not None and n_new:
                # _expand_level_host already ran the store filter; its
                # outputs are host-side numpy in ASCENDING PAYLOAD order
                # (the load-bearing invariant of the segment-streamed
                # materialize and of delta-record/trace correspondence)
                fps_host, pay_host = new_fps, new_payload
                new_payload = _pad_axis0(
                    jnp.asarray(pay_host), max(_pow2(n_new), 4 * self.chunk)
                )
            if n_new == 0:
                # the empty level's partials (saved during its expansion)
                # have no delta record to supersede them — wipe here so a
                # completed run leaves a clean directory
                if self.host_store is not None and checkpoint_dir:
                    self._wipe_partials(checkpoint_dir)
                break

            tail = None
            if mres is not None:
                # the fused program already materialized the level and
                # fetched its trace/delta arrays in the one control get
                new_frontier = mres["new_frontier"]
                pidx_np = mres["pidx"]
                slot_np = mres["slot"]
                bad_idx = mres["bad_idx"]
            else:
                # --- materialize the survivors (device-resident) --------
                # slice width must not exceed the payload capacity (a
                # custom cap_x < 4*chunk shrinks the dedup output below
                # 4*chunk).  The new frontier comes back fully built at
                # its quantized capacity (donated in-place slice writes —
                # the parent, the slices AND the concat result never
                # coexist)
                new_frontier, bads, n_slices, sl, frontier = (
                    self._materialize_grow(
                        frontier, new_payload, n_new, pay_np=pay_host
                    )
                )
                # trace spill: the external-store path already holds the
                # payloads host-side — no device round-trip there.  The
                # device path submits its level-tail fetch (trace arrays +
                # the delta record's fps slice) to the async window instead
                # of blocking here, so the ~24 B/state tail crosses the host
                # link WHILE the store merge below runs on the device
                # (window 0 = the serial fetch-in-place chain).
                if pay_host is not None:
                    pidx_np = (pay_host // K).astype(np.int64)
                    slot_np = (pay_host % K).astype(np.int64)
                else:
                    pidx32 = (new_payload[: n_slices * sl] // K).astype(U32C)
                    # fetch width must match _save_delta's: a u16 cast here
                    # would wrap slots at K > 65535 before the widened save
                    # ever saw them
                    slot_jdt = jnp.uint16 if K <= 0xFFFF else jnp.uint32
                    slot16 = (
                        new_payload[: n_slices * sl] % K
                    ).astype(slot_jdt)
                    tree = [pidx32, slot16]
                    if (checkpoint_dir and checkpoint_every) or (
                        self._tier_active()
                    ):
                        # the delta record's fps (pow2-quantized device
                        # slice, host trim — see the checkpoint block);
                        # the tiered level tail needs them host-side
                        # regardless (the generation probe's input)
                        w_ck = min(new_fps.shape[0],
                                   max(_pow2(n_new), self.chunk))
                        tree.append(new_fps[:w_ck])
                    tail = graft_pipeline.DeferredFetch(
                        self.pipeline, tuple(tree)
                    )
                bad_idx = -1
                for si, b in enumerate(bads):
                    if b >= 0:
                        bad_idx = si * sl + int(b)
                        break
            # --- tiered level tail: probe the demoted generations -------
            # The fused/staged hot-slab probe can mistake a demoted
            # fingerprint's revisit for fresh; the generation probe
            # (sieve -> warm -> cold, store/tiered.py) finds exactly
            # those rows and ONE small compaction program drops them
            # from the materialized frontier — counts stay bit-identical
            # to the uncapped run.  The hit fps were re-inserted into
            # the hot slab by the very probe that admitted them: that
            # is the re-heat, so the next revisit resolves on device.
            n_new_store = n_new  # kernel-fresh (= hot-slab delta) count
            fps_np_lvl = None    # host-side POST-filter level fps
            tier_traced = False  # pidx/slot already host-filtered here
            # in-kernel sieve fast path: the fused level counted its
            # fresh lanes' sieve hits on device — ZERO hits provably
            # means no spilled revisits (blooms have no false
            # negatives), so the exact generation probe is skipped
            # outright (the common case once the working set moves past
            # the spilled prefix)
            if mres is not None and mres.get("tier_done"):
                # segment-streamed level: the sieve fast path / exact
                # generation probe already ran per segment inside
                # _expand_level_mega_segs — mres["fps"] is post-filter
                pass
            elif (self._tier_active() and n_new and mres is not None
                    and self._sieve_ready()
                    and mres.get("tier_hits", -1) == 0):
                self.tiered.stats["sieve_skips"] = (
                    self.tiered.stats.get("sieve_skips", 0) + 1
                )
            elif self._tier_active() and n_new:
                if mres is not None:
                    fps_pre = np.asarray(mres["fps"], np.uint64)
                else:
                    h = tail.get()
                    fps_pre = np.asarray(h[2])[:n_new].astype(np.uint64)
                n_keep, tier_keep, new_frontier = self._tier_filter_level(
                    depth, n_new, fps_pre,
                    new_frontier, new_frontier.voted_for.shape[0],
                )
                if tier_keep is None:
                    fps_np_lvl = fps_pre[:n_new]
                else:
                    fps_np_lvl = fps_pre[:n_new][tier_keep]
                    if mres is not None:
                        pidx_np = pidx_np[tier_keep]
                        slot_np = slot_np[tier_keep]
                        mres["fps"] = fps_np_lvl
                    else:
                        pidx_np = np.asarray(
                            h[0]
                        )[:n_new].astype(np.int64)[tier_keep]
                        slot_np = np.asarray(
                            h[1]
                        )[:n_new].astype(np.int64)[tier_keep]
                        tier_traced = True
                    if bad_idx >= 0:
                        # a violating row is truly new by construction
                        # (its FIRST visit is where the invariant scan
                        # sees it; generation members were scanned clean
                        # at theirs) — remap past the dropped revisits
                        assert tier_keep[bad_idx], (
                            "invariant violation attributed to an "
                            "already-visited (generation) row"
                        )
                        bad_idx = int(np.count_nonzero(tier_keep[:bad_idx]))
                    n_new = n_keep
                if n_new == 0:
                    # every fresh lane was a generation revisit: this IS
                    # the uncapped run's fixpoint level — adopt the slab
                    # (the re-heats stay hot; its count is the KERNEL
                    # fresh count) and stop exactly like the n_new == 0
                    # break above (mult already added, no delta record)
                    self.hstore.adopt(self._hs_pending, n_new_store)
                    self._hs_pending = None
                    n_f = 0
                    break
            # the audit re-expands sampled rows from their PARENTS, so
            # the pre-swap frontier must outlive the swap (audit runs
            # only; production keeps the old drop-at-swap lifetime)
            parent_prev = frontier if self.audit else None
            frontier = new_frontier
            if resilience.fault_flag("tensor.flip") and not isinstance(
                frontier, list
            ):
                # injected silent corruption: one bit of the first live
                # frontier row flips ON DEVICE after materialize — the
                # recorded fingerprints disagree with the slab from here
                # on, which is exactly what --audit must catch
                frontier = self._flip_frontier_row(frontier)

            # --- bookkeeping, store merge -------------------------------
            distinct += n_new
            level_sizes.append(n_new)
            depth += 1

            if self.host_store is None and self.use_hashstore:
                # the fused probe-and-insert already built the updated
                # slab — adopt the pending copy (no merge, no re-sort)
                # and grow BETWEEN levels when the next level's worst
                # case (~2x this one) would cross the 1/2 load line, so
                # mid-level overflow redos stay the rare backstop
                # adopt the KERNEL-fresh count: under the tiered store
                # the slab also re-heated this level's generation
                # revisits, so its occupancy delta is n_new_store, not
                # the post-filter n_new the distinct counter takes
                # (the segment-streamed path adopted per segment inside
                # — nothing pending there)
                if self._hs_pending is not None:
                    self.hstore.adopt(self._hs_pending, n_new_store)
                    self._hs_pending = None
                if mres is not None:
                    # free conservation check: the fused program counted
                    # the pending slab's live slots in its control
                    # vector — they must equal the distinct set exactly
                    # (or, once generations exist, the hot-tier count
                    # the engine tracks insert-exactly)
                    resilience.integrity.occupancy_check(
                        "device hash slab", mres["slab_live"],
                        self.hstore.count if self._tier_active()
                        else distinct,
                        level=depth,
                    )
                if self.hstore.need_grow(extra=2 * n_new) or (
                    self._tier_on() and self.hstore.count > 0
                    and not self.tiered.slab_fits(self.hstore.cap)
                ):
                    try:
                        # the between-level grow: under the tiered
                        # budget this is the COMMON demotion site (no
                        # redo needed — the level is already committed);
                        # it also DRAINS a soft over-budget slab left by
                        # a level whose fresh set alone exceeded the hot
                        # budget (seated transiently, demoted here)
                        how = self._slab_grow_or_demote(
                            depth, expected=2 * n_new
                        )
                    except Exception as e:  # graftlint: waive[GL003]
                        # grow failure degrades to the sort path (the
                        # adopted slab holds the full visited set)
                        visited = self._degrade_hashstore(e)
                    else:
                        if how == "grew":
                            graft_obs.grow("slab", self.hstore.cap)
            elif self.host_store is None:
                # merge, then trim the store to a pow4 capacity >= distinct;
                # new_fps is survivor-compacted, so slicing keeps every
                # real fingerprint and bounds the sort input.  The presize
                # floors pin both widths so deep runs reuse one compiled
                # merge instead of one per magnitude.
                w = max(_pow2(n_new), self.chunk)
                if self._presize_merge:
                    w = max(w, min(self._presize_merge, new_fps.shape[0]))
                graft_sanitize.note_dispatch("device.merge")
                visited = _merge_sorted(visited, new_fps[:w])[
                    : max(_cap4(distinct + 1), self._presize_vcap)
                ]
            if mres is None and pay_host is None and not tier_traced:
                # level tail boundary: everything after this needs the
                # trace arrays host-side (window 0 already fetched them
                # at submit, serially; the tiered correction above may
                # have consumed + filtered them already)
                h = tail.get()
                pidx_np = np.asarray(h[0])[:n_new].astype(np.int64)
                slot_np = np.asarray(h[1])[:n_new].astype(np.int64)
            trace_levels.append((pidx_np, slot_np))
            n_f = n_new

            graft_obs.level_commit(
                depth, n_new, distinct, generated,
                slab_cap=(
                    self.hstore.cap
                    if self.host_store is None and self.use_hashstore
                    and self.hstore is not None else 0
                ),
            )
            if self.progress is not None:
                self.progress(
                    dict(
                        level=depth,
                        frontier=n_new,
                        distinct=distinct,
                        generated=generated,
                        elapsed=time.monotonic() - t0,
                    )
                )
            if graft_sanitize.tracking():
                # per-level shape signature: a compile in a level whose
                # signature matches the previous level's is a SILENT
                # retrace (the regression class the sanitizer exists to
                # catch); any signature change is a declared shape event
                if isinstance(frontier, list):
                    fcap = tuple(_seg_rows(s) for s in frontier)
                else:
                    fcap = frontier.voted_for.shape[0]
                if self.host_store is not None:
                    vshape = 0
                elif self.use_hashstore:
                    vshape = self.hstore.cap
                else:
                    vshape = visited.shape[0]
                sig = (
                    fcap,
                    vshape,
                    mres["cap_out"] if mres is not None
                    else int(new_payload.shape[0]),
                    self.cap_x, self.cap_g, self.cap_m,
                    getattr(self, "_san_lanes", 0),
                )
                if sig != getattr(self, "_san_sig", None):
                    graft_sanitize.note_shape_event(f"level shapes {sig}")
                    self._san_sig = sig
                graft_sanitize.level_tick()
            if bad_idx >= 0:
                if isinstance(frontier, list):
                    L0 = _seg_rows(frontier[0])
                    bseg, boff = divmod(bad_idx, L0)
                    bsrc = frontier[bseg]
                    if isinstance(bsrc, _HostSeg):
                        bad_tree = Frontier(
                            **{f: jnp.asarray(v[boff : boff + 1])
                               for f, v in bsrc.fields.items()}
                        )
                    else:
                        bad_tree = jax.tree.map(
                            lambda x: x[boff : boff + 1], bsrc
                        )
                else:
                    bad_tree = jax.tree.map(
                        lambda x: x[bad_idx : bad_idx + 1], frontier
                    )
                one = self._inflate(bad_tree)
                name = self._bad_invariant_name(one, 0)
                return CheckResult(
                    False, distinct, generated, depth, tuple(level_sizes),
                    (
                        f"Invariant {name} is violated",
                        self._trace(trace_levels, depth, bad_idx),
                    ),
                )
            # --- sampled recomputation audit (BEFORE the level's delta
            # record commits: a caught level never enters the log) -----
            if self.audit and n_new:
                if fps_np_lvl is not None:
                    level_fps_ref = fps_np_lvl
                elif mres is not None:
                    level_fps_ref = mres["fps"]
                elif fps_host is not None:
                    level_fps_ref = fps_host
                else:
                    level_fps_ref = new_fps
                problems = self._audit_level(
                    parent_prev, frontier, pidx_np, slot_np,
                    level_fps_ref,
                    n_new, depth,
                )
                graft_obs.audit(
                    depth, min(self.audit, n_new),
                    len(problems or []),
                )
                if problems:
                    return self._audit_rewind(
                        problems, depth, max_depth, checkpoint_dir,
                        checkpoint_every,
                    )
            # checkpoint only invariant-clean levels: a resumed run never
            # re-checks its loaded frontier, so saving before the check
            # could hide a violation behind a crash+resume.  Delta-log
            # format: every level appends its (parent, slot, fps) record
            # (the replay chain needs every level, so checkpoint_every
            # only gates whether checkpointing happens at all).
            if checkpoint_dir and checkpoint_every:
                # with a host store the device fps are pre-filter — the
                # log must hold exactly the level's NEW fingerprints.
                # Device slice at a POW2-quantized width, trim host-side:
                # a raw [:n_new] slice compiled one eager program per
                # level — latent under the sorted store (its per-level
                # capacity steps declared shape events that excused the
                # compile), surfaced by the hash slab's constant shape
                if fps_np_lvl is not None:
                    # the tiered correction already holds the exact
                    # post-filter level fps host-side
                    fps_np = fps_np_lvl
                elif mres is not None:
                    # the fused program's one control fetch carried them
                    fps_np = mres["fps"]
                elif fps_host is not None:
                    fps_np = fps_host.astype(np.uint64)
                else:
                    # prefetched through the level-tail window above
                    fps_np = np.asarray(
                        tail.get()[2]
                    )[:n_new].astype(np.uint64)
                self._save_delta(
                    checkpoint_dir, depth, pidx_np, slot_np, fps_np,
                    level_mult, n_new,
                )
                # slab snapshot next to the delta log (versioned; resume
                # loads it when it matches, else rebuilds from the
                # replayed fps — never the source of truth).  The dump
                # fetches + rewrites the WHOLE slab, so it runs on the
                # shared size-aware interval (hashstore.dump_interval /
                # TLA_RAFT_SLAB_DUMP; 0 = off).
                dump_every = (
                    hashstore.dump_interval(self.hstore.cap * 8)
                    if self.use_hashstore else 0
                )
                if (self.use_hashstore and dump_every
                        and depth % dump_every == 0):
                    # slab-occupancy conservation check at the dump
                    # cadence: the snapshot about to be trusted by a
                    # future resume must count exactly the distinct set
                    # (the hot-tier count once generations exist — a
                    # tiered resume rebuilds from the log regardless)
                    resilience.integrity.occupancy_check(
                        "device hash slab", self.hstore.occupancy(),
                        self.hstore.count if self._tier_active()
                        else distinct,
                        level=depth,
                    )
                    self.hstore.dump(
                        os.path.join(checkpoint_dir, "hslab.npz"),
                        depth, int(self.orbit), run_fp=self._run_fp,
                    )
                if self.host_store is not None:
                    # the level's per-group partials are superseded by its
                    # delta record (only the in-flight level ever has any)
                    self._wipe_partials(checkpoint_dir)
            if self.watchdog is not None:
                self.watchdog.disarm()

        return CheckResult(
            True, distinct, generated, depth, tuple(level_sizes), None,
            self._action_counts(mult_per_slot),
        )
