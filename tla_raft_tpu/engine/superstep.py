"""Multi-level resident supersteps: N fused BFS levels per dispatch.

PR 9's megakernel cut a level to ONE device program + ONE ledgered
control fetch, but the host is still in the loop once per level — and
docs/PERF.md's gather-cliff analysis pins the residual floor on the
~38 ms FIXED dispatch/queue latency, not FLOPs, so shallow levels and
the sweep service's small configs remain pure launch tax.  This module
amortizes that floor to 1/N: a jitted, buffer-donating driver runs up
to N consecutive levels inside one ``lax.while_loop`` around the
megakernel's ``fused_level_core`` (expand -> probe-and-insert ->
materialize -> invariant — the SAME traced body, so the two paths
cannot drift), with each committed level's trace/delta record spooled
into a preallocated on-device ring buffer.  The host does ONE dispatch
+ ONE ledgered fetch per superstep; the fetch unpacks the ring into
exactly the per-level (pidx, slot, fps, mult, n_new) records the
checkpoint writer, trace reconstruction and resume already consume —
counts and violation stop points stay bit-identical on every golden
fixpoint.  BLEST and "Graph Traversal on Tensor Cores" (PAPERS.md)
keep BFS iterations accelerator-resident the same way when the
frontier fits.

**Commit discipline.**  A level inside the loop COMMITS (slab adopted,
frontier swapped, ring appended, loop continues) only when it is
totally clean: no abort, no invariant violation, no overflow of any
class (cap_x compaction, slab probe window, cap_m message width,
cap_f output seating, ring high-water).  Anything else stops the loop
BEFORE that level commits — the returned control vector names the stop
level and reason, the committed prefix is adopted as usual, and the
stopped level replays through the per-level megakernel (retained
verbatim as the A/B and audit reference; ``--superstep 1`` reverts to
it entirely), whose existing grow-and-redo machinery re-enters against
the original slab exactly as before.  A clean level with zero new
states commits as the terminal FIXPOINT record (its mult still counts
toward ``generated``, matching the staged loop's break order).

**Static shapes.**  One frontier capacity ``cap_f`` (forecast max over
the span, quantized through the engine's one capacity ladder) seats
every level of the superstep — the expand while_loop's trip count is
data-bounded on the live ``n_f``, so overshoot costs nothing.  The
ring capacity chains from the forecast cap_out sequence (one rung per
level, margin-inflated, clamped at span * cap_f); ring appends are
drop-mode scatters at a dynamic offset, so the high-water check is
exact (off + n_new > R) and a stopped-for-ring level is clean — the
next superstep simply restarts there with a fresh ring (a fresh ring
always seats at least one level, so progress is guaranteed).

Default ON at span ``DEFAULT_SPAN`` wherever the per-level megakernel
is eligible; ``TLA_RAFT_SUPERSTEP=N`` / ``--superstep N`` set the span
(0/1 = off).  The ``--audit`` legacy re-expansion needs every level's
parent frontier alive on device, which the resident loop consumes by
design — audit runs force the per-level path (documented in
docs/PERF.md).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from . import forecast
from . import megakernel as mk

U64 = jnp.uint64
I64 = jnp.int64
I32 = jnp.int32
# numpy scalars: module import stays device-free (graftlint GL001)
SENT = np.uint64(0xFFFFFFFFFFFFFFFF)

# default levels per dispatch: deep enough to amortize the dispatch
# floor by 4x, shallow enough that a forecast miss (one stopped level
# replayed per-level) stays cheap against the span it saved
DEFAULT_SPAN = 4

# control-vector layout (i64[SS_LEN]) — the one scalar bundle the host
# reads per superstep
SS_LEVELS = 0     # committed levels (incl. a terminal fixpoint level)
SS_REASON = 1     # stop reason (REASON_* below)
SS_NF = 2         # frontier rows after the last committed level
SS_OFF = 3        # ring entries used by the committed prefix
SS_SLAB_LIVE = 4  # live slots of the returned slab (conservation)
SS_FLAGS = 5      # the STOPPED level's cause bits (FLAG_* below) —
#                   the host grows the overflowed budget BEFORE the
#                   per-level replay, so a stopped level costs one
#                   attempt + one redo exactly like the per-level path
SS_LEN = 6

FLAG_OVF_X = 1      # a chunk overflowed its cap_x compaction budget
FLAG_OVF_SLAB = 2   # a probe window filled (grow + redo)
FLAG_OVF_M = 4      # a child overflowed the cap_m msg-id width
FLAG_OVF_OUT = 8    # n_new > cap_f (cannot seat the next frontier)
FLAG_ABORT = 16     # split-brain abort in the stopped level
FLAG_BAD = 32       # invariant violation in the stopped level
# host-synthesized refinement of FLAG_OVF_SLAB (never set on device:
# the device reports slab PRESSURE; the budget is host policy): the
# grow the stop asked for would exceed the tiered store's device
# budget, so the host DEMOTES a generation instead of growing and the
# stopped level replays per-level against the drained slab — "demote,
# then redo" where the untiered path would "grow or die"
# (store/tiered.py; once a generation exists, supersteps stand down to
# span 1 — the resident loop cannot host-correct mid-window)
FLAG_OVF_SLAB_TIER = 64
# the spill sieve flagged POSSIBLE generation revisits in the stopped
# level (tier_hits > 0): the level is otherwise clean, but its counts
# are provisional until the host's exact tier probe corrects it — the
# per-level replay needs NO budget growth, just the tiered filter.
# Levels with ZERO sieve hits provably contain no spilled revisits
# (blooms have no false negatives) and commit in-window — that is what
# restores span-N residency under spill (ops/sieve.py)
FLAG_TIER = 128

# stop reasons: RUN means the while_loop exhausted its span — every
# level committed clean (the steady state).  STOP marks an uncommitted
# level (abort / violation / any overflow class): the host replays it
# through the per-level megakernel.  RING marks a CLEAN level that did
# not fit the ring: the next superstep restarts there.  FIX is the
# committed terminal fixpoint level.
REASON_RUN = 0
REASON_STOP = 1
REASON_RING = 2
REASON_FIX = 3

REASON_NAMES = {
    REASON_RUN: "span",
    REASON_STOP: "stop",
    REASON_RING: "ring",
    REASON_FIX: "fixpoint",
}


def span_from_env(default: int = DEFAULT_SPAN) -> int:
    """Levels per dispatch; <= 1 reverts to the per-level megakernel.
    Env wins; an installed autotuner plan's ``superstep_span`` is the
    fallback (tune/plans.py precedence)."""
    v = os.environ.get("TLA_RAFT_SUPERSTEP")
    if v is None or v == "":
        from ..tune import active

        return max(1, int(active.get("superstep_span", default)))
    return max(1, int(v))


# shared jit cache, the megakernel's bound-the-closure-pins discipline:
# the traced body is fully determined by (kernel identity, chunk,
# cap_x, cap_m, canon, span, donation) plus the static (cap_f, ring)
# arguments; same-config engines share one program set
_PROG_CACHE: "dict" = {}
_PROG_CACHE_MAX = 16


def superstep_program_for(eng, span: int, donate: bool):
    key = (eng.kern, eng.chunk, eng.cap_x, eng.cap_m, eng.canon,
           int(span), bool(donate))
    entry = _PROG_CACHE.get(key)
    if entry is not None:
        prog, owner = entry
        # staleness guard (see megakernel.level_program_for): the body
        # reads the CREATOR's budgets at trace time, so a cached
        # program is reusable only while the creator matches the key
        if (owner.kern is eng.kern and owner.chunk == eng.chunk
                and owner.cap_x == eng.cap_x
                and owner.cap_m == eng.cap_m
                and owner.canon == eng.canon):
            _PROG_CACHE.pop(key)
            _PROG_CACHE[key] = (prog, owner)
            return prog
    prog = build_superstep_program(eng, span, donate)
    # flight-recorder breadcrumb (see megakernel.level_program_for)
    from ..obs import telemetry as _obs

    _obs.emit("program", kind="superstep", span=int(span),
              chunk=eng.chunk, cap_x=eng.cap_x, cap_m=eng.cap_m)
    _PROG_CACHE[key] = (prog, eng)
    while len(_PROG_CACHE) > _PROG_CACHE_MAX:
        _PROG_CACHE.pop(next(iter(_PROG_CACHE)))
    return prog


def build_superstep_program(eng, span: int, donate: bool):
    """The jitted N-level driver for one engine configuration.

    Static arguments: ``cap_f`` (the one frontier capacity every level
    of the superstep runs at — a chunk multiple >= the input frontier's
    capacity; smaller inputs are zero-padded in-trace) and ``ring``
    (the trace-spool capacity, >= cap_f).  ``sieve`` is a traced
    operand — the spill sieve's device words (the 1-word sentinel while
    tiering is off); jit retraces automatically when its shape changes,
    so one cached program serves each filter size.  Returns

      ``(frontier_out [cap_f], slab_out, ctrl i64[SS_LEN],
         meta_n i64[span], meta_mult i64[span, K],
         ring_fps u64[R], ring_pidx u32[R], ring_slot u16|u32[R])``

    where ``frontier_out`` is the last committed level's frontier — on
    a STOP it is the stopped level's PARENT, which is exactly what the
    per-level replay needs.  Ring/meta content beyond the committed
    prefix is garbage by contract (the host slices by the per-level
    counts).
    """
    chunk = eng.chunk
    cap_x = eng.cap_x
    K = eng.K
    span = int(span)
    slot_dt = jnp.uint16 if K <= 0xFFFF else jnp.uint32

    def superstep_body(frontier, slab, n_f, lvl_cap, sieve, cap_f: int,
                       ring: int):
        # trace-time staleness tripwire (see megakernel.level_body)
        if eng.cap_x != cap_x or eng.chunk != chunk:
            raise RuntimeError(
                "superstep program stale: creator engine's budgets "
                f"changed (cap_x {cap_x}->{eng.cap_x}, chunk "
                f"{chunk}->{eng.chunk}); re-fetch via "
                "superstep_program_for"
            )
        cap_in = frontier.voted_for.shape[0]
        if cap_in > cap_f or cap_f % chunk or ring < 1:
            raise RuntimeError(
                f"superstep statics invalid: cap_in={cap_in}, "
                f"cap_f={cap_f}, chunk={chunk}, ring={ring}"
            )
        if cap_in < cap_f:
            # seat the input in the span-wide frontier buffer (zero
            # padding = the staged path's dead-tail convention; the
            # data-bounded expand never reads past n_f)
            frontier = jax.tree.map(
                lambda x: jnp.concatenate(
                    [x, jnp.zeros((cap_f - cap_in,) + x.shape[1:],
                                  x.dtype)]
                ),
                frontier,
            )

        R = ring
        lane = jnp.arange(cap_f, dtype=I64)

        def cond(c):
            lvl, _off, reason = c[0], c[1], c[2]
            # lvl_cap is a TRACED operand (min(span, levels remaining
            # to --max-depth)): one compiled program serves every
            # remainder instead of minting a program per residual span
            # — depth-capped sweep jobs would otherwise pay a fresh
            # XLA compile for each distinct cap % span
            return (
                (reason == REASON_RUN) & (lvl < span)
                & (lvl.astype(I64) < lvl_cap)
            )

        def body(c):
            (lvl, off, _reason, _flags, n_f, fr, slab, rf, rp, rs, mn,
             mm) = c
            (new_fr, slab2, n_new, abort_at, ovf_x, ovf_slab, ovf_m,
             bad, mult, fps_out, pay_out, tier_hits) = mk.fused_level_core(
                eng, fr, slab, n_f, sieve, cap_f, chunk, cap_x
            )
            abort = abort_at < n_f
            ovf_out = n_new > cap_f  # next frontier cannot seat
            ring_ovf = off + n_new > R
            # sieve hits = POSSIBLE spilled revisits: the level must
            # not commit until the host's exact tier probe corrects it
            # (zero hits = provably clean, commits in-window)
            tier_stop = tier_hits > 0
            stop = (abort | ovf_x | ovf_slab | (ovf_m & (n_new > 0))
                    | ovf_out | (bad >= 0) | tier_stop)
            commit = ~stop & ~ring_ovf
            # ring append: drop-mode scatter at the dynamic offset —
            # writes beyond the committed prefix (an uncommitted
            # level's lanes, dead lanes past n_new) land out of range
            # or in garbage territory the host never reads
            idx = jnp.where(lane < n_new, off + lane, R)
            rf = rf.at[idx].set(fps_out, mode="drop")
            rp = rp.at[idx].set(
                (pay_out // K).astype(jnp.uint32), mode="drop"
            )
            rs = rs.at[idx].set(
                (pay_out % K).astype(slot_dt), mode="drop"
            )
            mn = mn.at[lvl].set(n_new)
            mm = jax.lax.dynamic_update_slice(
                mm, mult[None, :], (lvl, jnp.zeros((), I32))
            )
            fix = commit & (n_new == 0)
            reason2 = jnp.where(
                stop, REASON_STOP,
                jnp.where(
                    ring_ovf, REASON_RING,
                    jnp.where(fix, REASON_FIX, REASON_RUN),
                ),
            ).astype(I32)
            flags2 = (
                ovf_x.astype(I32) * FLAG_OVF_X
                + ovf_slab.astype(I32) * FLAG_OVF_SLAB
                + (ovf_m & (n_new > 0)).astype(I32) * FLAG_OVF_M
                + ovf_out.astype(I32) * FLAG_OVF_OUT
                + abort.astype(I32) * FLAG_ABORT
                + (bad >= 0).astype(I32) * FLAG_BAD
                + tier_stop.astype(I32) * FLAG_TIER
            )
            sel = lambda a, b: jnp.where(commit, a, b)  # noqa: E731
            fr2 = jax.tree.map(sel, new_fr, fr)
            return (
                lvl + commit.astype(I32),
                off + jnp.where(commit, n_new, 0),
                reason2,
                jnp.where(stop, flags2, jnp.zeros((), I32)),
                jnp.where(commit, n_new, n_f),
                fr2,
                sel(slab2, slab),
                rf, rp, rs, mn, mm,
            )

        init = (
            jnp.zeros((), I32),                      # lvl
            jnp.zeros((), I64),                      # off
            jnp.full((), REASON_RUN, I32),           # reason
            jnp.zeros((), I32),                      # stop flags
            n_f.astype(I64),
            frontier,
            slab,
            jnp.full((R,), SENT, U64),               # ring fps
            jnp.zeros((R,), jnp.uint32),             # ring pidx
            jnp.zeros((R,), slot_dt),                # ring slot
            jnp.zeros((span,), I64),                 # meta n_new
            jnp.zeros((span, K), I64),               # meta mult
        )
        (lvl, off, reason, flags, n_f_out, fr, slab, rf, rp, rs, mn,
         mm) = jax.lax.while_loop(cond, body, init)
        ctrl = jnp.stack([
            lvl.astype(I64),
            reason.astype(I64),
            n_f_out,
            off,
            (slab != SENT).sum().astype(I64),
            flags.astype(I64),
        ])
        return fr, slab, ctrl, mn, mm, rf, rp, rs

    return jax.jit(
        superstep_body,
        static_argnames=("cap_f", "ring"),
        donate_argnums=(0,) if donate else (),
    )


def unpack_ring(ctrl, meta_n, meta_mult, ring_fps, ring_pidx,
                ring_slot):
    """The superstep fetch -> per-level delta/trace records.

    Returns ``(recs, reason, n_f, slab_live, flags)`` — ``flags`` is
    the SS_FLAGS stop-cause bitmask — where ``recs`` is one
    dict per committed level — ``n_new``, ``mult`` i64[K], ``fps``
    u64[n_new], ``pidx``/``slot`` i64[n_new] — in level order, exactly
    the record shape the per-level megakernel fetch produces (the
    checkpoint writer, trace reconstruction and resume consume either
    verbatim)."""
    ctrl = np.asarray(ctrl, np.int64)
    levels = int(ctrl[SS_LEVELS])
    recs = []
    off = 0
    mn = np.asarray(meta_n, np.int64)
    mm = np.asarray(meta_mult, np.int64)
    for i in range(levels):
        n_new = int(mn[i])
        recs.append(dict(
            n_new=n_new,
            mult=mm[i],
            fps=np.asarray(
                ring_fps[off:off + n_new], np.uint64
            ),
            pidx=np.asarray(
                ring_pidx[off:off + n_new]
            ).astype(np.int64),
            slot=np.asarray(
                ring_slot[off:off + n_new]
            ).astype(np.int64),
        ))
        off += n_new
    reason = REASON_NAMES.get(int(ctrl[SS_REASON]), "stop")
    return (recs, reason, int(ctrl[SS_NF]), int(ctrl[SS_SLAB_LIVE]),
            int(ctrl[SS_FLAGS]))


def ring_capacity(fut, span: int, cap_f: int, pow2) -> int:
    """Ring slots for one superstep, chained from the forecast cap_out
    sequence: one rung per forecast level (1.25-margined like the
    prewarm ladder, clamped at cap_f — a level can never commit more
    than it can seat), padded with the last rung (or cap_f outright
    when there is no signal yet), quantized pow2 and clamped to
    [cap_f, span * cap_f].  Small capacities pin the ring at the
    span * cap_f ceiling outright: the fetch overage is kilobytes
    while a forecast-wiggled ring would mint a fresh compiled program
    per rung — compile count, not memory, is the cost down there
    (the same reasoning as the megakernel's 4*chunk floor)."""
    if span * cap_f <= (1 << 16):
        return pow2(span * cap_f)
    if fut:
        m = forecast.cap_margin()
        rungs = [min(int(f * m) + 1, cap_f) for f in fut[:span]]
        rungs += [rungs[-1]] * (span - len(rungs))
        est = sum(rungs)
    else:
        est = span * cap_f
    est = max(est, cap_f)
    return min(pow2(est), pow2(span * cap_f))


def ledger_trace(cfg=None, span: int = 2):
    """Closed jaxpr of the superstep driver at the audit's tiny
    reference shapes — the graftlint layer-2 (GL010) registration: the
    while_loop wraps the megakernel's fused_level_core, so the budget
    pins the same residue (hashstore probe rounds + materialize
    parent gathers) and the ring spool must stay scatter-drop only."""
    from ..config import RaftConfig
    from ..models.raft import init_batch
    from ..ops import hashstore
    from .bfs import JaxChecker

    if cfg is None:
        cfg = RaftConfig(
            n_servers=2, n_vals=1, max_election=1, max_restart=1,
        )
    eng = JaxChecker(cfg, chunk=64, use_hashstore=True, megakernel=True)
    fr0, _ovf = eng._deflate(init_batch(cfg, 1))
    fr = eng._frontier_struct(fr0, 64)
    slab = jax.ShapeDtypeStruct((hashstore.MIN_CAP,), jnp.uint64)
    n_f = jax.ShapeDtypeStruct((), jnp.int64)
    sieve = jax.ShapeDtypeStruct((1,), jnp.uint64)
    prog = build_superstep_program(eng, span, donate=False)
    return jax.make_jaxpr(
        lambda f, s, n, lc, sv: prog(f, s, n, lc, sv, cap_f=64, ring=128)
    )(fr, slab, n_f, jax.ShapeDtypeStruct((), jnp.int64), sieve)
