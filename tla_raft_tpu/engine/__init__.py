"""The TPU checker engine: BFS driver, dedup store, invariants, traces."""

from .bfs import JaxChecker  # noqa: F401
