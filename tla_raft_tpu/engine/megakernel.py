"""Whole-level megakernel: one jitted device program per BFS level.

docs/PERF.md (Findings 1-2) pins the hot-path cost structure on launch
count, not FLOPs: every chunk pays ~38 ms of fixed dispatch/queue
latency on the tunneled backend, and a staged level is still 4-5
separate device programs (expand span, visited filter, fused dedup,
materialize slices, invariant scan) plus their control fetches — small
and mid-size levels run at the launch floor rather than hardware speed.
PR 6's MXU rewrite removed the structural blocker (the hot kernels are
gather/scatter-free matmul pipelines), so the whole level fuses into
ONE program, the stage-fusion move BLEST and "Graph Traversal on
Tensor Cores" (PAPERS.md) use to keep BFS resident on the accelerator:

1. **chunked expand inside a ``lax.while_loop``** — the trip count is
   data-bounded (``i * chunk < n_f``) while every shape is static, the
   repo's fixed-shape idiom, so padded frontier capacity never costs
   dead chunk expansions; each trip runs the engine's unchanged
   ``_expand_chunk_impl`` body (MXU guards + compact + materialize +
   fingerprints) and lands its cap_x compacted candidates in a
   preallocated lane buffer via ``dynamic_update_slice``;
2. **fused hashstore probe-and-insert** over the whole level's lanes
   (ops/hashstore.py ``probe_and_insert_impl`` — uniqueness, visited
   membership and the store update in one pass, the min-(fp_full,
   payload) representative per view fingerprint preserved, so counts
   stay bit-identical to the staged path);
3. **materialize** of the fresh frontier as a ``lax.scan`` over
   slice-bounded ``_mat_slice_impl`` bodies (the transient message-set
   inflate stays slice-sized, exactly the staged path's memory bound);
4. **invariant/abort scan** folded into the materialize slices, reduced
   to one first-bad index.

The program returns the new frontier, the pending slab, and a small
control vector (new-frontier count, abort position, overflow flags,
first-bad index, slab load) plus the level's trace/delta arrays
(pidx/slot/fps, pre-cast to their checkpoint dtypes) — the host
completes ONE ledgered fetch per level (through the pipeline's
``DeferredFetch``, so the transfer ledger and the ``pipeline.window``
fault site both still see it) and dispatches the next level.  Every
overflow class re-enters the engine's existing grow-and-redo machinery
against the ORIGINAL slab (the kernels are functional; the pending
slab is discarded), and checkpoint/delta commits, trace
reconstruction and the ``--audit N`` legacy re-expansion consume the
fused outputs unchanged.

Buffer donation: on backends that support it (TPU/GPU — the CPU runner
ignores donation), the frontier argument is donated and returned as a
pass-through output.  Input-output aliasing makes the pass-through
zero-copy, which keeps the parent frontier alive for the overflow-redo
loop and the integrity audit while giving XLA in-place freedom over
the frontier-shaped intermediates.

The staged path is retained verbatim as the A/B and audit reference:
``--megakernel 0`` / ``TLA_RAFT_MEGAKERNEL=0`` reverts, and the engine
falls back per level for the regimes the fused program does not cover
(orbit's split programs, the external host store beyond the group
fusion, a degraded hash store, and grouped ultra-deep levels where the
staged visited pre-filter bounds the candidate working set).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

U64 = jnp.uint64
I64 = jnp.int64
I32 = jnp.int32
# numpy scalars: module import stays device-free (graftlint GL001)
SENT = np.uint64(0xFFFFFFFFFFFFFFFF)
BIG = np.int64(1 << 62)

# control-vector layout (i64[CTRL_LEN]); the one fused scalar bundle the
# host reads per level
CTRL_N_NEW = 0      # fresh (new-frontier) states this level
CTRL_ABORT = 1      # first split-brain parent index, BIG if none
CTRL_OVF_X = 2      # a chunk overflowed its cap_x compaction budget
CTRL_OVF_SLAB = 3   # a probe window filled (grow + redo vs ORIGINAL slab)
CTRL_OVF_M = 4      # a child overflowed the cap_m sparse msg-id width
CTRL_BAD = 5        # first invariant-violating new row, -1 if none
CTRL_SLAB_LIVE = 6  # live slots of the pending slab (= distinct', free
#                     conservation signal for integrity.occupancy_check)
CTRL_TIER_HITS = 7  # fresh lanes the spill sieve flagged as POSSIBLE
#                     generation revisits (0 = provably none: the level
#                     can commit without any host tier correction)
CTRL_LEN = 8


def enabled_by_env() -> bool:
    """Megakernel default: ON; ``TLA_RAFT_MEGAKERNEL=0`` reverts to the
    staged per-stage program chain (the A/B and audit reference)."""
    return os.environ.get("TLA_RAFT_MEGAKERNEL", "1") != "0"


def donation_supported() -> bool:
    """Input buffer donation is a no-op (with a log-spam warning) on the
    CPU runner; only enable it where XLA honors the aliasing."""
    try:
        return jax.default_backend() in ("tpu", "gpu", "cuda", "rocm")
    except Exception:  # graftlint: waive[GL003] — a backend probe
        # failure just means "no donation"; it must never take the
        # checker down
        return False


def mat_slice_width(cap_out: int, chunk: int) -> int:
    """Materialize slice width: the largest chunk multiple <= 8*chunk
    that tiles ``cap_out`` evenly (capacities are {2^k, 3*2^(k-1)}
    chunk multiples, so a divisor always exists down to ``chunk``).
    Mirrors the staged path's 8x-chunk slice bound — the in-program
    transient (the per-slice message-set inflate) stays slice-sized."""
    if cap_out <= 8 * chunk:
        return cap_out
    for mult in (8, 4, 2, 1):
        if cap_out % (mult * chunk) == 0:
            return mult * chunk
    return chunk


# shared jit cache for the fused program: the traced body is fully
# determined by (kernel identity, chunk, cap_x, cap_m, canon, donation)
# — the kernel itself is lru-cached per config (ops/successor
# .get_kernel), so two engines on the same config at the same budgets
# share ONE jitted program and its compiled executables instead of
# re-tracing per instance (the test suite builds dozens of same-config
# checkers; a per-instance cache would pay the fused program's compile
# each time).  Bounded LRU: a cached program's closure pins its creator
# engine (and through it the device hash slab), so unbounded growth in
# a many-config sweep process would be a device-memory leak — eviction
# caps the pinned set (the service's BucketPrograms cache uses the
# same bound-the-closure-pins discipline).
_PROG_CACHE: "dict" = {}
_PROG_CACHE_MAX = 16


def level_program_for(eng, donate: bool):
    key = (eng.kern, eng.chunk, eng.cap_x, eng.cap_m, eng.canon,
           bool(donate))
    entry = _PROG_CACHE.get(key)
    if entry is not None:
        prog, owner = entry
        # staleness guard: the traced body reads the CREATOR's state at
        # trace time (new shapes trace lazily), so the cached program is
        # reusable only while the creator still matches the key — a
        # cap_x/cap_m growth mutates the creator and re-registers it
        # under its new key, orphaning this entry
        if (owner.kern is eng.kern and owner.chunk == eng.chunk
                and owner.cap_x == eng.cap_x
                and owner.cap_m == eng.cap_m
                and owner.canon == eng.canon):
            # LRU touch
            _PROG_CACHE.pop(key)
            _PROG_CACHE[key] = (prog, owner)
            return prog
    prog = build_level_program(eng, donate)
    # flight-recorder breadcrumb: a fresh fused program was built (the
    # compile itself lands on the compile track when it first runs)
    from ..obs import telemetry as _obs

    _obs.emit("program", kind="megakernel", chunk=eng.chunk,
              cap_x=eng.cap_x, cap_m=eng.cap_m)
    _PROG_CACHE[key] = (prog, eng)
    while len(_PROG_CACHE) > _PROG_CACHE_MAX:
        _PROG_CACHE.pop(next(iter(_PROG_CACHE)))
    return prog


def fused_level_core(eng, frontier, slab, n_f, sieve, cap_out: int,
                     chunk: int, cap_x: int):
    """The traced body of ONE fused BFS level — the shared core both the
    per-level program below and the multi-level superstep driver
    (engine/superstep.py) trace, so the two paths can never drift on
    the level semantics (same expand while_loop, same probe-and-insert,
    same materialize scan, same invariant reduce).

    ``chunk``/``cap_x`` are the builder's SNAPSHOT of the engine's
    budgets (the staleness tripwire in the callers compares them
    against the live engine before tracing).  ``sieve`` is the spill
    sieve's device word image (``u64[M]``, M a power of two; the 1-word
    all-zero sentinel while tiering is off) — fresh lanes are probed
    in-program (ops/sieve.py) and the hit count returned, so a level
    with zero hits provably contains no spilled revisits.  Returns
    ``(new_frontier [cap_out], slab2, n_new i64, abort_at i64,
       ovf_x bool, ovf_slab bool, ovf_m bool, bad_global i64,
       mult i64[K], fps_out u64[cap_out], pay_out i64[cap_out],
       tier_hits i64)``
    with ``pay_out`` the survivors' raw payloads (pidx*K+slot) in lane
    (= payload-ascending) order.
    """
    from ..ops import hashstore
    from ..ops import sieve as sieve_mod

    K = eng.K
    cap_f = frontier.voted_for.shape[0]
    n_chunks = cap_f // chunk
    N = n_chunks * cap_x  # level-wide candidate lane budget

    # -- 1. chunked expand: while_loop with a data-bounded trip
    # count over static shapes — dead chunks beyond n_f never run
    def cond(c):
        i = c[0]
        return i.astype(I64) * chunk < n_f

    def body(c):
        i, cv_b, cf_b, cp_b, mult, ab, ovf = c
        start = i.astype(I64) * chunk
        part = jax.tree.map(
            lambda x: jax.lax.dynamic_slice_in_dim(
                x, i * chunk, chunk
            ),
            frontier,
        )
        cv, cf, cp, m, a, o = eng._expand_chunk_impl(part, start, n_f)
        off = i * cap_x
        cv_b = jax.lax.dynamic_update_slice(cv_b, cv, (off,))
        cf_b = jax.lax.dynamic_update_slice(cf_b, cf, (off,))
        cp_b = jax.lax.dynamic_update_slice(cp_b, cp, (off,))
        return (
            i + 1, cv_b, cf_b, cp_b,
            mult + m, jnp.minimum(ab, a), ovf | o,
        )

    init = (
        jnp.zeros((), I32),
        jnp.full((N,), SENT, U64),
        jnp.full((N,), SENT, U64),
        jnp.full((N,), -1, I64),
        jnp.zeros((K,), I64),
        jnp.asarray(BIG, I64),
        jnp.zeros((), bool),
    )
    (_i, cv_buf, cf_buf, cp_buf, mult, abort_at,
     ovf_x) = jax.lax.while_loop(cond, body, init)

    # -- 2. fused probe-and-insert: uniqueness + membership + store
    # update in one pass; fresh lanes compact to a prefix in LANE
    # (= payload-ascending) order, the staged path's exact contract
    slab2, fresh, n_new, ovf_slab = hashstore.probe_and_insert_impl(
        slab, cv_buf, cf_buf, cp_buf
    )
    new_fps, new_pay = hashstore.compact_fresh(fresh, cv_buf, cp_buf, N)
    if cap_out > N:
        # tiny cap_x configs: the frontier-capacity quantizer's
        # >= chunk floor can exceed the lane budget — pad with dead
        # lanes (n_new <= N always, so nothing real is cut)
        new_fps = jnp.concatenate(
            [new_fps, jnp.full((cap_out - N,), SENT, U64)]
        )
        new_pay = jnp.concatenate(
            [new_pay, jnp.full((cap_out - N,), -1, I64)]
        )
    fps_out = new_fps[:cap_out]
    pay_out = new_pay[:cap_out]

    # -- spill-sieve probe over the fresh lanes: a definite-miss never
    # leaves the device; dead (SENT-padded) lanes can never count (a
    # fresh view fingerprint is never the sentinel)
    tier_hit = sieve_mod.probe_impl(sieve, fps_out) & (fps_out != SENT)
    tier_hits = tier_hit.sum().astype(I64)

    # -- 3+4. materialize + invariant scan over slice-bounded scan
    # steps.  cap_out is a forecast (it overshoots n_new by design,
    # that is what makes the shape static), so slices wholly beyond
    # n_new are SKIPPED via lax.cond — the scan body is sequential,
    # the dead branch emits zeros (exactly the staged path's
    # zero-padded frontier tail) and the overshoot costs nothing
    sl = mat_slice_width(cap_out, chunk)
    n_slices = cap_out // sl

    def live_slice(args):
        pay_slice, take = args
        return eng._mat_slice_impl(frontier, pay_slice, take)

    def dead_slice(args):
        pay_slice, _take = args
        child = jax.tree.map(
            lambda x: jnp.zeros(
                (sl,) + x.shape[1:], x.dtype
            ),
            frontier,
        )
        return child, jnp.asarray(-1, I64), jnp.zeros((), bool)

    def mat_body(_carry, si):
        pay_slice = jax.lax.dynamic_slice_in_dim(pay_out, si * sl, sl)
        take = jnp.clip(n_new - si.astype(I64) * sl, 0, sl)
        child, bad_at, ovf_m = jax.lax.cond(
            take > 0, live_slice, dead_slice, (pay_slice, take)
        )
        return _carry, (child, bad_at, ovf_m)

    _c, (children, bad_ats, ovf_ms) = jax.lax.scan(
        mat_body, jnp.zeros((), I32), jnp.arange(n_slices, dtype=I32)
    )
    new_frontier = jax.tree.map(
        lambda x: x.reshape((cap_out,) + x.shape[2:]), children
    )
    # first bad global index: slices stack in order, so the minimum
    # of (si*sl + first_bad_in_slice) IS the first bad overall
    sli = jnp.arange(n_slices, dtype=I64)
    badg = jnp.where(bad_ats >= 0, sli * sl + bad_ats, BIG)
    bad_min = badg.min()
    bad_global = jnp.where(bad_min >= BIG, jnp.asarray(-1, I64), bad_min)

    return (new_frontier, slab2, n_new, abort_at, ovf_x, ovf_slab,
            ovf_ms.any(), bad_global, mult, fps_out, pay_out, tier_hits)


def build_level_program(eng, donate: bool):
    """The jitted whole-level program for one engine configuration.

    Closes over the engine's chunk/cap_x/cap_m/canon/kernel state, so
    the engine rebuilds it whenever any of those change (the same
    re-jit discipline as ``_jit_expand_programs``).  ``cap_out`` — the
    new frontier's static capacity — is a static argument: the shape
    ladder quantizes it through ``_frontier_cap`` and the AOT prewarmer
    compiles the forecast rungs ahead of depth.

    Returns outputs
      ``(new_frontier, slab2, ctrl i64[CTRL_LEN], mult i64[K],
         fps u64[cap_out], pidx u32[cap_out], slot u16|u32[cap_out]
         [, frontier_passthrough])``
    with the pass-through present only under donation (input-output
    aliasing makes it zero-copy; it keeps the parent frontier alive for
    redo and audit).
    """
    chunk = eng.chunk
    cap_x = eng.cap_x
    K = eng.K
    slot_dt = jnp.uint16 if K <= 0xFFFF else jnp.uint32

    def level_body(frontier, slab, n_f, sieve, cap_out: int):
        # trace-time staleness tripwire: the body calls the creator
        # engine's methods, which read its LIVE cap_x/chunk — if the
        # creator's budgets drifted from this build's snapshot, a lazy
        # re-trace would write wrong-width chunk outputs at the old
        # stride (silent candidate corruption).  Callers re-resolve
        # through level_program_for per level, so this can only fire on
        # a plumbing regression — loudly, not silently.
        if eng.cap_x != cap_x or eng.chunk != chunk:
            raise RuntimeError(
                "megakernel program stale: creator engine's budgets "
                f"changed (cap_x {cap_x}->{eng.cap_x}, chunk "
                f"{chunk}->{eng.chunk}); re-fetch via level_program_for"
            )
        (new_frontier, slab2, n_new, abort_at, ovf_x, ovf_slab, ovf_m,
         bad_global, mult, fps_out, pay_out, tier_hits) = fused_level_core(
            eng, frontier, slab, n_f, sieve, cap_out, chunk, cap_x
        )

        ctrl = jnp.stack([
            n_new.astype(I64),
            abort_at,
            ovf_x.astype(I64),
            ovf_slab.astype(I64),
            ovf_m.astype(I64),
            bad_global,
            (slab2 != SENT).sum().astype(I64),
            tier_hits,
        ])
        pidx_out = (pay_out // K).astype(jnp.uint32)
        slot_out = (pay_out % K).astype(slot_dt)
        outs = (new_frontier, slab2, ctrl, mult, fps_out, pidx_out,
                slot_out)
        if donate:
            # pass-through keeps the donated parent alive for the
            # overflow-redo loop and the audit (aliased, zero-copy)
            outs = outs + (frontier,)
        return outs

    return jax.jit(
        level_body,
        static_argnames=("cap_out",),
        donate_argnums=(0,) if donate else (),
    )


def ledger_trace(cfg=None):
    """Closed jaxpr of the megakernel at the audit's tiny reference
    shapes — the graftlint layer-2 registration (golden ledger + the
    GL010 gather/scatter budget: the MXU expand/materialize inside
    contribute 0 data-indexed gathers; the ledgered residue is the
    hashstore probe rounds and the materialize parent-row gathers)."""
    from ..config import RaftConfig
    from ..models.raft import init_batch
    from ..ops import hashstore
    from .bfs import JaxChecker

    if cfg is None:
        cfg = RaftConfig(
            n_servers=2, n_vals=1, max_election=1, max_restart=1,
        )
    eng = JaxChecker(cfg, chunk=64, use_hashstore=True, megakernel=True)
    fr0, _ovf = eng._deflate(init_batch(cfg, 1))
    fr = eng._frontier_struct(fr0, 64)
    slab = jax.ShapeDtypeStruct((hashstore.MIN_CAP,), jnp.uint64)
    n_f = jax.ShapeDtypeStruct((), jnp.int64)
    sieve = jax.ShapeDtypeStruct((1,), jnp.uint64)
    prog = build_level_program(eng, donate=False)
    return jax.make_jaxpr(
        lambda f, s, n, sv: prog(f, s, n, sv, cap_out=64)
    )(fr, slab, n_f, sieve)
