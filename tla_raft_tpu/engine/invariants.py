"""Batched invariant and probe kernels (Raft.tla:432-507).

Every predicate evaluates a whole batch of states at once -> bool[N]
(True = holds).  ``Inv`` (Raft.tla:502) binds LeaderHasAllCommittedEntries
(Raft.tla:491-499), the single invariant the reference checks
(Raft.cfg:33-34).  The rest are the reference's debug probes — predicates
deliberately written to be *violated* to prove reachability (SURVEY.md
§4.3); run them through the ``~name`` negation extension to reproduce that
workflow.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..config import CANDIDATE, FOLLOWER, LEADER, RaftConfig

I32 = jnp.int32


def leader_has_all_committed_entries(cfg: RaftConfig, st, tables=None):
    """LeaderHasAllCommittedEntries — Raft.tla:491-499.

    Either no Leader exists, or ∃ a Leader l such that no other server p
    with currentTerm[p] <= currentTerm[l] commits past l's log or commits
    an entry differing from l's.  Note the spec's ∃-quantifier over
    leaders (one good leader satisfies it) — reproduced exactly.
    """
    S, L = cfg.S, cfg.L
    ct = st.current_term.astype(I32)
    ci = st.commit_index.astype(I32)
    ll = st.log_len.astype(I32)
    is_leader = st.role == LEADER  # [N, S]
    not_self = ~jnp.eye(S, dtype=bool)[None]
    applies = not_self & (ct[:, None, :] <= ct[:, :, None])  # [N, l, p]
    over = ci[:, None, :] > ll[:, :, None]
    mism = (st.log_term[:, None, :, :] != st.log_term[:, :, None, :]) | (
        st.log_val[:, None, :, :] != st.log_val[:, :, None, :]
    )  # [N, l, p, L]
    in_prefix = jnp.arange(L)[None, None, None, :] < ci[:, None, :, None]
    differs = (mism & in_prefix).any(-1)
    bad = applies & (over | differs)
    ok_l = is_leader & ~bad.any(-1)
    return ~is_leader.any(-1) | ok_l.any(-1)


def raft_can_commt(cfg, st, tables=None):
    """RaftCanCommt [sic] — Raft.tla:434."""
    return (st.commit_index.astype(I32) > 1).any(-1)


def follower_can_commit(cfg, st, tables=None):
    """FollowerCanCommit — Raft.tla:436-439."""
    return ((st.role == FOLLOWER) & (st.commit_index.astype(I32) > 1)).any(-1)


def commit_all(cfg, st, tables=None):
    """CommitAll — Raft.tla:442 (literal constant 3)."""
    return (st.commit_index.astype(I32) == 3).all(-1)


def no_split_vote(cfg, st, tables=None):
    """NoSplitVote — Raft.tla:444-449: no two Leaders share a term."""
    S = cfg.S
    lead = st.role == LEADER
    ct = st.current_term.astype(I32)
    pair = (
        lead[:, :, None]
        & lead[:, None, :]
        & (ct[:, :, None] == ct[:, None, :])
        & ~jnp.eye(S, dtype=bool)[None]
    )
    return ~pair.any((-2, -1))


def exist_leader_and_candidate(cfg, st, tables=None):
    """ExistLeaderAndCandidate — Raft.tla:483-487."""
    return (st.role == LEADER).any(-1) & (st.role == CANDIDATE).any(-1)


def no_all_commit(cfg, st, tables):
    """NoAllCommit — Raft.tla:451-481: a specific negative-scenario probe.

    ∃ s1 # s2, s2 # s3 with a fixed role/commit/matchIndex configuration
    plus three message-existence conditions; needs the GuardTables message
    pattern masks for the two AppendReq existentials.
    """
    S = cfg.S
    ct = st.current_term.astype(I32)
    ci = st.commit_index.astype(I32)
    mi = st.match_index.astype(I32)
    role = st.role
    N = role.shape[0]

    hold = jnp.zeros((N,), bool)
    for s1 in range(S):
        for s2 in range(S):
            if s2 == s1:
                continue
            for s3 in range(S):
                if s3 == s2:  # spec only requires s1 # s2 /\ s2 # s3
                    continue
                base = (
                    (role[:, s1] == LEADER)
                    & (role[:, s2] == FOLLOWER)
                    & (role[:, s3] == FOLLOWER)
                    & (ct[:, s1] == ct[:, s3])
                    & (ci[:, s1] == 2)
                    & (ci[:, s2] == 2)
                    & (ci[:, s3] == 1)
                    & (mi[:, s1, s2] == 2)
                    & (mi[:, s1, s3] == 2)
                )
                if s1 == s3:
                    continue  # messages below need s1 -> s3 with s1 # s3
                t3 = jnp.clip(ct[:, s3] - 1, 0, cfg.T - 1)
                # AppendReq s1->s3 at term t3 with prevLogIndex = 1
                m1_mask = tables.aq_block[s1, s3, t3, 0]  # [N, W]
                m1 = ((st.msgs & m1_mask) != 0).any(-1) & (ct[:, s3] >= 1)
                # AppendResp s3->s1 at t3, prevLogIndex 1, success
                mid = tables.uni.encode_appendresp(
                    s3 + 1, s1 + 1, jnp.clip(ct[:, s3], 1, cfg.T), 1, 1
                ).astype(I32)
                word = jnp.take_along_axis(st.msgs, (mid >> 5)[:, None], axis=-1)[:, 0]
                m2 = ((word >> (mid & 31).astype(jnp.uint32)) & 1).astype(bool)
                # AppendReq s1->s3 with prevLogIndex = 2, any term
                if cfg.L >= 2:
                    m3_mask = tables.aq_block[s1, s3, 0, 1]
                    for t in range(1, cfg.T):  # bitwise union over terms
                        m3_mask = m3_mask | tables.aq_block[s1, s3, t, 1]
                    m3 = ((st.msgs & m3_mask) != 0).any(-1)
                else:
                    m3 = jnp.zeros((N,), bool)
                hold = hold | (base & m1 & m2 & m3)
    return hold


INVARIANT_KERNELS = {
    "Inv": leader_has_all_committed_entries,
    "LeaderHasAllCommittedEntries": leader_has_all_committed_entries,
    "RaftCanCommt": raft_can_commt,
    "FollowerCanCommit": follower_can_commit,
    "CommitAll": commit_all,
    "NoSplitVote": no_split_vote,
    "NoAllCommit": no_all_commit,
    "ExistLeaderAndCandidate": exist_leader_and_candidate,
}


def resolve_invariant_kernel(name: str):
    """Resolve an invariant name; leading ``~`` negates (probe workflow)."""
    if name.startswith("~"):
        inner = INVARIANT_KERNELS[name[1:]]
        return lambda cfg, st, tables: ~inner(cfg, st, tables)
    fn = INVARIANT_KERNELS[name]
    return lambda cfg, st, tables: fn(cfg, st, tables)
