"""Predictive frontier-growth forecasting for capacity pre-sizing.

Growth-triggered capacity changes recompile the full level program —
minutes per shape on a real mesh, hours when they cascade (the round-4
depth-14 virtual-mesh attempt died on reactive cap_x doubling:
docs/MESH_DEEP.json).  This module turns the measured frontier-growth
model from BASELINE.md into a forecast the engines use to size
capacities ONCE for the whole run, so each program shape compiles once.

The model: on BFS level n the new-state count grows by a ratio r_n that
decays roughly linearly with depth (measured on the reference config:
r drops ~0.017-0.03 per level through the 10^8-state range, BASELINE.md
"golden counts").  Extrapolation marches the last observed ratio down by
the observed decay; errors land well inside the pow2 rounding the
capacity layer applies (from 20 observed levels the level-28 forecast is
within 5% of the measured 45.1M).

Reference analog: TLC sizes its fingerprint set and queue up front from
-Xmx heap flags (/root/reference/myrun.sh:3) rather than reallocating
mid-run; here the "heap flag" is derived from the spec's own measured
growth curve instead of hand tuning.
"""

from __future__ import annotations

# measured ratio decay per level on the reference sweep (BASELINE.md);
# used when fewer than 4 level ratios have been observed
DEFAULT_DECAY = 0.017
# forecasts from fewer observed levels than this are noise (early BFS
# ratios on the reference family swing 1.0-3.0)
MIN_LEVELS = 6
# capacity decisions trust the model at most this many levels ahead: a
# short noisy prefix extrapolates to nonsense at long range (a 14-state
# observed prefix once "forecast" a 3x10^10-state level and the presize
# tried to compile a 67M-lane program).  The per-level ratchet re-floors
# with ever-better forecasts as real levels land, so a long run pays a
# handful of planned resizes instead of one giant wrong one.
PRESIZE_HORIZON = 8

# capacity inflation over the raw forecast: the margin every presize
# floor, the prewarm ladder and the superstep ring share.  Hand-set at
# 1.25 (forecast error lands inside pow2 rounding at that inflation);
# TLA_RAFT_CAP_MARGIN overrides, else the installed autotuner plan's
# ``cap_margin`` knob (tune/plans.py) — one accessor so the three
# consumers cannot drift on the value.
DEFAULT_CAP_MARGIN = 1.25


def cap_margin(default: float = DEFAULT_CAP_MARGIN) -> float:
    import os

    env = os.environ.get("TLA_RAFT_CAP_MARGIN")
    if env:
        return max(1.0, float(env))
    from ..tune import active

    return max(1.0, float(active.get("cap_margin", default)))


def pow2ceil(n: int) -> int:
    """Smallest power of two >= n (>= 1)."""
    return 1 << max(0, int(n) - 1).bit_length()


def _ratio_model(level_sizes) -> tuple[float, float]:
    """(last growth ratio, per-level ratio decay) from observed levels."""
    f = [int(x) for x in level_sizes if x > 0]
    if len(f) < 2:
        return 3.0, DEFAULT_DECAY  # early fan-out: conservative
    ratios = [f[i] / f[i - 1] for i in range(1, len(f))]
    r = ratios[-1]
    # the decay itself shrinks with depth, so estimate from the LAST
    # three ratio steps only (median: one skewed level can't bend it);
    # measured on the golden record this tracks the forward decay
    # within ~7% over an 8-level horizon
    diffs = [
        ratios[i - 1] - ratios[i]
        for i in range(max(1, len(ratios) - 3), len(ratios))
    ]
    if diffs:
        diffs.sort()
        d = diffs[len(diffs) // 2]
    else:
        d = DEFAULT_DECAY
    # clamp: negative observed decay (noise) would forecast super-
    # exponential growth; huge decay would truncate the run to nothing.
    # Both clamps are conservative for CAPACITY use (they over-predict).
    return r, min(0.08, max(0.005, d))


def forecast_new_states(
    level_sizes,
    target_depth: int | None,
    max_levels: int = 128,
) -> list[int]:
    """Extrapolated per-level new-state counts beyond the observed prefix.

    ``level_sizes``: observed new states for levels 0..L (level 0 is the
    single init state).  Returns forecasts for levels L+1..target_depth;
    with ``target_depth=None`` (fixpoint run) the projection runs until
    the modeled frontier decays below 1 state or ``max_levels`` is hit.
    Empty when the target is already reached or there is no signal yet.
    """
    obs = [int(x) for x in level_sizes]
    depth_now = len(obs) - 1
    if depth_now < 1 or (target_depth is not None and target_depth <= depth_now):
        return []
    r, d = _ratio_model(obs)
    if target_depth is None:
        # open horizon: a noise-floored decay would extrapolate early
        # ratios into astronomically large "fixpoints" (observed: 10^29
        # on a 50-state config).  Force at least the measured reference
        # decay, and below: trust the projection only if it CONVERGES.
        d = max(d, DEFAULT_DECAY)
    out: list[int] = []
    f = float(obs[-1])
    level = depth_now
    while len(out) < max_levels:
        level += 1
        if target_depth is not None and level > target_depth:
            break
        r = max(0.05, r - d)
        f = f * r
        if f < 1.0:
            break
        out.append(int(f) + 1)
    if target_depth is None and len(out) >= max_levels:
        return []  # projection never reached a fixpoint: no usable signal
    return out


def horizon_forecast(level_sizes, distinct: int, target_depth: int | None):
    """The one shared presize signal: (peak_new, final_distinct, budget).

    Horizon-limited (PRESIZE_HORIZON) per-level forecast plus the
    TLA_RAFT_PRESIZE_BYTES budget, parsed in exactly one place so the
    two engines cannot drift on the model (they still apply their own
    engine-specific margins and pow2 quantization to these numbers).
    Returns None when there is no usable signal yet.
    """
    import os

    fut = forecast_new_states(level_sizes, target_depth)[:PRESIZE_HORIZON]
    if not fut:
        return None
    budget = int(float(os.environ.get("TLA_RAFT_PRESIZE_BYTES", "4e9")))
    return max(fut), distinct + sum(fut), budget


def shape_plan(level_sizes, target_depth: int | None,
               margin: float | None = None) -> list[int]:
    """Margin-inflated per-level row forecast — the AOT prewarm's input.

    One entry per forecast level over the horizon: the new-state rows
    that level is expected to need, inflated by the same 1.25 margin
    the presize floors apply.  The engines quantize these through their
    own capacity functions (pow2 / half-step / chunk-multiple) into the
    ladder of program shapes worth compiling ahead of time
    (engine/pipeline.Prewarmer); emitting the raw rows from ONE place
    keeps the prewarmed ladder and the presize floors from drifting.
    Empty when there is no usable signal yet.
    """
    if margin is None:
        margin = cap_margin()
    fut = forecast_new_states(level_sizes, target_depth)[:PRESIZE_HORIZON]
    return [int(f * margin) + 1 for f in fut]


def pow2_ladder(lo: int, hi: int) -> list[int]:
    """Power-of-two capacities strictly above ``lo`` up to ceil(hi).

    The magnitude steps a growing structure will visit on its way from
    the current capacity to a forecast peak — each one a program shape
    the prewarmer can compile before the run needs it."""
    out: list[int] = []
    c = pow2ceil(max(1, lo))
    if c <= lo:
        c <<= 1
    top = pow2ceil(max(1, hi))
    while c <= top:
        out.append(c)
        c <<= 1
    return out


def sieve_bytes(dev_bytes: int) -> int:
    """Device bytes the spill sieve will pin once tiering demotes its
    first generation (ops/sieve.py sieve_words_for: 1/8 of the hot
    budget by default, TLA_RAFT_SIEVE_BYTES overrides) — charged into
    the pre-OOM HBM forecast ahead of the first demotion, because the
    filter is allocated at FULL size the moment spill starts."""
    from ..ops.sieve import sieve_words_for

    return sieve_words_for(int(dev_bytes)) * 8


def forecast_final_distinct(level_sizes, distinct: int,
                            target_depth: int | None) -> int:
    """Forecast total distinct states at the end of the run."""
    return distinct + sum(forecast_new_states(level_sizes, target_depth))


def forecast_peak_new(level_sizes, target_depth: int | None) -> int:
    """Forecast the largest per-level new-state count over the run."""
    fut = forecast_new_states(level_sizes, target_depth)
    return max(fut, default=0)


def per_device_forecast(level_sizes, distinct: int,
                        target_depth: int | None, n_devices: int):
    """Per-device capacity signal for the 1/D-sharded deep-sweep mesh.

    Fingerprint ownership (fp % D) is hash-uniform, so each device's
    share of a forecast level is ~peak/D with multiplicative skew that
    shrinks as levels grow; the 1.35x margin covers the +3-sigma
    binomial skew down to ~100-state shares (below that the absolute
    +32 floor dominates).  Returns None when there is no usable signal,
    else a dict of per-device row forecasts:

      peak_rows:  largest per-level new-state share one device owns
      final_rows: final distinct-state share one device owns — the
                  entry forecast for the per-owner membership
                  structures: the deep sieve cache and the hash-slab
                  visited shards (ops/hashstore.py slab_rows sizes a
                  slab from this at the quantized <=1/2 load factor;
                  8 B/slot => ~16 B per forecast entry against the
                  byte budget)
      budget:     TLA_RAFT_PRESIZE_BYTES, passed through for the same
                  clamping the engines already apply
    """
    sig = horizon_forecast(level_sizes, distinct, target_depth)
    if sig is None:
        return None
    peak_new, final_distinct, budget = sig
    share = peak_new / n_devices
    peak_rows = int(share * 1.35) + 32
    final_rows = int(final_distinct / n_devices * 1.35) + 32
    return dict(peak_rows=peak_rows, final_rows=final_rows, budget=budget)
