// Honest native CPU baseline: a multithreaded explicit-state checker of
// the same spec family (/root/reference/Raft.tla under Raft.cfg
// semantics: VIEW dedup + SYMMETRY canonicalization + INVARIANT Inv),
// built to stand in for the reference's actual runtime — TLC with
// `-workers 4` (/root/reference/myrun.sh:3) — which cannot run here
// (external Java jar, not vendored, zero egress).  The TPU engine's
// `vs_baseline` is measured against THIS checker (bench.py), not the
// pure-Python oracle, so the multiplier measures checker quality rather
// than Python interpreter overhead (VERDICT round 2, missing #2).
//
// Semantics are a line-for-line transcription of the differential oracle
// (tla_raft_tpu/oracle/explicit.py, itself cited against Raft.tla):
//   * the 11 live Next disjuncts (Raft.tla:416-430),
//   * VIEW = the 8 real vars (Raft.tla:38), aux excluded,
//   * SYMMETRY symmServers (Raft.cfg:24): canonical fingerprint is the
//     min over all S! server permutations of a 64-bit multilinear hash
//     of the permuted view (set-sum over messages, so no per-perm sort),
//   * Inv = LeaderHasAllCommittedEntries (Raft.tla:491-499, the spec's
//     exists-a-good-leader form) checked on every distinct state,
//   * the in-path split-brain Assert (Raft.tla:185),
//   * deadlock NOT reported (`-deadlock`, myrun.sh:3).
//
// Exploration is level-synchronous BFS, parallelized across worker
// threads per level (frontier slices -> per-thread candidate buffers ->
// one parallel sort + scan for dedup).  Distinct-state counts are
// deterministic and thread-count-independent: within a level, duplicate
// view fingerprints collapse to the min-(canonical-full-encoding)
// representative, a deterministic refinement of TLC's first-writer-wins
// (the same policy family as the TPU engine; see oracle/explicit.py
// "Representative choice").
//
// Build: g++ -O3 -march=native -std=c++17 -pthread cpubase.cpp -o cpubase
// Run:   ./cpubase [S V maxElection maxRestart maxDepth threads]
// Emits one JSON line with per-level counts and states/sec.

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

// ---- bounds (compile-time caps; runtime config must fit) -------------
constexpr int MAXS = 7;   // servers
constexpr int MAXL = 8;   // log entries incl. the (0,0) sentinel
constexpr int MAXM = 127; // messages per reachable state

constexpr uint8_t FOLLOWER = 0, CANDIDATE = 1, LEADER = 2;
constexpr uint8_t VOTE_REQ = 0, VOTE_RESP = 1, APPEND_REQ = 2,
                  APPEND_RESP = 3;

struct Cfg {
  int S = 3, V = 2, maxE = 3, maxR = 3;
  int majority() const { return S / 2 + 1; }
};

// ---- message packing (one u32 per message) ---------------------------
// type:2 | src:3 | dst:3 | term:4 | f4:4 | f5:4 | has_entry:1 |
// eterm:4 | eval:3  (f4 = lastLogIndex/prevLogIndex, f5 =
// lastLogTerm/prevLogTerm/succ; leaderCommit rides in bits 28..31)
struct Msg {
  static uint32_t pack(uint8_t type, uint8_t src, uint8_t dst, uint8_t term,
                       uint8_t f4 = 0, uint8_t f5 = 0, bool has_e = false,
                       uint8_t eterm = 0, uint8_t eval = 0, uint8_t lc = 0) {
    return uint32_t(type) | uint32_t(src) << 2 | uint32_t(dst) << 5 |
           uint32_t(term) << 8 | uint32_t(f4) << 12 | uint32_t(f5) << 16 |
           uint32_t(has_e) << 20 | uint32_t(eterm) << 21 |
           uint32_t(eval) << 25 | uint32_t(lc) << 28;
  }
  static uint8_t type(uint32_t m) { return m & 3; }
  static uint8_t src(uint32_t m) { return (m >> 2) & 7; }
  static uint8_t dst(uint32_t m) { return (m >> 5) & 7; }
  static uint8_t term(uint32_t m) { return (m >> 8) & 15; }
  static uint8_t f4(uint32_t m) { return (m >> 12) & 15; }
  static uint8_t f5(uint32_t m) { return (m >> 16) & 15; }
  static bool has_e(uint32_t m) { return (m >> 20) & 1; }
  static uint8_t eterm(uint32_t m) { return (m >> 21) & 15; }
  static uint8_t eval(uint32_t m) { return (m >> 25) & 7; }
  static uint8_t lc(uint32_t m) { return (m >> 28) & 15; }
  // apply a server permutation p (1-based images) to src/dst
  static uint32_t permute(uint32_t m, const uint8_t *p) {
    uint32_t keep = m & ~uint32_t((7 << 2) | (7 << 5));
    return keep | uint32_t(p[src(m) - 1]) << 2 | uint32_t(p[dst(m) - 1]) << 5;
  }
};

// ---- state (12 variables, oracle/explicit.py OState) ------------------
struct State {
  uint8_t voted_for[MAXS];       // 0 = None
  uint8_t current_term[MAXS];
  uint8_t role[MAXS];
  uint8_t log_term[MAXS][MAXL];  // [s][i] = logs[s][i+1].term (TLA 1-based)
  uint8_t log_val[MAXS][MAXL];
  uint8_t log_len[MAXS];         // = Len(logs[s]), >= 1 (sentinel)
  uint8_t match_index[MAXS][MAXS];
  uint8_t next_index[MAXS][MAXS];
  uint8_t commit_index[MAXS];
  uint8_t election_count, restart_count;
  uint8_t pending[MAXS];         // bitmask over dst (S <= 8)
  uint8_t val_sent;              // bitmask over vals (V <= 8); 1 = FALSE
  uint8_t n_msgs;
  uint32_t msgs[MAXM];           // ascending, unique

  bool has_msg(uint32_t m) const {
    return std::binary_search(msgs, msgs + n_msgs, m);
  }
  // set-union insert; aborts loudly on overflow — a silently dropped
  // message would make the baseline explore a smaller (wrong) space
  void add_msg(uint32_t m) {
    uint32_t *pos = std::lower_bound(msgs, msgs + n_msgs, m);
    if (pos != msgs + n_msgs && *pos == m) return;
    if (n_msgs >= MAXM) {
      std::fprintf(stderr, "cpubase: message-set width exceeded MAXM=%d\n",
                   MAXM);
      std::abort();
    }
    std::memmove(pos + 1, pos, (msgs + n_msgs - pos) * sizeof(uint32_t));
    *pos = m;
    n_msgs++;
  }
};

State init_state(const Cfg &cfg) {  // Init — Raft.tla:93-105
  State st;
  std::memset(&st, 0, sizeof(State));
  for (int s = 0; s < cfg.S; s++) {
    st.role[s] = FOLLOWER;
    st.log_len[s] = 1;  // the (0,0) sentinel, Raft.tla:97
    st.commit_index[s] = 1;
    for (int t = 0; t < cfg.S; t++) {
      st.match_index[s][t] = 1;
      st.next_index[s][t] = 2;
    }
  }
  return st;
}

// ---- canonical fingerprint under SYMMETRY + VIEW ----------------------

uint64_t mix64(uint64_t x) {  // splitmix64 finalizer
  x += 0x9e3779b97f4a7c15ull;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
  return x ^ (x >> 31);
}

struct Perms {
  int P = 1;
  uint8_t p[5040][MAXS];    // images, 1-based: server s -> p[s-1]
  uint8_t inv[5040][MAXS];  // preimages: slot i holds server inv[i]
  void build(int S) {
    uint8_t idx[MAXS];
    for (int i = 0; i < S; i++) idx[i] = i + 1;
    P = 0;
    do {
      for (int i = 0; i < S; i++) p[P][i] = idx[i];
      for (int i = 0; i < S; i++) inv[P][idx[i] - 1] = i + 1;
      P++;
    } while (std::next_permutation(idx, idx + S));
  }
};

// Hash of the permuted VIEW (Raft.tla:38 field order; messages as an
// order-independent set-sum so permutation needs no re-sort).
uint64_t view_hash(const Cfg &cfg, const State &st, const uint8_t *p,
                   const uint8_t *inv) {
  uint64_t h = 0x243f6a8885a308d3ull;
  auto pv = [&](uint8_t x) -> uint8_t { return x ? p[x - 1] : 0; };
  for (int i = 0; i < cfg.S; i++) {
    int j = inv[i] - 1;  // original slot feeding permuted slot i
    h = mix64(h ^ pv(st.voted_for[j]));
    h = mix64(h ^ st.current_term[j]);
    uint64_t lh = st.log_len[j];
    for (int k = 0; k < st.log_len[j]; k++)
      lh = mix64(lh ^ (uint64_t(st.log_term[j][k]) << 8 | st.log_val[j][k]));
    h = mix64(h ^ lh);
    for (int t = 0; t < cfg.S; t++)
      h = mix64(h ^ st.match_index[j][inv[t] - 1]);
    for (int t = 0; t < cfg.S; t++)
      h = mix64(h ^ st.next_index[j][inv[t] - 1]);
    h = mix64(h ^ st.commit_index[j]);
    h = mix64(h ^ st.role[j]);
  }
  uint64_t msum = 0;
  for (int i = 0; i < st.n_msgs; i++)
    msum += mix64(0x452821e638d01377ull ^ Msg::permute(st.msgs[i], p));
  return mix64(h ^ msum);
}

uint64_t canon_fp(const Cfg &cfg, const Perms &perms, const State &st) {
  uint64_t best = ~0ull;
  for (int pi = 0; pi < perms.P; pi++) {
    uint64_t h = view_hash(cfg, st, perms.p[pi], perms.inv[pi]);
    if (h < best) best = h;
  }
  return best;
}

// Canonical FULL encoding (all 12 vars, permuted, lexicographic min over
// perms): the deterministic representative tiebreak for view-fp
// collisions within a level (aux vars differ -> future enabledness
// differs; cf. oracle/explicit.py "Representative choice").
void full_bytes(const Cfg &cfg, const State &st, const uint8_t *p,
                const uint8_t *inv, std::vector<uint8_t> &out) {
  out.clear();
  auto pv = [&](uint8_t x) -> uint8_t { return x ? p[x - 1] : 0; };
  for (int i = 0; i < cfg.S; i++) {
    int j = inv[i] - 1;
    out.push_back(pv(st.voted_for[j]));
    out.push_back(st.current_term[j]);
    out.push_back(st.role[j]);
    out.push_back(st.log_len[j]);
    for (int k = 0; k < st.log_len[j]; k++) {
      out.push_back(st.log_term[j][k]);
      out.push_back(st.log_val[j][k]);
    }
    for (int t = 0; t < cfg.S; t++) out.push_back(st.match_index[j][inv[t] - 1]);
    for (int t = 0; t < cfg.S; t++) out.push_back(st.next_index[j][inv[t] - 1]);
    out.push_back(st.commit_index[j]);
    uint8_t pend = 0;  // pendingResponse permutes on both axes
    for (int t = 0; t < cfg.S; t++)
      if (st.pending[j] >> (inv[t] - 1) & 1) pend |= 1 << t;
    out.push_back(pend);
  }
  std::vector<uint32_t> pm(st.n_msgs);
  for (int i = 0; i < st.n_msgs; i++) pm[i] = Msg::permute(st.msgs[i], p);
  std::sort(pm.begin(), pm.end());
  for (uint32_t m : pm) {
    out.push_back(m & 0xff); out.push_back(m >> 8 & 0xff);
    out.push_back(m >> 16 & 0xff); out.push_back(m >> 24 & 0xff);
  }
  out.push_back(st.election_count);
  out.push_back(st.restart_count);
  out.push_back(st.val_sent);
}

void canon_full_bytes(const Cfg &cfg, const Perms &perms, const State &st,
                      std::vector<uint8_t> &best) {
  std::vector<uint8_t> cur;
  best.clear();
  for (int pi = 0; pi < perms.P; pi++) {
    full_bytes(cfg, st, perms.p[pi], perms.inv[pi], cur);
    if (best.empty() || cur < best) best.swap(cur);
  }
}

// ---- Inv = LeaderHasAllCommittedEntries (Raft.tla:491-499) ------------
bool inv_ok(const Cfg &cfg, const State &st) {
  bool any_leader = false;
  for (int l = 0; l < cfg.S; l++) {
    if (st.role[l] != LEADER) continue;
    any_leader = true;
    bool bad = false;
    for (int q = 0; q < cfg.S && !bad; q++) {
      if (q == l || st.current_term[q] > st.current_term[l]) continue;
      int cip = st.commit_index[q];
      if (cip > st.log_len[l]) { bad = true; break; }
      for (int i = 0; i < cip; i++)
        if (st.log_term[q][i] != st.log_term[l][i] ||
            st.log_val[q][i] != st.log_val[l][i]) { bad = true; break; }
    }
    if (!bad) return true;  // the spec's exists-quantifier
  }
  return !any_leader;
}

// ---- successor generation (the 11 live Next disjuncts) ----------------

struct Emit {
  std::vector<State> *out;
  uint64_t generated = 0;
  bool split_brain = false;
  void operator()(const State &st) { out->push_back(st); }
};

// BecomeCandidate(s) — Raft.tla:107-130 / explicit.py:119
void become_candidate(const Cfg &cfg, const State &st, int s, Emit &em) {
  if (st.election_count >= cfg.maxE) return;
  if (st.role[s] == LEADER) return;
  State nx = st;
  uint8_t nt = st.current_term[s] + 1;
  nx.election_count++;
  nx.current_term[s] = nt;
  nx.role[s] = CANDIDATE;
  nx.voted_for[s] = s + 1;
  uint8_t lli = st.log_len[s], llt = st.log_term[s][st.log_len[s] - 1];
  for (int p = 0; p < cfg.S; p++)
    if (p != s)
      nx.add_msg(Msg::pack(VOTE_REQ, s + 1, p + 1, nt, lli, llt));
  em.generated++;
  em(nx);
}

// UpdateTerm(s) — Raft.tla:175-188 / explicit.py:146 (branch b carries
// the in-path split-brain Assert, Raft.tla:185)
void update_term(const Cfg &cfg, const State &st, int s, Emit &em) {
  uint8_t cur = st.current_term[s];
  for (int i = 0; i < st.n_msgs; i++) {
    uint32_t m = st.msgs[i];
    if (Msg::dst(m) != s + 1) continue;
    uint8_t term = Msg::term(m);
    if (term > cur) {
      State nx = st;
      nx.role[s] = FOLLOWER;
      nx.current_term[s] = term;
      nx.voted_for[s] = 0;
      em.generated++;
      em(nx);
    }
    if (term == cur && Msg::type(m) == APPEND_REQ) {
      if (st.role[s] == LEADER) { em.split_brain = true; return; }
      if (st.role[s] == CANDIDATE) {
        State nx = st;
        nx.role[s] = FOLLOWER;
        em.generated++;
        em(nx);
      }
    }
  }
}

// ResponseVote(s) — Raft.tla:132-155 / explicit.py:174
void response_vote(const Cfg &cfg, const State &st, int s, Emit &em) {
  if (st.role[s] != FOLLOWER) return;
  uint8_t cur = st.current_term[s];
  uint8_t my_lli = st.log_len[s], my_llt = st.log_term[s][st.log_len[s] - 1];
  for (int i = 0; i < st.n_msgs; i++) {
    uint32_t m = st.msgs[i];
    if (Msg::type(m) != VOTE_REQ || Msg::dst(m) != s + 1 ||
        Msg::term(m) != cur)
      continue;
    uint8_t src = Msg::src(m);
    if (st.voted_for[s] != 0 && st.voted_for[s] != src) continue;
    uint8_t m_lli = Msg::f4(m), m_llt = Msg::f5(m);
    if (!(m_llt > my_llt || (m_llt == my_llt && m_lli >= my_lli))) continue;
    uint32_t grant = Msg::pack(VOTE_RESP, s + 1, src, Msg::term(m));
    if (st.has_msg(grant)) continue;
    State nx = st;
    nx.add_msg(grant);
    nx.voted_for[s] = src;
    em.generated++;
    em(nx);
  }
}

// BecomeLeader(s) — Raft.tla:157-173 / explicit.py:204
void become_leader(const Cfg &cfg, const State &st, int s, Emit &em) {
  if (st.role[s] != CANDIDATE) return;
  uint8_t cur = st.current_term[s];
  int resps = 0;
  for (int i = 0; i < st.n_msgs; i++) {
    uint32_t m = st.msgs[i];
    if (Msg::type(m) == VOTE_RESP && Msg::dst(m) == s + 1 &&
        Msg::term(m) == cur)
      resps++;
  }
  if (resps + 1 < cfg.majority()) return;  // self-vote, Raft.tla:164
  State nx = st;
  nx.role[s] = LEADER;
  for (int u = 0; u < cfg.S; u++) {
    nx.match_index[s][u] = (u == s) ? st.log_len[s] : 1;
    nx.next_index[s][u] = st.log_len[s] + 1;
  }
  nx.pending[s] = 0;
  em.generated++;
  em(nx);
}

// ClientReq(s) — Raft.tla:233-240 / explicit.py:230
void client_req(const Cfg &cfg, const State &st, int s, Emit &em) {
  if (st.role[s] != LEADER) return;
  for (int v = 0; v < cfg.V; v++) {
    if (st.val_sent >> v & 1) continue;
    State nx = st;
    nx.val_sent |= 1 << v;  // := FALSE
    nx.log_term[s][st.log_len[s]] = st.current_term[s];
    nx.log_val[s][st.log_len[s]] = v + 1;
    nx.log_len[s]++;
    nx.match_index[s][s] = st.log_len[s] + 1;
    em.generated++;
    em(nx);
  }
}

// LeaderAppendEntry(s) — Raft.tla:242-269 / explicit.py:249
void leader_append_entry(const Cfg &cfg, const State &st, int s, Emit &em) {
  if (st.role[s] != LEADER) return;
  for (int dst = 0; dst < cfg.S; dst++) {
    if (dst == s) continue;
    uint8_t ni = st.next_index[s][dst];
    if (ni > st.log_len[s] + 1) continue;
    if (st.pending[s] >> dst & 1) continue;
    uint8_t pli = ni - 1, plt = st.log_term[s][pli - 1];
    bool has_e = ni <= st.log_len[s];
    uint32_t m = Msg::pack(APPEND_REQ, s + 1, dst + 1, st.current_term[s],
                           pli, plt, has_e,
                           has_e ? st.log_term[s][ni - 1] : 0,
                           has_e ? st.log_val[s][ni - 1] : 0,
                           st.commit_index[s]);
    if (st.has_msg(m)) continue;
    State nx = st;
    nx.pending[s] |= 1 << dst;
    nx.add_msg(m);
    em.generated++;
    em(nx);
  }
}

// FollowerAcceptEntry(s) — Raft.tla:275-300 / explicit.py:292 (no
// \notin-msgs guard on the accept response)
void follower_accept_entry(const Cfg &cfg, const State &st, int s, Emit &em) {
  if (st.role[s] != FOLLOWER) return;
  uint8_t cur = st.current_term[s];
  for (int i = 0; i < st.n_msgs; i++) {
    uint32_t m = st.msgs[i];
    if (Msg::type(m) != APPEND_REQ || Msg::dst(m) != s + 1 ||
        Msg::term(m) != cur)
      continue;
    uint8_t pli = Msg::f4(m), plt = Msg::f5(m);
    if (!(pli <= st.log_len[s] && st.log_term[s][pli - 1] == plt)) continue;
    bool has_e = Msg::has_e(m);
    int n_ent = has_e ? 1 : 0;
    // new_log = log[:pli] + entries (explicit.py:305)
    uint8_t nl_len = pli + n_ent;
    uint8_t nt[MAXL], nv[MAXL];
    for (int k = 0; k < pli; k++) { nt[k] = st.log_term[s][k]; nv[k] = st.log_val[s][k]; }
    if (has_e) { nt[pli] = Msg::eterm(m); nv[pli] = Msg::eval(m); }
    bool append_new = nl_len > st.log_len[s];
    bool truncated = false;
    if (!append_new) {  // prefix comparison, explicit.py:307
      for (int k = 0; k < nl_len; k++)
        if (nt[k] != st.log_term[s][k] || nv[k] != st.log_val[s][k]) {
          truncated = true;
          break;
        }
    }
    uint8_t lc = Msg::lc(m);
    uint8_t new_commit = std::max(st.commit_index[s],
                                  std::min(lc, nl_len));
    State nx = st;
    nx.add_msg(Msg::pack(APPEND_RESP, s + 1, Msg::src(m), Msg::term(m),
                         pli + n_ent, 1));
    nx.commit_index[s] = new_commit;
    if (truncated || append_new) {
      nx.log_len[s] = nl_len;
      for (int k = 0; k < MAXL; k++) {
        nx.log_term[s][k] = k < nl_len ? nt[k] : 0;
        nx.log_val[s][k] = k < nl_len ? nv[k] : 0;
      }
    }
    em.generated++;
    em(nx);
  }
}

// FollowerRejectEntry(s) — Raft.tla:302-321 / explicit.py:320
void follower_reject_entry(const Cfg &cfg, const State &st, int s, Emit &em) {
  if (st.role[s] != FOLLOWER) return;
  uint8_t cur = st.current_term[s];
  for (int i = 0; i < st.n_msgs; i++) {
    uint32_t m = st.msgs[i];
    if (Msg::type(m) != APPEND_REQ || Msg::dst(m) != s + 1 ||
        Msg::term(m) != cur)
      continue;
    uint8_t pli = Msg::f4(m), plt = Msg::f5(m);
    if (pli <= st.log_len[s] && st.log_term[s][pli - 1] == plt) continue;
    uint32_t rej =
        Msg::pack(APPEND_RESP, s + 1, Msg::src(m), Msg::term(m), pli, 0);
    if (st.has_msg(rej)) continue;
    State nx = st;
    nx.add_msg(rej);
    em.generated++;
    em(nx);
  }
}

// HandleAppendResp(s) — Raft.tla:374-396 / explicit.py:337
void handle_append_resp(const Cfg &cfg, const State &st, int s, Emit &em) {
  if (st.role[s] != LEADER) return;
  uint8_t cur = st.current_term[s];
  for (int i = 0; i < st.n_msgs; i++) {
    uint32_t m = st.msgs[i];
    if (Msg::type(m) != APPEND_RESP || Msg::dst(m) != s + 1 ||
        Msg::term(m) != cur)
      continue;
    uint8_t src = Msg::src(m) - 1, pli = Msg::f4(m);
    bool succ = Msg::f5(m);
    if (!(st.pending[s] >> src & 1)) continue;
    if (succ) {
      if (!(st.match_index[s][src] < pli)) continue;  // Raft.tla:383
      State nx = st;
      nx.match_index[s][src] = pli;
      nx.next_index[s][src] = pli + 1;
      nx.pending[s] &= ~(1 << src);
      em.generated++;
      em(nx);
    } else {
      if (pli + 1 != st.next_index[s][src]) continue;  // Raft.tla:391
      if (!(pli > st.match_index[s][src])) continue;   // Raft.tla:392
      State nx = st;
      nx.pending[s] &= ~(1 << src);
      nx.next_index[s][src] = pli;
      em.generated++;
      em(nx);
    }
  }
}

// LeaderCanCommit(s) — Raft.tla:398-407 / explicit.py:380
void leader_can_commit(const Cfg &cfg, const State &st, int s, Emit &em) {
  if (st.role[s] != LEADER) return;
  uint8_t row[MAXS];
  for (int t = 0; t < cfg.S; t++) row[t] = st.match_index[s][t];
  std::sort(row, row + cfg.S);
  uint8_t median = row[cfg.majority() - 1];  // MajoritySize-th smallest
  if (median <= st.commit_index[s]) return;
  State nx = st;
  nx.commit_index[s] = median;
  em.generated++;
  em(nx);
}

// Restart(s) — Raft.tla:409-414 / explicit.py:394 (leader-only step-down)
void restart(const Cfg &cfg, const State &st, int s, Emit &em) {
  if (st.role[s] != LEADER) return;
  if (st.restart_count >= cfg.maxR) return;
  State nx = st;
  nx.restart_count++;
  nx.role[s] = FOLLOWER;
  em.generated++;
  em(nx);
}

void successors(const Cfg &cfg, const State &st, Emit &em) {
  for (int s = 0; s < cfg.S && !em.split_brain; s++) {
    become_candidate(cfg, st, s, em);
    update_term(cfg, st, s, em);
    response_vote(cfg, st, s, em);
    become_leader(cfg, st, s, em);
    client_req(cfg, st, s, em);
    leader_append_entry(cfg, st, s, em);
    follower_accept_entry(cfg, st, s, em);
    follower_reject_entry(cfg, st, s, em);
    handle_append_resp(cfg, st, s, em);
    leader_can_commit(cfg, st, s, em);
    restart(cfg, st, s, em);
  }
}

// ---- visited set: open-addressing u64 table, read-only during a level -
struct FpSet {
  std::vector<uint64_t> tab;  // 0 = empty (fp 0 is remapped to 1)
  size_t mask = 0, n = 0;
  void init(size_t cap) {
    size_t c = 64;
    while (c < cap * 2) c <<= 1;
    tab.assign(c, 0);
    mask = c - 1;
    n = 0;
  }
  bool contains(uint64_t fp) const {
    if (!fp) fp = 1;
    for (size_t i = fp & mask;; i = (i + 1) & mask) {
      if (tab[i] == fp) return true;
      if (!tab[i]) return false;
    }
  }
  void insert(uint64_t fp) {  // caller guarantees capacity + absence
    if (!fp) fp = 1;
    for (size_t i = fp & mask;; i = (i + 1) & mask) {
      if (tab[i] == fp) return;
      if (!tab[i]) { tab[i] = fp; n++; return; }
    }
  }
  void maybe_grow(size_t incoming) {
    if ((n + incoming) * 2 < tab.size()) return;
    std::vector<uint64_t> old;
    old.swap(tab);
    size_t c = old.size();
    while (c < (n + incoming) * 2) c <<= 1;
    tab.assign(c, 0);
    mask = c - 1;
    size_t keep = n;
    n = 0;
    for (uint64_t fp : old)
      if (fp) insert(fp);
    (void)keep;
  }
};

struct Cand {
  uint64_t fp;
  uint32_t tid;   // producing thread
  uint32_t idx;   // index into that thread's state buffer
};

int run(const Cfg &cfg, int max_depth, int n_threads) {
  Perms perms;
  perms.build(cfg.S);
  auto t0 = std::chrono::steady_clock::now();

  State init = init_state(cfg);
  FpSet visited;
  visited.init(1 << 20);
  visited.insert(canon_fp(cfg, perms, init));
  if (!inv_ok(cfg, init)) {
    std::fprintf(stderr, "Invariant violated at Init\n");
    return 1;
  }
  std::vector<State> frontier{init};
  std::vector<uint64_t> level_sizes{1};
  std::atomic<uint64_t> generated{0};
  std::atomic<bool> split_brain{false}, inv_bad{false};
  uint64_t distinct = 1;
  int depth = 0;

  while (!frontier.empty()) {
    if (max_depth >= 0 && depth >= max_depth) break;
    size_t NF = frontier.size();
    std::vector<std::vector<State>> buf(n_threads);
    std::vector<std::vector<Cand>> cands(n_threads);
    auto worker = [&](int tid) {
      Emit em;
      std::vector<State> succ;
      em.out = &succ;
      uint64_t gen = 0;
      for (size_t i = tid; i < NF; i += n_threads) {
        succ.clear();
        em.generated = 0;
        successors(cfg, frontier[i], em);
        gen += em.generated;
        if (em.split_brain) { split_brain = true; return; }
        for (State &nx : succ) {
          uint64_t fp = canon_fp(cfg, perms, nx);
          if (visited.contains(fp)) continue;
          cands[tid].push_back(
              {fp, uint32_t(tid), uint32_t(buf[tid].size())});
          buf[tid].push_back(nx);
        }
      }
      generated += gen;
    };
    std::vector<std::thread> ts;
    for (int t = 0; t < n_threads; t++) ts.emplace_back(worker, t);
    for (auto &t : ts) t.join();
    if (split_brain) {
      std::fprintf(stderr, "split brain Assert fired (Raft.tla:185)\n");
      return 1;
    }
    // level-wide dedup: sort candidates by fp, group, deterministic
    // min-(canonical-full-encoding) representative per group
    std::vector<Cand> all;
    size_t total = 0;
    for (auto &c : cands) total += c.size();
    all.reserve(total);
    for (auto &c : cands) all.insert(all.end(), c.begin(), c.end());
    std::sort(all.begin(), all.end(), [](const Cand &a, const Cand &b) {
      if (a.fp != b.fp) return a.fp < b.fp;
      if (a.tid != b.tid) return a.tid < b.tid;
      return a.idx < b.idx;
    });
    std::vector<State> next;
    visited.maybe_grow(all.size());
    size_t i = 0;
    std::vector<uint8_t> best_bytes, cur_bytes;
    while (i < all.size()) {
      size_t j = i + 1;
      while (j < all.size() && all[j].fp == all[i].fp) j++;
      size_t pick = i;
      if (j - i > 1) {
        canon_full_bytes(cfg, perms, buf[all[i].tid][all[i].idx],
                         best_bytes);
        for (size_t k = i + 1; k < j; k++) {
          canon_full_bytes(cfg, perms, buf[all[k].tid][all[k].idx],
                           cur_bytes);
          if (cur_bytes < best_bytes) {
            best_bytes.swap(cur_bytes);
            pick = k;
          }
        }
      }
      const State &rep = buf[all[pick].tid][all[pick].idx];
      visited.insert(all[i].fp);
      if (!inv_ok(cfg, rep)) inv_bad = true;
      next.push_back(rep);
      i = j;
    }
    if (next.empty()) break;
    distinct += next.size();
    level_sizes.push_back(next.size());
    depth++;
    frontier.swap(next);
    {
      // per-level progress so a crashed/killed deep run still leaves a
      // usable record on stderr (the JSON only prints at the end)
      double el = std::chrono::duration<double>(
                      std::chrono::steady_clock::now() - t0).count();
      std::fprintf(stderr,
                   "[cpubase] level %d: new %llu, distinct %llu, "
                   "generated %llu, %.0fs\n",
                   depth, (unsigned long long)level_sizes.back(),
                   (unsigned long long)distinct,
                   (unsigned long long)generated.load(), el);
      std::fflush(stderr);
    }
    if (inv_bad) {
      std::fprintf(stderr, "Invariant Inv violated at depth %d\n", depth);
      return 1;
    }
  }

  auto t1 = std::chrono::steady_clock::now();
  double secs = std::chrono::duration<double>(t1 - t0).count();
  std::printf("{\"impl\": \"cpubase_cpp\", \"threads\": %d, "
              "\"S\": %d, \"V\": %d, \"max_election\": %d, "
              "\"max_restart\": %d, \"distinct\": %llu, "
              "\"generated\": %llu, \"depth\": %d, \"seconds\": %.3f, "
              "\"rate\": %.1f, \"level_sizes\": [",
              n_threads, cfg.S, cfg.V, cfg.maxE, cfg.maxR,
              (unsigned long long)distinct,
              (unsigned long long)generated.load(), depth, secs,
              distinct / secs);
  for (size_t i = 0; i < level_sizes.size(); i++)
    std::printf("%s%llu", i ? ", " : "", (unsigned long long)level_sizes[i]);
  std::printf("]}\n");
  return 0;
}

}  // namespace

int main(int argc, char **argv) {
  Cfg cfg;
  int max_depth = -1, n_threads = int(std::thread::hardware_concurrency());
  if (argc > 1) cfg.S = std::atoi(argv[1]);
  if (argc > 2) cfg.V = std::atoi(argv[2]);
  if (argc > 3) cfg.maxE = std::atoi(argv[3]);
  if (argc > 4) cfg.maxR = std::atoi(argv[4]);
  if (argc > 5) max_depth = std::atoi(argv[5]);
  if (argc > 6) n_threads = std::atoi(argv[6]);
  if (n_threads < 1) n_threads = 1;  // hardware_concurrency() may be 0
  // compile-time caps: MAXS servers, MAXL log entries, and the packed
  // message fields (term/index fields are 4 bits, vals 3)
  if (cfg.S > MAXS || cfg.V + 1 > MAXL || cfg.maxE > 15 || cfg.V > 7) {
    std::fprintf(stderr, "bounds exceed compile-time caps\n");
    return 2;
  }
  return run(cfg, max_depth, n_threads);
}
