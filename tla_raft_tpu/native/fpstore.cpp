// External-memory fingerprint store: the host-side tier of the checker's
// dedup table.
//
// TLC keeps its FPSet (the 64-bit fingerprint dedup table) in JVM heap and
// spills to the states/ metadir when it outgrows memory
// (/root/reference/myrun.sh:3 sizes the heap 4-12 GB for exactly this;
// /root/reference/.gitignore:2 reveals the spill dir).  The TPU engine
// keeps the hot store in HBM as a sorted u64 array; when a run outgrows
// the HBM budget this store takes over on the host: an LSM-style set of
// sorted immutable runs (one file per flushed batch) over a sorted
// in-memory buffer, with batched membership queries (binary search per
// run, memory-mapped).
//
// Interface is plain C for ctypes.  Single-threaded by design: the engine
// calls it once per BFS level with large batches, so per-call overhead is
// amortized; batch queries walk each run with a galloping lower_bound.
//
// Build: g++ -O2 -std=c++17 -shared -fPIC fpstore.cpp -o libfpstore.so

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Run {
  uint64_t* data = nullptr;  // mmap'd sorted unique fingerprints
  size_t n = 0;
  int fd = -1;
  std::string path;
  // per-run blocked bloom (ops/sieve.py's C++ twin: one u64 word per
  // block, 4 bits from disjoint 6-bit fields of a salted second mix).
  // Built in memory at write_run, never persisted: a reopened store
  // starts empty (resume rebuilds from the delta log), so the filter's
  // lifetime matches the mmap's.  No false negatives — a miss skips
  // the run's binary search outright.
  std::vector<uint64_t> bloom;
  uint64_t bloom_mask = 0;  // word-index mask (size - 1)
};

constexpr uint64_t kBloomSalt = 0x9E3779B97F4A7C15ull;
constexpr int kBloomBits = 4;

uint64_t mix64(uint64_t x) {
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D9ECA592EAF335ull;
  return x ^ (x >> 31);
}

void bloom_word_mask(uint64_t fp, uint64_t& word, uint64_t& mask) {
  word = mix64(fp);
  uint64_t h2 = mix64(fp ^ kBloomSalt);
  mask = 0;
  for (int i = 0; i < kBloomBits; i++)
    mask |= 1ull << ((h2 >> (6 * i)) & 63);
}

void bloom_build(Run& r) {
  // ~8 bits/key design load (64-bit words, one word per 8 keys),
  // power-of-two so the block index is a mask; floored at 64 words
  size_t words = 64;
  while (words * 8 < r.n) words <<= 1;
  r.bloom.assign(words, 0);
  r.bloom_mask = words - 1;
  for (size_t i = 0; i < r.n; i++) {
    uint64_t w, m;
    bloom_word_mask(r.data[i], w, m);
    r.bloom[w & r.bloom_mask] |= m;
  }
}

bool bloom_maybe(const Run& r, uint64_t fp) {
  if (r.bloom.empty()) return true;  // no filter: must search
  uint64_t w, m;
  bloom_word_mask(fp, w, m);
  return (r.bloom[w & r.bloom_mask] & m) == m;
}

struct FPStore {
  std::string dir;
  size_t mem_budget;           // max in-memory buffer entries before spill
  std::vector<uint64_t> mem;   // sorted unique in-memory tier
  std::vector<Run> runs;       // on-disk sorted runs, newest last
  size_t total = 0;            // total unique fingerprints
  int next_run_id = 0;
  uint64_t bloom_skips = 0;    // run binary searches avoided by blooms
};

bool contains_sorted(const uint64_t* a, size_t n, uint64_t x) {
  const uint64_t* e = a + n;
  const uint64_t* it = std::lower_bound(a, e, x);
  return it != e && *it == x;
}

int write_run(FPStore* s, const std::vector<uint64_t>& v) {
  char name[64];
  std::snprintf(name, sizeof name, "/run_%06d.fp", s->next_run_id++);
  std::string path = s->dir + name;
  int fd = ::open(path.c_str(), O_RDWR | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -1;
  size_t bytes = v.size() * sizeof(uint64_t);
  if (::ftruncate(fd, (off_t)bytes) != 0) { ::close(fd); return -1; }
  void* p = ::mmap(nullptr, bytes, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (p == MAP_FAILED) { ::close(fd); return -1; }
  std::memcpy(p, v.data(), bytes);
  ::msync(p, bytes, MS_ASYNC);
  Run r;
  r.data = (uint64_t*)p;
  r.n = v.size();
  r.fd = fd;
  r.path = path;
  bloom_build(r);
  s->runs.push_back(std::move(r));
  return 0;
}

void drop_run(Run& r) {
  if (r.data) ::munmap(r.data, r.n * sizeof(uint64_t));
  if (r.fd >= 0) ::close(r.fd);
  ::unlink(r.path.c_str());
  r.data = nullptr;
}

// Merge every run + the memory tier into one run (k-way linear merge).
int compact(FPStore* s) {
  std::vector<uint64_t> merged;
  merged.reserve(s->total);
  std::vector<std::pair<const uint64_t*, const uint64_t*>> cursors;
  for (auto& r : s->runs) cursors.push_back({r.data, r.data + r.n});
  cursors.push_back({s->mem.data(), s->mem.data() + s->mem.size()});
  // simple k-way: repeatedly take the min cursor head
  while (true) {
    const uint64_t* best = nullptr;
    size_t bi = 0;
    for (size_t i = 0; i < cursors.size(); i++) {
      if (cursors[i].first < cursors[i].second &&
          (!best || *cursors[i].first < *best)) {
        best = cursors[i].first;
        bi = i;
      }
    }
    if (!best) break;
    if (merged.empty() || merged.back() != *best) merged.push_back(*best);
    cursors[bi].first++;
  }
  for (auto& r : s->runs) drop_run(r);
  s->runs.clear();
  s->mem.clear();
  s->total = merged.size();
  if (!merged.empty() && write_run(s, merged) != 0) return -1;
  return 0;
}

}  // namespace

extern "C" {

FPStore* fpstore_open(const char* dir, uint64_t mem_budget_entries) {
  auto* s = new FPStore;
  s->dir = dir;
  s->mem_budget = mem_budget_entries ? mem_budget_entries : (64u << 20) / 8;
  ::mkdir(dir, 0755);
  return s;
}

uint64_t fpstore_count(FPStore* s) { return s->total; }
uint64_t fpstore_num_runs(FPStore* s) { return s->runs.size(); }
uint64_t fpstore_bloom_skips(FPStore* s) { return s->bloom_skips; }

// For each query: out[i] = 1 if fps[i] already present, else 0.
// Does NOT insert.
void fpstore_contains(FPStore* s, const uint64_t* fps, uint64_t n,
                      uint8_t* out) {
  for (uint64_t i = 0; i < n; i++) {
    uint64_t x = fps[i];
    bool hit = contains_sorted(s->mem.data(), s->mem.size(), x);
    for (auto it = s->runs.rbegin(); !hit && it != s->runs.rend(); ++it) {
      if (!bloom_maybe(*it, x)) { s->bloom_skips++; continue; }
      hit = contains_sorted(it->data, it->n, x);
    }
    out[i] = hit ? 1 : 0;
  }
}

// Insert a batch; out[i] = 1 iff fps[i] was newly inserted (0 = duplicate).
// Returns the number of new fingerprints, or UINT64_MAX on I/O error.
uint64_t fpstore_insert(FPStore* s, const uint64_t* fps, uint64_t n,
                        uint8_t* out) {
  std::vector<uint64_t> fresh;
  fresh.reserve(n);
  uint64_t added = 0;
  for (uint64_t i = 0; i < n; i++) {
    uint64_t x = fps[i];
    bool hit = contains_sorted(s->mem.data(), s->mem.size(), x);
    for (auto it = s->runs.rbegin(); !hit && it != s->runs.rend(); ++it) {
      if (!bloom_maybe(*it, x)) { s->bloom_skips++; continue; }
      hit = contains_sorted(it->data, it->n, x);
    }
    if (out) out[i] = hit ? 0 : 1;
    if (!hit) fresh.push_back(x);
  }
  // dedup the fresh batch (duplicates inside one call)
  std::sort(fresh.begin(), fresh.end());
  std::vector<uint64_t> uniq;
  uniq.reserve(fresh.size());
  for (uint64_t x : fresh)
    if (uniq.empty() || uniq.back() != x) uniq.push_back(x);
  // fix out[] for intra-batch duplicates: recount via membership of uniq
  if (out && uniq.size() != fresh.size()) {
    std::vector<uint64_t> seen;
    seen.reserve(fresh.size());
    for (uint64_t i = 0; i < n; i++) {
      if (!out[i]) continue;
      uint64_t x = fps[i];
      if (std::binary_search(seen.begin(), seen.end(), x)) {
        out[i] = 0;
      } else {
        seen.insert(std::lower_bound(seen.begin(), seen.end(), x), x);
      }
    }
  }
  added = uniq.size();
  // merge into the memory tier
  std::vector<uint64_t> merged;
  merged.reserve(s->mem.size() + uniq.size());
  std::merge(s->mem.begin(), s->mem.end(), uniq.begin(), uniq.end(),
             std::back_inserter(merged));
  s->mem.swap(merged);
  s->total += added;
  if (s->mem.size() >= s->mem_budget) {
    if (write_run(s, s->mem) != 0) return ~0ull;
    s->mem.clear();
    if (s->runs.size() > 16 && compact(s) != 0) return ~0ull;
  }
  return added;
}

int fpstore_compact(FPStore* s) { return compact(s); }

void fpstore_close(FPStore* s) {
  for (auto& r : s->runs) drop_run(r);
  delete s;
}

}  // extern "C"
