"""Native runtime tier: lazy-built C++ components bound via ctypes.

``HostFPStore`` wraps fpstore.cpp — the external-memory fingerprint store
that takes over TLC's FPSet role (JVM heap + ``states/`` disk spill,
/root/reference/myrun.sh:3, .gitignore:2) when a run's visited set
outgrows device HBM.  The shared library is compiled on first use with the
system toolchain and cached next to the source.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_DIR = os.path.dirname(__file__)
_SRC = os.path.join(_DIR, "fpstore.cpp")
_SO = os.path.join(_DIR, "libfpstore.so")
_BASE_SRC = os.path.join(_DIR, "cpubase.cpp")
_BASE_BIN = os.path.join(_DIR, "cpubase")


def build_native(force: bool = False) -> str:
    """Compile fpstore.cpp -> libfpstore.so (cached by mtime)."""
    if (
        not force
        and os.path.exists(_SO)
        and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)
    ):
        return _SO
    tmp = _SO + ".tmp"
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", _SRC, "-o", tmp],
        check=True,
        capture_output=True,
    )
    # compiled-.so cache swap, not a checkpoint artifact
    os.replace(tmp, _SO)  # graftlint: waive[GL009]
    return _SO


def build_cpubase(force: bool = False) -> str:
    """Compile cpubase.cpp -> the native CPU baseline checker binary.

    The multithreaded C++ explicit-state checker of the same spec family
    (the honest stand-in for `tlc -workers N`, BASELINE.md) — bench.py
    measures `vs_baseline` against it."""
    if (
        not force
        and os.path.exists(_BASE_BIN)
        and os.path.getmtime(_BASE_BIN) >= os.path.getmtime(_BASE_SRC)
    ):
        return _BASE_BIN
    tmp = _BASE_BIN + ".tmp"
    subprocess.run(
        ["g++", "-O3", "-march=native", "-std=c++17", "-pthread", "-w",
         _BASE_SRC, "-o", tmp],
        check=True,
        capture_output=True,
    )
    # compiled-binary cache swap, not a checkpoint artifact
    os.replace(tmp, _BASE_BIN)  # graftlint: waive[GL009]
    return _BASE_BIN


_lib = None


def _load():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(build_native())
        lib.fpstore_open.restype = ctypes.c_void_p
        lib.fpstore_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.fpstore_count.restype = ctypes.c_uint64
        lib.fpstore_count.argtypes = [ctypes.c_void_p]
        lib.fpstore_num_runs.restype = ctypes.c_uint64
        lib.fpstore_num_runs.argtypes = [ctypes.c_void_p]
        lib.fpstore_bloom_skips.restype = ctypes.c_uint64
        lib.fpstore_bloom_skips.argtypes = [ctypes.c_void_p]
        lib.fpstore_contains.restype = None
        lib.fpstore_contains.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.fpstore_insert.restype = ctypes.c_uint64
        lib.fpstore_insert.argtypes = lib.fpstore_contains.argtypes
        lib.fpstore_compact.restype = ctypes.c_int
        lib.fpstore_compact.argtypes = [ctypes.c_void_p]
        lib.fpstore_close.restype = None
        lib.fpstore_close.argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


class HostFPStore:
    """Sorted-run external-memory u64 set with batched insert/membership."""

    def __init__(self, dirpath: str, mem_budget_entries: int = 0):
        os.makedirs(dirpath, exist_ok=True)
        self._dir = dirpath
        self._budget = mem_budget_entries
        self._lib = _load()
        self._h = self._lib.fpstore_open(
            dirpath.encode(), ctypes.c_uint64(mem_budget_entries)
        )
        if not self._h:
            raise RuntimeError("fpstore_open failed")

    def __len__(self) -> int:
        return int(self._lib.fpstore_count(self._h))

    @property
    def num_runs(self) -> int:
        return int(self._lib.fpstore_num_runs(self._h))

    @property
    def bloom_skips(self) -> int:
        """Run binary searches avoided by the per-run blooms (built at
        spill time, in-memory only — see fpstore.cpp).  Bloom hits are
        not proof of membership, so the filter only short-circuits the
        per-run search; it never feeds the phase-1 drop."""
        return int(self._lib.fpstore_bloom_skips(self._h))

    def _ptrs(self, fps: np.ndarray):
        fps = np.ascontiguousarray(fps, np.uint64)
        out = np.zeros(len(fps), np.uint8)
        return (
            fps,
            out,
            fps.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )

    def contains(self, fps: np.ndarray) -> np.ndarray:
        fps, out, p_in, p_out = self._ptrs(fps)
        self._lib.fpstore_contains(self._h, p_in, len(fps), p_out)
        return out.astype(bool)

    def insert(self, fps: np.ndarray) -> np.ndarray:
        """Insert a batch; returns the is-new mask (False = already seen,
        including duplicates earlier in the same batch)."""
        fps, out, p_in, p_out = self._ptrs(fps)
        added = self._lib.fpstore_insert(self._h, p_in, len(fps), p_out)
        if added == np.uint64(0xFFFFFFFFFFFFFFFF):
            raise IOError("fpstore spill failed")
        return out.astype(bool)

    def compact(self) -> None:
        if self._lib.fpstore_compact(self._h) != 0:
            raise IOError("fpstore compact failed")

    def clear(self) -> None:
        """Empty the store in place (delta-log resume rebuilds it).

        Reopens a fresh native handle (close unlinks this handle's run
        files) and sweeps any orphaned ``run_*.fp`` left by a crashed
        process — those were never loaded, but they waste disk and their
        names will be reused.
        """
        import glob

        self.close()
        for f in glob.glob(os.path.join(self._dir, "run_*.fp")):
            os.unlink(f)
        self._h = self._lib.fpstore_open(
            self._dir.encode(), ctypes.c_uint64(self._budget)
        )
        if not self._h:
            raise RuntimeError("fpstore_open failed")

    def close(self) -> None:
        if self._h:
            self._lib.fpstore_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # graftlint: waive[GL003] — a destructor at
            # interpreter teardown must never raise, whatever the cause
            pass


def insert_sharded(stores: list, fps: np.ndarray) -> int:
    """Split ``fps`` by owner (fp % len(stores)) and insert each share
    into its store concurrently; returns the total newly-inserted count.

    The ctypes insert releases the GIL for the C++ sort/merge/spill, so
    D shards insert in parallel on a multi-core host — the deep-sweep
    mesh uses this to rebuild its per-owner stores on resume (and its
    level loop uses the same property for the double-buffered tail)."""
    from concurrent.futures import ThreadPoolExecutor

    D = len(stores)
    fps = np.ascontiguousarray(fps, np.uint64)
    shares = [fps[fps % np.uint64(D) == o] for o in range(D)]
    if len(fps) and not bool(np.all(fps[1:] >= fps[:-1])):
        # both resume callers pass np.unique output (sorted), and the
        # owner filter of a sorted array stays sorted — the O(n log n)
        # per-share re-sort only runs for unsorted inputs, so slab- or
        # log-sourced rebuilds skip the store-insert path's last
        # host-side sort entirely (the single-CPU rebuild tail)
        shares = [np.sort(s) for s in shares]

    def one(o):
        return int(stores[o].insert(shares[o]).sum()) if len(shares[o]) else 0

    from ..analysis.sanitize import forbid_device_dispatch_in_thread

    with ThreadPoolExecutor(
        max_workers=min(D, os.cpu_count() or 2),
        initializer=forbid_device_dispatch_in_thread,
    ) as ex:
        return sum(ex.map(one, range(D)))
