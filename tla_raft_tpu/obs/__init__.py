"""Unified telemetry subsystem (docs/OBSERVABILITY.md).

* ``telemetry`` — the process-wide event hub + crash-tolerant
  ``events.jsonl`` flight recorder every level loop publishes into.
* ``tracefile`` — Chrome trace-event (Perfetto) timeline export.
* ``progress``  — live progress line + fixpoint ETA forecasting.
* ``metrics``   — counter/gauge/histogram snapshots for the service.

Host-purity contract (graftlint GL012): nothing under ``obs/`` may
import jax, sync with a device, or dispatch a program — telemetry
observes the run, it never participates in it.  Module imports are
stdlib-only (GL001 device-free import contract).
"""

from . import metrics, progress, telemetry, tracefile  # noqa: F401
