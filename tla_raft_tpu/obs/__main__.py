"""CLI: ``python -m tla_raft_tpu.obs`` — telemetry reporting tools.

    python -m tla_raft_tpu.obs report RUN_DIR [BASELINE_RUN_DIR] [--json]
    python -m tla_raft_tpu.obs trace  RUN_DIR [-o OUT.json]
    python -m tla_raft_tpu.obs metrics ROOT
    python -m tla_raft_tpu.obs trend  [BENCH_DIR] [--check] [--json]

``report`` renders a per-level table (wall, new states, dispatches,
fetch wait, grows) from a run directory's ``events.jsonl``; with a
second run dir it prints the two runs side by side with per-level and
total deltas (the overhead/regression A/B view).  ``trace`` exports
the Chrome trace-event JSON timeline (load it in
https://ui.perfetto.dev), merging any ``--profile`` device capture
beside the host lanes.  ``metrics`` pretty-prints a service root's
``metrics.json``.  ``trend`` renders the normalized ``docs/bench/``
perf series (obs/trend.py) and, with ``--check``, exits non-zero on a
hard regression (count drift, dispatch-budget drift).

Runs missing optional event kinds (no superstep windows at
``--superstep 1``, no tier events on untiered runs, no device capture
without ``--profile``) degrade to blank columns/absent tracks — never
an error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import metrics as obs_metrics
from . import tracefile
from . import trend as obs_trend
from .telemetry import EVENTS_NAME, read_events


def _events_path(run_dir: str) -> str:
    return (
        run_dir if run_dir.endswith(".jsonl")
        else os.path.join(run_dir, EVENTS_NAME)
    )


def summarize_events(events: list[dict]) -> dict:
    """Per-level table + run totals from a raw event stream (the
    post-hoc twin of TelemetryHub.snapshot, for ``report``)."""
    levels: list[dict] = []
    cur = dict(dispatches=0, fetches=0, fetch_wait_s=0.0, grows=0,
               redos=0, checkpoint_s=0.0, tier_wait_s=0.0)
    boundary = 0.0
    totals = dict(
        events=len(events), levels=0, dispatches=0, fetches=0,
        fetch_wait_s=0.0, compiles=0, compile_s=0.0, checkpoints=0,
        checkpoint_s=0.0, grows=0, redos=0, supersteps=0,
        superstep_levels=0, watchdog_trips=0, wall_s=0.0,
        distinct=0, generated=0,
        # optional-kind columns: stay zero on runs without the
        # subsystem (untiered, --superstep 1, no --profile) and the
        # renderers blank them out rather than erroring
        tier_demotions=0, tier_probes=0, tier_wait_s=0.0,
        programs_profiled=0, pre_oom_forecasts=0,
        lock_waits=0, lock_wait_s=0.0,
    )
    locks: dict[str, dict] = {}
    for doc in events:
        try:
            t = float(doc.get("t", 0.0))
        except (TypeError, ValueError):
            t = 0.0
        k = doc.get("ev")
        totals["wall_s"] = max(totals["wall_s"], t)
        if k == "run_begin":
            boundary = t
        elif k == "dispatch":
            cur["dispatches"] += 1
            totals["dispatches"] += 1
        elif k == "fetch":
            cur["fetches"] += 1
            totals["fetches"] += 1
            cur["fetch_wait_s"] += float(doc.get("s") or 0.0)
            totals["fetch_wait_s"] += float(doc.get("s") or 0.0)
        elif k == "grow":
            cur["grows"] += 1
            totals["grows"] += 1
        elif k == "redo":
            cur["redos"] += 1
            totals["redos"] += 1
        elif k == "compile":
            totals["compiles"] += 1
            totals["compile_s"] += float(doc.get("s") or 0.0)
        elif k == "checkpoint":
            totals["checkpoints"] += 1
            cur["checkpoint_s"] += float(doc.get("s") or 0.0)
            totals["checkpoint_s"] += float(doc.get("s") or 0.0)
        elif k == "superstep_commit":
            totals["supersteps"] += 1
            totals["superstep_levels"] += int(doc.get("levels") or 0)
        elif k == "watchdog_trip":
            totals["watchdog_trips"] += 1
        elif k == "tier_demote":
            totals["tier_demotions"] += 1
            cur["tier_wait_s"] += float(doc.get("s") or 0.0)
            totals["tier_wait_s"] += float(doc.get("s") or 0.0)
        elif k == "tier_probe":
            totals["tier_probes"] += 1
            cur["tier_wait_s"] += float(doc.get("s") or 0.0)
            totals["tier_wait_s"] += float(doc.get("s") or 0.0)
        elif k == "program_profile":
            totals["programs_profiled"] += 1
        elif k == "lock_held":
            locks[str(doc.get("name"))] = dict(
                n=int(doc.get("n") or 0),
                wait_s=float(doc.get("wait_s") or 0.0),
                held_s=float(doc.get("held_s") or 0.0),
                max_wait_s=float(doc.get("max_wait_s") or 0.0),
                max_held_s=float(doc.get("max_held_s") or 0.0),
            )
        elif k == "lock_wait":
            totals["lock_waits"] += 1
            totals["lock_wait_s"] += float(doc.get("wait_s") or 0.0)
        elif k == "pre_oom_forecast":
            totals["pre_oom_forecasts"] += 1
        elif k == "level_commit":
            levels.append(dict(
                level=int(doc.get("level") or 0),
                seconds=round(t - boundary, 4),
                n_new=int(doc.get("n_new") or 0),
                **{kk: (round(v, 4) if isinstance(v, float) else v)
                   for kk, v in cur.items()},
            ))
            totals["levels"] += 1
            totals["distinct"] = int(doc.get("distinct") or 0)
            totals["generated"] = int(doc.get("generated") or 0)
            boundary = t
            cur = dict(dispatches=0, fetches=0, fetch_wait_s=0.0,
                       grows=0, redos=0, checkpoint_s=0.0,
                       tier_wait_s=0.0)
    for k in ("fetch_wait_s", "compile_s", "checkpoint_s", "wall_s",
              "tier_wait_s", "lock_wait_s"):
        totals[k] = round(totals[k], 4)
    rep = dict(levels=levels, totals=totals)
    if locks:
        rep["locks"] = locks
    return rep


def _print_table(tag: str, rep: dict, out) -> None:
    t = rep["totals"]
    # optional-subsystem columns degrade to blank, never error: a
    # --superstep 1 run has no windows, an untiered run no tier waits
    tiered = bool(t.get("tier_demotions") or t.get("tier_probes"))
    print(f"== {tag}: {t['levels']} levels, {t['distinct']:,} distinct, "
          f"wall {t['wall_s']:.2f}s ==", file=out)
    print(f"{'lvl':>4} {'new':>10} {'sec':>9} {'disp':>5} "
          f"{'fetch':>5} {'wait_s':>8} {'grow':>4} {'redo':>4}"
          + (f" {'tier_s':>8}" if tiered else ""),
          file=out)
    for lv in rep["levels"]:
        tier_col = (
            f" {lv.get('tier_wait_s', 0.0):>8.3f}" if tiered else ""
        )
        print(
            f"{lv['level']:>4} {lv['n_new']:>10,} {lv['seconds']:>9.3f} "
            f"{lv['dispatches']:>5} {lv['fetches']:>5} "
            f"{lv['fetch_wait_s']:>8.3f} {lv['grows']:>4} "
            f"{lv['redos']:>4}" + tier_col,
            file=out,
        )
    print(
        f"totals: {t['dispatches']} dispatches "
        f"({t['levels'] / max(t['dispatches'], 1):.2f} levels/dispatch), "
        f"{t['fetches']} fetches ({t['fetch_wait_s']:.3f}s wait), "
        f"{t['compiles']} compiles ({t['compile_s']:.1f}s), "
        f"{t['checkpoints']} checkpoints ({t['checkpoint_s']:.3f}s), "
        f"{t['grows']} grows / {t['redos']} redos, "
        f"{t['supersteps']} supersteps / {t['superstep_levels']} levels",
        file=out,
    )
    extras = []
    if tiered:
        extras.append(
            f"tiered: {t.get('tier_demotions', 0)} demotions, "
            f"{t.get('tier_probes', 0)} probes "
            f"({t.get('tier_wait_s', 0.0):.3f}s wait)"
        )
    if t.get("programs_profiled"):
        extras.append(
            f"device cost: {t['programs_profiled']} program profiles"
        )
    if t.get("pre_oom_forecasts"):
        extras.append(
            f"PRE-OOM forecasts: {t['pre_oom_forecasts']}"
        )
    if t.get("lock_waits"):
        extras.append(
            f"lock contention: {t['lock_waits']} slow acquire(s) "
            f"({t.get('lock_wait_s', 0.0):.3f}s blocked)"
        )
    if extras:
        print("        " + "; ".join(extras), file=out)
    # GRAFT_TSAN lock profile: one row per instrumented lock, worst
    # offenders (by total hold) first
    if rep.get("locks"):
        print(f"{'lock':<36} {'acq':>7} {'wait_s':>9} {'max_w':>8} "
              f"{'held_s':>9} {'max_h':>8}", file=out)
        rows = sorted(
            rep["locks"].items(), key=lambda kv: -kv[1]["held_s"]
        )
        for name, st in rows:
            print(
                f"{name:<36} {st['n']:>7} {st['wait_s']:>9.4f} "
                f"{st['max_wait_s']:>8.4f} {st['held_s']:>9.4f} "
                f"{st['max_held_s']:>8.4f}",
                file=out,
            )


def _cmd_report(args) -> int:
    events, dropped = read_events(_events_path(args.run_dir))
    if not events:
        print(f"{args.run_dir}: no readable events", file=sys.stderr)
        return 2
    rep = summarize_events(events)
    if dropped:
        rep["totals"]["torn_lines"] = dropped
    if args.baseline:
        bev, bdropped = read_events(_events_path(args.baseline))
        if not bev:
            print(f"{args.baseline}: no readable events",
                  file=sys.stderr)
            return 2
        brep = summarize_events(bev)
        if args.json:
            print(json.dumps(dict(run=rep, baseline=brep)))
            return 0
        _print_table(args.run_dir, rep, sys.stdout)
        print()
        _print_table(args.baseline, brep, sys.stdout)
        print()
        aw, bw = rep["totals"]["wall_s"], brep["totals"]["wall_s"]
        print("== compare (run vs baseline) ==")
        print(f"wall: {aw:.2f}s vs {bw:.2f}s "
              f"({100 * (aw - bw) / max(bw, 1e-9):+.2f}%)")
        n = min(len(rep["levels"]), len(brep["levels"]))
        for la, lb in zip(rep["levels"][:n], brep["levels"][:n]):
            ds = la["seconds"] - lb["seconds"]
            print(
                f"  level {la['level']:>3}: {la['seconds']:>8.3f}s vs "
                f"{lb['seconds']:>8.3f}s ({ds:+.3f}s), "
                f"disp {la['dispatches']} vs {lb['dispatches']}"
            )
        return 0
    if args.json:
        print(json.dumps(rep))
    else:
        _print_table(args.run_dir, rep, sys.stdout)
        if dropped:
            print(f"(torn tail: {dropped} undecodable line(s) dropped)")
    return 0


def _cmd_trace(args) -> int:
    src = _events_path(args.run_dir)
    run_dir = (
        args.run_dir if os.path.isdir(args.run_dir)
        else os.path.dirname(args.run_dir) or "."
    )
    out = args.out or os.path.join(run_dir, "trace.json")
    stats = tracefile.export(
        src, out, run_dir=run_dir,
        max_device_events=args.max_device_events,
    )
    if stats["events"] == 0:
        print(f"{src}: no readable events", file=sys.stderr)
        return 2
    print(
        f"wrote {stats['trace_events']} trace events "
        f"(from {stats['events']} run events"
        + (f", {stats['dropped']} torn" if stats["dropped"] else "")
        + (f", {stats['device_events']} device-lane events merged"
           if stats.get("device_events") else "")
        + f") to {stats['out']} — load in https://ui.perfetto.dev"
    )
    if stats.get("device_dropped"):
        print(
            f"(device lanes truncated: {stats['device_dropped']} "
            "shortest slices dropped — raise --max-device-events to "
            "keep them)"
        )
    return 0


def _cmd_trend(args) -> int:
    series = obs_trend.load_series(args.bench_dir)
    hard, soft = obs_trend.regressions(series)
    if args.json:
        print(json.dumps(dict(
            records=len(series), hard=hard, soft=soft, series=series,
        )))
    else:
        obs_trend.render(series)
        for w in soft:
            print(f"warning: trend: {w}")
        for f in hard:
            print(f"FAIL: trend: {f}")
        print(
            f"trend: {len(series)} record(s), {len(hard)} hard "
            f"regression(s), {len(soft)} warning(s) — "
            + ("FAIL" if hard else "OK")
        )
    if not series and args.check:
        print(f"{args.bench_dir}: no trend records", file=sys.stderr)
        return 2
    return 1 if hard and args.check else 0


def _cmd_metrics(args) -> int:
    doc = obs_metrics.load(args.root)
    if doc is None:
        print(f"{args.root}: no readable metrics.json", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(doc))
    else:
        obs_metrics.render(doc)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tla_raft_tpu.obs")
    sub = p.add_subparsers(dest="cmd", required=True)

    pr = sub.add_parser("report", help="per-level telemetry table")
    pr.add_argument("run_dir",
                    help="run dir holding events.jsonl (or the file)")
    pr.add_argument("baseline", nargs="?", default=None,
                    help="second run dir to compare against")
    pr.add_argument("--json", action="store_true")

    pt = sub.add_parser("trace", help="export Chrome trace JSON")
    pt.add_argument("run_dir")
    pt.add_argument("-o", "--out", default=None)
    pt.add_argument("--max-device-events", type=int,
                    default=tracefile.MAX_DEVICE_EVENTS,
                    help="device-lane merge budget (shortest slices "
                         "drop first past it; 0 = unbounded)")

    pm = sub.add_parser("metrics", help="render a service metrics.json")
    pm.add_argument("root")
    pm.add_argument("--json", action="store_true")

    pd = sub.add_parser(
        "trend", help="render the docs/bench/ perf-trend series"
    )
    pd.add_argument("bench_dir", nargs="?",
                    default=obs_trend.BENCH_DIRNAME,
                    help="series directory (default: docs/bench)")
    pd.add_argument("--check", action="store_true",
                    help="exit non-zero on a hard regression "
                         "(count/dispatch-budget drift) — the CI gate")
    pd.add_argument("--json", action="store_true")

    args = p.parse_args(argv)
    if args.cmd == "report":
        return _cmd_report(args)
    if args.cmd == "trace":
        return _cmd_trace(args)
    if args.cmd == "trend":
        return _cmd_trend(args)
    return _cmd_metrics(args)


if __name__ == "__main__":
    sys.exit(main())
