"""Live progress line + fixpoint ETA forecasting.

``check.py --progress`` (and the service worker's ``run --progress``)
render one carriage-return-updated status line per committed level:

    level 9  frontier 12,408  distinct 54,201  3,412 st/s  slab 31%
    2.8 lvl/disp  ETA 0:48

The ETA comes from the level-size trend: BFS frontiers of these state
spaces grow geometrically, peak, then decay toward the fixpoint.  Once
the growth ratio decays, the remaining states are forecast by
projecting the ratio's own decay forward (a second-order geometric
model — the same shape engine/forecast.py fits for capacity planning)
and dividing by the observed steady states/second.  While the frontier
is still growing with no decay signal, the honest answer is "unknown"
(rendered ``ETA —``).

Host-pure (graftlint GL012) and dependency-free: arithmetic over the
stats dicts the engines already publish, plus the telemetry hub's
aggregate snapshot when one is installed.
"""

from __future__ import annotations

import sys

# forecast horizon: project at most this many future levels (a model
# that needs more is extrapolating noise — report unknown instead)
MAX_HORIZON = 64


def forecast_remaining_states(level_sizes) -> float | None:
    """Forecast NEW states still to be found before the fixpoint.

    Second-order geometric projection: with recent level sizes
    ``..., a, b, c`` the growth ratio is ``r = c/b`` and its per-level
    decay ``d = (c/b)/(b/a)`` (clamped to <= 1 — acceleration is not a
    convergence signal).  Future sizes are ``c*r*d, c*r*d^2*r*d,
    ...`` summed until they fall below one state.  Returns None while
    the trend gives no finite forecast (still growing, too few
    levels).
    """
    s = [float(x) for x in level_sizes if x and x > 0]
    if len(s) < 3:
        return None
    a, b, c = s[-3], s[-2], s[-1]
    r = c / b
    d = min((c / b) / (b / a), 1.0) if a > 0 else 1.0
    if r >= 1.0 and d >= 1.0:
        return None  # still growing, no decay signal yet
    rem, size = 0.0, c
    for _ in range(MAX_HORIZON):
        r *= d
        size *= r
        if size < 1.0:
            break
        rem += size
    else:
        if r >= 1.0:
            return None  # never converged inside the horizon
        # slow but subcritical decay: close the geometric tail
        rem += size * r / (1.0 - r)
    return rem


def eta_seconds(level_sizes, rate: float) -> float | None:
    """Seconds to fixpoint at ``rate`` states/s; None = unknown."""
    rem = forecast_remaining_states(level_sizes)
    if rem is None or rate <= 0:
        return None
    return rem / rate


def fmt_eta(seconds: float | None) -> str:
    if seconds is None:
        return "—"
    seconds = max(0, int(round(seconds)))
    h, rest = divmod(seconds, 3600)
    m, s = divmod(rest, 60)
    return f"{h}:{m:02d}:{s:02d}" if h else f"{m}:{s:02d}"


class ProgressLine:
    """Render engine progress stats as one live status line.

    Feed it the per-level stats dict the engines already emit
    (``level``/``frontier``/``distinct``/``generated``/``elapsed``);
    it keeps the level-size history for the ETA forecast and reads
    levels/dispatch + slab load off the installed telemetry hub when
    there is one.  ``update()`` returns the rendered line;
    ``write()`` paints it over the previous one (CR, no newline);
    ``done()`` terminates the line.
    """

    def __init__(self, stream=None, width: int = 100):
        self.stream = stream if stream is not None else sys.stderr
        self.width = width
        self.level_sizes: list[int] = []
        self._painted = False
        self.last_line = ""

    def update(self, stats: dict, snap: dict | None = None) -> str:
        if snap is None:
            from . import telemetry

            hub = telemetry.current()
            snap = hub.snapshot() if hub is not None else None
        lvl = stats.get("level", 0)
        frontier = int(stats.get("frontier", 0))
        distinct = int(stats.get("distinct", 0))
        elapsed = float(stats.get("elapsed", 0.0)) or 1e-9
        self.level_sizes.append(frontier)
        rate = distinct / elapsed
        parts = [
            f"level {lvl}",
            f"frontier {frontier:,}",
            f"distinct {distinct:,}",
            f"{rate:,.0f} st/s",
        ]
        if snap:
            if snap.get("slab_cap"):
                parts.append(f"slab {100 * snap['slab_load']:.0f}%")
            if snap.get("dispatches"):
                parts.append(f"{snap['levels_per_dispatch']:.2f} lvl/disp")
            hbm = snap.get("hbm") or {}
            if hbm.get("budget_bytes"):
                # live device-memory gauge vs the --dev-bytes budget;
                # the pre-OOM forecast flags the line before the
                # reactive overflow machinery would trip
                parts.append(f"hbm {100 * hbm.get('used_frac', 0):.0f}%")
                if hbm.get("pre_oom_forecasts"):
                    parts.append("PRE-OOM")
            elif hbm.get("working_set_bytes"):
                parts.append(
                    f"hbm {hbm['working_set_bytes'] / 1e6:.0f}MB"
                )
        if "configs_alive" in stats:  # service bucket progress
            parts.append(f"{stats['configs_alive']} cfg alive")
        parts.append(
            f"ETA {fmt_eta(eta_seconds(self.level_sizes, rate))}"
        )
        self.last_line = "  ".join(parts)[: self.width]
        return self.last_line

    def write(self, stats: dict, snap: dict | None = None) -> None:
        line = self.update(stats, snap)
        pad = " " * max(0, self.width - len(line))
        self.stream.write("\r" + line + pad)
        self.stream.flush()
        self._painted = True

    def done(self) -> None:
        if self._painted:
            self.stream.write("\n")
            self.stream.flush()
            self._painted = False
