"""Perf-trend series: normalized bench records + regression flags.

The repo accumulated one bench artifact per round in three dialects —
legacy harness wrappers (``{"n", "cmd", "rc", "tail", "parsed"}``),
canonical ``tla-raft-bench/1`` round records, and ``tla-raft-bench-ab/1``
A/B records with per-arm walls — scattered between the repo root and
``docs/``.  This module folds them all into ONE ``docs/bench/`` series
with a single schema (``tla-raft-trend/1``), renders the trajectory
(tables + sparklines), and flags regressions:

* **hard** (exit non-zero from ``obs trend --check``): a later round of
  the SAME metric+config reports different model counts
  (distinct/generated/depth — the checker's correctness surface; wall
  clocks wobble, counts never may), or its dispatch amortization
  regresses (``levels_per_dispatch`` drops / worst
  dispatches-per-level grows — the GL011 budget surface, re-checked on
  the committed history).
* **soft** (warn only): the latest wall/rate is worse than the
  windowed median of its predecessors beyond a tolerance band — CPU
  walls on shared boxes are noisy, so walls warn, never fail.

``bench.py`` appends each round's record through
:func:`append_record`, so the series grows as a side effect of running
the bench — no separate bookkeeping step.  Host-pure (graftlint
GL012): stdlib only.
"""

from __future__ import annotations

import json
import os
import re

SCHEMA = "tla-raft-trend/1"
BENCH_DIRNAME = os.path.join("docs", "bench")

# soft-warn band: latest wall > median-of-window * (1 + this)
WALL_TOLERANCE = 0.35
# rate uses the inverse band (latest rate < median / (1 + this))
RATE_TOLERANCE = 0.35
MEDIAN_WINDOW = 5

_ROUND_RE = re.compile(r"r(\d+)")

SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values) -> str:
    """Unicode sparkline of a numeric series ('' when empty; gaps
    render as spaces)."""
    vals = [v for v in values if isinstance(v, (int, float))]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    out = []
    for v in values:
        if not isinstance(v, (int, float)):
            out.append(" ")
            continue
        out.append(SPARK[int((v - lo) / span * (len(SPARK) - 1))])
    return "".join(out)


def round_from_name(name: str) -> int | None:
    """``BENCH_r06.json`` / ``r17_tiered_ab.json`` -> the round."""
    m = _ROUND_RE.search(os.path.basename(name))
    return int(m.group(1)) if m else None


def normalize(doc: dict, *, round_no: int | None = None,
              source: str | None = None) -> dict | None:
    """One bench artifact (any historical dialect) -> one trend record.

    Returns None for artifacts with nothing comparable (e.g. a legacy
    wrapper whose ``parsed`` is null — the run crashed before the
    summary line).  The normalized record:

    ====================  ===============================================
    ``schema``            ``tla-raft-trend/1``
    ``round``             campaign round (int) — the series' x axis
    ``metric``            bench family (``raft_cfg_check_depth11`` ...)
    ``config``            config describe string (count-identity key)
    ``wall_s``            wall seconds (primary arm)
    ``rate``              steady states/s or jobs/h (primary arm)
    ``unit``              rate unit
    ``distinct``/``generated``/``depth``  model counts (count gate)
    ``parity``/``ok``     the round's own verdicts (tri-state)
    ``levels_per_dispatch``/``max_dispatches_per_level``  GL011 surface
    ``arms``              per-arm wall/rate for A/B records
    ``device``/``source``  provenance
    ====================  ===============================================
    """
    if not isinstance(doc, dict):
        return None
    # legacy harness wrapper: the payload is in "parsed"
    if "parsed" in doc and "schema" not in doc:
        inner = doc.get("parsed")
        if not isinstance(inner, dict):
            return None
        return normalize(inner, round_no=round_no, source=source)
    if doc.get("schema") == SCHEMA:
        out = dict(doc)
        if round_no is not None and out.get("round") is None:
            out["round"] = round_no
        return out

    out: dict = {"schema": SCHEMA, "round": round_no, "source": source}
    if doc.get("schema") == "tla-raft-bench-ab/1":
        # A/B record: keep both arms, promote the shipped/default arm
        # (the first) as the primary wall/rate
        out["metric"] = doc.get("metric") or _ab_metric(doc, source)
        arms = _ab_arms(doc)
        out["arms"] = arms
        if arms:
            first = next(iter(arms.values()))
            out["wall_s"] = first.get("wall_s")
            out["rate"] = first.get("rate")
        out["unit"] = doc.get("unit") or "distinct_states_per_sec"
        for k in ("config", "distinct", "generated", "depth",
                  "device"):
            if k in doc:
                out[k] = doc[k]
        out["parity"] = doc.get("parity",
                                doc.get("counts_bit_identical"))
        out["ok"] = doc.get("ok", out["parity"])
        return out

    # canonical bench/1 records and bare summary dicts share keys
    metric = doc.get("metric")
    if metric is None:
        return None
    out["metric"] = metric
    out["config"] = doc.get("config")
    out["wall_s"] = doc.get("wall_s")
    out["rate"] = (
        doc.get("steady_rate", doc.get("jobs_per_hour",
                                       doc.get("value")))
    )
    out["unit"] = doc.get("unit")
    for k in ("distinct", "generated", "depth", "parity", "ok",
              "device", "vs_baseline", "levels_per_dispatch",
              "steady_max_dispatches_per_level", "mesh", "mesh_deep",
              "tiered_bytes"):
        if k in doc and doc[k] is not None:
            out[k] = doc[k]
    if "steady_max_dispatches_per_level" in out:
        out["max_dispatches_per_level"] = out.pop(
            "steady_max_dispatches_per_level"
        )
    return out


def _ab_metric(doc: dict, source: str | None) -> str:
    """A/B records carry no metric field; derive one from the source
    file name (``BENCH_TIERED_AB_r17.json`` -> ``ab_tiered``)."""
    name = os.path.basename(source or "ab").lower()
    name = re.sub(r"^bench_", "", name)
    name = re.sub(r"_?ab_?r?\d*\.json$", "", name)
    return f"ab_{name or 'unknown'}"


def _ab_arms(doc: dict) -> dict:
    arms: dict = {}
    if isinstance(doc.get("arms"), dict):
        for name, arm in doc["arms"].items():
            if isinstance(arm, dict):
                arms[name] = dict(
                    wall_s=arm.get("wall_s"),
                    rate=arm.get("steady_rate", arm.get("rate",
                                 arm.get("jobs_per_hour"))),
                    **{k: arm[k] for k in (
                        "levels_per_dispatch",
                        "steady_max_dispatches_per_level",
                    ) if k in arm},
                )
    elif isinstance(doc.get("wall_s"), dict):
        rates = doc.get("steady_rate")
        rates = rates if isinstance(rates, dict) else {}
        for name, wall in doc["wall_s"].items():
            arms[name] = dict(wall_s=wall, rate=rates.get(name))
    return arms


def record_name(rec: dict) -> str:
    """Series file name: ``r<NN>_<metric>[_<variant>].json``."""
    rnd = rec.get("round")
    rnd = f"r{int(rnd):02d}" if rnd is not None else "rxx"
    metric = re.sub(r"[^A-Za-z0-9_.-]+", "_",
                    str(rec.get("metric", "unknown")))
    variant = rec.get("variant")
    suffix = (
        "_" + re.sub(r"[^A-Za-z0-9_.-]+", "_", str(variant))
        if variant else ""
    )
    return f"{rnd}_{metric}{suffix}.json"


def append_record(doc: dict, bench_dir: str,
                  round_no: int | None = None,
                  source: str | None = None,
                  variant: str | None = None) -> str | None:
    """Normalize one bench artifact into the series directory.

    Returns the written path (None when the artifact normalizes to
    nothing).  Same round + metric (+ variant) overwrites — re-running
    a round's bench updates its point instead of forking the series.
    ``variant`` disambiguates multiple same-metric runs of one round
    (cold/warm, different scale dials) — variants form their OWN trend
    key, so a cold-start wall never reads as a warm regression."""
    rec = normalize(doc, round_no=round_no, source=source)
    if rec is None:
        return None
    if variant:
        rec["variant"] = str(variant)
    os.makedirs(bench_dir, exist_ok=True)
    path = os.path.join(bench_dir, record_name(rec))
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(rec, fh, indent=1, sort_keys=True)
        fh.write("\n")
    # the trend gate re-reads and re-validates the whole series, so
    # this is a bench record, not a checkpoint artifact
    # graftlint: waive[GL009] — bench-series record, not a checkpoint
    os.replace(tmp, path)
    return path


def load_series(bench_dir: str) -> list[dict]:
    """Every readable record in the series, sorted by (round, metric).
    Unreadable/alien files are skipped — the gate reports on what IS
    comparable."""
    out: list[dict] = []
    try:
        names = sorted(os.listdir(bench_dir))
    except OSError:
        return []
    for name in names:
        if not name.endswith(".json"):
            continue
        path = os.path.join(bench_dir, name)
        try:
            with open(path, encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            continue
        rec = normalize(doc, round_no=round_from_name(name),
                        source=name)
        if rec is not None and rec.get("round") is not None:
            out.append(rec)
    out.sort(key=lambda r: (int(r["round"]), str(r.get("metric"))))
    return out


def _by_key(series: list[dict]) -> dict:
    groups: dict = {}
    for rec in series:
        key = (
            str(rec.get("metric")), str(rec.get("config")),
            str(rec.get("variant") or ""),
        )
        groups.setdefault(key, []).append(rec)
    return groups


def _median(vals):
    vals = sorted(vals)
    return vals[len(vals) // 2] if vals else None


def regressions(series: list[dict]) -> tuple[list[str], list[str]]:
    """(hard failures, soft warnings) over the normalized series.

    Hard: count drift (distinct/generated/depth changed between rounds
    of the same metric+config — a silently wrong checker), parity/ok
    flipping to False, and dispatch-budget drift (levels_per_dispatch
    shrinking / max dispatches growing).  Soft: latest wall/rate worse
    than the windowed median beyond the tolerance band.
    """
    hard: list[str] = []
    soft: list[str] = []
    for (metric, _cfg, variant), recs in _by_key(series).items():
        if len(recs) < 2:
            continue
        latest, prior = recs[-1], recs[:-1]
        tag = f"{metric}{f'/{variant}' if variant else ''} " \
              f"r{latest.get('round')}"
        # -- count identity (the correctness surface) -----------------
        for k in ("distinct", "generated", "depth"):
            base = next(
                (r[k] for r in reversed(prior) if r.get(k) is not None),
                None,
            )
            if base is not None and latest.get(k) is not None \
                    and latest[k] != base:
                hard.append(
                    f"{tag}: {k} drifted {base} -> {latest[k]} on an "
                    "identical config — count regression (the wall "
                    "clock may lie; counts may not)"
                )
        if latest.get("parity") is False or latest.get("ok") is False:
            hard.append(
                f"{tag}: round recorded "
                f"parity={latest.get('parity')} ok={latest.get('ok')}"
            )
        # -- dispatch-budget drift (the GL011 surface) ----------------
        base_lpd = next(
            (r["levels_per_dispatch"] for r in reversed(prior)
             if r.get("levels_per_dispatch") is not None), None,
        )
        if base_lpd and latest.get("levels_per_dispatch") is not None \
                and latest["levels_per_dispatch"] < base_lpd - 1e-9:
            hard.append(
                f"{tag}: levels/dispatch regressed {base_lpd} -> "
                f"{latest['levels_per_dispatch']} — the dispatch "
                "amortization shrank (GL011's surface, on the "
                "committed history)"
            )
        base_mdl = next(
            (r["max_dispatches_per_level"] for r in reversed(prior)
             if r.get("max_dispatches_per_level") is not None), None,
        )
        if base_mdl is not None \
                and latest.get("max_dispatches_per_level") is not None \
                and latest["max_dispatches_per_level"] > base_mdl:
            hard.append(
                f"{tag}: worst dispatches/level grew {base_mdl} -> "
                f"{latest['max_dispatches_per_level']}"
            )
        # -- wall/rate trend (soft: CPU walls are noisy) --------------
        walls = [r["wall_s"] for r in prior[-MEDIAN_WINDOW:]
                 if isinstance(r.get("wall_s"), (int, float))]
        med = _median(walls)
        if med and isinstance(latest.get("wall_s"), (int, float)) \
                and latest["wall_s"] > med * (1 + WALL_TOLERANCE):
            soft.append(
                f"{tag}: wall {latest['wall_s']:.1f}s vs windowed "
                f"median {med:.1f}s (+{WALL_TOLERANCE:.0%} band) — "
                "soft warn (CPU walls are noisy; silicon gates are "
                "the A/B records)"
            )
        rates = [r["rate"] for r in prior[-MEDIAN_WINDOW:]
                 if isinstance(r.get("rate"), (int, float))]
        med_r = _median(rates)
        if med_r and isinstance(latest.get("rate"), (int, float)) \
                and latest["rate"] < med_r / (1 + RATE_TOLERANCE):
            soft.append(
                f"{tag}: rate {latest['rate']:,.0f} vs windowed "
                f"median {med_r:,.0f} — soft warn"
            )
    return hard, soft


def render(series: list[dict], out=None) -> None:
    """Trajectory tables + sparklines, one block per metric family."""
    import sys

    out = out if out is not None else sys.stdout
    if not series:
        print("no trend records (docs/bench/ empty?)", file=out)
        return
    for (metric, _cfg, variant), recs in sorted(_by_key(series).items()):
        rates = [r.get("rate") for r in recs]
        label = f"{metric} [{variant}]" if variant else metric
        print(f"== {label}  {sparkline(rates)}", file=out)
        cfg = recs[-1].get("config")
        if cfg:
            print(f"   config: {cfg}", file=out)
        print(
            f"   {'rnd':>4} {'wall_s':>9} {'rate':>12} {'distinct':>10}"
            f" {'depth':>5} {'par':>4} {'lvl/disp':>8}", file=out,
        )
        for r in recs:
            par = r.get("parity")
            print(
                f"   {r.get('round', '?'):>4}"
                f" {_fmt(r.get('wall_s'), '9.1f')}"
                f" {_fmt(r.get('rate'), '12,.0f')}"
                f" {_fmt(r.get('distinct'), '10,d')}"
                f" {_fmt(r.get('depth'), '5d')}"
                f" {'  ok' if par else ('   ?' if par is None else ' BAD'):>4}"
                f" {_fmt(r.get('levels_per_dispatch'), '8.2f')}",
                file=out,
            )
        arms = recs[-1].get("arms")
        if arms:
            for name, arm in arms.items():
                print(
                    f"     arm {name}: wall "
                    f"{_fmt(arm.get('wall_s'), '.1f')}s, rate "
                    f"{_fmt(arm.get('rate'), ',.0f')}", file=out,
                )


def _fmt(v, spec: str) -> str:
    if v is None:
        width = re.match(r"(\d+)", spec)
        return " " * int(width.group(1)) if width else "-"
    try:
        if spec.endswith("d"):
            v = int(v)
        return format(v, spec)
    except (ValueError, TypeError):
        return str(v)
