"""Process-wide telemetry hub: the run flight recorder.

Every level loop, the async pipeline, the atomic checkpoint writer,
the watchdog and the sweep service publish typed, monotonic-timestamped
run events into one process-global hub (:func:`install` /
:func:`current`).  The hub

* appends each event crash-tolerantly to ``events.jsonl`` in the run
  directory — tmp-free ``"a"``-mode appends of self-checking lines
  (each line carries a CRC of its own payload in ``"d"``), so a torn
  tail is detected and tolerated on read instead of poisoning the
  stream (:func:`read_events`), and a resumed run heals the tail
  before appending (:func:`_heal_tail`);
* aggregates the per-level accounting (level wall times, dispatches,
  ledgered fetch waits, grow/redo counts, checkpoint I/O, compiles,
  superstep amortization) host-side, so ``check.py --json``'s
  ``telemetry`` block and bench.py read ONE bookkeeping instead of
  three ad-hoc meters.

Host-purity contract (graftlint GL012): this module — the whole
``obs/`` package — must never import jax, touch a device array, or
dispatch a program.  Publishing is a plain function call with a
``CURRENT is None`` fast path; with telemetry off every hook in the
tree is one global read + one branch.

Event taxonomy (docs/OBSERVABILITY.md):

================  ======================================================
``run_begin``     config + engine flags, wall-clock anchor
``run_end``       verdict, distinct/generated/depth
``level_begin``   ``level`` (1-based), ``frontier`` rows entering it
``level_commit``  ``level``, ``n_new``, ``distinct``, ``generated``,
                  ``slab_cap`` (0 = no device hash slab)
``superstep_begin/commit``  one multi-level resident dispatch window
``dispatch``      one device program dispatch (``tag`` = call site)
``fetch``         one ledgered pipeline fetch: ``s`` wait, ``b`` bytes
``compile``       one XLA backend compile: ``s``, ``declared`` (prewarm)
``checkpoint``    one atomic artifact commit: ``kind``, ``name``,
                  ``s``, ``b``
``grow``/``redo`` a named capacity budget grew / a level re-ran
``watchdog_arm``/``watchdog_trip``  hang-watchdog lifecycle
``audit``         one sampled-recomputation audit: ``rows``,
                  ``problems``
``retire``        one bucket member retired (service)
``exchange``      one mesh level's fingerprint-exchange bytes
``skew``          per-owner straggler skew of one mesh level
``shape``         a declared recompile cause (capacity/shape event)
``integrity``     a conservation/audit fail-stop fired
``tier_demote``   one hot-slab generation demotion (tiered store):
                  ``level``, ``n`` fps, ``gen`` id, ``s``, ``cold``
``tier_probe``    one warm/cold generation probe: ``level``, ``lanes``,
                  ``hits``, ``s`` wait (the spill-overlap metric)
``program_profile``  one compiled device program's XLA cost/memory
                  ledger (analysis/devprof.py): ``tag``, ``flops``,
                  ``bytes`` accessed, ``arg_b``/``out_b``/``tmp_b``/
                  ``code_b`` memory-analysis bytes
``buffer``        one registered long-lived device buffer (slab, ring,
                  frontier): ``name``, ``b`` bytes — the live-HBM gauge
``hbm_budget``    the run's device-memory budget (``--dev-bytes``)
``pre_oom_forecast``  the forecast NEXT level's working set would bust
                  the device budget: ``level``, ``need``, ``budget``
                  (predictive, vs. the reactive overflow-redo)
``profile_begin``/``profile_end``  one ``--profile N`` jax-profiler
                  capture window (``dir`` holds the device trace)
``lock_held``     one instrumented lock's whole-run aggregate
                  (``GRAFT_TSAN=1``, analysis/tsan.py): ``name``,
                  ``n`` acquires, ``wait_s``/``held_s`` totals,
                  ``max_wait_s``/``max_held_s``
``lock_wait``     one acquire that blocked past the contention
                  threshold: ``name``, ``wait_s`` (the trace's
                  contention track)
================  ======================================================

Rotation: the stream is capped at ``TLA_RAFT_TELEMETRY_BYTES``
(default 64 MiB, 0 = unbounded); past the cap the file rotates to
``events.1.jsonl`` (older generations shift up) at level/superstep
boundaries, and :func:`read_events` follows the chain oldest-first.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib

# flush the append buffer every N events (level boundaries flush too);
# small enough that a SIGKILL loses at most a level's tail of events
FLUSH_EVERY = 64

EVENTS_NAME = "events.jsonl"

# rotation cap: a long tiered run would otherwise append unbounded
DEFAULT_MAX_BYTES = 64 << 20

CURRENT: "TelemetryHub | None" = None


def enabled_by_env() -> bool:
    """Telemetry default: ON; ``TLA_RAFT_TELEMETRY=0`` disables."""
    return os.environ.get("TLA_RAFT_TELEMETRY", "1") != "0"


def max_bytes_from_env() -> int:
    """Rotation byte budget (``TLA_RAFT_TELEMETRY_BYTES``; 0 = never
    rotate)."""
    v = os.environ.get("TLA_RAFT_TELEMETRY_BYTES")
    if v is None or v == "":
        return DEFAULT_MAX_BYTES
    return max(0, int(float(v)))


def rotated_paths(path: str) -> list[str]:
    """The sealed rotation chain of ``path``, OLDEST first
    (``events.N.jsonl`` ... ``events.1.jsonl``); empty when the stream
    never rotated."""
    base, ext = os.path.splitext(path)
    out: list[str] = []
    n = 1
    while os.path.exists(f"{base}.{n}{ext}"):
        out.append(f"{base}.{n}{ext}")
        n += 1
    return list(reversed(out))


def install(hub: "TelemetryHub | None") -> None:
    """Set the process-global hub (None = every hook is a no-op)."""
    global CURRENT
    CURRENT = hub


def current() -> "TelemetryHub | None":
    return CURRENT


def _clean(v):
    """JSON-safe field coercion (numpy scalars arrive from engines)."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    item = getattr(v, "item", None)
    if item is not None:
        try:
            return item()
        except (TypeError, ValueError):
            pass
    if isinstance(v, (list, tuple)):
        return [_clean(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _clean(x) for k, x in v.items()}
    return str(v)


def hbm_gauge(buffers: dict, program_temp: dict,
              budget: int = 0) -> dict:
    """The live device-memory gauge: registered long-lived buffers
    (slab, ring, frontier caps) + the worst profiled program's temp
    bytes.  Pure arithmetic — the one place the ``--json`` ``hbm``
    block and ``--progress`` compute occupancy, so the two can never
    disagree.  ``headroom_bytes`` is present only under a budget and
    may be negative (a transiently over-budget working set — the
    pre-OOM forecast's trigger condition)."""
    resident = int(sum(buffers.values()))
    temp_tag, temp_peak = None, 0
    for tag, b in program_temp.items():
        if b > temp_peak:
            temp_tag, temp_peak = tag, int(b)
    out = dict(
        buffers={k: int(v) for k, v in sorted(buffers.items())},
        resident_bytes=resident,
        temp_peak_bytes=temp_peak,
        temp_peak_program=temp_tag,
        working_set_bytes=resident + temp_peak,
    )
    if budget:
        out["budget_bytes"] = int(budget)
        out["headroom_bytes"] = int(budget) - resident - temp_peak
        out["used_frac"] = round(
            (resident + temp_peak) / budget, 4
        )
    return out


def _line_digest(core: str) -> str:
    return format(zlib.crc32(core.encode("utf-8")) & 0xFFFFFFFF, "08x")


def encode_event(ev: dict) -> str:
    """One self-checking JSONL line: payload + CRC of the payload."""
    core = json.dumps(ev, sort_keys=True, separators=(",", ":"))
    return json.dumps(
        dict(ev, d=_line_digest(core)),
        sort_keys=True, separators=(",", ":"),
    )


def decode_line(line: str) -> dict | None:
    """Parse + digest-check one line; None = torn/corrupt."""
    try:
        doc = json.loads(line)
    except ValueError:
        return None
    if not isinstance(doc, dict):
        return None
    d = doc.pop("d", None)
    core = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    if d != _line_digest(core):
        return None
    return doc


def _read_one(path: str) -> tuple[list[dict], int]:
    events: list[dict] = []
    dropped = 0
    try:
        with open(path, encoding="utf-8", errors="replace") as fh:
            lines = fh.read().splitlines()
    except (FileNotFoundError, OSError):
        return [], 0
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        doc = decode_line(line)
        if doc is None:
            dropped = sum(1 for x in lines[i:] if x.strip())
            break
        events.append(doc)
    return events, dropped


def read_events(path: str, follow_rotation: bool = True
                ) -> tuple[list[dict], int]:
    """Read an event stream, tolerating a torn tail.

    Returns ``(events, dropped)``: every digest-verified event up to
    the first bad line per file, and the count of lines dropped (0 on
    a clean stream).  Never raises on torn/corrupt content — a crashed
    writer's half-line is the EXPECTED failure mode.  A rotated stream
    (``events.N.jsonl`` siblings) is spliced back together oldest-
    first, so ``report``/``trace`` see the whole run.
    """
    events: list[dict] = []
    dropped = 0
    chain = rotated_paths(path) if follow_rotation else []
    for p in chain + [path]:
        ev, dr = _read_one(p)
        events.extend(ev)
        dropped += dr
    return events, dropped


def _heal_tail(path: str) -> int:
    """Truncate a torn tail so a resumed run appends after the last
    good, newline-terminated line (an unterminated tail is torn even
    if it happens to parse — appending after it would corrupt the next
    line).  Returns the number of lines dropped."""
    if not os.path.exists(path):
        return 0
    with open(path, "rb") as fh:
        data = fh.read()
    keep = 0  # byte offset after the last good terminated line
    dropped = 0
    off, n = 0, len(data)
    while off < n:
        nl = data.find(b"\n", off)
        if nl < 0:
            if data[off:].strip():
                dropped += 1
            break
        raw = data[off:nl]
        if raw.strip() and decode_line(
            raw.decode("utf-8", "replace")
        ) is None:
            dropped += sum(
                1 for x in data[off:].split(b"\n") if x.strip()
            )
            break
        off = nl + 1
        keep = off
    if keep < n:
        with open(path, "r+b") as fh:
            fh.truncate(keep)
    return dropped


def _last_event_t(path: str, tail_bytes: int = 1 << 16) -> float | None:
    """Timestamp of the last verified event line (reads only the tail;
    None on an empty/unreadable stream)."""
    try:
        with open(path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            size = fh.tell()
            fh.seek(max(0, size - tail_bytes))
            chunk = fh.read()
    except OSError:
        return None
    for raw in reversed(chunk.split(b"\n")):
        if not raw.strip():
            continue
        doc = decode_line(raw.decode("utf-8", "replace"))
        if doc is not None:
            try:
                return float(doc.get("t", 0.0))
            except (TypeError, ValueError):
                return None
    return None


class TelemetryHub:
    """One run's flight recorder + host-side aggregate accounting.

    ``run_dir=None`` keeps the stream in memory only (the aggregates —
    the ``--json`` ``telemetry`` block — still work); with a run dir
    the stream appends to ``<run_dir>/events.jsonl``.  Usable as a
    context manager: installs itself as the process hub on enter,
    uninstalls + flushes on exit.
    """

    def __init__(self, run_dir: str | None = None,
                 path: str | None = None,
                 max_bytes: int | None = None):
        if path is None and run_dir is not None:
            path = os.path.join(run_dir, EVENTS_NAME)
        self.path = path
        self.max_bytes = (
            max_bytes_from_env() if max_bytes is None else max(0, max_bytes)
        )
        self.rotations = 0
        self._size = 0  # active file's byte size (approx, append-only)
        self.healed_lines = 0
        self._fh = None
        self._buf: list[str] = []
        # two locks: _lock guards the in-memory buffer + aggregates
        # (held only for list/dict ops — emit can never block on a
        # hung filesystem), _io_lock serializes the actual file writes
        self._lock = threading.Lock()
        self._io_lock = threading.Lock()
        self._t0 = time.monotonic()
        # resumed stream: rebase this run's clock past the existing
        # stream's last timestamp so the spliced events.jsonl stays
        # monotonic and the exported trace never overlays the crashed
        # run with the resumed one.  Healing the torn tail happens NOW
        # (eagerly) so the rebase reads only verified lines.
        self._t_off = 0.0
        if path is not None and os.path.exists(path):
            self.healed_lines = _heal_tail(path)
            self._size = os.path.getsize(path)
            last = _last_event_t(path)
            if last is None:
                # active file healed to empty (crash right after a
                # rotation): the clock rebase reads the newest SEALED
                # generation so the spliced chain stays monotonic
                for p in reversed(rotated_paths(path)):
                    last = _last_event_t(p)
                    if last is not None:
                        break
            if last is not None:
                self._t_off = last + 1e-6
        elif path is not None:
            # fresh active file, but a rotated chain may survive from
            # a crashed predecessor — rebase past it
            for p in reversed(rotated_paths(path)):
                last = _last_event_t(p)
                if last is not None:
                    self._t_off = last + 1e-6
                    break
        self.n_events = 0
        # -- aggregates (the --json telemetry block) ----------------------
        self.levels = 0
        self.level_seconds: list[float] = []
        self.level_new: list[int] = []
        self.dispatches_per_level: list[int] = []
        self.fetches_per_level: list[int] = []
        self.dispatches = 0
        self.fetches = 0
        self.fetch_wait_s = 0.0
        self.fetch_bytes = 0
        self.compiles = 0
        self.compile_s = 0.0
        self.prewarm_compiles = 0
        self.checkpoints = 0
        self.checkpoint_s = 0.0
        self.checkpoint_bytes = 0
        self.grows: dict[str, int] = {}
        self.redos = 0
        self.supersteps = 0
        self.superstep_levels = 0
        self.watchdog_armed = 0
        self.watchdog_trips = 0
        self.audit_levels = 0
        self.audit_rows = 0
        self.audit_problems = 0
        # graftsync lock profiler (analysis/tsan.py): per-lock
        # hold/wait aggregates published at disarm + threshold
        # contention events published at the blocking acquire
        self.locks: dict[str, dict] = {}
        self.lock_waits = 0
        self.lock_wait_s = 0.0
        self.retired = 0
        self.exchange_bytes = 0
        self.exchange_raw_bytes = 0
        self.integrity_failures = 0
        # tiered visited store (store/tiered.py): demotions + per-tier
        # probe accounting — probe-wait vs level wall is the
        # spill-overlap acceptance metric (docs/PERF.md)
        self.tier_demotions = 0
        self.tier_spilled = 0
        self.tier_probes = 0
        self.tier_probe_lanes = 0
        self.tier_probe_hits = 0
        self.tier_probe_wait_s = 0.0
        # device-cost observatory (analysis/devprof.py): per-program
        # XLA cost/memory ledgers + the live-HBM gauge assembled from
        # the registered long-lived buffers and the worst program temp
        self.programs_profiled = 0
        self.program_temp: dict[str, int] = {}  # tag -> max temp bytes
        self.program_flops: dict[str, float] = {}  # tag -> max flops
        self.hbm_buffers: dict[str, int] = {}  # name -> live bytes
        self.hbm_budget = 0
        self.pre_oom_forecasts = 0
        self.last_pre_oom: dict | None = None
        self.profile_windows = 0
        self.slab_cap = 0
        self.distinct = 0
        self._last_boundary = self._t_off
        self._lvl_dispatches = 0
        self._lvl_fetches = 0

    # -- lifecycle --------------------------------------------------------

    def __enter__(self):
        install(self)
        return self

    def __exit__(self, *exc):
        install(None)
        self.close()
        return False

    def _open(self):
        if self._fh is None and self.path is not None:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            # append-mode flight recorder: the torn-tail heal already
            # ran at construction, so this append lands cleanly
            self._fh = open(self.path, "a", encoding="utf-8")
        return self._fh

    def flush(self) -> None:
        if self.path is None:
            return
        with self._lock:
            buf, self._buf = self._buf, []
        if not buf:
            return
        data = "".join(buf)
        with self._io_lock:
            fh = self._open()
            fh.write(data)
            fh.flush()
            self._size += len(data)

    def flush_best_effort(self, timeout: float = 2.0) -> None:
        """Bounded-time flush for paths that must never block (the
        watchdog's hard-exit ladder): the write runs on a daemon side
        thread and is abandoned after ``timeout`` — a hung filesystem
        must not wedge the thread whose whole job is converting hangs
        into clean exits."""
        t = threading.Thread(target=self.flush, daemon=True)
        t.start()
        t.join(timeout)

    def close(self) -> None:
        self.flush()
        with self._io_lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    # -- publishing -------------------------------------------------------

    def emit(self, ev: str, **fields) -> None:
        t = round(self._t_off + time.monotonic() - self._t0, 6)
        doc = {"t": t, "ev": ev}
        for k, v in fields.items():
            doc[k] = _clean(v)
        line = encode_event(doc) + "\n"
        with self._lock:
            self._buf.append(line)
            self.n_events += 1
            do_flush = len(self._buf) >= FLUSH_EVERY
            self._aggregate(ev, t, doc)
        # NOTE: watchdog_trip is deliberately NOT in the force-flush
        # set — the watchdog thread must never block on a hung
        # filesystem (it uses flush_best_effort instead)
        if do_flush or ev in (
            "level_commit", "superstep_commit", "run_end",
            "checkpoint", "integrity",
        ):
            self.flush()
            # rotation happens only at these committed boundaries, so
            # a generation never splits a level's events mid-window
            if ev in ("level_commit", "superstep_commit"):
                self._maybe_rotate()

    def _maybe_rotate(self) -> None:
        """Rotate ``events.jsonl`` -> ``events.1.jsonl`` (older
        generations shift up) once the active file exceeds the byte
        budget.  Called at level/superstep boundaries only."""
        if (self.path is None or not self.max_bytes
                or self._size < self.max_bytes):
            return
        with self._io_lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            base, ext = os.path.splitext(self.path)
            n = 1
            while os.path.exists(f"{base}.{n}{ext}"):
                n += 1
            for i in range(n, 1, -1):
                # the stream is already self-checking per line, so a
                # rotation rename is not a checkpoint commit
                # graftlint: waive[GL009] — log-rotation rename
                os.replace(f"{base}.{i - 1}{ext}", f"{base}.{i}{ext}")
            if os.path.exists(self.path):
                # graftlint: waive[GL009] — log-rotation rename (above)
                os.replace(self.path, f"{base}.1{ext}")
            self._size = 0
            self.rotations += 1

    def _aggregate(self, ev: str, t: float, doc: dict) -> None:
        if ev == "dispatch":
            self.dispatches += 1
            self._lvl_dispatches += 1
        elif ev == "fetch":
            self.fetches += 1
            self._lvl_fetches += 1
            self.fetch_wait_s += float(doc.get("s") or 0.0)
            self.fetch_bytes += int(doc.get("b") or 0)
        elif ev == "level_commit":
            self.levels += 1
            self.level_seconds.append(round(t - self._last_boundary, 6))
            self._last_boundary = t
            self.level_new.append(int(doc.get("n_new") or 0))
            self.dispatches_per_level.append(self._lvl_dispatches)
            self.fetches_per_level.append(self._lvl_fetches)
            self._lvl_dispatches = 0
            self._lvl_fetches = 0
            self.slab_cap = int(doc.get("slab_cap") or 0)
            self.distinct = int(doc.get("distinct") or 0)
        elif ev == "compile":
            if doc.get("declared"):
                self.prewarm_compiles += 1
            else:
                self.compiles += 1
            self.compile_s += float(doc.get("s") or 0.0)
        elif ev == "checkpoint":
            self.checkpoints += 1
            self.checkpoint_s += float(doc.get("s") or 0.0)
            self.checkpoint_bytes += int(doc.get("b") or 0)
        elif ev == "grow":
            b = str(doc.get("budget"))
            self.grows[b] = self.grows.get(b, 0) + 1
        elif ev == "redo":
            self.redos += 1
        elif ev == "superstep_commit":
            self.supersteps += 1
            self.superstep_levels += int(doc.get("levels") or 0)
        elif ev == "lock_held":
            self.locks[str(doc.get("name"))] = dict(
                n=int(doc.get("n") or 0),
                wait_s=float(doc.get("wait_s") or 0.0),
                held_s=float(doc.get("held_s") or 0.0),
                max_wait_s=float(doc.get("max_wait_s") or 0.0),
                max_held_s=float(doc.get("max_held_s") or 0.0),
            )
        elif ev == "lock_wait":
            self.lock_waits += 1
            self.lock_wait_s += float(doc.get("wait_s") or 0.0)
        elif ev == "watchdog_arm":
            self.watchdog_armed += 1
        elif ev == "watchdog_trip":
            self.watchdog_trips += 1
        elif ev == "audit":
            self.audit_levels += 1
            self.audit_rows += int(doc.get("rows") or 0)
            self.audit_problems += int(doc.get("problems") or 0)
        elif ev == "retire":
            self.retired += 1
        elif ev == "exchange":
            self.exchange_bytes += int(doc.get("bytes") or 0)
            self.exchange_raw_bytes += int(doc.get("raw") or 0)
        elif ev == "integrity":
            self.integrity_failures += 1
        elif ev == "tier_demote":
            self.tier_demotions += 1
            self.tier_spilled += int(doc.get("n") or 0)
        elif ev == "tier_probe":
            self.tier_probes += 1
            self.tier_probe_lanes += int(doc.get("lanes") or 0)
            self.tier_probe_hits += int(doc.get("hits") or 0)
            self.tier_probe_wait_s += float(doc.get("s") or 0.0)
        elif ev == "program_profile":
            self.programs_profiled += 1
            tag = str(doc.get("tag"))
            tmp = int(doc.get("tmp_b") or 0)
            if tmp > self.program_temp.get(tag, -1):
                self.program_temp[tag] = tmp
            fl = float(doc.get("flops") or 0.0)
            if fl > self.program_flops.get(tag, -1.0):
                self.program_flops[tag] = fl
        elif ev == "buffer":
            self.hbm_buffers[str(doc.get("name"))] = int(
                doc.get("b") or 0
            )
        elif ev == "hbm_budget":
            self.hbm_budget = int(doc.get("b") or 0)
        elif ev == "pre_oom_forecast":
            self.pre_oom_forecasts += 1
            self.last_pre_oom = dict(
                level=doc.get("level"), need=doc.get("need"),
                budget=doc.get("budget"),
            )
        elif ev == "profile_end":
            self.profile_windows += int(doc.get("windows") or 0)
        elif ev == "run_begin":
            self._last_boundary = t

    # -- reporting --------------------------------------------------------

    def snapshot(self) -> dict:
        """The ``--json`` ``telemetry`` block (also bench.py's source
        for ``level_seconds`` / ``dispatches_per_level``)."""
        with self._lock:
            out = dict(
                events=self.n_events,
                file=self.path,
                levels=self.levels,
                level_seconds=list(self.level_seconds),
                level_new=list(self.level_new),
                dispatches=self.dispatches,
                dispatches_per_level=list(self.dispatches_per_level),
                fetches=self.fetches,
                fetches_per_level=list(self.fetches_per_level),
                fetch_wait_s=round(self.fetch_wait_s, 6),
                fetch_bytes=self.fetch_bytes,
                compiles=self.compiles,
                prewarm_compiles=self.prewarm_compiles,
                compile_s=round(self.compile_s, 3),
                checkpoints=self.checkpoints,
                checkpoint_s=round(self.checkpoint_s, 6),
                checkpoint_bytes=self.checkpoint_bytes,
                grows=dict(self.grows),
                redos=self.redos,
                supersteps=self.supersteps,
                superstep_levels=self.superstep_levels,
                levels_per_dispatch=round(
                    self.levels / max(self.dispatches, 1), 3
                ),
                watchdog=dict(
                    armed=self.watchdog_armed, trips=self.watchdog_trips
                ),
                retired=self.retired,
                integrity_failures=self.integrity_failures,
            )
            if self.audit_levels:
                out["audit"] = dict(
                    levels=self.audit_levels, rows=self.audit_rows,
                    problems=self.audit_problems,
                )
            if self.exchange_bytes or self.exchange_raw_bytes:
                out["exchange_bytes"] = self.exchange_bytes
                out["exchange_raw_bytes"] = self.exchange_raw_bytes
            if self.programs_profiled:
                out["programs_profiled"] = self.programs_profiled
                out["program_temp_bytes"] = dict(self.program_temp)
            if self.locks:
                out["locks"] = {k: dict(v) for k, v in self.locks.items()}
            if self.lock_waits:
                out["lock_waits"] = self.lock_waits
                out["lock_wait_s"] = round(self.lock_wait_s, 6)
            if self.rotations:
                out["rotations"] = self.rotations
            if self.profile_windows:
                out["profile_windows"] = self.profile_windows
            if self.hbm_buffers or self.hbm_budget:
                hbm = hbm_gauge(
                    self.hbm_buffers, self.program_temp,
                    self.hbm_budget,
                )
                if self.pre_oom_forecasts:
                    hbm["pre_oom_forecasts"] = self.pre_oom_forecasts
                    hbm["last_pre_oom"] = dict(self.last_pre_oom or {})
                out["hbm"] = hbm
            if self.tier_demotions or self.tier_probes:
                out["tiered"] = dict(
                    demotions=self.tier_demotions,
                    spilled=self.tier_spilled,
                    probes=self.tier_probes,
                    probe_lanes=self.tier_probe_lanes,
                    probe_hits=self.tier_probe_hits,
                    probe_wait_s=round(self.tier_probe_wait_s, 6),
                )
            if self.slab_cap:
                out["slab_cap"] = self.slab_cap
                out["slab_load"] = round(
                    self.distinct / max(self.slab_cap, 1), 4
                )
            return out


# -- publishing hooks (each is a no-op unless a hub is installed) ---------
# The fast path is ONE global read + ONE branch: with telemetry off the
# engines pay nothing measurable per event site.

def emit(ev: str, **fields) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit(ev, **fields)


def run_begin(**fields) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit("run_begin", wall=time.time(), **fields)


def run_end(**fields) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit("run_end", **fields)


def level_begin(level, frontier) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit("level_begin", level=level, frontier=frontier)


def level_commit(level, n_new, distinct, generated,
                 slab_cap: int = 0) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit("level_commit", level=level, n_new=n_new,
                 distinct=distinct, generated=generated,
                 slab_cap=slab_cap)


def superstep_begin(**fields) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit("superstep_begin", **fields)


def superstep_commit(levels, **fields) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit("superstep_commit", levels=levels, **fields)


def dispatch(tag: str) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit("dispatch", tag=tag)


def fetch_done(seconds: float, nbytes: int = 0) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit("fetch", s=round(seconds, 6), b=nbytes)


def compile_done(seconds: float, declared: bool) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit("compile", s=round(seconds, 4), declared=declared)


def checkpoint(kind: str, name: str, seconds: float,
               nbytes: int) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit("checkpoint", kind=kind, name=name,
                 s=round(seconds, 6), b=nbytes)


def grow(budget: str, to=None) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit("grow", budget=budget, to=to)


def redo(budget: str) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit("redo", budget=budget)


def watchdog_arm(context: str, budget: float) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit("watchdog_arm", ctx=context, budget=round(budget, 3))


def watchdog_trip(context: str, stage: str) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit("watchdog_trip", ctx=context, stage=stage)


def audit(level, rows, problems) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit("audit", level=level, rows=rows, problems=problems)


def retire(slot, ok, depth, violation=None) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit("retire", slot=slot, ok=ok, depth=depth,
                 violation=violation)


def worker_lifecycle(name: str, status: str, serial: int,
                     **fields) -> None:
    """Pool-membership transition (service/pool.py): register / beat /
    drain / deregister / swept-dead, keyed by the worker's record
    status so a fleet timeline can be reconstructed from the event
    stream alone."""
    hub = CURRENT
    if hub is not None:
        hub.emit("worker", name=name, status=status, serial=serial,
                 **fields)


def exchange(level, nbytes, raw, candidates=0, sieved=0) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit("exchange", level=level, bytes=nbytes, raw=raw,
                 candidates=candidates, sieved=sieved)


def skew(level, value) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit("skew", level=level, skew=round(float(value), 4))


def shape(reason: str) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit("shape", reason=reason)


def integrity(what: str) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit("integrity", what=what)


def tier_demote(level, n, gen, seconds, cold: bool = False) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit("tier_demote", level=level, n=n, gen=gen,
                 s=round(seconds, 6), cold=cold)


def tier_probe(level, lanes, hits, sieve: int = 0,
               wait_s: float = 0.0) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit("tier_probe", level=level, lanes=lanes, hits=hits,
                 sieve=sieve, s=round(wait_s, 6))


def tier_compact(level, runs, n, seconds) -> None:
    """One LSM generation merge (store/tiered.py _maybe_compact):
    ``runs`` cold runs folded into one ``n``-fingerprint sorted run
    (+ its bloom side-car) in ``seconds`` of host wall."""
    hub = CURRENT
    if hub is not None:
        hub.emit("tier_compact", level=level, runs=runs, n=n,
                 s=round(seconds, 6))


def sieve_refresh(level, words, n_added, fp_rate) -> None:
    """The engine re-uploaded the spill sieve to the device (a demotion
    bumped the host filter's version): filter size, keys added, and the
    predicted false-positive rate at the new load."""
    hub = CURRENT
    if hub is not None:
        hub.emit("sieve_refresh", level=level, words=int(words),
                 n=int(n_added), fp_rate=round(float(fp_rate), 6))


def sieve_stop(level, hits) -> None:
    """A resident superstep stopped on in-kernel sieve hits (FLAG_TIER):
    the stopped level replays per-level through the exact generation
    probe.  ``hits`` is the device-counted filter-hit lanes (true
    revisits + false positives; the replay's tier_probe event tells
    them apart), or -1 when the stop path did not fetch the count (the
    superstep control vector carries only the FLAG_TIER bit)."""
    hub = CURRENT
    if hub is not None:
        hub.emit("sieve_stop", level=level, hits=int(hits))


def fseg_page(token, rows, seconds) -> None:
    """One spilled frontier segment paged back from the warm tier
    (store/tiered.py FrontierPager.load); the matching spill is already
    visible as the ``checkpoint`` event its ``kind="fseg"`` commit
    emits."""
    hub = CURRENT
    if hub is not None:
        hub.emit("fseg_page", token=int(token), rows=int(rows),
                 s=round(seconds, 6))


def program_profile(tag: str, **metrics) -> None:
    """One compiled program's XLA cost/memory ledger (flops, bytes
    accessed, argument/output/temp/code bytes) — published from the
    compile choke points by analysis/devprof.py, once per program
    shape."""
    hub = CURRENT
    if hub is not None:
        hub.emit("program_profile", tag=tag, **metrics)


def buffer(name: str, nbytes) -> None:
    """Register/resize one long-lived device buffer (the HBM gauge):
    the newest ``b`` per name wins — emit 0 to retire a buffer."""
    hub = CURRENT
    if hub is not None:
        hub.emit("buffer", name=name, b=int(nbytes))


def hbm_budget(nbytes) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit("hbm_budget", b=int(nbytes))


def pre_oom(level, need_bytes, budget_bytes, **fields) -> None:
    """The forecast next level's working set would bust the device
    budget — the predictive twin of the reactive overflow-redo."""
    hub = CURRENT
    if hub is not None:
        hub.emit("pre_oom_forecast", level=level, need=int(need_bytes),
                 budget=int(budget_bytes), **fields)


def profile_begin(trace_dir: str, windows: int) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit("profile_begin", dir=trace_dir, windows=windows)


def profile_end(trace_dir: str, windows: int) -> None:
    hub = CURRENT
    if hub is not None:
        hub.emit("profile_end", dir=trace_dir, windows=windows)
