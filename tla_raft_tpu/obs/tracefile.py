"""Chrome trace-event export: the run timeline, Perfetto-loadable.

Converts an ``events.jsonl`` stream (obs/telemetry.py) into the Chrome
trace-event JSON format — load the output in https://ui.perfetto.dev
(or ``chrome://tracing``) to SEE superstep dispatch amortization and
the expand/fetch/checkpoint overlap the async pipeline buys.

Track layout (one pid, one tid per track):

=====  ==================  ==========================================
tid    track               events
=====  ==================  ==========================================
1      levels              one ``X`` slice per committed level
                           (boundary-to-boundary wall time)
2      superstep windows   ``B``/``E`` pairs per resident dispatch
                           window
3      device dispatch     instants, one per program dispatch (tag)
4      fetch window        ``X`` slices, one per ledgered pipeline
                           fetch (the measured wait)
5      checkpoint I/O      ``X`` slices, one per atomic commit
6      compile             ``X`` slices, one per XLA backend compile
7      grow/redo           instants (named budget)
8      watchdog/audit      instants (arm/trip, audit, retire,
                           integrity)
9      tiered store        ``X`` slices per demotion and per warm/cold
                           generation probe (spill-overlap readout)
=====  ==================  ==========================================

Timestamps are microseconds on the hub's monotonic clock, so every
``ts`` is non-negative and non-decreasing per track, and every ``B``
has a matching ``E`` (a window left open by a crash is closed at the
stream's last timestamp).  Host-pure (graftlint GL012): stdlib only.
"""

from __future__ import annotations

import glob
import gzip
import json
import os

from .telemetry import read_events

PID = 1
TRACKS = {
    1: "levels",
    2: "superstep windows",
    3: "device dispatch",
    4: "fetch window",
    5: "checkpoint I/O",
    6: "compile",
    7: "grow/redo",
    8: "watchdog/audit",
    9: "tiered store",
    10: "device cost",
    11: "lock contention",
}


def _us(t: float) -> int:
    return max(0, int(round(float(t) * 1e6)))


def to_chrome_trace(events: list[dict]) -> dict:
    """Event stream -> Chrome trace-event JSON document."""
    out: list[dict] = []
    for tid, name in TRACKS.items():
        out.append(dict(
            ph="M", pid=PID, tid=tid, name="thread_name",
            args=dict(name=name),
        ))

    def ev(ph, tid, name, t, dur=None, args=None):
        e = dict(ph=ph, pid=PID, tid=tid, name=str(name), ts=_us(t),
                 cat="tla-raft")
        if dur is not None:
            e["dur"] = max(0, int(round(dur * 1e6)))
        if args:
            e["args"] = args
        out.append(e)

    boundary = 0.0
    open_window = None
    last_t = 0.0
    for doc in events:
        t = float(doc.get("t", 0.0))
        last_t = max(last_t, t)
        kind = doc.get("ev")
        if kind == "run_begin":
            boundary = t
            ev("i", 1, "run_begin", t, args={
                k: v for k, v in doc.items() if k not in ("t", "ev")
            })
        elif kind == "run_end":
            ev("i", 1, "run_end", t, args={
                k: v for k, v in doc.items() if k not in ("t", "ev")
            })
        elif kind == "level_commit":
            ev("X", 1, f"level {doc.get('level')}", boundary,
               dur=t - boundary,
               args=dict(n_new=doc.get("n_new"),
                         distinct=doc.get("distinct"),
                         generated=doc.get("generated")))
            boundary = t
        elif kind == "superstep_begin":
            if open_window is not None:
                # a begin with no commit (stopped window re-entered):
                # close the dangling B so pairs stay matched
                ev("E", 2, "superstep", t)
            ev("B", 2, "superstep", t)
            open_window = t
        elif kind == "superstep_commit":
            if open_window is None:
                ev("B", 2, "superstep", t)
            ev("E", 2, "superstep", t,
               args=dict(levels=doc.get("levels")))
            open_window = None
        elif kind == "dispatch":
            ev("i", 3, doc.get("tag", "dispatch"), t)
        elif kind == "fetch":
            s = float(doc.get("s") or 0.0)
            ev("X", 4, "fetch", t - s, dur=s,
               args=dict(bytes=doc.get("b")))
        elif kind == "lock_wait":
            # GRAFT_TSAN contention: the slice spans the blocked
            # acquire (t is the acquisition instant)
            s = float(doc.get("wait_s") or 0.0)
            ev("X", 11, f"wait {doc.get('name')}", t - s, dur=s,
               args=dict(name=doc.get("name")))
        elif kind == "checkpoint":
            s = float(doc.get("s") or 0.0)
            ev("X", 5, f"commit {doc.get('kind')}", t - s, dur=s,
               args=dict(name=doc.get("name"), bytes=doc.get("b")))
        elif kind == "compile":
            s = float(doc.get("s") or 0.0)
            ev("X", 6,
               "prewarm compile" if doc.get("declared") else "compile",
               t - s, dur=s)
        elif kind in ("grow", "redo"):
            ev("i", 7, f"{kind} {doc.get('budget')}", t)
        elif kind == "watchdog_arm":
            ev("i", 8, "watchdog arm", t,
               args=dict(ctx=doc.get("ctx"), budget=doc.get("budget")))
        elif kind == "watchdog_trip":
            ev("i", 8, f"WATCHDOG TRIP ({doc.get('stage')})", t,
               args=dict(ctx=doc.get("ctx")))
        elif kind == "tier_demote":
            s = float(doc.get("s") or 0.0)
            ev("X", 9, f"demote gen {doc.get('gen')}", t - s, dur=s,
               args=dict(level=doc.get("level"), n=doc.get("n"),
                         cold=doc.get("cold")))
        elif kind == "tier_probe":
            s = float(doc.get("s") or 0.0)
            ev("X", 9, "gen probe", t - s, dur=s,
               args=dict(level=doc.get("level"),
                         lanes=doc.get("lanes"),
                         hits=doc.get("hits")))
        elif kind in ("program_profile", "buffer", "hbm_budget",
                      "pre_oom_forecast", "profile_begin",
                      "profile_end"):
            name = {
                "program_profile":
                    f"profile {doc.get('tag')}",
                "buffer": f"buffer {doc.get('name')}",
                "hbm_budget": "hbm budget",
                "pre_oom_forecast":
                    f"PRE-OOM FORECAST (level {doc.get('level')})",
                "profile_begin": "profiler start",
                "profile_end": "profiler stop",
            }[kind]
            ev("i", 10, name, t, args={
                k: v for k, v in doc.items() if k not in ("t", "ev")
            })
        elif kind in ("audit", "retire", "integrity", "shape",
                      "exchange", "skew"):
            ev("i", 8, kind, t, args={
                k: v for k, v in doc.items() if k not in ("t", "ev")
            })
    if open_window is not None:
        ev("E", 2, "superstep", last_t)
    return dict(
        traceEvents=out,
        displayTimeUnit="ms",
        otherData=dict(source="tla_raft_tpu.obs"),
    )


# device lanes from --profile captures merge in as separate processes
# starting at this pid (host lanes stay at PID=1)
DEVICE_PID_BASE = 100

_MERGE_PHASES = {"X", "M", "i", "I", "B", "E", "C"}


def _profile_dirs(events: list[dict], run_dir: str | None) -> list[str]:
    """Capture dirs named by profile_begin events, plus the run dir's
    conventional ``profile/`` (covers a relocated run dir whose events
    recorded the original absolute path)."""
    dirs: list[str] = []
    for ev in events:
        if ev.get("ev") == "profile_begin" and ev.get("dir"):
            dirs.append(str(ev["dir"]))
    if run_dir:
        dirs.append(os.path.join(run_dir, "profile"))
    out, seen = [], set()
    for d in dirs:
        if not os.path.isdir(d):
            continue
        # dedup by resolved path: the profile_begin event records the
        # original (possibly relative) dir and the run-dir convention
        # adds another spelling of the same directory
        real = os.path.realpath(d)
        if real not in seen:
            seen.add(real)
            out.append(d)
    return out


# device-lane merge budget: the profiler's host lanes on CPU emit
# ~10^6 sub-microsecond slices per window (codegen internals); past the
# budget the SHORTEST slices are dropped first — the timeline keeps the
# compute that matters and the drop is reported, never silent
MAX_DEVICE_EVENTS = 250_000


def merge_device_lanes(doc: dict, events: list[dict],
                       run_dir: str | None = None,
                       max_events: int = MAX_DEVICE_EVENTS
                       ) -> tuple[int, int]:
    """Merge ``--profile`` device traces into the host timeline.

    A capture's ``profile_begin`` event carries BOTH the trace dir and
    the hub timestamp of ``start_trace`` — and the jax Perfetto trace's
    timestamps are microseconds from that same instant, so shifting
    every device event by the begin event's ``t`` lands the device
    lanes on the host clock: dispatch instant -> device compute ->
    fetch-wait read off one timeline.  Device processes are re-pinned
    to pids >= ``DEVICE_PID_BASE`` (the host tracks keep PID 1) and
    their process names prefixed ``device:``.  Missing/torn captures
    merge nothing — the host trace stays valid.  Returns
    ``(merged, dropped)`` device-event counts.
    """
    out = doc["traceEvents"]
    pid_map: dict = {}
    meta: list[dict] = []
    slices: list[dict] = []
    offsets = {
        str(ev.get("dir")): float(ev.get("t") or 0.0)
        for ev in events if ev.get("ev") == "profile_begin"
    }
    default_off = min(offsets.values()) if offsets else 0.0
    for d in _profile_dirs(events, run_dir):
        off_us = offsets.get(d, default_off) * 1e6
        for path in sorted(glob.glob(os.path.join(
            d, "plugins", "profile", "*", "perfetto_trace.json.gz"
        ))):
            try:
                with gzip.open(path, "rt", encoding="utf-8",
                               errors="replace") as fh:
                    dev = json.load(fh)
            except (OSError, ValueError, EOFError):
                continue  # torn capture: keep the host trace valid
            evs = (
                dev.get("traceEvents", [])
                if isinstance(dev, dict) else dev
            )
            for e in evs:
                if not isinstance(e, dict):
                    continue
                ph, pid = e.get("ph"), e.get("pid")
                if ph not in _MERGE_PHASES or pid is None:
                    continue
                if pid not in pid_map:
                    pid_map[pid] = DEVICE_PID_BASE + len(pid_map)
                e2 = dict(e, pid=pid_map[pid], cat="device")
                if ph == "M":
                    if (e.get("name") == "process_name"
                            and isinstance(e.get("args"), dict)):
                        e2["args"] = dict(
                            e["args"],
                            name=f"device: {e['args'].get('name')}",
                        )
                    meta.append(e2)
                    continue
                e2["ts"] = float(e.get("ts") or 0.0) + off_us
                if ph in ("B", "E"):
                    # B/E pairs are never droppable: losing one side
                    # of a pair breaks the nesting the merged trace
                    # guarantees (they ride with the metadata)
                    meta.append(e2)
                else:
                    slices.append(e2)
    dropped = 0
    if max_events and len(slices) > max_events:
        # keep the longest slices (instants/counters sort as dur 0 but
        # are few); the drop is REPORTED by the caller, never silent
        slices.sort(key=lambda e: -float(e.get("dur") or 0.0))
        dropped = len(slices) - max_events
        slices = slices[:max_events]
    out.extend(meta)
    out.extend(slices)
    return len(meta) + len(slices), dropped


def export(events_path: str, out_path: str,
           run_dir: str | None = None,
           max_device_events: int = MAX_DEVICE_EVENTS) -> dict:
    """events.jsonl -> Chrome trace JSON file; returns small stats.

    ``run_dir`` (when given) also merges any ``--profile`` device
    capture found beside the stream into the same timeline."""
    events, dropped = read_events(events_path)
    doc = to_chrome_trace(events)
    device_events, device_dropped = merge_device_lanes(
        doc, events, run_dir, max_events=max_device_events
    )
    with open(out_path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    return dict(
        events=len(events), dropped=dropped,
        trace_events=len(doc["traceEvents"]),
        device_events=device_events,
        device_dropped=device_dropped, out=out_path,
    )
