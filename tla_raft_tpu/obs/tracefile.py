"""Chrome trace-event export: the run timeline, Perfetto-loadable.

Converts an ``events.jsonl`` stream (obs/telemetry.py) into the Chrome
trace-event JSON format — load the output in https://ui.perfetto.dev
(or ``chrome://tracing``) to SEE superstep dispatch amortization and
the expand/fetch/checkpoint overlap the async pipeline buys.

Track layout (one pid, one tid per track):

=====  ==================  ==========================================
tid    track               events
=====  ==================  ==========================================
1      levels              one ``X`` slice per committed level
                           (boundary-to-boundary wall time)
2      superstep windows   ``B``/``E`` pairs per resident dispatch
                           window
3      device dispatch     instants, one per program dispatch (tag)
4      fetch window        ``X`` slices, one per ledgered pipeline
                           fetch (the measured wait)
5      checkpoint I/O      ``X`` slices, one per atomic commit
6      compile             ``X`` slices, one per XLA backend compile
7      grow/redo           instants (named budget)
8      watchdog/audit      instants (arm/trip, audit, retire,
                           integrity)
9      tiered store        ``X`` slices per demotion and per warm/cold
                           generation probe (spill-overlap readout)
=====  ==================  ==========================================

Timestamps are microseconds on the hub's monotonic clock, so every
``ts`` is non-negative and non-decreasing per track, and every ``B``
has a matching ``E`` (a window left open by a crash is closed at the
stream's last timestamp).  Host-pure (graftlint GL012): stdlib only.
"""

from __future__ import annotations

import json

from .telemetry import read_events

PID = 1
TRACKS = {
    1: "levels",
    2: "superstep windows",
    3: "device dispatch",
    4: "fetch window",
    5: "checkpoint I/O",
    6: "compile",
    7: "grow/redo",
    8: "watchdog/audit",
    9: "tiered store",
}


def _us(t: float) -> int:
    return max(0, int(round(float(t) * 1e6)))


def to_chrome_trace(events: list[dict]) -> dict:
    """Event stream -> Chrome trace-event JSON document."""
    out: list[dict] = []
    for tid, name in TRACKS.items():
        out.append(dict(
            ph="M", pid=PID, tid=tid, name="thread_name",
            args=dict(name=name),
        ))

    def ev(ph, tid, name, t, dur=None, args=None):
        e = dict(ph=ph, pid=PID, tid=tid, name=str(name), ts=_us(t),
                 cat="tla-raft")
        if dur is not None:
            e["dur"] = max(0, int(round(dur * 1e6)))
        if args:
            e["args"] = args
        out.append(e)

    boundary = 0.0
    open_window = None
    last_t = 0.0
    for doc in events:
        t = float(doc.get("t", 0.0))
        last_t = max(last_t, t)
        kind = doc.get("ev")
        if kind == "run_begin":
            boundary = t
            ev("i", 1, "run_begin", t, args={
                k: v for k, v in doc.items() if k not in ("t", "ev")
            })
        elif kind == "run_end":
            ev("i", 1, "run_end", t, args={
                k: v for k, v in doc.items() if k not in ("t", "ev")
            })
        elif kind == "level_commit":
            ev("X", 1, f"level {doc.get('level')}", boundary,
               dur=t - boundary,
               args=dict(n_new=doc.get("n_new"),
                         distinct=doc.get("distinct"),
                         generated=doc.get("generated")))
            boundary = t
        elif kind == "superstep_begin":
            if open_window is not None:
                # a begin with no commit (stopped window re-entered):
                # close the dangling B so pairs stay matched
                ev("E", 2, "superstep", t)
            ev("B", 2, "superstep", t)
            open_window = t
        elif kind == "superstep_commit":
            if open_window is None:
                ev("B", 2, "superstep", t)
            ev("E", 2, "superstep", t,
               args=dict(levels=doc.get("levels")))
            open_window = None
        elif kind == "dispatch":
            ev("i", 3, doc.get("tag", "dispatch"), t)
        elif kind == "fetch":
            s = float(doc.get("s") or 0.0)
            ev("X", 4, "fetch", t - s, dur=s,
               args=dict(bytes=doc.get("b")))
        elif kind == "checkpoint":
            s = float(doc.get("s") or 0.0)
            ev("X", 5, f"commit {doc.get('kind')}", t - s, dur=s,
               args=dict(name=doc.get("name"), bytes=doc.get("b")))
        elif kind == "compile":
            s = float(doc.get("s") or 0.0)
            ev("X", 6,
               "prewarm compile" if doc.get("declared") else "compile",
               t - s, dur=s)
        elif kind in ("grow", "redo"):
            ev("i", 7, f"{kind} {doc.get('budget')}", t)
        elif kind == "watchdog_arm":
            ev("i", 8, "watchdog arm", t,
               args=dict(ctx=doc.get("ctx"), budget=doc.get("budget")))
        elif kind == "watchdog_trip":
            ev("i", 8, f"WATCHDOG TRIP ({doc.get('stage')})", t,
               args=dict(ctx=doc.get("ctx")))
        elif kind == "tier_demote":
            s = float(doc.get("s") or 0.0)
            ev("X", 9, f"demote gen {doc.get('gen')}", t - s, dur=s,
               args=dict(level=doc.get("level"), n=doc.get("n"),
                         cold=doc.get("cold")))
        elif kind == "tier_probe":
            s = float(doc.get("s") or 0.0)
            ev("X", 9, "gen probe", t - s, dur=s,
               args=dict(level=doc.get("level"),
                         lanes=doc.get("lanes"),
                         hits=doc.get("hits")))
        elif kind in ("audit", "retire", "integrity", "shape",
                      "exchange", "skew"):
            ev("i", 8, kind, t, args={
                k: v for k, v in doc.items() if k not in ("t", "ev")
            })
    if open_window is not None:
        ev("E", 2, "superstep", last_t)
    return dict(
        traceEvents=out,
        displayTimeUnit="ms",
        otherData=dict(source="tla_raft_tpu.obs"),
    )


def export(events_path: str, out_path: str) -> dict:
    """events.jsonl -> Chrome trace JSON file; returns small stats."""
    events, dropped = read_events(events_path)
    doc = to_chrome_trace(events)
    with open(out_path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    return dict(
        events=len(events), dropped=dropped,
        trace_events=len(doc["traceEvents"]), out=out_path,
    )
