"""Service metrics: counters, gauges, histograms + atomic snapshots.

The sweep-service daemon keeps one :class:`Metrics` registry and
commits its snapshot to ``<root>/metrics.json`` each scheduler pass
through the resilience layer's atomic JSON writer (tmp -> digest ->
rename -> manifest), so a metrics read never sees a torn document and
a scrape survives the daemon dying mid-pass.  ``service status
--metrics`` and ``python -m tla_raft_tpu.obs report`` render it.

Host-pure (graftlint GL012); ``resilience`` is imported lazily inside
:meth:`Metrics.commit` (stdlib-only module import, like the rest of
``obs/``).
"""

from __future__ import annotations

import threading
import time

METRICS_NAME = "metrics.json"
SCHEMA = "tla-raft-metrics/1"


class Counter:
    """Monotonic event count."""

    def __init__(self):
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += int(n)

    def set(self, v) -> None:
        """Adopt an externally-accumulated total (the scheduler's
        stats dict counts some events itself)."""
        self.value = int(v)


class Gauge:
    """Last-written value."""

    def __init__(self):
        self.value = 0.0

    def set(self, v) -> None:
        self.value = float(v)


class Histogram:
    """Streaming summary: count/sum/min/max (+ mean in the snapshot)."""

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def observe(self, v) -> None:
        v = float(v)
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)

    def summary(self) -> dict:
        return dict(
            count=self.count,
            sum=round(self.sum, 6),
            min=self.min,
            max=self.max,
            mean=round(self.sum / self.count, 6) if self.count else None,
        )


class Metrics:
    """Named metric registry -> JSON snapshot -> atomic commit."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: dict[str, Counter] = {}
        self.gauges: dict[str, Gauge] = {}
        self.histograms: dict[str, Histogram] = {}
        self._t0 = time.time()

    def counter(self, name: str) -> Counter:
        with self._lock:
            return self.counters.setdefault(name, Counter())

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            return self.gauges.setdefault(name, Gauge())

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            return self.histograms.setdefault(name, Histogram())

    def snapshot(self) -> dict:
        with self._lock:
            return dict(
                schema=SCHEMA,
                wall=round(time.time(), 3),
                uptime_s=round(time.time() - self._t0, 3),
                counters={k: c.value for k, c in
                          sorted(self.counters.items())},
                gauges={k: g.value for k, g in
                        sorted(self.gauges.items())},
                histograms={k: h.summary() for k, h in
                            sorted(self.histograms.items())},
            )

    def commit(self, root: str, name: str = METRICS_NAME) -> str:
        """Atomically commit the snapshot to ``<root>/<name>``."""
        from .. import resilience

        return resilience.commit_json(
            root, name, self.snapshot(), kind="metrics",
        )


def load(root: str, name: str = METRICS_NAME) -> dict | None:
    """Digest-verified read side of :meth:`Metrics.commit`."""
    from .. import resilience

    return resilience.load_json_verified(root, name)


def render(doc: dict, out=None) -> None:
    """Human table for ``service status --metrics``."""
    import sys

    out = out if out is not None else sys.stdout
    if not doc:
        print("no metrics.json (daemon not started?)", file=out)
        return
    print(
        f"metrics @ {time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(doc.get('wall', 0)))}"
        f" (uptime {doc.get('uptime_s', 0):.0f}s)",
        file=out,
    )
    for k, v in (doc.get("counters") or {}).items():
        print(f"  {k:>28}: {v}", file=out)
    for k, v in (doc.get("gauges") or {}).items():
        print(f"  {k:>28}: {v:g}", file=out)
    for k, h in (doc.get("histograms") or {}).items():
        if h.get("count"):
            print(
                f"  {k:>28}: n={h['count']} mean={h['mean']:g} "
                f"min={h['min']:g} max={h['max']:g}",
                file=out,
            )
        else:
            print(f"  {k:>28}: n=0", file=out)
