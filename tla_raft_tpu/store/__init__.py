"""Visited-store tier structures (device-external).

``store.tiered`` holds the HBM-hot / host-warm / disk-cold visited
tiers (docs/PERF.md "Tiered visited store"); the device-resident hot
slab itself lives in ``ops/hashstore.py`` and stays owned by the
engines.  Import is device-free (GL001) — the one device kernel here
imports jax lazily.
"""

from .tiered import (  # noqa: F401
    TieredVisitedStore,
    drop_rows,
    repartition,
    store_bytes_from_env,
    sweep_gens,
    warm_bytes_from_env,
)
