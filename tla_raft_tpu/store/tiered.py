"""Out-of-core visited store: HBM-hot / host-warm / disk-cold tiers.

The hashstore slab (PR 3) made membership O(1) on device, but it also
pinned the maximum ``|visited|`` to device memory — the one axis where
this reproduction still lost to TLC, whose disk-backed FPSet bounds the
state space by storage, not RAM (PAPER.md, SURVEY.md §3.2).  This
module is that tier structure for the device engine:

* **hot** — the open-addressing slab in HBM (``ops/hashstore.py``,
  unchanged layout).  Every candidate's membership-and-insert still
  runs as the fused on-device probe; the hot tier IS the sieve that
  keeps the lower tiers out of the common path (a hot hit is provably
  visited and never probes further down).
* **warm** — host-RAM **generations**: sorted, immutable fingerprint
  runs demoted from the hot slab when its quantized-load growth would
  exceed the device budget (``--dev-bytes`` / ``TLA_RAFT_STORE_BYTES``).
  Eviction is **by generation** — a full sorted run, never individual
  entries — so warm/cold probes stay ``searchsorted``-exact and the
  union of tiers is exactly the visited set.
* **cold** — generations whose host-RAM residency was evicted under the
  warm budget (``--warm-bytes`` / ``TLA_RAFT_WARM_BYTES``).  Every
  demotion commits its run through the ONE atomic checkpoint writer
  (``resilience.commit_npz``, kind ``gen`` — graftlint GL009 pins
  that), so a cold probe re-loads the committed file through a bounded
  LRU page cache; with no spill directory the generation simply stays
  warm.

**Probe protocol** (the level-tail correction both engine device paths
run): the fused device program probes-and-inserts against the hot slab
alone, so a level's "fresh" set may contain revisits of demoted
fingerprints.  The host probes exactly those fresh fingerprints —
sieve first (a bounded sorted cache of fingerprints already confirmed
spilled-visited), then warm runs, then cold runs — and the engine
drops the hit rows from the already-materialized frontier with one
small compaction program (:func:`drop_rows`).  On the engine device
paths the probe is a synchronous level-tail step whose blocking cost
is published per probe (``tier_probe``); on the external-store and
mesh paths the equivalent warm/cold membership rides the PR 5 async
fetch window / deferred tail, overlapping the next group's expand.  The hit fingerprints
were re-inserted into the hot slab by the very probe that mistook them
for fresh, which is the re-heat: the next revisit hits hot and never
reaches this code.  Counts stay bit-identical to an uncapped run
because dropping a visited row is exactly what the uncapped fused
probe would have done (representative choice is per-fingerprint-group
and unaffected; kept lanes preserve payload-ascending order).

**Crash/elastic contract**: the delta log remains the single source of
truth.  Generations are an optimization the resume REBUILDS from the
replayed per-level fingerprints (each generation then covers whole
levels, so the tier total is exactly ``distinct``); stale ``gen_*``
files from the crashed incarnation are discarded first.  Generations
carry the ``fp % D`` partition tag of their writer
(``(part_d, owner)``), and :func:`repartition` re-buckets a D-tagged
generation set onto D' owners with the same owner remap PR 8's elastic
resume applies to slabs — the mesh tiers (per-owner host stores +
their disk runs) rebuild through the same replay machinery.

Host-purity: probes and demotion bookkeeping are pure numpy and safe
from worker threads (GL007 — no device dispatch); the only device code
here is :func:`drop_rows_impl`, the row-compaction kernel the ENGINE
dispatches from its main thread (registered under the GL010
gather/scatter budget as ``store.tiered_compact``).
"""

from __future__ import annotations

import functools
import os
import time
import zipfile
from collections import OrderedDict

import numpy as np

from ..obs import telemetry as _obs

SENT = np.uint64(0xFFFFFFFFFFFFFFFF)

GEN_PREFIX = "gen_"
GEN_VERSION = 1

# default host-RAM budget for warm generations before the oldest ones
# drop to cold (disk-only): 1 GiB — big enough that CPU/test runs never
# touch the cold path unless asked to, small enough that a laptop-class
# host survives a multi-billion-state sweep's spill
DEFAULT_WARM_BYTES = 1 << 30

# sieve bound: fingerprints confirmed spilled-visited, kept sorted for
# the pre-generation probe.  8 MB of u64s; beyond it the oldest half is
# dropped (the sieve is a pure optimization cache — a miss only costs a
# generation probe, never correctness)
SIEVE_MAX = 1 << 20

# generation side-car suffix: the per-run bloom filter persisted beside
# each committed gen_*.npz (ops/sieve.py), probed before a cold disk
# load so the level tail touches disk only on likely hits
SIDECAR_SUFFIX = ".sieve.npz"

# LSM compaction fanout: once the COLD run count exceeds it, every cold
# generation merges into one sorted run (full-level compaction, the
# same policy the native host store applies to its run files at 16 —
# native/fpstore.cpp), bounding both the per-probe run walk and the
# open-file count of a billion-state sweep
DEFAULT_COMPACT_FANOUT = 8


# spilled-frontier segment files: one npz per demoted frontier segment
# (kind="fseg" through the atomic writer), committed by FrontierPager
# when a level's frontier working set outgrows the host budget
FSEG_PREFIX = "fseg_"


def compact_fanout_from_env() -> int:
    v = os.environ.get("TLA_RAFT_COMPACT_FANOUT")
    if v:
        return max(1, int(v))
    from ..tune import active

    return max(1, int(active.get("compact_fanout", DEFAULT_COMPACT_FANOUT)))


def fseg_bytes_from_env() -> int:
    """Host-RAM budget for paged-out frontier segments before they
    spill on to the warm tier (``TLA_RAFT_FSEG_BYTES``; 0 = disk spill
    off, host RAM is the only frontier overflow tier).  The env wins;
    an installed autotuner plan's ``fseg_bytes`` is the fallback."""
    v = os.environ.get("TLA_RAFT_FSEG_BYTES")
    if v:
        return int(float(v))
    from ..tune import active

    return int(active.get("fseg_bytes", 0))


def store_bytes_from_env() -> int:
    """The hot-tier device budget: ``TLA_RAFT_STORE_BYTES`` (bytes; 0 =
    unbounded = tiering off)."""
    v = os.environ.get("TLA_RAFT_STORE_BYTES")
    return int(float(v)) if v else 0


def warm_bytes_from_env() -> int:
    v = os.environ.get("TLA_RAFT_WARM_BYTES")
    if v:
        return int(float(v))
    from ..tune import active

    return int(active.get("warm_bytes", DEFAULT_WARM_BYTES))


class Generation:
    """One demoted run: sorted unique u64 fingerprints.

    ``fps`` is the warm residency (None when cold — the committed file
    at ``path`` is then the only copy); ``lo``/``hi`` give the free
    range reject, ``(part_d, owner)`` the fp % D partition tag.
    ``sidecar`` is the run's bloom filter (ops/sieve.py SpillSieve,
    ~1.5 B/key), lazily loaded from ``sidecar_path`` and rebuilt from
    the generation itself when the persisted copy is torn or stale."""

    __slots__ = ("gid", "n", "lo", "hi", "fps", "path", "part_d",
                 "owner", "depth", "sidecar", "sidecar_path")

    def __init__(self, gid: int, fps: np.ndarray, *, path=None,
                 part_d: int = 1, owner: int = 0, depth: int = 0):
        fps = np.asarray(fps, np.uint64)
        self.gid = gid
        self.n = len(fps)
        self.lo = np.uint64(fps[0]) if self.n else SENT
        self.hi = np.uint64(fps[-1]) if self.n else np.uint64(0)
        self.fps = fps
        self.path = path
        self.part_d = part_d
        self.owner = owner
        self.depth = depth
        self.sidecar = None
        self.sidecar_path = None

    @property
    def nbytes(self) -> int:
        return self.n * 8

    @property
    def cold(self) -> bool:
        return self.fps is None


def _load_gen_fps(path: str) -> np.ndarray:
    """Re-load a cold generation's committed run (raises on a missing/
    torn file: cold data has no other copy, so silently returning an
    empty run would turn revisits into duplicate states)."""
    try:
        with np.load(path) as z:
            return np.asarray(z["fps"], np.uint64)
    except (OSError, ValueError, KeyError, EOFError,
            zipfile.BadZipFile) as e:
        raise IOError(
            f"cold generation {path} unreadable ({e}) — the visited "
            "set cannot be proven without it; restart from the delta "
            "log (--recover rebuilds every tier)"
        ) from e


class TieredVisitedStore:
    """Warm/cold generation bookkeeping + probes for one run.

    The HOT slab stays owned by the engine (``DeviceHashStore``); this
    object owns everything below it.  All methods are host-side numpy
    and safe to call from the external-store paths' worker threads
    (no device dispatch, GL007); the engine's level tail calls
    ``probe`` synchronously from the main thread — the measured
    ``probe_wait_s`` is that blocking cost, published per probe as a
    ``tier_probe`` event.
    """

    def __init__(self, dev_bytes: int, *, warm_bytes: int | None = None,
                 spill_dir: str | None = None, run_fp: str | None = None,
                 part_d: int = 1, owner: int = 0):
        self.dev_bytes = int(dev_bytes)
        self.warm_bytes = (
            warm_bytes_from_env() if warm_bytes is None else int(warm_bytes)
        )
        self.spill_dir = spill_dir
        self.run_fp = run_fp
        self.part_d = part_d
        self.owner = owner
        self.gens: list[Generation] = []
        self._next_gid = 0
        self.sieve = np.empty(0, np.uint64)
        # the device-resident spill sieve (ops/sieve.py): ONE blocked
        # bloom over EVERY demoted fingerprint, allocated at full size
        # on the first demotion (growing a bloom would re-hash every
        # spilled fp — cold reloads — so it trades graceful fp-rate
        # degradation past design load for never touching disk) and fed
        # at demote time.  The engine uploads ``spill_sieve.words`` and
        # refreshes on ``version`` bumps; its in-kernel probe is what
        # lets supersteps hold span N under spill.
        self.spill_sieve = None
        self.compact_fanout = compact_fanout_from_env()
        # cold page cache: gid -> fps, LRU-bounded by the warm budget
        # (a loaded cold run is warm residency like any other)
        self._cold_cache: OrderedDict[int, np.ndarray] = OrderedDict()
        self.stats = dict(
            demotions=0, spilled=0, cold_gens=0,
            probes=0, probe_lanes=0, probe_hits=0,
            sieve_hits=0, warm_hits=0, cold_hits=0,
            cold_loads=0, cold_load_s=0.0, probe_wait_s=0.0,
            reheats=0, tier_redos=0,
            compactions=0, compact_runs=0, compact_s=0.0,
            sidecar_skips=0, sidecar_rebuilds=0,
        )

    # -- policy -----------------------------------------------------------

    @property
    def active(self) -> bool:
        """True once at least one generation exists (probes required)."""
        return bool(self.gens)

    @property
    def max_hot_entries(self) -> int:
        """Entries the hot slab may hold inside the device budget at
        the enforced <= 1/2 load factor (0 = unbounded).  One under the
        half-slot mark: ``slab_rows(cap/2)`` rounds UP to the next
        power of two, so exactly cap/2 entries would demand a slab
        twice the budget."""
        if not self.dev_bytes:
            return 0
        return max(self.hot_slot_budget() // 2 - 1, 1)

    def hot_slot_budget(self) -> int:
        """Largest power-of-two slab (slots) that fits the device
        budget — the quantized form every sizing decision uses, so a
        pow2 rounding can never overshoot the budget."""
        if not self.dev_bytes:
            return 0
        slots = self.dev_bytes // 8
        return 1 << max(slots.bit_length() - 1, 0) if slots else 1

    def slab_fits(self, cap: int) -> bool:
        """May a slab of ``cap`` u64 slots live in the hot budget?"""
        return not self.dev_bytes or cap * 8 <= self.dev_bytes

    def spilled_distinct(self) -> int:
        """Total fingerprints across generations.  Exact ONLY when the
        generations are disjoint (the resume rebuild guarantees that —
        each generation covers whole levels); during a run, re-heated
        fingerprints may appear in several runs and this is an upper
        bound (membership is a union either way)."""
        return sum(g.n for g in self.gens)

    # -- demotion ---------------------------------------------------------

    def demote(self, fps: np.ndarray, *, depth: int = 0) -> Generation:
        """Seal one sorted run from the hot slab's live fingerprints.

        ``fps`` is the slab's live (non-SENT) content, host-side; the
        caller resets the device slab afterwards.  The run commits to
        the spill directory through the atomic writer (crash at any
        point leaves the delta log authoritative — a resumed run
        discards and rebuilds every generation), then the warm budget
        evicts the oldest warm residencies to cold and the LSM
        compaction bound merges the cold runs when they outgrow the
        fanout.  Every demoted fingerprint also lands in the global
        spill sieve (the device-resident filter) and the run's bloom
        side-car commits beside it."""
        t0 = time.monotonic()
        fps = np.asarray(fps, np.uint64)
        fps = np.unique(fps[fps != SENT])
        gen = Generation(
            self._next_gid, fps, part_d=self.part_d, owner=self.owner,
            depth=depth,
        )
        self._next_gid += 1
        if gen.n:
            if self.spill_sieve is None:
                from ..ops import sieve as sieve_mod

                self.spill_sieve = sieve_mod.SpillSieve(
                    sieve_mod.sieve_words_for(self.dev_bytes)
                )
            self.spill_sieve.add(fps)
        if self.spill_dir is not None and gen.n:
            from .. import resilience

            name = f"{GEN_PREFIX}{gen.gid:04d}.npz"
            gen.path = resilience.commit_npz(
                self.spill_dir, name,
                dict(
                    fps=fps,
                    meta=np.asarray(
                        [GEN_VERSION, gen.gid, gen.n, depth,
                         self.part_d, self.owner],
                        np.int64,
                    ),
                ),
                kind="gen", depth=depth, run_fp=self.run_fp,
            )
            self._commit_sidecar(gen, depth)
        if gen.n:
            self.gens.append(gen)
        self.stats["demotions"] += 1
        self.stats["spilled"] += gen.n
        self._enforce_warm()
        self._maybe_compact(depth)
        _obs.tier_demote(
            depth, gen.n, gen.gid, time.monotonic() - t0,
            cold=gen.cold,
        )
        return gen

    def _commit_sidecar(self, gen: Generation, depth: int) -> None:
        """Build the run's bloom side-car and commit it beside the run
        (kind ``sieve`` -> the ``sieve.tmp``/``sieve.commit`` fault
        sites).  Pure acceleration state: a torn/stale/lost side-car
        quarantines and rebuilds from the generation itself, never
        affecting membership."""
        from .. import resilience
        from ..ops import sieve as sieve_mod

        gen.sidecar = sieve_mod.SpillSieve.build(gen.fps)
        name = f"{GEN_PREFIX}{gen.gid:04d}{SIDECAR_SUFFIX}"
        gen.sidecar_path = resilience.commit_npz(
            self.spill_dir, name,
            dict(
                words=gen.sidecar.words,
                meta=np.asarray(
                    [sieve_mod.SIEVE_VERSION, gen.gid, gen.n,
                     len(gen.sidecar.words)],
                    np.int64,
                ),
            ),
            kind="sieve", depth=depth, run_fp=self.run_fp,
        )

    def _maybe_compact(self, depth: int = 0) -> None:
        """LSM merge: when the COLD run count exceeds the fanout, merge
        every cold generation into one sorted run (committed kind
        ``compact`` -> the ``compact.tmp``/``compact.commit`` fault
        sites) with a fresh bloom side-car, then discard the inputs.
        Commit-then-discard order makes a kill at any instruction safe:
        resume sweeps ALL ``gen_*`` files and rebuilds the tier layout
        from the delta log, so a crash can never double-count a
        generation; in-process, ``self.gens`` swaps only after the
        merged run is durable.  Full-level merge (not size-tiered):
        write amplification is bounded by the fanout trigger itself —
        each spilled fp is rewritten at most once per fanout's worth of
        new cold runs — and the probe walk shrinks to <= fanout runs
        plus the warm tail."""
        cold = [g for g in self.gens if g.cold]
        if len(cold) <= self.compact_fanout or self.spill_dir is None:
            return
        from .. import resilience
        from ..ops import sieve as sieve_mod

        t0 = time.monotonic()
        merged = np.unique(
            np.concatenate([self._gen_fps(g) for g in cold])
        )
        gen = Generation(
            self._next_gid, merged, part_d=self.part_d,
            owner=self.owner, depth=depth,
        )
        self._next_gid += 1
        name = f"{GEN_PREFIX}{gen.gid:04d}.npz"
        gen.path = resilience.commit_npz(
            self.spill_dir, name,
            dict(
                fps=merged,
                meta=np.asarray(
                    [GEN_VERSION, gen.gid, gen.n, depth,
                     self.part_d, self.owner],
                    np.int64,
                ),
            ),
            kind="compact", depth=depth, run_fp=self.run_fp,
        )
        gen.sidecar = sieve_mod.SpillSieve.build(merged)
        gen.sidecar_path = resilience.commit_npz(
            self.spill_dir, f"{GEN_PREFIX}{gen.gid:04d}{SIDECAR_SUFFIX}",
            dict(
                words=gen.sidecar.words,
                meta=np.asarray(
                    [sieve_mod.SIEVE_VERSION, gen.gid, gen.n,
                     len(gen.sidecar.words)],
                    np.int64,
                ),
            ),
            kind="sieve", depth=depth, run_fp=self.run_fp,
        )
        # the merged run is durable — NOW swap the in-memory view and
        # discard the inputs (their side-cars ride along)
        drop_names = []
        for g in cold:
            if g.path is not None:
                drop_names.append(os.path.basename(g.path))
            if g.sidecar_path is not None:
                drop_names.append(os.path.basename(g.sidecar_path))
            self._cold_cache.pop(g.gid, None)
        cold_ids = {g.gid for g in cold}
        self.gens = [gen] + [
            g for g in self.gens if g.gid not in cold_ids
        ]
        if drop_names:
            resilience.discard_artifacts(self.spill_dir, drop_names)
        self._enforce_warm()
        dt = time.monotonic() - t0
        self.stats["compactions"] += 1
        self.stats["compact_runs"] += len(cold)
        self.stats["compact_s"] += dt
        _obs.tier_compact(depth, len(cold), gen.n, dt)

    # -- side-cars --------------------------------------------------------

    def _gen_sidecar(self, g: Generation):
        """The run's bloom filter, or None when unavailable.

        Warm-held side-cars return instantly; a committed one lazily
        loads with full validation — manifest digest (catches torn and
        flipped bytes after commit), format version and (gid, n, words)
        meta (catches a stale side-car adopted across a crashed
        compaction).  ANY failure quarantines the file and REBUILDS the
        filter from the generation itself (one disk load — the same
        cost a missing side-car always had), so a bad side-car can
        never manufacture a false negative."""
        if g.sidecar is not None:
            return g.sidecar
        if g.sidecar_path is None:
            return None
        from ..ops import sieve as sieve_mod
        from ..resilience import manifest as _manifest

        name = os.path.basename(g.sidecar_path)
        try:
            state = _manifest.Manifest.load(
                os.path.dirname(g.sidecar_path)
            ).verify(name)
            if state != "ok":
                raise IOError(f"side-car {name}: manifest says {state}")
            with np.load(g.sidecar_path) as z:
                words = np.asarray(z["words"], np.uint64)
                meta = np.asarray(z["meta"], np.int64)
            if (
                meta[0] != sieve_mod.SIEVE_VERSION or meta[1] != g.gid
                or meta[2] != g.n or meta[3] != len(words)
                or len(words) == 0 or len(words) & (len(words) - 1)
            ):
                raise IOError(
                    f"side-car {name}: stale meta {meta.tolist()} for "
                    f"generation (gid={g.gid}, n={g.n})"
                )
            g.sidecar = sieve_mod.SpillSieve.from_words(
                words, n_added=int(meta[2])
            )
        except Exception as e:  # graftlint: waive[GL003] — a side-car
            # is acceleration state with a full fallback: quarantine
            # whatever failed (digest, zip, meta) and rebuild from the
            # generation run, which IS membership-authoritative
            import sys

            print(
                f"[tiered] side-car {name} quarantined ({e}); "
                "rebuilding from the generation run", file=sys.stderr,
            )
            from .. import resilience

            resilience.discard_artifacts(
                os.path.dirname(g.sidecar_path), [name]
            )
            g.sidecar_path = None
            g.sidecar = sieve_mod.SpillSieve.build(self._gen_fps(g))
            self.stats["sidecar_rebuilds"] += 1
        return g.sidecar

    def _enforce_warm(self) -> None:
        """Evict the oldest warm generations to cold (disk-only) until
        warm residency fits the budget.  Without a committed file the
        generation must stay warm — RAM is then the only copy."""
        def warm_bytes():
            return (
                sum(g.nbytes for g in self.gens if g.fps is not None)
                + sum(v.nbytes for v in self._cold_cache.values())
            )

        while self._cold_cache and warm_bytes() > self.warm_bytes:
            self._cold_cache.popitem(last=False)
        for g in self.gens:
            if warm_bytes() <= self.warm_bytes:
                break
            if g.fps is not None and g.path is not None:
                g.fps = None
                self.stats["cold_gens"] += 1

    # -- probes -----------------------------------------------------------

    def _gen_fps(self, g: Generation) -> np.ndarray:
        if g.fps is not None:
            return g.fps
        hit = self._cold_cache.get(g.gid)
        if hit is not None:
            self._cold_cache.move_to_end(g.gid)
            return hit
        t0 = time.monotonic()
        fps = _load_gen_fps(g.path)
        self.stats["cold_loads"] += 1
        self.stats["cold_load_s"] += time.monotonic() - t0
        self._cold_cache[g.gid] = fps
        self._enforce_warm()
        return fps

    def probe(self, fps: np.ndarray, *, level: int = 0) -> np.ndarray:
        """hit bool[N]: which fingerprints are in some generation.

        Probe order: sieve (confirmed spilled-visited cache) first,
        then warm generations oldest-first, then cold ones — each with
        the free [lo, hi] range reject.  Hits feed back into the sieve
        so repeat offenders (between their first hit and the hot
        re-heat landing) never reach the cold tier twice."""
        t0 = time.monotonic()
        fps = np.asarray(fps, np.uint64)
        hit = np.zeros(len(fps), bool)
        live = fps != SENT
        self.stats["probes"] += 1
        self.stats["probe_lanes"] += int(live.sum())
        sieve_this = 0
        if len(self.sieve):
            pos = np.searchsorted(self.sieve, fps)
            sh = live & (
                self.sieve[np.clip(pos, 0, len(self.sieve) - 1)] == fps
            )
            sieve_this = int(sh.sum())
            self.stats["sieve_hits"] += sieve_this
            hit |= sh
        pending = live & ~hit
        for g in self.gens:
            if not pending.any():
                break
            if not g.n:
                continue
            inr = pending & (fps >= g.lo) & (fps <= g.hi)
            if not inr.any():
                continue
            was_cold = g.fps is None and g.gid not in self._cold_cache
            if was_cold:
                # bloom side-car first: a definite miss for every
                # in-range lane means the disk run CANNOT hold any of
                # them (no false negatives) — skip the cold load
                # entirely; a filter hit (true or false positive) pays
                # the exact searchsorted probe below
                sc = self._gen_sidecar(g)
                if sc is not None and not sc.contains(fps[inr]).any():
                    self.stats["sidecar_skips"] += 1
                    continue
            run = self._gen_fps(g)
            pos = np.searchsorted(run, fps[inr])
            gh = run[np.clip(pos, 0, len(run) - 1)] == fps[inr]
            if gh.any():
                idx = np.nonzero(inr)[0][gh]
                hit[idx] = True
                pending[idx] = False
                key = "cold_hits" if was_cold else "warm_hits"
                self.stats[key] += int(gh.sum())
        n_hit = int(hit.sum())
        self.stats["probe_hits"] += n_hit
        if n_hit:
            self._sieve_add(fps[hit])
        wait = time.monotonic() - t0
        self.stats["probe_wait_s"] += wait
        _obs.tier_probe(
            level, int(live.sum()), n_hit, sieve=sieve_this,
            wait_s=wait,
        )
        return hit

    def _sieve_add(self, fps: np.ndarray) -> None:
        merged = np.union1d(self.sieve, fps)
        if len(merged) > SIEVE_MAX:
            # drop the LOW half: arbitrary but deterministic — the
            # sieve is a cache, correctness never depends on it
            merged = merged[len(merged) // 2:]
        self.sieve = merged

    def all_fps(self) -> np.ndarray:
        """Every spilled fingerprint (degradation/debug path: the
        sorted-store fallback must absorb the whole union)."""
        if not self.gens:
            return np.empty(0, np.uint64)
        return np.unique(
            np.concatenate([self._gen_fps(g) for g in self.gens])
        )

    # -- resume -----------------------------------------------------------

    def rebuild(self, level_fps, *, hot_slots: int) -> np.ndarray:
        """Re-tier a delta-log replay: feed per-level fingerprint
        arrays oldest-first; whole levels demote together whenever the
        accumulated hot set would no longer fit ``hot_slots`` at the
        <= 1/2 load factor.  Returns the fingerprints that stay hot.
        Generations built here cover whole levels, so they are DISJOINT
        and the tier total is exactly the replayed distinct count."""
        # one under the half-slot mark, like max_hot_entries: exactly
        # hot_slots/2 entries would make slab_rows round up to a slab
        # twice the budget
        budget = max(hot_slots // 2 - 1, 1)
        acc: list[np.ndarray] = []
        acc_n = 0
        last_depth = 0
        for depth, fps in level_fps:
            fps = np.asarray(fps, np.uint64)
            if acc_n and acc_n + len(fps) > budget:
                self.demote(np.concatenate(acc), depth=last_depth)
                acc, acc_n = [], 0
            while len(fps) > budget:
                # one level bigger than the whole hot tier (monolith
                # seeds, deep-level replays): split it across runs —
                # disjointness holds, membership is a union
                self.demote(fps[:budget], depth=depth)
                fps = fps[budget:]
            acc.append(fps)
            acc_n += len(fps)
            last_depth = depth
        return (
            np.concatenate(acc) if acc else np.empty(0, np.uint64)
        )


def sweep_gens(ckdir: str) -> int:
    """Discard every committed generation file AND bloom side-car in a
    checkpoint directory (the ``gen_*.npz`` glob matches
    ``gen_*.sieve.npz`` too).  Resume rebuilds the tier layout from the
    delta log, so stale runs from the crashed incarnation are noise —
    and sweeping them FIRST is what makes a kill mid-compaction safe:
    the commit-then-discard window can leave both the merged run and
    its inputs on disk, and only this sweep guarantees the overlapping
    sets never double-count (the resume re-demotes a fresh, disjoint
    generation sequence).  Orphaned ``.tmp_*`` files are the atomic
    writer's own sweep; this extends that hygiene to the committed-but-
    stale class."""
    import glob

    from .. import resilience

    names = [
        os.path.basename(f)
        for f in glob.glob(os.path.join(ckdir, f"{GEN_PREFIX}*.npz"))
    ]
    if names:
        resilience.discard_artifacts(ckdir, names)
    return len(names)


class FrontierPager:
    """Warm-tier paging for frontier segments (``kind="fseg"``).

    The visited tiers bound the SLAB's residency; this pager bounds the
    FRONTIER's.  A deep level's working set is (parent segments +
    sealed child segments); once the engine's host-RAM paging
    (`engine/bfs._HostSeg`) itself outgrows ``TLA_RAFT_FSEG_BYTES``,
    the overflow segments commit here through the same atomic
    ``commit_npz`` machinery the generations use — crash mid-write
    leaves only a ``.tmp_*`` the writer's own sweep removes, a
    committed-but-orphaned segment is swept on resume
    (:func:`sweep_fsegs`; the delta log rebuilds frontiers, so fseg
    files are NEVER a recovery input).  Spilled segments reload on
    demand when the next level's walk reaches them — the walks consume
    segments in ascending order, so residency is a moving window over
    the level, not the whole level.

    All methods are host-side numpy (no device dispatch, GL007).
    """

    def __init__(self, spill_dir: str, *, run_fp: str | None = None):
        self.spill_dir = spill_dir
        self.run_fp = run_fp
        self._next_tok = 0
        self._names: dict[int, str] = {}
        self.stats = dict(
            fseg_spills=0, fseg_loads=0, fseg_bytes=0,
            fseg_load_s=0.0, fseg_live_peak=0,
        )

    @property
    def live(self) -> int:
        return len(self._names)

    def spill(self, fields: dict, *, depth: int = -1) -> int:
        """Commit one frontier segment's field dict; returns a token."""
        from .. import resilience

        tok = self._next_tok
        self._next_tok += 1
        name = f"{FSEG_PREFIX}{tok:05d}.npz"
        resilience.commit_npz(
            self.spill_dir, name, dict(fields), kind="fseg",
            depth=depth, run_fp=self.run_fp,
        )
        self._names[tok] = name
        self.stats["fseg_spills"] += 1
        self.stats["fseg_bytes"] += sum(
            int(np.prod(v.shape)) * v.dtype.itemsize
            for v in fields.values()
        )
        self.stats["fseg_live_peak"] = max(
            self.stats["fseg_live_peak"], self.live
        )
        return tok

    def load(self, tok: int) -> dict:
        """Page one spilled segment back into host RAM."""
        t0 = time.monotonic()
        path = os.path.join(self.spill_dir, self._names[tok])
        with np.load(path) as z:
            fields = {k: z[k] for k in z.files}
        self.stats["fseg_loads"] += 1
        self.stats["fseg_load_s"] += time.monotonic() - t0
        _obs.fseg_page(tok, fields["voted_for"].shape[0],
                       time.monotonic() - t0)
        return fields

    def retire(self, toks) -> None:
        """Discard consumed segments' artifacts (one manifest commit)."""
        from .. import resilience

        names = [self._names.pop(t) for t in toks if t in self._names]
        if names:
            resilience.discard_artifacts(self.spill_dir, names)

    def retire_all(self) -> None:
        self.retire(list(self._names))


def sweep_fsegs(ckdir: str) -> int:
    """Discard every committed frontier-segment file in a checkpoint
    directory.  Frontier segments are per-level transients — resume
    rebuilds the frontier from the delta log, so fseg files from a
    crashed incarnation are pure noise (and, unswept, dead disk)."""
    import glob

    from .. import resilience

    names = [
        os.path.basename(f)
        for f in glob.glob(os.path.join(ckdir, f"{FSEG_PREFIX}*.npz"))
    ]
    if names:
        resilience.discard_artifacts(ckdir, names)
    return len(names)


def repartition(gens: list[np.ndarray], d_new: int) -> list[np.ndarray]:
    """Owner-remap a generation set onto ``d_new`` owners (fp % D').

    The same move PR 8's elastic resume applies to hash slabs, pointed
    at spilled runs: the input runs' union re-buckets into one sorted
    run per new owner.  Exact for any old partition — membership is a
    union, and re-sorting per bucket keeps every probe
    searchsorted-exact."""
    allf = (
        np.unique(np.concatenate([np.asarray(g, np.uint64) for g in gens]))
        if gens else np.empty(0, np.uint64)
    )
    return [
        np.ascontiguousarray(allf[(allf % np.uint64(d_new)) == o])
        for o in range(d_new)
    ]


# -- the row-compaction kernel (the one device program of this module) ----

def drop_rows_impl(tree, keep, n_keep):
    """Compact a materialized frontier's kept rows to the prefix.

    ``tree`` is any pytree of [cap, ...] arrays (the engine's Frontier),
    ``keep`` bool[cap] (True rows survive the generation probe),
    ``n_keep`` their count (traced).  Kept rows keep their relative
    order (stable argsort) — the payload-ascending order every engine
    pins — and dead rows zero out exactly like the staged path's padded
    frontier tail.  One data-indexed gather per field (the honest
    residue GL010 ledgers as ``store.tiered_compact``)."""
    import jax
    import jax.numpy as jnp

    cap = keep.shape[0]
    order = jnp.argsort(~keep, stable=True)
    lane = jnp.arange(cap)

    def one(x):
        live = (lane < n_keep).reshape((cap,) + (1,) * (x.ndim - 1))
        return jnp.where(live, x[order], jnp.zeros_like(x))

    return jax.tree.map(one, tree)


@functools.cache
def _drop_rows_jit():
    import jax

    return jax.jit(drop_rows_impl)


def drop_rows(tree, keep, n_keep):
    return _drop_rows_jit()(tree, keep, n_keep)


def ledger_trace(cfg=None):
    """Closed jaxpr of the compaction kernel at the audit's tiny
    reference shapes — the graftlint layer-2 registration (GL010): the
    budget pins one gather per frontier field, nothing data-indexed
    beyond that."""
    import jax
    import jax.numpy as jnp

    from ..config import RaftConfig
    from ..engine.bfs import JaxChecker
    from ..models.raft import init_batch

    if cfg is None:
        cfg = RaftConfig(
            n_servers=2, n_vals=1, max_election=1, max_restart=1,
        )
    eng = JaxChecker(cfg, chunk=64, use_hashstore=True)
    fr0, _ovf = eng._deflate(init_batch(cfg, 1))
    fr = eng._frontier_struct(fr0, 64)
    keep = jax.ShapeDtypeStruct((64,), jnp.bool_)
    n = jax.ShapeDtypeStruct((), jnp.int64)
    return jax.make_jaxpr(drop_rows_impl)(fr, keep, n)
