"""CLI entry point: the L4 launcher layer, TPU-native.

The reference's launcher is ``myrun.sh`` (/root/reference/myrun.sh:3):
``java ... tla2tools.jar -deadlock -workers 4 -config Raft.cfg Raft.tla $@
2>&1 | tee raft.log``.  This module is the checker it launches when
``-backend=jax`` is selected: it parses the same ``.cfg`` file
(cfgparse.py), runs the TPU BFS engine (engine/bfs.py) or the pure-Python
oracle, prints TLC-shaped progress/result lines, and tees everything to
``raft.log`` — keeping the reference's observability contract (grep-able
state counts + verdict, SURVEY.md §5 "metrics/logging").

Usage:
  python -m tla_raft_tpu.check --config /root/reference/Raft.cfg \
      [--backend jax|oracle] [--max-depth N] [--chunk N] \
      [--invariant NAME]... [--no-symmetry] [--no-view] \
      [--checkpoint-dir states] [--recover states] \
      [--log raft.log] [--servers N] [--vals N] [--max-election N] \
      [--max-restart N]

Flags mirror TLC where an analog exists: ``--workers`` is accepted and
ignored (parallelism is the device mesh, not a thread count);
``--recover`` matches TLC's ``-recover``; deadlock checking is disabled
with no off switch, matching the pinned ``-deadlock`` flag.
"""

from __future__ import annotations

import argparse
import contextlib
import dataclasses
import json
import os
import sys
import time

from .config import MSG_TYPE_NAMES, ROLE_NAMES, RaftConfig
from .cfgparse import load_raft_config


class Tee:
    def __init__(self, *streams):
        self.streams = streams

    def write(self, s):
        for st in self.streams:
            st.write(s)

    def flush(self):
        for st in self.streams:
            st.flush()


def format_state(cfg: RaftConfig, st) -> str:
    """Pretty-print one oracle OState, TLA-style."""
    S = cfg.S

    def server_fn(vals, fmt=str):
        return "[" + ", ".join(f"s{i + 1} |-> {fmt(v)}" for i, v in enumerate(vals)) + "]"

    def fmt_vote(v):
        return f"s{v}" if v else "None"

    def fmt_log(log):
        return "<<" + ", ".join(f"[term |-> {t}, val |-> {'v%d' % v if v else 'None'}]" for t, v in log) + ">>"

    def fmt_msg(m):
        t = MSG_TYPE_NAMES[m[0]]
        if m[0] == 0:  # VoteReq
            return (f"[type |-> {t}, src |-> s{m[1]}, dst |-> s{m[2]}, term |-> {m[3]}, "
                    f"lastLogIndex |-> {m[4]}, lastLogTerm |-> {m[5]}]")
        if m[0] == 1:  # VoteResp
            return f"[type |-> {t}, src |-> s{m[1]}, dst |-> s{m[2]}, term |-> {m[3]}]"
        if m[0] == 2:  # AppendReq
            ent = ", ".join(f"[term |-> {et}, val |-> v{ev}]" for et, ev in m[6])
            return (f"[type |-> {t}, src |-> s{m[1]}, dst |-> s{m[2]}, term |-> {m[3]}, "
                    f"prevLogIndex |-> {m[4]}, prevLogTerm |-> {m[5]}, "
                    f"entries |-> <<{ent}>>, leaderCommit |-> {m[7]}]")
        return (f"[type |-> {t}, src |-> s{m[1]}, dst |-> s{m[2]}, term |-> {m[3]}, "
                f"prevLogIndex |-> {m[4]}, succ |-> {'TRUE' if m[5] else 'FALSE'}]")

    lines = [
        f"/\\ votedFor = {server_fn(st.voted_for, fmt_vote)}",
        f"/\\ currentTerm = {server_fn(st.current_term)}",
        f"/\\ role = {server_fn(st.role, lambda r: ROLE_NAMES[r])}",
        f"/\\ logs = {server_fn(st.logs, fmt_log)}",
        f"/\\ matchIndex = {server_fn(st.match_index, lambda r: '[' + ', '.join(f's{j + 1} |-> {x}' for j, x in enumerate(r)) + ']')}",
        f"/\\ nextIndex = {server_fn(st.next_index, lambda r: '[' + ', '.join(f's{j + 1} |-> {x}' for j, x in enumerate(r)) + ']')}",
        f"/\\ commitIndex = {server_fn(st.commit_index)}",
        "/\\ msgs = {" + ",\n            ".join(fmt_msg(m) for m in sorted(st.msgs)) + "}",
        f"/\\ electionCount = {st.election_count}",
        f"/\\ restartCount = {st.restart_count}",
        f"/\\ valSent = [" + ", ".join(
            f"v{i + 1} |-> {'None' if v == 0 else 'FALSE'}" for i, v in enumerate(st.val_sent)
        ) + "]",
    ]
    return "\n".join(lines)


def trace_doc(cfg: RaftConfig, trace) -> list[dict]:
    """Serializable counterexample trace: one ``{action, state}`` dict
    per step, states rendered exactly as :func:`print_trace` shows
    them.  The one place trace rendering is defined — the CLI's
    pretty-printer and the sweep service's ``result.json`` trace block
    both come from here, so a service-reconstructed trace is
    byte-equal to what ``check.py`` would print for the same run."""
    return [
        dict(
            action="Initial predicate" if action == "Init" else action,
            state=format_state(cfg, st),
        )
        for action, st in trace
    ]


def print_trace(cfg: RaftConfig, trace, out):
    print("The behavior up to this point is:", file=out)
    for i, step in enumerate(trace_doc(cfg, trace)):
        print(f"\nSTATE {i + 1}: <{step['action']}>", file=out)
        print(step["state"], file=out)


def _report_preempted(e, out, logf) -> int:
    """Preemption is a RESUMABLE outcome, not an error: say where the
    durable state sits and exit 75 (EX_TEMPFAIL — the supervisor and
    any fleet scheduler relaunch on it)."""
    print(f"Preempted: {e}.", file=out)
    if e.checkpoint_dir:
        print(f"Resume with --recover {e.checkpoint_dir}", file=out)
    if logf:
        logf.close()
    return 75


def _has_checkpoints(ckdir: str) -> bool:
    import glob

    return bool(
        glob.glob(os.path.join(ckdir, "delta_*.npz"))
        or glob.glob(os.path.join(ckdir, "mdelta_*.npz"))
        or os.path.exists(os.path.join(ckdir, "base.npz"))
    )


def _mxu_arg(args):
    """--mxu-expand / --no-mxu-expand -> the checkers' use_mxu argument
    (None = the TLA_RAFT_MXU env default, which is ON)."""
    if args.no_mxu_expand:
        return False
    if args.mxu_expand is None:
        return None
    return bool(args.mxu_expand)


def _supervise(args, raw_argv) -> int:
    """Supervisor mode: run the check as a child process, relaunching a
    crashed/preempted child from its own checkpoint directory up to N
    times (TLC deployments wrap the jar in exactly this kind of babysit
    loop; ``--supervise`` builds it in).  Terminal exits — clean sweep
    (0), model violation (1), usage error (2), sanitizer findings (3)
    — are returned as-is; anything else (SIGKILL, OOM, preemption exit
    75) relaunches with ``--recover`` pointing at the checkpoint dir,
    where the self-healing resume quarantines whatever the crash tore."""
    import subprocess

    if not args.checkpoint_dir:
        print(
            "--supervise requires --checkpoint-dir (the relaunch "
            "resumes from it)",
            file=sys.stderr,
        )
        return 2
    child_args = []
    skip = False
    for a in raw_argv:
        if skip:
            skip = False
            continue
        if a == "--supervise":
            skip = True
            continue
        if a.startswith("--supervise="):
            continue
        child_args.append(a)
    attempts = 0
    while True:
        cmd = [sys.executable, "-m", "tla_raft_tpu.check", *child_args]
        if (
            "--recover" not in child_args
            and _has_checkpoints(args.checkpoint_dir)
        ):
            cmd += ["--recover", args.checkpoint_dir]
        rc = subprocess.call(cmd)
        # terminal: clean sweep (0), model violation (1), usage error
        # (2), sanitizer findings (3), integrity fail-stop (4 —
        # deterministic corruption; relaunching cannot outrun it)
        if rc in (0, 1, 2, 3, 4):
            return rc
        attempts += 1
        if attempts > args.supervise:
            print(
                f"supervise: giving up after {attempts - 1} "
                f"relaunch(es) (last exit {rc})",
                file=sys.stderr,
            )
            return rc
        print(
            f"supervise: child exited {rc}; relaunch "
            f"{attempts}/{args.supervise} from {args.checkpoint_dir}",
            file=sys.stderr,
        )


class _Silent:
    def write(self, s):
        pass

    def flush(self):
        pass


def summarize(res, chk=None, seconds: float | None = None,
              hub=None) -> dict:
    """CheckResult -> the canonical ``--json`` summary schema.

    The one place the schema is defined: the CLI's ``--json`` line, the
    sweep service's ``result.json`` records and the programmatic
    :func:`run_check` return value all come from here, so they can
    never drift apart.  Keys beginning with ``_`` carry non-JSON
    payloads (the raw result/checker objects) and are stripped by
    :func:`summary_public` before anything is serialized.  ``hub`` (a
    telemetry hub, when the run carried one) contributes the unified
    ``telemetry`` block — level wall times, dispatches, fetch waits,
    grow/redo counts, checkpoint I/O, straggler skew — in ONE place
    instead of per-subsystem ad-hoc keys.
    """
    out = dict(
        ok=res.ok,
        distinct=res.distinct,
        generated=res.generated,
        depth=res.depth,
        # the crash-matrix tests diff these against an
        # uninterrupted run's, level by level
        level_sizes=list(res.level_sizes),
        mxu=getattr(chk, "use_mxu", None),
        megakernel=getattr(chk, "megakernel", None),
        superstep=getattr(chk, "superstep_span", None),
        seconds=round(seconds, 3) if seconds is not None else None,
        violation=res.violation[0] if res.violation else None,
    )
    # integrity-audit counters (single-device --audit runs only)
    aud = getattr(chk, "audit_stats", None)
    if aud and aud.get("levels"):
        out["audit"] = dict(aud)
    # tiered visited store (store/tiered.py): demotion + per-tier probe
    # accounting — present once a device budget actually spilled
    tiered = getattr(chk, "tiered", None)
    if tiered is not None and (
        tiered.stats["demotions"] or tiered.stats["probes"]
    ):
        out["tiered"] = dict(
            tiered.stats,
            dev_bytes=tiered.dev_bytes,
            generations=len(tiered.gens),
            probe_wait_s=round(tiered.stats["probe_wait_s"], 6),
            cold_load_s=round(tiered.stats["cold_load_s"], 6),
        )
        out["sieve"] = bool(getattr(chk, "sieve_enabled", False))
        # superstep window accounting (supersteps/levels/stop causes):
        # under spill this is the span-survival evidence — sieve-clean
        # windows keep committing levels, sieve_stops count the exact
        # per-level corrections (ops/sieve.py)
        ss = getattr(chk, "_ss_stats", None)
        if ss and ss.get("supersteps"):
            out["superstep_stats"] = {
                k: int(v) for k, v in sorted(ss.items())
            }
        # spilled-frontier paging (store/tiered.py FrontierPager)
        fpager = getattr(chk, "_fpager", None)
        if fpager is not None and fpager.stats["fseg_spills"]:
            out["fseg"] = dict(
                fpager.stats,
                fseg_load_s=round(fpager.stats["fseg_load_s"], 6),
            )
    # adaptive sieve governor (tune/adaptive.py): present whenever the
    # measured arm/stand-down policy saw a window or flipped state —
    # the BENCH_SIEVE_AB record's evidence that the policy engaged
    gov = getattr(chk, "sieve_governor", None)
    if gov is not None and (
        gov.stats["windows"] or gov.stats["stand_downs"]
        or gov.stats["rearms"]
    ):
        out["sieve_governor"] = gov.snapshot()
    # per-owner straggler/skew metrics (mesh runs); kept at top level
    # for compatibility AND folded into the telemetry block below
    skew = getattr(chk, "skew", None)
    if skew is not None and getattr(skew, "levels", 0):
        out["straggler"] = skew.summary()
    if hub is not None:
        tel = hub.snapshot()
        if "straggler" in out:
            tel["straggler"] = out["straggler"]
        out["telemetry"] = tel
        if "hbm" in tel:
            # the live device-memory gauge (registered buffers + worst
            # program temp, vs the --dev-bytes budget) surfaces at the
            # top level beside the counts it prices
            out["hbm"] = tel["hbm"]
    return out


def summary_public(summary: dict) -> dict:
    """The JSON-serializable view of a :func:`run_check` summary."""
    return {k: v for k, v in summary.items() if not k.startswith("_")}


def run_check(
    cfg: RaftConfig,
    *,
    backend: str = "jax",
    max_depth: int | None = None,
    chunk: int | None = None,
    checkpoint_dir: str | None = None,
    checkpoint_every: int = 1,
    recover: str | None = None,
    fpstore_dir: str | None = None,
    mesh: int = 0,
    exchange: str = "all_to_all",
    mesh_deep: bool = False,
    seg_rows: int = 1 << 15,
    sieve: bool = True,
    compress: bool = True,
    cap_x: int = 4096,
    canon: str = "late",
    use_hashstore: bool | None = None,
    pipeline: bool | None = None,
    pipeline_window: int | None = None,
    prewarm: bool | None = None,
    use_mxu: bool | None = None,
    megakernel: bool | None = None,
    superstep: int | None = None,
    audit: int = 0,
    audit_retries: int = 3,
    watchdog: float = 0.0,
    telemetry: bool | None = None,
    profile: int = 0,
    dev_bytes: int | None = None,
    warm_bytes: int | None = None,
    plan=None,
    progress=None,
    out=None,
    install_signals: bool = False,
) -> dict:
    """One in-process model-checking run -> the ``--json`` summary dict.

    The programmatic core ``main`` used to inline: the sweep service,
    the bench harness and the tests all invoke the checker through
    here instead of shelling out through argv.  ``out`` (a writable
    stream, or None for silence) receives the same informational lines
    the CLI prints; ``progress`` is the per-level stats callback.
    Raises ``resilience.Preempted`` on cooperative preemption (the CLI
    maps it to exit 75) and propagates engine errors as exceptions —
    policy (exit codes, tee logs, trace pretty-printing) stays with the
    caller.  Extra ``_res`` / ``_chk`` / ``_sanitizer`` / ``_hub`` keys
    carry the raw objects for callers that need the violation trace,
    the exchange meter or the telemetry hub; ``summary_public`` strips
    them.

    ``plan`` selects the autotuned knob plan (tune/plans.py):
    ``None`` resolves the run's shape regime against the active plan
    cache (``TLA_RAFT_PLAN``: ``0`` disables, unset/``1`` reads the
    committed default cache, a path reads that file); ``False``/``"0"``
    forces the hand-set defaults; a dict is used as the knob set
    directly; a path string resolves against that file.  Explicit
    arguments (``chunk``, ``superstep``, ``pipeline_window``, ...)
    always beat the plan — it only fills values the caller left unset —
    and counts are bit-identical under any plan (knobs move shapes and
    schedules, never semantics).

    ``telemetry`` (default: ``TLA_RAFT_TELEMETRY``, on) installs the
    process-wide flight recorder (obs/telemetry.py) for the run: every
    level, dispatch, ledgered fetch, compile, checkpoint commit,
    grow/redo and watchdog event lands in ``<checkpoint_dir>/
    events.jsonl`` (in-memory aggregation only when the run has no
    checkpoint dir and ``TLA_RAFT_TELEMETRY_DIR`` is unset), and the
    returned summary carries the unified ``telemetry`` block.  A hub
    already installed by an outer caller (bench, the service bucket
    loop) is reused, never re-anchored or closed.
    """
    from .obs import telemetry as obs_telemetry

    tel_on = (
        obs_telemetry.enabled_by_env() if telemetry is None
        else bool(telemetry)
    ) and backend != "oracle"
    hub = None
    own_hub = False
    if tel_on:
        hub = obs_telemetry.current()
        if hub is None:
            run_dir = (
                checkpoint_dir
                or os.environ.get("TLA_RAFT_TELEMETRY_DIR")
                or None
            )
            hub = obs_telemetry.TelemetryHub(run_dir=run_dir)
            obs_telemetry.install(hub)
            own_hub = True
            obs_telemetry.run_begin(
                config=cfg.describe(), backend=backend, mesh=mesh,
                mesh_deep=mesh_deep, recover=bool(recover),
            )
    # -- autotuned plan resolution (tune/plans.py) --------------------
    # Resolved AFTER the hub install so the plan_applied event lands in
    # this run's flight recorder; installed only when no outer caller
    # (the tuner's probe loop, the service bucket pass) already holds
    # the registry, and fully restored on exit either way.
    from .ops import hashstore as _hashstore
    from .tune import active as _plan_active
    from .tune import plans as _plans

    if plan is False or plan == "0":
        plan_knobs: dict = {}
    elif isinstance(plan, dict):
        plan_knobs = _plans.clamp(plan)
    elif isinstance(plan, str) and plan not in ("", "1"):
        plan_knobs = _plans.resolve(cfg, backend, path=plan)
    else:
        plan_knobs = _plans.resolve(cfg, backend)
    own_plan = bool(plan_knobs) and _plan_active.installed() is None
    prev_pw = None
    if own_plan:
        _plan_active.install(plan_knobs)
        if "probe_window" in plan_knobs:
            prev_pw = _hashstore.probe_window()
            _hashstore.set_probe_window(int(plan_knobs["probe_window"]))
        obs_telemetry.emit(
            "plan_applied",
            regime=_plans.regime_key(cfg, backend),
            knobs=dict(plan_knobs),
        )
        if out is not None:
            print(
                f"Autotuned plan: {_plans.regime_key(cfg, backend)} -> "
                f"{plan_knobs} (TLA_RAFT_PLAN=0 reverts)", file=out,
            )
    if chunk is None:
        chunk = int(plan_knobs.get("chunk", 1024)) if own_plan else 1024
    try:
        summary = _run_check_impl(
            cfg, backend=backend, max_depth=max_depth, chunk=chunk,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every, recover=recover,
            fpstore_dir=fpstore_dir, mesh=mesh, exchange=exchange,
            mesh_deep=mesh_deep, seg_rows=seg_rows, sieve=sieve,
            compress=compress, cap_x=cap_x, canon=canon,
            use_hashstore=use_hashstore, pipeline=pipeline,
            pipeline_window=pipeline_window, prewarm=prewarm,
            use_mxu=use_mxu, megakernel=megakernel,
            superstep=superstep, audit=audit,
            audit_retries=audit_retries, watchdog=watchdog,
            profile=profile,
            dev_bytes=dev_bytes, warm_bytes=warm_bytes,
            hub=hub, progress=progress, out=out,
            install_signals=install_signals,
        )
        if own_plan:
            summary["plan"] = dict(plan_knobs)
        return summary
    finally:
        if own_plan:
            _plan_active.clear()
            if prev_pw is not None:
                _hashstore.set_probe_window(prev_pw)
        if own_hub:
            obs_telemetry.install(None)
            hub.close()


def _run_check_impl(
    cfg: RaftConfig,
    *,
    backend,
    max_depth,
    chunk,
    checkpoint_dir,
    checkpoint_every,
    recover,
    fpstore_dir,
    mesh,
    exchange,
    mesh_deep,
    seg_rows,
    sieve,
    compress,
    cap_x,
    canon,
    use_hashstore,
    pipeline,
    pipeline_window,
    prewarm,
    use_mxu,
    megakernel,
    superstep,
    audit,
    audit_retries,
    watchdog,
    profile,
    dev_bytes,
    warm_bytes,
    hub,
    progress,
    out,
    install_signals,
) -> dict:
    if mesh_deep and not mesh:
        raise ValueError("mesh_deep requires mesh >= 1")
    if mesh_deep and not fpstore_dir:
        raise ValueError("mesh_deep requires fpstore_dir")
    if warm_bytes is None and os.environ.get("TLA_RAFT_WARM_BYTES"):
        # honor the env on the external-store paths too (the tiered
        # slab path reads it internally); when NEITHER is set those
        # stores keep their native 64 MiB buffer default — the 1 GiB
        # tiered-generation default must not silently re-budget them
        warm_bytes = int(float(os.environ["TLA_RAFT_WARM_BYTES"]))
    out = out if out is not None else _Silent()
    t0 = time.monotonic()
    sanitizer = None
    tsan = None
    chk = None  # the engine instance (None on the oracle backend)
    if backend == "oracle":
        from .oracle import OracleChecker

        res = OracleChecker(cfg).run(max_depth=max_depth)
    else:
        from . import resilience
        from .platform import setup_jax

        jax = setup_jax()
        if hub is not None:
            # publish XLA backend compiles into the flight recorder
            # (idempotent, armed only after setup_jax picked the
            # platform)
            from .analysis.sanitize import obs_watch_compiles

            obs_watch_compiles()
        if install_signals:
            # SIGTERM/SIGINT request a cooperative preemption: the
            # engine finishes the in-flight level, flushes its
            # checkpoints, and raises Preempted -> exit 75 (resumable);
            # a second signal kills immediately.  CLI-only — library
            # callers (the service daemon owns its own handlers) poll
            # the flag.
            resilience.install_signal_handlers()

        from .engine import JaxChecker

        if os.environ.get("GRAFT_SANITIZE") == "1":
            # graftlint layer 3 (docs/ANALYSIS.md): host-transfer ledger
            # + per-level compile-count ledger + dispatch-thread guard
            from .analysis.sanitize import Sanitizer

            sanitizer = Sanitizer()
            print(
                f"Sanitizer: armed (warmup {sanitizer.warmup_levels} "
                f"levels, {'strict' if sanitizer.strict else 'counting'} "
                "transfer guard)",
                file=out,
            )

        if os.environ.get("GRAFT_TSAN") == "1":
            # graftsync layer 2 (docs/ANALYSIS.md): happens-before
            # sanitizer + lock contention profiler over the known
            # thread boundaries; composes with GRAFT_SANITIZE.
            # GRAFT_TSAN_STRICT=1 raises at the racing access instead
            # of reporting at exit (exit code 3 either way).
            from .analysis.tsan import TSan

            tsan = TSan(
                strict=os.environ.get("GRAFT_TSAN_STRICT") == "1"
            )
            print(
                "TSan: armed (happens-before sanitizer, "
                f"{'strict' if tsan.strict else 'report-at-exit'}; "
                "lock profiler on)",
                file=out,
            )

        print(f"Devices: {jax.devices()}", file=out)

        # per-level hang watchdog (resilience/elastic.py): --watchdog S
        # arms every level with max(S, 8 * last level seconds); a hung
        # dispatch becomes a clean resumable exit 75 instead of an
        # infinite stall
        wd = None
        if watchdog and watchdog > 0 and backend != "oracle":
            wd = resilience.elastic.Watchdog(float(watchdog))
            resilience.elastic.install_watchdog(wd)
            print(f"Watchdog: armed (floor {float(watchdog)}s/level)",
                  file=out)

        # opt-in jax-profiler capture (--profile N, default off): the
        # device-side twin of the flight recorder — N dispatch windows
        # (supersteps on the fused path) traced into
        # <run_dir>/profile/, merged beside the host lanes by
        # `obs trace` (analysis/devprof.py)
        prof = None
        if profile and int(profile) > 0:
            from .analysis import devprof as graft_devprof

            prof_dir = (
                checkpoint_dir
                or os.environ.get("TLA_RAFT_TELEMETRY_DIR")
            )
            if hub is None:
                # without the flight recorder there is no
                # profile_begin merge anchor and no events.jsonl for
                # `obs trace` to hang the device lanes off — a capture
                # would be unreachable through the documented flow
                print(
                    "--profile needs telemetry on (the profile_begin "
                    "event anchors the device-lane merge; flag "
                    "ignored)", file=out,
                )
            elif not prof_dir:
                print(
                    "--profile needs --checkpoint-dir (or "
                    "TLA_RAFT_TELEMETRY_DIR): the device trace lands "
                    "beside events.jsonl (flag ignored)", file=out,
                )
            else:
                prof = graft_devprof.ProfilerCapture(
                    prof_dir, int(profile)
                )
                if prof.start():
                    graft_devprof.install_profiler(prof)
                    print(
                        f"Profiler: capturing {prof.windows} dispatch "
                        f"window(s) -> {prof.trace_dir}", file=out,
                    )
                else:
                    prof = None

        def wd_teardown():
            # on EVERY exit (Preempted, device loss, IntegrityError
            # propagate to the caller by contract): a leaked watchdog
            # thread would park forever and a stale global would
            # swallow the next run's heartbeats
            if prof is not None:
                from .analysis import devprof as graft_devprof

                prof.stop()
                graft_devprof.install_profiler(None)
            if wd is not None:
                wd.cancel()
                resilience.elastic.install_watchdog(None)

        # elastic relaunch: a recovery run on a shrunken fleet (device
        # loss) re-shards onto the surviving devices instead of
        # refusing to start; fresh runs keep the strict mesh check
        if mesh and recover:
            mesh = resilience.elastic.effective_mesh(mesh, out)

        host_store = None  # single-device external store (mesh has its own)
        if fpstore_dir and not mesh:
            from .native import HostFPStore

            # --warm-bytes bounds the store's in-RAM buffer; past it
            # the native tier spills sorted runs to disk (the warm/cold
            # boundary of the external-store loop)
            host_store = HostFPStore(
                fpstore_dir,
                mem_budget_entries=(
                    max(warm_bytes // 8, 1) if warm_bytes else 0
                ),
            )
            if not recover:
                # sweep run files orphaned by a crashed earlier process
                # (never loaded, but they waste disk and shadow names)
                host_store.clear()
            print(f"Native FP store: {fpstore_dir}", file=out)

        sanctx = sanitizer if sanitizer is not None else (
            contextlib.nullcontext()
        )
        tsanctx = tsan if tsan is not None else contextlib.nullcontext()
        if mesh:
            if dev_bytes:
                print(
                    "--dev-bytes applies to the single-device engine's "
                    "hot slab; mesh out-of-core runs tier through the "
                    "owner-sharded external stores (--fpstore-dir) "
                    "with --warm-bytes bounding their RAM (flag "
                    "ignored)", file=out,
                )
            if fpstore_dir:
                # mesh x external store: one HostFPStore per owner shard
                # (fp % D), host-filtered after the all_to_all routing
                print(f"Native FP store (owner-sharded x{mesh}): "
                      f"{fpstore_dir}", file=out)
            from .parallel import ShardedChecker, make_mesh

            chk = ShardedChecker(
                cfg, make_mesh(mesh), cap_x=cap_x,
                exchange=exchange, progress=progress, canon=canon,
                host_store_dir=fpstore_dir or None,
                deep=mesh_deep, seg_rows=seg_rows,
                sieve=sieve, compress=compress,
                use_hashstore=(
                    True if use_hashstore is None else use_hashstore
                ),
                pipeline=pipeline,
                pipeline_window=pipeline_window,
                use_mxu=use_mxu,
                watchdog=wd,
                warm_bytes=warm_bytes,
            )
            if audit:
                print(
                    "--audit applies to the single-device engine; mesh "
                    "runs keep the always-on conservation checks "
                    "(count reconciliation, store occupancy)",
                    file=out,
                )
            try:
                with sanctx, tsanctx:
                    res = chk.run(
                        max_depth=max_depth,
                        checkpoint_dir=checkpoint_dir,
                        checkpoint_every=checkpoint_every,
                        resume_from=recover,
                    )
            finally:
                wd_teardown()
            if mesh_deep and chk.meter.levels:
                # run-summary exchange ledger: the sieve+compress bytes
                # vs what the uncompressed exchange would have moved
                s = chk.meter.summary()
                print(
                    f"Exchange: {s['exchanged_bytes']:,} fp bytes over "
                    f"{s['levels']} levels (uncompressed equivalent "
                    f"{s['raw_bytes']:,}; reduction {s['reduction']}x; "
                    f"sieved {s['sieved']:,} of {s['candidates']:,} "
                    "candidates)",
                    file=out,
                )
                for lv in s["per_level"]:
                    print(
                        f"  level {lv['level']}: {lv['exchanged_bytes']:,}"
                        f" B (raw {lv['raw_bytes']:,} B, "
                        f"x{lv['reduction']}), sieved {lv['n_sieved']:,}"
                        f"/{lv['n_candidates']:,}",
                        file=out,
                    )
        else:
            with sanctx, tsanctx:
                chk = JaxChecker(
                    cfg, chunk=chunk, progress=progress,
                    host_store=host_store, canon=canon,
                    use_hashstore=(
                        True if use_hashstore is None else use_hashstore
                    ),
                    pipeline=pipeline,
                    pipeline_window=pipeline_window,
                    use_mxu=use_mxu,
                    megakernel=megakernel,
                    superstep=superstep,
                    prewarm=prewarm,
                    audit=audit,
                    audit_retries=audit_retries,
                    watchdog=wd,
                    store_bytes=dev_bytes,
                    warm_bytes=warm_bytes,
                )
                if dev_bytes:
                    print(
                        f"Tiered visited store: hot slab budget "
                        f"{dev_bytes:,} B (demotions spill to "
                        "host/disk generations)", file=out,
                    )
                if audit:
                    print(
                        f"Integrity audit: {audit} sampled rows/level "
                        "re-expanded through the legacy kernels "
                        f"(fail-stop after {audit_retries} strikes)",
                        file=out,
                    )
                try:
                    res = chk.run(
                        max_depth=max_depth,
                        checkpoint_dir=checkpoint_dir,
                        checkpoint_every=checkpoint_every,
                        resume_from=recover,
                    )
                finally:
                    wd_teardown()

    if hub is not None:
        from .obs import telemetry as obs_telemetry

        obs_telemetry.run_end(
            ok=res.ok, distinct=res.distinct,
            generated=res.generated, depth=res.depth,
        )
    summary = summarize(res, chk, time.monotonic() - t0, hub=hub)
    summary["_res"] = res
    summary["_chk"] = chk
    summary["_sanitizer"] = sanitizer
    summary["_tsan"] = tsan
    summary["_hub"] = hub
    return summary


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tla_raft_tpu.check")
    p.add_argument("--config", default="/root/reference/Raft.cfg",
                   help="TLC .cfg file (single source of truth for constants)")
    p.add_argument("--spec", default=None,
                   help="TLA+ spec file to validate against the compiled "
                        "semantics (default: Raft.tla next to the cfg)")
    p.add_argument("--backend", choices=("jax", "oracle"), default="jax")
    p.add_argument("--workers", type=int, default=None,
                   help="accepted for myrun.sh compatibility; ignored")
    p.add_argument("--max-depth", type=int, default=None)
    p.add_argument("--chunk", type=int, default=None,
                   help="expand rows per device dispatch (default: the "
                        "autotuned plan's chunk, else 1024)")
    p.add_argument("--invariant", action="append", default=None,
                   help="override INVARIANT (repeatable; ~Name negates)")
    p.add_argument("--no-symmetry", action="store_true")
    p.add_argument("--no-view", action="store_true")
    p.add_argument("--mutate", action="append", default=None,
                   choices=("median-bug", "double-vote", "legacy-append",
                            "become-follower"),
                   help="compile in a planted spec bug or a dead legacy "
                        "action variant (SURVEY §4.4; the checker must "
                        "then find a violation or a state-count "
                        "divergence from the live spec)")
    p.add_argument("--servers", type=int, default=None, help="override |Servers|")
    p.add_argument("--vals", type=int, default=None, help="override |Vals|")
    p.add_argument("--max-election", type=int, default=None)
    p.add_argument("--max-restart", type=int, default=None)
    p.add_argument("--fpstore-dir", default=None,
                   help="use the native external-memory fingerprint store "
                        "(TLC's states/ spill analog) rooted at this dir")
    p.add_argument("--checkpoint-dir", default=None,
                   help="write per-level delta-log checkpoints here "
                        "(both backends; the single-device external-store "
                        "path also spills per-group partial records "
                        "inside a level)")
    p.add_argument("--checkpoint-every", type=int, default=1,
                   help="0 disables checkpointing; any other value "
                        "records EVERY level (the delta-log replay chain "
                        "cannot skip levels)")
    p.add_argument("--recover", default=None,
                   help="resume from a checkpoint: the --checkpoint-dir "
                        "directory (delta log) or a monolith .npz; "
                        "corrupt/torn/unmanifested records are "
                        "quarantined and the run resumes from the last "
                        "good contiguous prefix (docs/ROBUSTNESS.md)")
    p.add_argument("--fault", action="append", default=None,
                   metavar="SITE:ACTION[@N]",
                   help="deterministic fault injection (repeatable): "
                        "kill/torn/flip/fail at a named site's Nth hit, "
                        "e.g. delta.commit:kill@3 (docs/ROBUSTNESS.md; "
                        "env TLA_RAFT_FAULT takes the same grammar)")
    p.add_argument("--supervise", type=int, default=0, metavar="N",
                   help="supervisor mode: run the check as a child "
                        "process and relaunch it from its own "
                        "--checkpoint-dir up to N times after a crash "
                        "or preemption (model verdicts, usage errors "
                        "and integrity fail-stops are terminal, never "
                        "relaunched)")
    p.add_argument("--audit", type=int, default=0, metavar="N",
                   help="end-to-end integrity audit: every level, "
                        "re-expand N deterministic frontier rows "
                        "through the retained legacy kernels and "
                        "cross-check children/guards/fingerprints "
                        "against the hot path; on mismatch the level "
                        "is quarantined and the run rewinds to the "
                        "last committed checkpoint (single-device "
                        "engine; docs/ROBUSTNESS.md)")
    p.add_argument("--audit-retries", type=int, default=3, metavar="R",
                   help="fail-stop (exit 4) after R reproducible audit "
                        "mismatches (default 3)")
    p.add_argument("--watchdog", type=float, default=0.0, metavar="SECS",
                   help="per-level hang watchdog: arm every level with "
                        "a deadline of max(SECS, 8x the previous "
                        "level's wall time); a hung device dispatch "
                        "becomes a clean resumable exit 75 instead of "
                        "an infinite stall (0 = off)")
    p.add_argument("--mesh", type=int, default=0,
                   help="run distributed over an N-device mesh (0 = single device)")
    p.add_argument("--exchange", choices=("all_to_all", "all_gather"),
                   default="all_to_all", help="distributed fingerprint exchange")
    p.add_argument("--mesh-deep", action="store_true",
                   help="sharded deep sweep: frontier 1/D across devices "
                        "as uniform segment lists, sieve-and-compress "
                        "fingerprint exchange, double-buffered level tail "
                        "(requires --mesh and --fpstore-dir)")
    p.add_argument("--seg-rows", type=int, default=1 << 15,
                   help="rows per frontier segment per device (deep mode)")
    p.add_argument("--no-sieve", action="store_true",
                   help="deep mode: disable the pre-routing visited sieve")
    p.add_argument("--no-compress", action="store_true",
                   help="deep mode: fetch raw u64 fingerprints instead of "
                        "the delta-packed stream")
    p.add_argument("--no-pipeline", action="store_true",
                   help="disable the async intra-level pipeline "
                        "(overlapped expand/fetch/insert windows) — the "
                        "serial fetch-after-dispatch chain; counts are "
                        "bit-identical either way (env: "
                        "TLA_RAFT_PIPELINE=0)")
    p.add_argument("--pipeline-window", type=int, default=None,
                   metavar="N",
                   help="bounded in-flight fetch groups of the async "
                        "pipeline (default 2; 0 = serial; env: "
                        "TLA_RAFT_PIPELINE_WINDOW)")
    p.add_argument("--prewarm", type=int, choices=(0, 1), default=None,
                   help="forecast-driven AOT program prewarm: compile "
                        "the deep-level shape ladder in a background "
                        "thread while shallow levels run (default: on "
                        "for tunneled backends; env: TLA_RAFT_PREWARM; "
                        "single-device engine only — ignored with "
                        "--mesh)")
    p.add_argument("--mxu-expand", type=int, choices=(0, 1), default=None,
                   help="MXU-native expand: guard coefficient matmul + "
                        "gather-free materialize (ops/mxu_expand.py). "
                        "Default on; 0 reverts to the legacy per-lane "
                        "kernels (A/B — counts are bit-identical). "
                        "env: TLA_RAFT_MXU")
    p.add_argument("--no-mxu-expand", action="store_true",
                   help="shorthand for --mxu-expand 0")
    p.add_argument("--megakernel", type=int, choices=(0, 1), default=None,
                   help="whole-level megakernel: fuse expand -> "
                        "probe-and-insert -> materialize -> invariant "
                        "scan into ONE jitted program per level with one "
                        "ledgered control fetch (engine/megakernel.py). "
                        "Default on; 0 reverts to the staged program "
                        "chain (A/B — counts are bit-identical). "
                        "Single-device engine; the external-store path "
                        "fuses expand+dedup per group. env: "
                        "TLA_RAFT_MEGAKERNEL")
    p.add_argument("--superstep", type=int, default=None, metavar="N",
                   help="multi-level resident supersteps: run up to N "
                        "consecutive fused levels inside ONE device "
                        "program with ONE ledgered ring fetch "
                        "(engine/superstep.py) — the dispatch floor "
                        "amortizes to 1/N.  Default 4; "
                        "1 reverts to the per-level megakernel "
                        "(A/B — counts are bit-identical).  Requires "
                        "the fused path (--megakernel 1); --audit "
                        "forces per-level.  env: TLA_RAFT_SUPERSTEP")
    p.add_argument("--dev-bytes", type=float, default=None,
                   metavar="BYTES",
                   help="device-memory budget for the HOT visited tier "
                        "(the on-device hash slab): growth past it "
                        "demotes whole generations to host RAM / disk "
                        "(store/tiered.py) instead of growing — "
                        "|visited| becomes storage-bounded like TLC's "
                        "disk FPSet.  0/unset = unbounded (hot-only; "
                        "counts are bit-identical either way).  env: "
                        "TLA_RAFT_STORE_BYTES")
    p.add_argument("--warm-bytes", type=float, default=None,
                   metavar="BYTES",
                   help="host-RAM budget for the WARM tier: demoted "
                        "generations past it drop to cold (disk-only, "
                        "re-read through an LRU page cache; default "
                        "1 GiB); on the external-store paths this "
                        "bounds the native store's in-RAM buffer "
                        "before it spills sorted runs (unset = the "
                        "native 64 MiB default).  env: "
                        "TLA_RAFT_WARM_BYTES")
    p.add_argument("--no-hashstore", action="store_true",
                   help="revert to the sort-based visited path (lexsort "
                        "+ searchsorted + sorted merge) instead of the "
                        "on-device open-addressing fingerprint store")
    p.add_argument("--cap-x", type=int, default=4096,
                   help="per-device candidate capacity (distributed mode)")
    p.add_argument("--canon", choices=("late", "expand"), default="late",
                   help="candidate canonicalization: 'late' fingerprints "
                        "only compacted candidates (default; required for "
                        "big symmetry groups), 'expand' folds the hash "
                        "into every fan-out lane")
    p.add_argument("--log", default="raft.log")
    p.add_argument("--coverage", action="store_true",
                   help="print per-action fired-transition counts (TLC -coverage)")
    p.add_argument("--json", action="store_true", help="emit a final JSON summary line")
    p.add_argument("--telemetry", type=int, choices=(0, 1), default=None,
                   help="run flight recorder (obs/telemetry.py): typed "
                        "run events appended crash-tolerantly to "
                        "events.jsonl in the checkpoint dir, plus the "
                        "unified telemetry block in --json.  Default "
                        "on; 0 disables.  Host-side only — counts and "
                        "dispatch/fetch budgets are identical either "
                        "way.  env: TLA_RAFT_TELEMETRY")
    p.add_argument("--profile", type=int, default=0, metavar="N",
                   help="opt-in device profiler: capture jax.profiler "
                        "traces for the first N dispatch windows "
                        "(supersteps on the fused path) into "
                        "<checkpoint-dir>/profile/, then `python -m "
                        "tla_raft_tpu.obs trace` merges the device "
                        "lanes into trace.json beside the host lanes. "
                        "Default off; counts are bit-identical either "
                        "way")
    p.add_argument("--plan", default=None, metavar="PATH|0|1",
                   help="autotuned knob plan (tune/plans.py): 0 forces "
                        "the hand-set defaults, 1 (or unset) resolves "
                        "the committed plan cache, a path resolves that "
                        "file; TLA_RAFT_PLAN is the env twin")
    p.add_argument("--tune", type=int, default=0, metavar="DEPTH",
                   help="probe-search this config's knob regime to "
                        "depth DEPTH before the run (tune/search.py) "
                        "and commit the winner to the plan cache; the "
                        "run then executes under it")
    p.add_argument("--progress", action="store_true",
                   help="live one-line progress display (states/s, "
                        "frontier, slab load, levels/dispatch, "
                        "forecast ETA to fixpoint) instead of one "
                        "Progress line per level")
    args = p.parse_args(argv)

    if args.supervise:
        return _supervise(args, argv if argv is not None else sys.argv[1:])
    if args.fault:
        from .resilience import faults as _faults

        _faults.install(";".join(args.fault))

    cfg = load_raft_config(args.config)
    overrides = {}
    if args.invariant:
        overrides["invariants"] = tuple(args.invariant)
    if args.no_symmetry:
        overrides["symmetry"] = False
    if args.no_view:
        overrides["use_view"] = False
    if args.mutate:
        overrides["mutations"] = tuple(args.mutate)
    if args.servers is not None:
        overrides["n_servers"] = args.servers
    if args.vals is not None:
        overrides["n_vals"] = args.vals
    if args.max_election is not None:
        overrides["max_election"] = args.max_election
    if args.max_restart is not None:
        overrides["max_restart"] = args.max_restart
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)

    logf = open(args.log, "w") if args.log and args.log != "-" else None
    out = Tee(sys.stdout, logf) if logf else sys.stdout
    if args.mesh_deep and not args.mesh:
        # without this guard the run would silently fall through to the
        # single-device engine and be mistaken for a deep-sweep result
        print("--mesh-deep requires --mesh N (the sharded deep sweep "
              "runs on a device mesh)", file=out)
        return 2
    t0 = time.monotonic()
    print(f"tla-raft-tpu checker: backend={args.backend}", file=out)
    print(f"Config {args.config}: {cfg.describe()}", file=out)

    # structural spec validation (SURVEY.md §7.2 step 1): the compiled
    # semantics must match the spec file actually sitting next to the cfg
    spec_path = args.spec
    if spec_path is None:
        cand = os.path.join(os.path.dirname(os.path.abspath(args.config)), "Raft.tla")
        spec_path = cand if os.path.exists(cand) else None
    if spec_path:
        from .tla_frontend import validate_spec

        problems = validate_spec(spec_path)
        if problems:
            for pr in problems:
                print(f"SPEC MISMATCH: {pr}", file=out)
            print("Refusing to check a spec that diverges from the compiled "
                  "semantics (pass --spec '' to skip).", file=out)
            return 2
        print(f"Spec {spec_path}: structure matches compiled semantics.", file=out)

    pline = None
    if args.progress:
        from .obs.progress import ProgressLine

        pline = ProgressLine(stream=sys.stderr)

    def progress(s):
        rate = s["distinct"] / max(s["elapsed"], 1e-9)
        if pline is not None:
            # live CR-updated line on stderr; the grep-able per-level
            # Progress lines keep landing in the log file (or on
            # stdout under --log - , which is a different stream from
            # the live line — the grep contract survives either way)
            pline.write(s)
            print(
                f"Progress: level {s['level']}, frontier "
                f"{s['frontier']}, distinct {s['distinct']}, "
                f"generated {s['generated']}, {rate:,.0f} states/s",
                file=logf if logf else out,
            )
            return
        print(
            f"Progress: level {s['level']}, frontier {s['frontier']}, "
            f"distinct {s['distinct']}, generated {s['generated']}, "
            f"{rate:,.0f} states/s",
            file=out,
        )
        out.flush()

    if args.mesh and args.prewarm:
        print("--prewarm applies to the single-device engine "
              "only; the mesh level loops compile their program "
              "set in line (flag ignored)", file=out)
    if args.mesh and args.mesh_deep and not args.fpstore_dir:
        print("--mesh-deep requires --fpstore-dir (the sharded "
              "deep sweep filters through per-owner external "
              "stores)", file=out)
        return 2
    from . import resilience

    if args.tune and args.backend == "jax":
        # probe-search this regime first, commit the winner, then run
        # under it (the commit target is the --plan path when given,
        # else the TLA_RAFT_PLAN-active cache)
        from .tune import plans as _plans
        from .tune import search as _tune_search

        tune_path = (
            args.plan if args.plan and args.plan not in ("0", "1")
            else _plans.plan_path()
        )
        _tune_search.tune(
            cfg, backend=args.backend, path=tune_path,
            commit=tune_path is not None,
            max_depth=args.tune, out=out,
            dev_bytes=int(args.dev_bytes) if args.dev_bytes else None,
        )

    try:
        summary = run_check(
            cfg,
            backend=args.backend,
            max_depth=args.max_depth,
            chunk=args.chunk,
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_every=args.checkpoint_every,
            recover=args.recover,
            fpstore_dir=args.fpstore_dir,
            mesh=args.mesh,
            exchange=args.exchange,
            mesh_deep=args.mesh_deep,
            seg_rows=args.seg_rows,
            sieve=not args.no_sieve,
            compress=not args.no_compress,
            cap_x=args.cap_x,
            canon=args.canon,
            use_hashstore=not args.no_hashstore,
            pipeline=False if args.no_pipeline else None,
            pipeline_window=args.pipeline_window,
            prewarm=(
                None if args.prewarm is None else bool(args.prewarm)
            ),
            use_mxu=_mxu_arg(args),
            megakernel=(
                None if args.megakernel is None else bool(args.megakernel)
            ),
            superstep=args.superstep,
            audit=args.audit,
            audit_retries=args.audit_retries,
            watchdog=args.watchdog,
            telemetry=(
                None if args.telemetry is None else bool(args.telemetry)
            ),
            profile=args.profile,
            dev_bytes=(
                int(args.dev_bytes) if args.dev_bytes else None
            ),
            warm_bytes=(
                int(args.warm_bytes) if args.warm_bytes else None
            ),
            plan=args.plan,
            progress=progress,
            out=out,
            install_signals=(args.backend != "oracle"),
        )
    except resilience.Preempted as e:
        return _report_preempted(e, out, logf)
    except resilience.integrity.IntegrityError as e:
        # the whole integrity family is exit 4: an audit mismatch that
        # reproduced across its rewind budget (AuditFailStop) AND the
        # always-on conservation checks (exchange count reconciliation,
        # slab occupancy, corrupt fp stream) — none of these is a model
        # verdict, and exiting 1 would report a fake violation to the
        # supervisor and every fleet scheduler watching the code
        print(f"Integrity fail-stop: {e}", file=out)
        if logf:
            logf.close()
        return 4
    except Exception as e:  # graftlint: waive[GL003] — classifier
        # catch: device-loss errors map to exit 75, everything else
        # re-raises unchanged two lines down
        if resilience.elastic.is_device_loss(e):
            # a mesh participant failed: committed levels are durable,
            # so this is RESUMABLE — exit 75 (EX_TEMPFAIL) like a
            # preemption; --supervise relaunches and the elastic
            # resume re-shards onto the surviving devices
            print(f"Device loss: {type(e).__name__}: {e}.", file=out)
            if args.checkpoint_dir:
                print(
                    f"Resume with --recover {args.checkpoint_dir} "
                    "(any surviving device count: owner remap "
                    "re-shards the log)",
                    file=out,
                )
            if logf:
                logf.close()
            return 75
        raise
    res = summary["_res"]
    chk = summary["_chk"]
    sanitizer = summary["_sanitizer"]
    tsan = summary.get("_tsan")
    hub = summary.get("_hub")

    if pline is not None:
        pline.done()
    dt = time.monotonic() - t0
    print(file=out)
    if hub is not None and hub.path:
        print(
            f"Telemetry: {hub.n_events} events -> {hub.path} "
            "(timeline: python -m tla_raft_tpu.obs trace "
            f"{os.path.dirname(hub.path)})",
            file=out,
        )
    if sanitizer is not None:
        sanitizer.print_report(out)
    if tsan is not None:
        tsan.print_report(out)
    if res.ok:
        print("Model checking completed. No error has been found.", file=out)
    else:
        kind, trace = res.violation
        print(f"Error: {kind}.", file=out)
        if trace is not None:
            print_trace(cfg, trace, out)
    print(
        f"{res.generated} states generated, {res.distinct} distinct states "
        f"found, depth {res.depth}.",
        file=out,
    )
    # TLC prints the odds its 64-bit fingerprint set silently collided; the
    # rebuild dedups on the same 64-bit-universe hash, so report the same
    # birthday bound: E[collisions] ~= n(n-1)/2^65 (myrun.sh raft.log contract)
    coll = res.distinct * max(res.distinct - 1, 0) / 2.0**65
    print(
        f"The probability of a fingerprint collision is calculated to be "
        f"{coll:.3g}.",
        file=out,
    )
    if args.coverage and res.action_counts:
        print("Action coverage (transitions fired):", file=out)
        for name, n in sorted(res.action_counts.items(), key=lambda kv: -kv[1]):
            print(f"  {name}: {n}", file=out)
    print(f"Finished in {dt:.1f}s ({res.distinct / max(dt, 1e-9):,.0f} distinct states/s).", file=out)
    if args.json:
        # the one schema (summarize): ok/distinct/generated/depth/
        # level_sizes/mxu/seconds/violation/telemetry — shared with
        # run_check and the sweep service's result.json records
        print(json.dumps(summarize(res, chk, dt, hub=hub)), file=out)
    if logf:
        logf.close()
    if res.ok and (
        (sanitizer is not None and not sanitizer.ok)
        or (tsan is not None and not tsan.ok)
    ):
        # sanitizer/tsan findings on an otherwise-clean run: distinct
        # exit code so CI can tell "model violation" from "runtime
        # hygiene"
        return 3
    return 0 if res.ok else 1


if __name__ == "__main__":
    sys.exit(main())
